"""Pallas TPU kernels: tiled one-hot-matmul sparse row ops (gather + update).

Round-3 hardware data (docs/round3_notes.md prims table) showed XLA:TPU's row
machinery is descriptor-bound: scatter-add ~55-106 ns/row, gather ~22 ns/row,
segment_sum ~45 ns/row, against a ~0.1 ns/row bandwidth bound — and the
backward scatter + row-wise optimizer IS the train step (tiny: 1228 ms vs a
2.3 ms roofline). The round-3 response kernels (ops/pallas_scatter.py) stream
per-row DMAs, but the r03 tunnel toolchain rejects every `make_async_copy`
kernel (remote_compile HTTP 500, 4/4 failures).

This module takes a different shape, chosen so that EVERY memory access is a
regular BlockSpec block stream — the one Pallas form already proven to
compile on this toolchain (the one-hot MXU kernel in ops/pallas_lookup.py
compiles and is bit-accurate). No `make_async_copy`, no per-row DMA, no
semaphores:

    sort ids once (XLA sort_key_val: measured 1.9 ns/key), then walk the
    table in row TILES and the sorted id stream in CHUNKS. Grid = the
    (tile, chunk) overlap pairs. Each step builds a [tile, chunk] one-hot
    on the VPU from an iota compare and contracts it with the chunk's
    gradient rows on the MXU:

        dense_tile_grad += onehot(ids_chunk - tile_base) @ grad_chunk

    Duplicate ids aggregate *inside the matmul* — no dedup pass, no
    segment_sum, no scatter anywhere. The optimizer (sgd/adagrad) applies
    as a dense elementwise VPU op on the tile when its last chunk lands,
    then the tile streams back to HBM. Gather is the transpose:

        rows_chunk += onehot(ids_chunk - tile_base)^T-form @ table_tile

    HBM traffic is block-sequential (the access pattern of a blocked
    matmul), so the cost model is bytes/bandwidth, not descriptors/row:
    ~visited tiles * tile bytes * 2(read+write) * arrays — for the round-3
    bench shapes that is ~25 ms on tiny's 70.2M x 16 bucket and ~8 ms on
    DLRM's 2.6M x 128 bucket vs the measured 600+/90+ ms XLA scatter paths.

This is the TPU-native analogue of the reference backward kernel's
sort -> unique -> segment-reduce pipeline (reference:
cc/kernels/embedding_lookup_kernels.cu:603-775, cub radix sort at :645-661),
re-shaped for a machine whose fast paths are systolic matmul and sequential
DMA rather than warp-level shared-memory staging.

Semantics contract (shared by all entry points):
  * ids may contain duplicates in any order; invalid ids (id < 0 or
    id >= V) contribute nothing (XLA mode="drop" parity).
  * update kernels aggregate duplicate rows first (sum), matching the
    reference's unique-grad contract; adagrad uses the aggregated total
    (acc += total^2), identical to sparse_update.sparse_adagrad.
  * aggregation order differs from XLA's scatter order, so results match
    to f32 tolerance, not bit-exactly (tests pin ~1e-5 relative).

Status: interpret-mode tested everywhere (tests/test_pallas_tiled.py,
tests/test_pallas_fused.py); compiled use is gated on
`prevalidate_tiled()` / `prevalidate_pallas_fused()` against the
attached chip. Dispatch lives in sparse_update behind
DET_SCATTER_IMPL=tiled (raw-stream kernels, f32-tolerance parity) and
DET_SCATTER_IMPL=pallas (the ISSUE 12 fused strategy: deduped-row
appliers + the weighted gather->combine forward, bit-exact vs the XLA
sort path — see the fused section below).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

# shared rounding pin (see its docstring): the in-kernel optimizer
# arithmetic must round at exactly the seams the XLA sort path rounds at
# (scatter-operand materialization / the pinned adam products), or
# context-dependent backend FMA contraction breaks the fused strategy's
# bit-exactness. Every kernel's hp block carries a trailing RUNTIME 0.0
# (an SMEM load the compiler cannot prove constant) as the pin operand.
from distributed_embeddings_tpu.ops.sparse_update import (fp_round,
                                                          round_pin)


# Process-cached backend probe (ISSUE 12 satellite bugfix): the default
# interpret decision used to re-consult jax.default_backend() on every
# kernel call, so a backend flip mid-process (config update between the
# forward trace and the update trace) could run one step's phases in
# DIFFERENT modes. One probe per process; every entry point — the
# optimizer kernels, the row appliers AND tiled_gather_sorted — shares
# the cached verdict, so forward and update phases of one step can never
# diverge. An explicit interpret= argument always wins.
_BACKEND_INTERPRET: Optional[bool] = None


def _interpret_default(interpret: Optional[bool]) -> bool:
    global _BACKEND_INTERPRET
    if interpret is None:
        if _BACKEND_INTERPRET is None:
            _BACKEND_INTERPRET = jax.default_backend() != "tpu"
        return _BACKEND_INTERPRET
    return bool(interpret)


# defaults; wrappers shrink them for tiny shapes. tile bounds VMEM
# (tile * max(width,128) * 4B per buffered array), chunk bounds the one-hot
# slab and the MXU contraction depth.
_TILE = 1024     # table rows per tile (multiple of 8)
_CHUNK = 512     # sorted ids per chunk (multiple of 128)


def _sort_ids(ids: jax.Array, contribs: Optional[jax.Array], vocab: int):
    """Sort ids ascending with invalid ids (neg / >= vocab) keyed to `vocab`
    so they land at the end; permute contribs alongside. Returns
    (sorted_keys [N] in [0, vocab], sorted_rows or None, perm)."""
    n = ids.shape[0]
    iota = lax.iota(jnp.int32, n)
    ids = ids.astype(jnp.int32)
    key = jnp.where((ids >= 0) & (ids < vocab), ids, jnp.int32(vocab))
    sid, perm = lax.sort_key_val(key, iota)
    rows = None if contribs is None else jnp.take(contribs, perm, axis=0)
    return sid, rows, perm


def _chunk_layout(sid: jax.Array, vocab: int, chunk: int, tile: int):
    """Pad the sorted id stream to whole chunks plus one all-filler chunk,
    and compute each real chunk's first/last table tile.

    Returns (kids2d [n_chunks+1, chunk] int32 with -1 fillers,
             pad_rows  total padded id count including the filler chunk,
             chunk_first [n_chunks], chunk_last [n_chunks], n_chunks).

    Filler handling: invalid ids carry sort key == vocab; for TILE MAPPING
    they are collapsed onto the last valid id so a half-filler boundary
    chunk does not claim to span to the end of the table (which would drag
    the pair walk across every trailing tile)."""
    n = sid.shape[0]
    n_chunks = -(-n // chunk)
    pad = n_chunks * chunk - n
    sid = jnp.concatenate([sid, jnp.full((pad,), vocab, jnp.int32)])
    num_valid = jnp.searchsorted(sid, vocab).astype(jnp.int32)
    last_valid = sid[jnp.maximum(num_valid - 1, 0)]
    last_valid = jnp.where(num_valid > 0, last_valid, 0)
    mapped = jnp.clip(jnp.where(sid < vocab, sid, last_valid), 0, vocab - 1)
    tiles = (mapped // tile).reshape(n_chunks, chunk)
    chunk_first = tiles[:, 0]
    chunk_last = tiles[:, -1]
    kids = jnp.where(sid < vocab, sid, -1)
    # one pure-filler chunk at index n_chunks: padded grid steps point here
    # and contribute exactly zero
    kids2d = jnp.concatenate(
        [kids, jnp.full((chunk,), -1, jnp.int32)]).reshape(n_chunks + 1,
                                                           chunk)
    return kids2d, (n_chunks + 1) * chunk, chunk_first, chunk_last, n_chunks


def _tile_major_pairs(chunk_first, chunk_last, n_tiles: int, n_chunks: int):
    """Static-size (tile, chunk) pair walk, TILE-major: for each tile, the
    chunks overlapping it (>=1 per tile — empty tiles get one zero-
    contribution dummy so every output tile block is visited and written).
    Pairs are monotone in tile, so each tile's pairs are consecutive and
    the out block revisit/flush pattern is exact.

    Returns (tof [G], cof [G]) int32 with G = n_tiles + n_chunks static;
    padded trailing pairs map to (last tile, filler chunk)."""
    g_count = n_tiles + n_chunks
    t_iota = lax.iota(jnp.int32, n_tiles)
    lo = jnp.searchsorted(chunk_last, t_iota, side="left").astype(jnp.int32)
    hi = (jnp.searchsorted(chunk_first, t_iota, side="right").astype(
        jnp.int32) - 1)
    span = jnp.maximum(1, hi - lo + 1)
    pstart = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(span)[:-1].astype(jnp.int32)])
    total = pstart[-1] + span[-1]
    g_iota = lax.iota(jnp.int32, g_count)
    tof = jnp.clip(
        jnp.searchsorted(pstart, g_iota, side="right").astype(jnp.int32) - 1,
        0, n_tiles - 1)
    cof = jnp.clip(jnp.take(lo, tof) + (g_iota - jnp.take(pstart, tof)),
                   0, n_chunks - 1)
    cof = jnp.where(g_iota < total, cof, jnp.int32(n_chunks))
    tof = jnp.where(g_iota < total, tof, jnp.int32(n_tiles - 1))
    return tof, cof


def _chunk_major_pairs(chunk_first, chunk_last, n_tiles: int, n_chunks: int):
    """CHUNK-major pair walk for gather: for each chunk, the tiles it spans
    (>=1). Monotone in chunk => each output rows-chunk block's visits are
    consecutive. Padded trailing pairs point at the all-filler chunk
    (index n_chunks, ids all -1), so they contribute exactly zero and the
    kernel stays branch-free.

    Returns (tof [G], cof [G]) with G = n_chunks + n_tiles static. The
    filler chunk's padded pairs also flush its all-zero output block,
    which the wrapper slices off."""
    g_count = n_chunks + n_tiles
    c_iota = lax.iota(jnp.int32, n_chunks)
    span = jnp.maximum(1, chunk_last - chunk_first + 1)
    pstart = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(span)[:-1].astype(jnp.int32)])
    total = pstart[-1] + span[-1]
    g_iota = lax.iota(jnp.int32, g_count)
    cof = jnp.clip(
        jnp.searchsorted(pstart, g_iota, side="right").astype(jnp.int32) - 1,
        0, n_chunks - 1)
    tof = jnp.clip(
        jnp.take(chunk_first, cof) + (g_iota - jnp.take(pstart, cof)),
        0, n_tiles - 1)
    # padded pairs -> filler chunk, reusing the last tile (already resident)
    cof = jnp.where(g_iota < total, cof, jnp.int32(n_chunks))
    tof = jnp.where(g_iota < total, tof, jnp.take(chunk_last,
                                                  jnp.int32(n_chunks - 1)))
    del c_iota
    return tof, cof


def _onehot(ids_row: jax.Array, tile_base, tile: int) -> jax.Array:
    """[tile, chunk] f32 one-hot: oh[r, j] = (ids_row[j] == tile_base + r).
    Invalid ids (-1 fillers, other-tile ids) match nothing."""
    local = (ids_row - tile_base)[None, :]
    r = lax.broadcasted_iota(jnp.int32, (tile, ids_row.shape[0]), 0)
    return (r == local).astype(jnp.float32)


# --------------------------------------------------------------------------
# update kernels (tile-major walk)
# --------------------------------------------------------------------------
def _flags(tof_ref, g, g_count):
    t = tof_ref[g]
    prev_t = tof_ref[jnp.maximum(g - 1, 0)]
    nxt_t = tof_ref[jnp.minimum(g + 1, g_count - 1)]
    first = (g == 0) | (prev_t != t)
    last = (g == g_count - 1) | (nxt_t != t)
    return t, first, last


def _sgd_kernel(tof_ref, cof_ref, ids_ref, grads_ref, hp_ref, table_ref,
                out_ref, acc_ref, *, tile: int, g_count: int):
    g = pl.program_id(0)
    t, first, last = _flags(tof_ref, g, g_count)
    oh = _onehot(ids_ref[0, :], t * tile, tile)
    part = lax.dot_general(oh, grads_ref[:].astype(jnp.float32),
                           (((1,), (0,)), ((), ())),
                           precision=lax.Precision.HIGHEST,
                           preferred_element_type=jnp.float32)

    @pl.when(first)
    def _():
        acc_ref[:] = part

    @pl.when(jnp.logical_not(first))
    def _():
        acc_ref[:] = acc_ref[:] + part

    @pl.when(last)
    def _():
        lr = hp_ref[0, 0]
        zero = hp_ref[0, 1]         # rounding pin (see fp_round)
        out_ref[:] = (table_ref[:].astype(jnp.float32)
                      - fp_round(lr * acc_ref[:], zero)).astype(
                          out_ref.dtype)


def _adagrad_kernel(tof_ref, cof_ref, ids_ref, grads_ref, hp_ref, table_ref,
                    accum_ref, out_t_ref, out_a_ref, acc_ref, *, tile: int,
                    g_count: int, eps: float):
    g = pl.program_id(0)
    t, first, last = _flags(tof_ref, g, g_count)
    oh = _onehot(ids_ref[0, :], t * tile, tile)
    part = lax.dot_general(oh, grads_ref[:].astype(jnp.float32),
                           (((1,), (0,)), ((), ())),
                           precision=lax.Precision.HIGHEST,
                           preferred_element_type=jnp.float32)

    @pl.when(first)
    def _():
        acc_ref[:] = part

    @pl.when(jnp.logical_not(first))
    def _():
        acc_ref[:] = acc_ref[:] + part

    @pl.when(last)
    def _():
        lr = hp_ref[0, 0]
        zero = hp_ref[0, 1]         # rounding pin (see fp_round)
        gs = acc_ref[:]
        a_new = accum_ref[:].astype(jnp.float32) + fp_round(gs * gs, zero)
        out_a_ref[:] = a_new.astype(out_a_ref.dtype)
        # untouched rows: gs == 0 -> delta == 0, accumulator unchanged
        out_t_ref[:] = (table_ref[:].astype(jnp.float32)
                        - fp_round(lr * gs * lax.rsqrt(a_new + eps),
                                   zero)).astype(out_t_ref.dtype)


def _update_call(kernel, n_out, table, extra_tables, sid, rows, hp,
                 chunk: int, tile: int, interpret, extra_scratch=()):
    """Shared pallas_call builder for the tile-major update kernels.
    extra_tables: additional [V, w] state arrays (adagrad accumulator,
    adam moments); extra_scratch: VMEM scratch beyond the grad
    accumulator (adam's touched-count column)."""
    vocab, width = table.shape
    kids2d, pad_rows, c_first, c_last, n_chunks = _chunk_layout(
        sid, vocab, chunk, tile)
    rows = jnp.concatenate(
        [rows.astype(jnp.float32),
         jnp.zeros((pad_rows - rows.shape[0], width), jnp.float32)])
    n_tiles = -(-vocab // tile)
    tof, cof = _tile_major_pairs(c_first, c_last, n_tiles, n_chunks)
    g_count = n_tiles + n_chunks
    tables = [table, *extra_tables]
    n_tab = len(tables)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(g_count,),
        in_specs=[
            pl.BlockSpec((1, chunk), lambda g, tof, cof: (cof[g], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((chunk, width), lambda g, tof, cof: (cof[g], 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(hp.shape, lambda g, tof, cof: (0, 0),
                         memory_space=pltpu.SMEM),
        ] + [
            pl.BlockSpec((tile, width), lambda g, tof, cof: (tof[g], 0),
                         memory_space=pltpu.VMEM)
            for _ in range(n_tab)
        ],
        out_specs=[
            pl.BlockSpec((tile, width), lambda g, tof, cof: (tof[g], 0),
                         memory_space=pltpu.VMEM)
            for _ in range(n_tab)
        ][:n_out] if n_out > 1 else pl.BlockSpec(
            (tile, width), lambda g, tof, cof: (tof[g], 0),
            memory_space=pltpu.VMEM),
        scratch_shapes=[pltpu.VMEM((tile, width), jnp.float32),
                        *extra_scratch],
    )
    out_shape = [jax.ShapeDtypeStruct(t.shape, t.dtype) for t in tables]
    out_shape = out_shape[:n_out] if n_out > 1 else out_shape[0]
    # operand indices include the 2 prefetch args: ids2d=2, rows=3, hp=4,
    # tables start at 5
    aliases = {5 + i: i for i in range(n_out)}
    return pl.pallas_call(
        functools.partial(kernel, tile=tile, g_count=g_count),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=_interpret_default(interpret),
    )(tof, cof, kids2d, rows, hp, *tables)


def _shrink(vocab: int, n: int, chunk: int, tile: int):
    """Clamp block sizes for small problems (keep multiples of 8/128)."""
    tile = min(tile, max(8, -(-vocab // 8) * 8))
    chunk = min(chunk, max(128, -(-n // 128) * 128))
    return chunk, tile


def _hp_with_pin(ids, lr, *extra):
    """SMEM hyperparameter block [1, n]: lr, any extra scalars, then the
    RUNTIME 0.0 every kernel reads as its rounding pin (see fp_round).
    The pin derives from the id stream — lr is usually a trace-time
    constant, and a constant hp block would let the backend fold the pin
    away; ids are traced in every real flow, which keeps the SMEM slot
    opaque."""
    vals = [jnp.asarray(lr, jnp.float32).reshape(())]
    vals += [jnp.asarray(e, jnp.float32).reshape(()) for e in extra]
    vals.append(round_pin(ids).reshape(()))
    return jnp.stack(vals).reshape(1, len(vals))


def _sorted_stream(ids, contribs, vocab: int, presorted):
    """(sid, permuted contrib rows) for an update kernel: fresh sort, or a
    caller-provided (sid, perm) — e.g. the forward lookup's sort reused by
    the backward over the SAME id stream (saves ~2 ns/key sort + the key
    build; XLA CSE does not merge the fwd/bwd sorts on its own, measured
    round 5 — see docs/perf_model.md 'Sort folding')."""
    if presorted is None:
        return _sort_ids(ids, contribs, vocab)[:2]
    sid, perm = presorted
    rows = None if contribs is None else jnp.take(contribs, perm, axis=0)
    return sid, rows


def tiled_sgd(table: jax.Array, ids: jax.Array, contribs: jax.Array, lr,
              chunk: int = _CHUNK, tile: int = _TILE,
              interpret: Optional[bool] = None,
              presorted=None) -> jax.Array:
    """table[ids] -= lr * contribs with duplicate aggregation in-kernel.
    Invalid ids dropped. lr may be traced (SMEM scalar). `presorted` may
    carry this id stream's (sid, perm) from a prior `_sort_ids`."""
    if ids.shape[0] == 0:
        return table
    chunk, tile = _shrink(table.shape[0], ids.shape[0], chunk, tile)
    sid, rows = _sorted_stream(ids, contribs, table.shape[0], presorted)
    hp = _hp_with_pin(sid, lr)
    return _update_call(_sgd_kernel, 1, table, [], sid, rows, hp,
                        chunk, tile, interpret)


def tiled_adagrad(table: jax.Array, accum: jax.Array, ids: jax.Array,
                  contribs: jax.Array, lr, eps: float = 1e-10,
                  chunk: int = _CHUNK, tile: int = _TILE,
                  interpret: Optional[bool] = None, presorted=None):
    """Fused row-wise adagrad with in-kernel duplicate aggregation:
        total[r]  = sum of contribs rows for r
        acc[r]   += total^2 ; table[r] -= lr * total * rsqrt(acc[r] + eps)
    Returns (table', accum'). Matches sparse_update.sparse_adagrad to f32
    tolerance. lr may be traced; eps is static."""
    if ids.shape[0] == 0:
        return table, accum
    chunk, tile = _shrink(table.shape[0], ids.shape[0], chunk, tile)
    sid, rows = _sorted_stream(ids, contribs, table.shape[0], presorted)
    hp = _hp_with_pin(sid, lr)
    out = _update_call(functools.partial(_adagrad_kernel, eps=eps), 2,
                       table, [accum], sid, rows, hp, chunk, tile, interpret)
    return out[0], out[1]


def _adam_kernel(tof_ref, cof_ref, ids_ref, grads_ref, hp_ref, table_ref,
                 mu_ref, nu_ref, out_t_ref, out_mu_ref, out_nu_ref, acc_ref,
                 cnt_ref, *, tile: int, g_count: int, b1: float, b2: float,
                 eps: float):
    """Lazy row-wise adam (sparse_update.sparse_adam semantics): moments
    decay ONLY on touched rows, so the kernel also accumulates a per-row
    id count (one extra all-ones matmul column) to build the touched mask
    — a zero gradient SUM on a touched row must still decay its moments,
    so `sum != 0` is not a usable mask."""
    g = pl.program_id(0)
    t, first, last = _flags(tof_ref, g, g_count)
    oh = _onehot(ids_ref[0, :], t * tile, tile)
    gf = grads_ref[:].astype(jnp.float32)
    part = lax.dot_general(oh, gf, (((1,), (0,)), ((), ())),
                           precision=lax.Precision.HIGHEST,
                           preferred_element_type=jnp.float32)
    cnt_part = jnp.sum(oh, axis=1, keepdims=True)        # [tile, 1]

    @pl.when(first)
    def _():
        acc_ref[:] = part
        cnt_ref[:] = cnt_part

    @pl.when(jnp.logical_not(first))
    def _():
        acc_ref[:] = acc_ref[:] + part
        cnt_ref[:] = cnt_ref[:] + cnt_part

    @pl.when(last)
    def _():
        lr = hp_ref[0, 0]
        c1 = hp_ref[0, 1]        # 1 - b1^count (precomputed outside)
        c2 = hp_ref[0, 2]        # 1 - b2^count
        gs = acc_ref[:]
        touched = cnt_ref[:] > 0.0                        # [tile, 1]
        zero = hp_ref[0, 3]         # rounding pin (see fp_round)
        mu_old = mu_ref[:].astype(jnp.float32)
        nu_old = nu_ref[:].astype(jnp.float32)
        mu_new = jnp.where(touched, fp_round(b1 * mu_old, zero)
                           + fp_round((1.0 - b1) * gs, zero), mu_old)
        nu_new = jnp.where(
            touched, fp_round(b2 * nu_old, zero)
            + fp_round((1.0 - b2) * fp_round(gs * gs, zero), zero),
            nu_old)
        delta = jnp.where(
            touched,
            -lr * (mu_new / c1) / (jnp.sqrt(nu_new / c2) + eps), 0.0)
        out_mu_ref[:] = mu_new.astype(out_mu_ref.dtype)
        out_nu_ref[:] = nu_new.astype(out_nu_ref.dtype)
        out_t_ref[:] = (table_ref[:].astype(jnp.float32)
                        + delta).astype(out_t_ref.dtype)


def tiled_adam(table: jax.Array, mu: jax.Array, nu: jax.Array, count,
               ids: jax.Array, contribs: jax.Array, lr, b1: float = 0.9,
               b2: float = 0.999, eps: float = 1e-8, chunk: int = _CHUNK,
               tile: int = _TILE, interpret: Optional[bool] = None,
               presorted=None):
    """Fused lazy row-wise adam with in-kernel duplicate aggregation;
    matches sparse_update.sparse_adam (touched rows decay, bias correction
    by global step count) to f32 tolerance. Returns (table, mu, nu, count);
    `count` increments exactly as the XLA rule does (including for a
    statically-empty grad shard)."""
    count = count + 1
    if ids.shape[0] == 0:
        return table, mu, nu, count
    cf = count.astype(jnp.float32)
    c1 = 1.0 - lax.pow(jnp.float32(b1), cf)
    c2 = 1.0 - lax.pow(jnp.float32(b2), cf)
    chunk, tile = _shrink(table.shape[0], ids.shape[0], chunk, tile)
    sid, rows = _sorted_stream(ids, contribs, table.shape[0], presorted)
    hp = _hp_with_pin(sid, lr, c1, c2)
    out = _update_call(
        functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps), 3,
        table, [mu, nu], sid, rows, hp, chunk, tile, interpret,
        extra_scratch=[pltpu.VMEM((tile, 1), jnp.float32)])
    return out[0], out[1], out[2], count


# --------------------------------------------------------------------------
# gather kernel (chunk-major walk)
# --------------------------------------------------------------------------
def _gather_kernel(tof_ref, cof_ref, ids_ref, *refs, tile: int,
                   g_count: int, vocab: int, weighted: bool = False):
    """Chunk-major gather: out[j] = table[ids[j]] — or, with `weighted`
    (the ISSUE 12 fused forward), w[j] * table[ids[j]]: the per-lane
    weight scales the one-hot COLUMN, so the weight multiply is free on
    the MXU and no separate [N, w] elementwise pass exists."""
    if weighted:
        w_ref, table_ref, out_ref = refs
    else:
        table_ref, out_ref = refs
    g = pl.program_id(0)
    c = cof_ref[g]
    prev_c = cof_ref[jnp.maximum(g - 1, 0)]
    first = (g == 0) | (prev_c != c)
    t = tof_ref[g]
    # contract the one-hot on the TILE axis. The last tile's
    # out-of-bounds rows must be zeroed before the contraction: their
    # buffer content is undefined (NaN in interpret mode) and
    # 0 * NaN = NaN would poison every output row of the chunk. (The
    # update kernels don't contract over tile rows, so undefined tail
    # rows stay confined there and are masked on write-back.)
    base = t * tile
    r_iota = lax.broadcasted_iota(jnp.int32, (tile, 1), 0)
    valid_row = (base + r_iota) < vocab
    tbl = jnp.where(valid_row, table_ref[:].astype(jnp.float32), 0.0)
    oh = _onehot(ids_ref[0, :], base, tile)              # [tile, chunk]
    if weighted:
        oh = oh * w_ref[0, :][None, :]
    part = lax.dot_general(oh, tbl,
                           (((0,), (0,)), ((), ())),     # sum over tile rows
                           precision=lax.Precision.HIGHEST,
                           preferred_element_type=jnp.float32)

    @pl.when(first)
    def _():
        out_ref[:] = part

    @pl.when(jnp.logical_not(first))
    def _():
        out_ref[:] = out_ref[:] + part


def _gather_call(table, sid, w_sorted, chunk: int, tile: int, interpret):
    """Shared pallas_call builder for the chunk-major gather walk; with
    `w_sorted` the weight stream rides a second chunk-indexed operand
    into the weighted kernel variant."""
    vocab, width = table.shape
    n = sid.shape[0]
    chunk, tile = _shrink(vocab, n, chunk, tile)
    kids2d, pad_rows, c_first, c_last, n_chunks = _chunk_layout(
        sid, vocab, chunk, tile)
    n_tiles = -(-vocab // tile)
    tof, cof = _chunk_major_pairs(c_first, c_last, n_tiles, n_chunks)
    g_count = n_chunks + n_tiles
    chunk_spec = pl.BlockSpec((1, chunk), lambda g, tof, cof: (cof[g], 0),
                              memory_space=pltpu.VMEM)
    operands = [kids2d]
    in_specs = [chunk_spec]
    if w_sorted is not None:
        operands.append(jnp.concatenate(
            [w_sorted.astype(jnp.float32),
             jnp.zeros((pad_rows - n,), jnp.float32)]).reshape(
                 n_chunks + 1, chunk))
        in_specs.append(chunk_spec)
    operands.append(table)
    in_specs.append(pl.BlockSpec((tile, width),
                                 lambda g, tof, cof: (tof[g], 0),
                                 memory_space=pltpu.VMEM))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(g_count,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((chunk, width),
                               lambda g, tof, cof: (cof[g], 0),
                               memory_space=pltpu.VMEM),
        scratch_shapes=[],
    )
    out = pl.pallas_call(
        functools.partial(_gather_kernel, tile=tile, g_count=g_count,
                          vocab=vocab, weighted=w_sorted is not None),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(
            ((n_chunks + 1) * chunk, width), jnp.float32),
        interpret=_interpret_default(interpret),
    )(tof, cof, *operands)
    return out[:n]


def tiled_gather_sorted(table: jax.Array, sid: jax.Array,
                        chunk: int = _CHUNK, tile: int = _TILE,
                        interpret: Optional[bool] = None) -> jax.Array:
    """rows[k] = table[sid[k]] for ASCENDING-sorted sid (as produced by
    `_sort_ids`); invalid ids (neg / >= V) yield zero rows (callers mask or
    ignore them — note this differs from XLA's clamp-gather). Output dtype
    f32. The block walk reads each table tile once per spanning chunk
    (sequential HBM), replacing the ~22 ns/row descriptor-bound XLA gather
    for large sorted batches."""
    if sid.shape[0] == 0:
        return jnp.zeros((0, table.shape[1]), jnp.float32)
    return _gather_call(table, sid, None, chunk, tile, interpret)


def _sort_with_inv(flat_ids, vocab: int, presorted):
    """(sid, perm, inv) of a flat id stream under the canonical key: the
    caller-provided triple verbatim, or one fresh sort plus the
    scatter-free second-sort inversion — the ONE derivation the tiled
    and fused lookups (forward and custom-vjp fwd) all share."""
    if presorted is not None:
        return presorted
    sid, _, perm = _sort_ids(flat_ids, None, vocab)
    iota = lax.iota(jnp.int32, perm.shape[0])
    return sid, perm, lax.sort_key_val(perm, iota)[1]


def tiled_gather(table: jax.Array, ids: jax.Array,
                 chunk: int = _CHUNK, tile: int = _TILE,
                 interpret: Optional[bool] = None,
                 presorted=None) -> jax.Array:
    """rows[k] = table[ids[k]] for arbitrary-order ids (invalid ids yield
    zero rows): sort + tiled sorted gather + inverse permute. `presorted`
    reuses a prior (sid, perm) of this id stream."""
    if ids.shape[0] == 0:
        return jnp.zeros((0, table.shape[1]), jnp.float32)
    if presorted is not None and len(presorted) == 2:
        # a 2-tuple carries no inverse: derive it scatter-free (an
        # .at[perm].set would reintroduce the ~100 ns/row scatter
        # lowering this whole path exists to avoid — round-3 prims)
        sid, perm = presorted
        iota = lax.iota(jnp.int32, perm.shape[0])
        inv = lax.sort_key_val(perm, iota)[1]
    else:
        sid, perm, inv = _sort_with_inv(ids, table.shape[0], presorted)
    rows = tiled_gather_sorted(table, sid, chunk, tile, interpret)
    return jnp.take(rows, inv, axis=0)


# --------------------------------------------------------------------------
# forward lookup-combine on the tiled gather (drop-in for the XLA
# gather+reduce in DistributedEmbedding._group_lookup)
# --------------------------------------------------------------------------
def _combine_prologue(params, ids, weights, combiner, presorted):
    """Shared lookup-wrapper prologue (tiled + fused): validate the
    combiner, default/normalize weights (mean pre-divides), clamp ids to
    XLA gather semantics, and clamp a caller presorted triple's keys the
    same way (positive OOB ids keep their clamp; NEGATIVE ids — already
    unspecified in the fused-bucket forward — read row V-1 on these
    paths instead of row 0)."""
    if combiner not in ("sum", "mean"):
        raise ValueError(f"Unsupported combiner {combiner}")
    if weights is None:
        weights = jnp.ones(ids.shape, jnp.float32)
    if combiner == "mean":
        denom = jnp.maximum(jnp.sum(weights, axis=1, keepdims=True), 1.0)
        weights = weights / denom
    ids = jnp.clip(ids, 0, params.shape[0] - 1)
    if presorted is not None:
        sid, perm, inv = presorted
        presorted = (jnp.minimum(sid, params.shape[0] - 1), perm, inv)
    return ids, weights, presorted


def _tiled_lookup_impl(params, ids, weights, interpret, presorted=None):
    b, k = ids.shape
    rows = tiled_gather(params, ids.reshape(-1), interpret=interpret,
                        presorted=presorted).reshape(b, k, -1)
    return jnp.einsum("bk,bkw->bw", weights.astype(jnp.float32), rows)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _tiled_lookup(params, ids, weights, presorted, interpret):
    return _tiled_lookup_impl(params, ids, weights, interpret,
                              presorted=presorted)


def _tiled_lookup_fwd(params, ids, weights, presorted, interpret):
    # sort once: the backward reuses (sid, perm, inv) for BOTH its
    # aggregation and its dweights gather (the id stream is identical, and
    # XLA CSE does not merge fwd/bwd sorts — measured round 5). A caller-
    # provided `presorted` (the tapped path's TapResiduals artifact) folds
    # even the forward's own sort away.
    sid, perm, inv = _sort_with_inv(ids.reshape(-1), params.shape[0],
                                    presorted)
    return (_tiled_lookup_impl(params, ids, weights, interpret,
                               presorted=(sid, perm, inv)),
            (params, ids, weights, sid, perm, inv))


def _tiled_lookup_bwd(interpret, res, g):
    # Dense-table cotangent WITHOUT a scatter (ADVICE r4: the previous
    # zeros.at[ids].add here was the exact ~100 ns/row lowering this module
    # exists to avoid): aggregate duplicate rows on the MXU via the sgd
    # kernel at lr = -1 over a zero table, reusing the forward's sort.
    # Only the DENSE train path differentiates through the lookup; the
    # sparse tapped path extracts gradients at the taps and applies them
    # via the tiled update kernels directly.
    params, ids, weights, sid, perm, inv = res
    flat_ids = ids.reshape(-1)
    contrib = (weights[..., None].astype(jnp.float32)
               * g[:, None, :].astype(jnp.float32)).reshape(-1, g.shape[-1])
    dtable = tiled_sgd(jnp.zeros(params.shape, jnp.float32), flat_ids,
                       contrib, -1.0, interpret=interpret,
                       presorted=(sid, perm)).astype(params.dtype)
    rows = tiled_gather(params, flat_ids, interpret=interpret,
                        presorted=(sid, perm, inv)).reshape(
        ids.shape[0], ids.shape[1], -1).astype(g.dtype)
    dweights = jnp.einsum("bkw,bw->bk", rows, g).astype(weights.dtype)
    return dtable, None, dweights, None


_tiled_lookup.defvjp(_tiled_lookup_fwd, _tiled_lookup_bwd)


# --------------------------------------------------------------------------
# fused sparse path (ISSUE 12, DET_SCATTER_IMPL=pallas): deduped-row
# appliers + weighted gather->combine forward
#
# The tiled_* kernels above take the RAW contribution stream and
# aggregate duplicates inside the matmul — results match XLA to f32
# tolerance (aggregation order differs). The fused strategy instead
# consumes the EXACT `sparse_update.dedup_sum` aggregation (bit-for-bit
# the XLA sort path's (rep, sums): unique ascending row ids, per-row
# totals, OOB fillers >= sentinel) and applies the optimizer as ONE
# tile-walk RMW stream per bucket. With a unique id stream the one-hot
# matmul is an exact PLACEMENT — each tile row receives its single total
# plus exact zeros — and the in-tile optimizer arithmetic mirrors the
# XLA sort path expression for expression, so the fused update is
# BIT-exact against it (asserted in tests/test_pallas_fused.py). The
# rep stream is canonical-sorted by dedup_sum's contract, so no sort
# happens here: the forward's folded GroupSort is the only sort in the
# step. Dispatch + gates live in sparse_update behind
# DET_SCATTER_IMPL=pallas.
# --------------------------------------------------------------------------
def _rows_prep(table, rep, sums, chunk: int, tile: int):
    chunk, tile = _shrink(table.shape[0], rep.shape[0], chunk, tile)
    return rep.astype(jnp.int32), sums, chunk, tile


def tiled_sgd_rows(table: jax.Array, rep: jax.Array, sums: jax.Array, lr,
                   chunk: int = _CHUNK, tile: int = _TILE,
                   interpret: Optional[bool] = None) -> jax.Array:
    """table[rep] -= lr * sums for a canonical-sorted UNIQUE `rep` stream
    (dedup_sum 'sort' output; fillers >= table rows are dropped).
    Bit-identical to ``table.at[rep].add(-lr * sums, mode="drop")`` —
    exact one-hot placement, one table-tile RMW stream. lr may be traced
    (SMEM scalar)."""
    if rep.shape[0] == 0:
        return table
    rep, sums, chunk, tile = _rows_prep(table, rep, sums, chunk, tile)
    hp = _hp_with_pin(rep, lr)
    return _update_call(_sgd_kernel, 1, table, [], rep, sums, hp,
                        chunk, tile, interpret)


def tiled_adagrad_rows(table: jax.Array, accum: jax.Array, rep: jax.Array,
                       sums: jax.Array, lr, eps: float = 1e-10,
                       chunk: int = _CHUNK, tile: int = _TILE,
                       interpret: Optional[bool] = None):
    """Fused adagrad over deduped rows — one RMW stream reads and writes
    each touched table+accumulator tile once:
        acc[r]   += sums[s]^2
        table[r] -= lr * sums[s] * rsqrt(acc[r] + eps)
    Bit-identical to sparse_update.sparse_adagrad's 'sort' path (same
    placement, same expression grouping). Returns (table', accum')."""
    if rep.shape[0] == 0:
        return table, accum
    rep, sums, chunk, tile = _rows_prep(table, rep, sums, chunk, tile)
    hp = _hp_with_pin(rep, lr)
    out = _update_call(functools.partial(_adagrad_kernel, eps=eps), 2,
                       table, [accum], rep, sums, hp, chunk, tile,
                       interpret)
    return out[0], out[1]


def tiled_adam_rows(table: jax.Array, mu: jax.Array, nu: jax.Array, count,
                    rep: jax.Array, sums: jax.Array, lr, b1: float = 0.9,
                    b2: float = 0.999, eps: float = 1e-8,
                    chunk: int = _CHUNK, tile: int = _TILE,
                    interpret: Optional[bool] = None):
    """Fused lazy adam over deduped rows (sparse_update.sparse_adam's
    touched-row semantics, bit-identical to its 'sort' path): the
    one-hot count column marks touched rows — a zero TOTAL on a touched
    row still decays its moments. Returns (table, mu, nu, count)."""
    count = count + 1
    if rep.shape[0] == 0:
        return table, mu, nu, count
    cf = count.astype(jnp.float32)
    # exact expression twin of sparse_adam's bias correction
    c1 = 1.0 - b1 ** cf
    c2 = 1.0 - b2 ** cf
    rep, sums, chunk, tile = _rows_prep(table, rep, sums, chunk, tile)
    hp = _hp_with_pin(rep, lr, c1, c2)
    out = _update_call(
        functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps), 3,
        table, [mu, nu], rep, sums, hp, chunk, tile, interpret,
        extra_scratch=[pltpu.VMEM((tile, 1), jnp.float32)])
    return out[0], out[1], out[2], count


# --------------------------------------------------------------------------
# fused forward: weighted gather (chunk-major walk, weights folded into
# the one-hot so one MXU contraction yields COMBINE-ready rows)
# --------------------------------------------------------------------------
def tiled_gather_sorted_weighted(table: jax.Array, sid: jax.Array,
                                 w_sorted: jax.Array,
                                 chunk: int = _CHUNK, tile: int = _TILE,
                                 interpret: Optional[bool] = None
                                 ) -> jax.Array:
    """rows[k] = w_sorted[k] * table[sid[k]] for ASCENDING-sorted sid;
    invalid ids (>= V keys) yield zero rows regardless of weight. Same
    block walk as `tiled_gather_sorted` (one shared builder); the weight
    multiply rides the one-hot, not a second pass over [N, w]."""
    if sid.shape[0] == 0:
        return jnp.zeros((0, table.shape[1]), jnp.float32)
    return _gather_call(table, sid, w_sorted, chunk, tile, interpret)


def _fused_lookup_impl(params, ids, weights, interpret, presorted=None):
    b, k = ids.shape
    sid, perm, inv = _sort_with_inv(ids.reshape(-1), params.shape[0],
                                    presorted)
    w_sorted = jnp.take(weights.reshape(-1).astype(jnp.float32), perm,
                        axis=0)
    rows = tiled_gather_sorted_weighted(params, sid, w_sorted,
                                        interpret=interpret)
    # scatter-free unpermute (second-sort take, see tiled_gather), then
    # the combine degenerates to a plain hotness-axis sum — the weights
    # already rode the gather
    rows = jnp.take(rows, inv, axis=0).reshape(b, k, -1)
    return jnp.sum(rows, axis=1)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4,))
def _fused_lookup(params, ids, weights, presorted, interpret):
    return _fused_lookup_impl(params, ids, weights, interpret,
                              presorted=presorted)


def _fused_lookup_fwd(params, ids, weights, presorted, interpret):
    # one sort serves forward gather, backward aggregation and the
    # dweights gather — identical structure to _tiled_lookup_fwd
    sid, perm, inv = _sort_with_inv(ids.reshape(-1), params.shape[0],
                                    presorted)
    return (_fused_lookup_impl(params, ids, weights, interpret,
                               presorted=(sid, perm, inv)),
            (params, ids, weights, sid, perm, inv))


# the backward is IDENTICAL to the tiled lookup's (same residual tuple):
# dense-table cotangent via the sgd kernel at lr = -1, scatter-free
_fused_lookup.defvjp(_fused_lookup_fwd, _tiled_lookup_bwd)


def fused_lookup_combine(params: jax.Array, ids: jax.Array,
                         weights: Optional[jax.Array] = None,
                         combiner: str = "sum",
                         interpret: Optional[bool] = None,
                         presorted=None) -> jax.Array:
    """Fused gather->combine forward (ISSUE 12): [V,W] table, [B,K] ids
    -> [B,W] in ONE weighted-gather kernel pass + a scatter-free
    unpermute + a plain hotness sum. Same contract as
    `tiled_embedding_lookup` (weights carry 0.0 in padded slots; mean
    pre-normalizes; positive OOB ids clamp like the XLA gather;
    differentiable in params and weights, scatter-free on the dense
    path). `presorted`: the canonical (sid, perm, inv) of the flattened
    id stream — the tapped forward's residual sort folds the fused
    forward's own sort away. Dispatch: DET_LOOKUP_PATH=fused in
    `dist_model_parallel._group_lookup`."""
    ids, weights, presorted = _combine_prologue(params, ids, weights,
                                                combiner, presorted)
    return _fused_lookup(params, ids, weights, presorted,
                         interpret).astype(params.dtype)


def tiled_embedding_lookup(params: jax.Array, ids: jax.Array,
                           weights: Optional[jax.Array] = None,
                           combiner: str = "sum",
                           interpret: Optional[bool] = None,
                           presorted=None) -> jax.Array:
    """Padded multi-hot lookup over the tiled gather: [V,W] table, [B,K]
    ids -> [B,W]. Same contract as pallas_lookup.fused_embedding_lookup
    (weights carry 0.0 in padded slots; mean pre-normalizes; OOB ids
    clamped to match XLA gather semantics). Differentiable in params and
    weights.

    `presorted`: optional (sid, perm, inv) of the FLATTENED id stream under
    the canonical key (embedding_ops.canonical_id_sort) — typically the
    tapped forward's residual sort. sid is clamped to V-1 here, so positive
    OOB ids keep their XLA clamp semantics; NEGATIVE ids (already
    unspecified in the fused-bucket forward) read row V-1 instead of row 0
    on this path."""
    ids, weights, presorted = _combine_prologue(params, ids, weights,
                                                combiner, presorted)
    return _tiled_lookup(params, ids, weights, presorted,
                         interpret).astype(params.dtype)
