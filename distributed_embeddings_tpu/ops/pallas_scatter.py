"""Pallas TPU kernel: row scatter-add with sorted-unique ids (RMW stream).

THE bottleneck of embedding training on this hardware is XLA:TPU's scatter
lowering: round-3 prims measured ~100-280 ns per scattered row against a
~0.1 ns/row bandwidth bound (docs/round3_notes.md), and every backward +
row-wise optimizer update funnels through it. The reference hits the same
op class with cub sort + a segment-reduce reusing its forward kernel
(reference: cc/kernels/embedding_lookup_kernels.cu:603-775); the TPU answer
is explicit DMA: after `dedup_sum` the update rows are UNIQUE, so a kernel
can stream read-modify-write row DMAs with no conflict hazard and no
atomics. Per grid step (one id tile, scalar-prefetched into SMEM):

    start + wait row reads of the tile        (tile_b copies in flight)
    add the delta block                       (VPU)
    start + wait row writes of the tile

Tiles themselves overlap through the grid pipeline (the delta blocks of
step i+1 stream in while step i runs); read/write overlap WITHIN a tile is
deliberately not attempted until the compiled path exists on hardware —
the r03 tunnel toolchain rejects every DMA kernel, so this kernel's first
job is to be the minimal correct RMW stream for the mosaic probe to gate.

OOB ids (the dedup filler tail, id >= V) issue no DMA at all — reads and
writes are predicated per row, so no dump row, no table copy, and the
table rides input_output_aliasing untouched except for the rows actually
updated.

Status: interpret-mode correct (tests/test_pallas_scatter.py); compiled
use is gated on `sparse_update.prevalidate_pallas_scatter()`. Dispatch
lives in sparse_update._row_scatter_add behind DET_SCATTER_IMPL=pallas-dma
(the 'pallas' value now names the fused deduped-row tile-walk strategy,
ISSUE 12 — this DMA family keeps its own gate for a future toolchain).
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default(interpret: Optional[bool]) -> bool:
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


# rows per tile; bounds VMEM (tile * width * 4B for the row buffer) and the
# number of concurrent row DMAs
_TILE = 256


def _scatter_kernel(ids_ref, delta_ref, table_ref, out_ref, rows_ref, rsem,
                    wsem, *, tile: int, vocab: int):
    """Grid step i processes ids[i*tile : (i+1)*tile]. table_ref/out_ref are
    the SAME HBM buffer (input_output_aliasing), so reads see prior tiles'
    writes only across grid steps — safe because ids are globally unique."""
    i = pl.program_id(0)
    base = i * tile

    def rd(j):
        row = ids_ref[base + j]
        return pltpu.make_async_copy(
            table_ref.at[row], rows_ref.at[j], rsem.at[j])

    def wr(j):
        row = ids_ref[base + j]
        return pltpu.make_async_copy(
            rows_ref.at[j], out_ref.at[row], wsem.at[j])

    def issue(j, fn):
        # fillers (id >= vocab) and negative ids issue no DMA: the XLA path
        # this replaces drops both via mode="drop" (ADVICE r3: a negative id
        # must not reach table_ref.at[row])
        row = ids_ref[base + j]
        @pl.when((row >= 0) & (row < vocab))
        def _():
            fn(j)

    jax.lax.fori_loop(0, tile,
                      lambda j, _: (issue(j, lambda k: rd(k).start()), 0)[1],
                      0)
    jax.lax.fori_loop(0, tile,
                      lambda j, _: (issue(j, lambda k: rd(k).wait()), 0)[1],
                      0)
    rows_ref[:] = rows_ref[:] + delta_ref[:].astype(rows_ref.dtype)
    jax.lax.fori_loop(0, tile,
                      lambda j, _: (issue(j, lambda k: wr(k).start()), 0)[1],
                      0)
    jax.lax.fori_loop(0, tile,
                      lambda j, _: (issue(j, lambda k: wr(k).wait()), 0)[1],
                      0)


def scatter_add_sorted_unique(table: jax.Array, ids: jax.Array,
                              delta: jax.Array,
                              interpret: Optional[bool] = None) -> jax.Array:
    """table[ids[k]] += delta[k] for UNIQUE ids (sorted preferred for HBM
    locality); ids >= V are dropped (dedup filler contract). Returns the
    updated table; donate `table` for a true in-place update — the table
    travels through input_output_aliasing, so HBM traffic is the touched
    rows only (read + write), not a table copy.
    """
    vocab, width = table.shape
    n = ids.shape[0]
    if n == 0:        # empty grad shard: XLA scatter handles this; match it
        return table
    tile = min(_TILE, n)
    pad = -n % tile
    if pad:
        # filler ids (>= vocab) — predicated out inside the kernel
        ids = jnp.concatenate(
            [ids, jnp.full((pad,), vocab, ids.dtype)])
        delta = jnp.concatenate(
            [delta, jnp.zeros((pad, width), delta.dtype)], axis=0)
        n += pad

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // tile,),
        in_specs=[
            pl.BlockSpec((tile, width), lambda i, ids_ref: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),      # table in HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((tile, width), table.dtype),
            pltpu.SemaphoreType.DMA((tile,)),
            pltpu.SemaphoreType.DMA((tile,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_scatter_kernel, tile=tile, vocab=vocab),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table.shape, table.dtype),
        input_output_aliases={2: 0},   # table (input 2 incl. prefetch) -> out
        interpret=_interpret_default(interpret),
    )(ids.astype(jnp.int32), delta, table)


# ---------------------------------------------------------------------------
# fused row-wise adagrad: one RMW stream updates table AND accumulator
# ---------------------------------------------------------------------------
def _adagrad_kernel(ids_ref, sums_ref, table_ref, acc_ref, out_t, out_a,
                    trows, arows, tr_sem, ar_sem, tw_sem, aw_sem,
                    *, tile: int, vocab: int, lr: float, eps: float):
    i = pl.program_id(0)
    base = i * tile

    def rd_t(j):
        return pltpu.make_async_copy(table_ref.at[ids_ref[base + j]],
                                     trows.at[j], tr_sem.at[j])

    def rd_a(j):
        return pltpu.make_async_copy(acc_ref.at[ids_ref[base + j]],
                                     arows.at[j], ar_sem.at[j])

    def wr_t(j):
        return pltpu.make_async_copy(trows.at[j],
                                     out_t.at[ids_ref[base + j]],
                                     tw_sem.at[j])

    def wr_a(j):
        return pltpu.make_async_copy(arows.at[j],
                                     out_a.at[ids_ref[base + j]],
                                     aw_sem.at[j])

    def guarded(j, fn):
        row = ids_ref[base + j]
        @pl.when((row >= 0) & (row < vocab))   # drop fillers AND negatives
        def _():
            fn(j)

    def loop(fn):
        jax.lax.fori_loop(0, tile,
                          lambda j, _: (guarded(j, fn), 0)[1], 0)

    loop(lambda j: rd_t(j).start())
    loop(lambda j: rd_a(j).start())
    loop(lambda j: rd_t(j).wait())
    loop(lambda j: rd_a(j).wait())

    s = sums_ref[:].astype(jnp.float32)
    acc_new = arows[:].astype(jnp.float32) + s * s
    delta = (-lr) * s * jax.lax.rsqrt(acc_new + eps)
    arows[:] = acc_new.astype(arows.dtype)
    trows[:] = (trows[:].astype(jnp.float32) + delta).astype(trows.dtype)

    loop(lambda j: wr_t(j).start())
    loop(lambda j: wr_a(j).start())
    loop(lambda j: wr_t(j).wait())
    loop(lambda j: wr_a(j).wait())


def adagrad_rows_sorted_unique(table: jax.Array, accum: jax.Array,
                               ids: jax.Array, sums: jax.Array, lr: float,
                               eps: float = 1e-10,
                               interpret: Optional[bool] = None):
    """Fused sparse adagrad on UNIQUE rows (dedup_sum output):

        acc[r]   += sums_r^2
        table[r] -= lr * sums_r * rsqrt(acc[r] + eps)

    in ONE read-modify-write stream per row pair — the XLA formulation
    costs two scatters plus a gather of the same rows (the dominant cost
    at 100-280 ns/row, round-3 prims). ids >= V are skipped; their sums
    must be zero. Returns (table', accum'), both alias their inputs.
    """
    vocab, width = table.shape
    n = ids.shape[0]
    if n == 0:        # empty grad shard: nothing to update
        return table, accum
    tile = min(_TILE, n)
    pad = -n % tile
    if pad:
        ids = jnp.concatenate([ids, jnp.full((pad,), vocab, ids.dtype)])
        sums = jnp.concatenate(
            [sums, jnp.zeros((pad, width), sums.dtype)], axis=0)
        n += pad

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // tile,),
        in_specs=[
            pl.BlockSpec((tile, width), lambda i, ids_ref: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),      # table
            pl.BlockSpec(memory_space=pltpu.ANY),      # accumulator
        ],
        out_specs=[pl.BlockSpec(memory_space=pltpu.ANY),
                   pl.BlockSpec(memory_space=pltpu.ANY)],
        scratch_shapes=[
            pltpu.VMEM((tile, width), table.dtype),
            pltpu.VMEM((tile, width), accum.dtype),
            pltpu.SemaphoreType.DMA((tile,)),
            pltpu.SemaphoreType.DMA((tile,)),
            pltpu.SemaphoreType.DMA((tile,)),
            pltpu.SemaphoreType.DMA((tile,)),
        ],
    )
    return pl.pallas_call(
        functools.partial(_adagrad_kernel, tile=tile, vocab=vocab,
                          lr=float(lr), eps=float(eps)),
        grid_spec=grid_spec,
        out_shape=[jax.ShapeDtypeStruct(table.shape, table.dtype),
                   jax.ShapeDtypeStruct(accum.shape, accum.dtype)],
        input_output_aliases={2: 0, 3: 1},   # table->out_t, acc->out_a
        interpret=_interpret_default(interpret),
    )(ids.astype(jnp.int32), sums, table, accum)
