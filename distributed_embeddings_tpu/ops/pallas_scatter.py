"""Pallas TPU kernel: row scatter-add with sorted-unique ids (RMW stream).

THE bottleneck of embedding training on this hardware is XLA:TPU's scatter
lowering: round-3 prims measured ~100-280 ns per scattered row against a
~0.1 ns/row bandwidth bound (docs/round3_notes.md), and every backward +
row-wise optimizer update funnels through it. The reference hits the same
op class with cub sort + a segment-reduce reusing its forward kernel
(reference: cc/kernels/embedding_lookup_kernels.cu:603-775); the TPU answer
is explicit DMA: after `dedup_sum` the update rows are UNIQUE AND SORTED,
so a kernel can stream read-modify-write row DMAs with no conflict hazard
and no atomics:

    for each id tile (scalar-prefetched into SMEM):
        start row reads for tile t+1           (double-buffered)
        wait reads of tile t, add delta rows   (VPU)
        start row writes of tile t             (fire-and-forget until drain)

OOB ids (the dedup filler tail, id >= V) are redirected to a scratch dump
row so the kernel stays branch-free; their deltas are zero by the dedup
contract, and the dump row is scratch — nothing real is harmed.

Status: interpret-mode correct (tests/test_pallas_scatter.py); compiled
use is gated on `tools/tpu_mosaic_probe.py` because the current tunnel
toolchain crashes on every DMA-kernel compile (round3_notes). Wire-up into
sparse_update is deliberately deferred until a hardware A/B exists.
"""

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _interpret_default(interpret: Optional[bool]) -> bool:
    if interpret is None:
        return jax.default_backend() != "tpu"
    return interpret


# rows in flight per buffer; bounds VMEM (2 slots * 2 buffers * TILE * w * 4B)
_TILE = 256


def _scatter_kernel(ids_ref, delta_ref, table_ref, out_ref, rows_ref, sems,
                    wsem, *, tile: int, width: int, vocab: int):
    """Grid step i processes ids[i*tile : (i+1)*tile]. table_ref/out_ref are
    the SAME HBM buffer (input_output_aliasing), so reads see prior tiles'
    writes only across grid steps — safe because ids are globally unique."""
    i = pl.program_id(0)
    base = i * tile

    def rd(j, slot):
        row = ids_ref[base + j]
        safe = jnp.where(row < vocab, row, vocab)     # dump row for fillers
        return pltpu.make_async_copy(
            table_ref.at[safe], rows_ref.at[slot, j], sems.at[slot, j])

    def wr(j, slot):
        row = ids_ref[base + j]
        safe = jnp.where(row < vocab, row, vocab)
        return pltpu.make_async_copy(
            rows_ref.at[slot, j], out_ref.at[safe], wsem.at[slot, j])

    def start_reads(slot):
        jax.lax.fori_loop(0, tile, lambda j, _: (rd(j, slot).start(), 0)[1],
                          0)

    def wait_reads(slot):
        jax.lax.fori_loop(0, tile, lambda j, _: (rd(j, slot).wait(), 0)[1],
                          0)

    # one grid step = one tile; the pipeline across tiles is the grid itself
    start_reads(0)
    wait_reads(0)
    rows_ref[0] = rows_ref[0] + delta_ref[:].astype(rows_ref.dtype)
    jax.lax.fori_loop(0, tile, lambda j, _: (wr(j, 0).start(), 0)[1], 0)
    jax.lax.fori_loop(0, tile, lambda j, _: (wr(j, 0).wait(), 0)[1], 0)


def scatter_add_sorted_unique(table: jax.Array, ids: jax.Array,
                              delta: jax.Array,
                              interpret: Optional[bool] = None) -> jax.Array:
    """table[ids[k]] += delta[k] for SORTED UNIQUE ids; ids >= V are dropped
    (dedup filler contract — their deltas must be zero). Returns the updated
    table; donate `table` for a true in-place update.

    The table travels through input_output_aliasing, so HBM traffic is the
    touched rows only (read + write), not a table copy.
    """
    vocab, width = table.shape
    n = ids.shape[0]
    tile = min(_TILE, n)
    pad = -n % tile
    if pad:
        # filler ids (>= vocab) with zero deltas — dropped by the dump row
        ids = jnp.concatenate(
            [ids, jnp.full((pad,), vocab, ids.dtype)])
        delta = jnp.concatenate(
            [delta, jnp.zeros((pad, width), delta.dtype)], axis=0)
        n += pad
    # +1 dump row absorbs filler reads/writes harmlessly
    table_x = jnp.concatenate(
        [table, jnp.zeros((1, width), table.dtype)], axis=0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // tile,),
        in_specs=[
            pl.BlockSpec((tile, width), lambda i, ids_ref: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.ANY),      # table in HBM
        ],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        scratch_shapes=[
            pltpu.VMEM((1, tile, width), table.dtype),
            pltpu.SemaphoreType.DMA((1, tile)),
            pltpu.SemaphoreType.DMA((1, tile)),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_scatter_kernel, tile=tile, width=width,
                          vocab=vocab),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct(table_x.shape, table.dtype),
        input_output_aliases={2: 0},   # table (input 2 incl. prefetch) -> out
        interpret=_interpret_default(interpret),
    )(ids.astype(jnp.int32), delta, table_x)
    return out[:vocab]
