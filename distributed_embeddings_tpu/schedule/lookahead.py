"""Lookahead execution engine: overlap batch N+1's embedding exchanges
with batch N's dense compute (ISSUE 9, ROADMAP item 1).

The production sparse step is strictly sequential on device:

    id exchange -> gather -> activation all_to_all -> dense fwd/bwd
                -> gradient transpose -> sparse update

The reference hides the exchange behind Horovod's NCCL streams; under
SPMD the same latency win needs the step itself restructured. This
engine splits it into three stages with a TWO-BATCH carry:

  prefetch  batch N+1's id exchange, table gather and activation
            all_to_all/psum_scatter run as a detached subgraph
            (`DistributedEmbedding.apply(_want_exchange=True)`) whose
            ops have NO data dependency on the dense stage — inside the
            one fused jitted step, XLA's latency-hiding scheduler is
            free to run these collectives under the dense compute
            (auditable: tools/hlo_audit.py's overlap arm proves the
            independence on the lowered HLO).
  dense     batch N's forward/backward over the CARRIED activations
            (`staged_exchange_scope`) — dp tables and the MLPs see
            current params; grads w.r.t. the carried activation blocks
            fall out of autodiff.
  drain     the dp->mp gradient transpose (`exchange_transpose`, the
            exact bwd collectives the monolithic step's autodiff runs)
            + the row-sparse table update (`ops.sparse_update.
            drain_sparse_apply` — the tail shared with
            `make_sparse_train_step`).

Correctness seam — the one real coupling between stages: batch N's
sparse update rewrites rows batch N+1's prefetch may have already
gathered. Both sides of that intersection are knowable HOST-side from
ids alone (`touched_row_keys` of N x the prefetched ids of N+1, per
sample via `prefetch_stale_mask`), so the engine re-exchanges exactly
the affected SAMPLES against the post-update tables at the start of the
next fused step (`patch_staged_carry`) — a fixed-capacity sub-batch, so
the compiled step never re-specializes. Untouched rows are unchanged by
a row-sparse update (sgd/adagrad write only touched rows; adam is lazy
per-touched-row by construction — the load-bearing property PR 4
documented), so patched == sequential BIT-exactly, by induction over
steps. A stale set larger than the patch capacity falls back to
re-running the already-compiled prefetch executable on the current
tables (bit-exact recompute, zero extra compiles). ``stale_ok=True``
skips the patch entirely: documented one-step-stale semantics (the
async-embedding trade common to prefetching parameter servers) for the
throughput ceiling.

Refused compositions (loud, at construction / fit time): hot-row
replication (the replicated hot shard moves DENSELY every step — under
adam even rows absent from the batch, so the touched-row patch cannot
cover it), host-offloaded buckets (their lookup runs outside the jitted
stage), multi-process runs (per-process patch bookkeeping under SPMD
lockstep), ragged/sparse input forms (per-sample patch selection would
be shape-dynamic), custom dp layer classes, and VocabManager rebind
cycles mid-window (fit refuses `vocab_every != 0`).

``lookahead=0`` delegates wholesale to `make_sparse_train_step` — the
bit-identical pre-pipeline step.
"""

import os
from typing import Optional

import numpy as np
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.ops.embedding_ops import RaggedIds, SparseIds
from distributed_embeddings_tpu.ops.sparse_update import drain_sparse_apply
from distributed_embeddings_tpu.parallel.staging import DoubleBufferSlots
from distributed_embeddings_tpu.training import (
    _dense_part, _merge_dense, _sparse_optimizer_setup, apply_updates,
    default_donate, make_sparse_train_step)

__all__ = ["LookaheadEngine", "default_lookahead"]


def default_lookahead() -> int:
    """``DET_LOOKAHEAD`` environment default for `training.fit`'s
    ``lookahead`` argument (0 = the sequential step; an explicit
    argument always wins). Resolves through the tune seam, so a
    tuned config-of-record can set it when no env override is
    present."""
    from distributed_embeddings_tpu.tune import resolve as _tune_resolve
    v = _tune_resolve.knob_value("DET_LOOKAHEAD", "0")
    try:
        n = int(v)
    except ValueError:
        raise ValueError(f"DET_LOOKAHEAD={v!r}: expected an integer")
    if n not in (0, 1):
        raise ValueError(
            f"DET_LOOKAHEAD={n}: only depths 0 (sequential) and 1 "
            "(one-batch prefetch) are supported")
    return n


class LookaheadEngine:
    """Staged-pipeline train step with a two-batch carry (module doc).

    Args:
      model: the `make_sparse_train_step` contract — exposes
        ``.embedding`` and ``loss_fn(params, numerical, cats, labels)``.
      optimizer / lr / dense_optimizer / strategy / fold_sort / donate:
        as `make_sparse_train_step` (the engine's lookahead=0 path IS
        that step; the fused step shares its optimizer construction).
      lookahead: 0 (sequential, bit-identical to the monolithic step) or
        1 (one-batch prefetch).
      stale_ok: skip the correctness patch — prefetched activations may
        be one sparse-update stale (bit-exactness forfeited, documented
        in docs/userguide.md).
      patch_capacity: max stale samples the fused step re-exchanges per
        step (default batch//8, rounded up to a multiple of the device
        count). Overflow falls back to a full prefetch recompute on the
        current tables — still bit-exact, no extra compile.

    Use:
      engine = LookaheadEngine(model, "adagrad", lr=0.05)
      opt_state = engine.init(params)
      for i in range(steps):
          params, opt_state, loss = engine.step(
              params, opt_state, batches[i],
              batches[i + 1] if i + 1 < steps else None)
    """

    def __init__(self, model, optimizer: str = "adagrad", lr=0.01,
                 dense_optimizer=None, strategy: str = "auto",
                 lookahead: int = 1, stale_ok: bool = False,
                 patch_capacity: Optional[int] = None,
                 donate: Optional[bool] = None, fold_sort: bool = True,
                 registry=None):
        if lookahead not in (0, 1):
            raise ValueError(
                f"lookahead={lookahead}: only depths 0 and 1 are "
                "supported (a deeper pipeline would need k-step patch "
                "composition)")
        self.model = model
        self.emb = model.embedding
        self.lookahead = int(lookahead)
        self.stale_ok = bool(stale_ok)
        self.patch_capacity = patch_capacity
        self.stats = {"steps": 0, "cold_fills": 0, "patch_overflows": 0,
                      "patched_steps": 0, "patched_samples": 0,
                      "patched_samples_max": 0}
        # registry mirror of self.stats (ISSUE 11): counters bumped from
        # THIS host-side driver body only — never inside a traced fn —
        # plus the per-stage compile-count gauges the "must stay 1" SLO
        # rule reads (tools/slo_tier1.json)
        from distributed_embeddings_tpu.obs.registry import MetricRegistry
        self._metrics = (registry if registry is not None
                         else MetricRegistry())
        emb = self.emb
        # ONE optimizer construction (training._sparse_optimizer_setup)
        # shared with the monolithic step — the bit-exactness contract
        # between the two step forms depends on it
        scheduled, sopt_for, dense_optimizer = _sparse_optimizer_setup(
            optimizer, lr, strategy, dense_optimizer,
            widths=emb.plan_widths())
        # lookahead=0 path AND the shared init_fn: the monolithic step
        # itself — delegation is what makes depth 0 bit-identical
        self._init_fn, self._base_step = make_sparse_train_step(
            model, optimizer, lr=lr, dense_optimizer=dense_optimizer,
            strategy=strategy, donate=donate, fold_sort=fold_sort)
        if self.lookahead == 0:
            self._prefetch = self._fused = None
            self._slots = None
            self._prev_touched = None
            return

        # ---- refusals: every composition the patch cannot cover -----
        if jax.process_count() > 1:
            raise NotImplementedError(
                "lookahead>0 is single-process only: per-process patch "
                "bookkeeping must stay in SPMD lockstep across hosts, "
                "which this engine does not coordinate yet")
        if emb._hot_buckets:
            raise NotImplementedError(
                "lookahead>0 does not support hot-row replicated buckets "
                "(the replicated hot shard updates densely every step — "
                "under adam even rows absent from the batch — so the "
                "touched-row patch cannot make prefetched activations "
                "exact)")
        if emb._offload_enabled:
            raise NotImplementedError(
                "lookahead>0 does not support host-offloaded buckets: "
                "their lookups run outside the jitted stage and cannot "
                "be carried or patched")
        if getattr(emb, "_dp_custom_layers", None):
            raise NotImplementedError(
                "lookahead>0 does not support custom embedding layer "
                "classes on dp tables (staged forwards run them outside "
                "shard_map)")
        if getattr(emb, "quantized_buckets", []):
            raise NotImplementedError(
                "lookahead>0 does not support quantized (int8/fp8) "
                "bucket storage: the drain applies f32 row rules and "
                "the touched-row patch carries f32 activations — "
                "neither decodes or re-encodes the per-row "
                "payload+scale leaves an HBM-resident quantized bucket "
                "trains through")
        if (not emb.strategy.input_groups[1]
                and not emb.strategy.input_groups[2]):
            raise ValueError(
                "lookahead>0 has nothing to prefetch: every table in "
                "this plan is data-parallel (no exchange collectives on "
                "the critical path — run with lookahead=0)")

        sort_spec = (optimizer, strategy) if fold_sort else None
        sort_arg = sort_spec if sort_spec is not None else False
        if donate is None:
            donate = default_donate()

        def constrain_carry(ex, row, res):
            """Pin the carry's shardings to the canonical layout (ex
            [world_src, B@axis, ...], everything else leading-axis
            sharded). Both carry producers — the warmup/fallback
            prefetch executable and the fused step — emit the same
            layout, so the fused step compiles ONCE per (plan,
            batch-shape) instead of re-specializing on whichever
            GSPMD-inferred output sharding fed it first."""
            if emb.mesh is None or emb.world_size == 1:
                return {"ex": ex, "row": row, "res": res}
            from jax.sharding import NamedSharding, PartitionSpec as P

            def con(tree, spec):
                sh = NamedSharding(emb.mesh, spec)
                return jax.tree.map(
                    lambda x: jax.lax.with_sharding_constraint(x, sh),
                    tree)

            res = type(res)(res.key, con(res.tp_ids, P(emb.axis)),
                            con(res.tp_w, P(emb.axis)),
                            con(res.row_ids, P(emb.axis)),
                            con(res.row_w, P(emb.axis)),
                            con(res.tp_sort, P(emb.axis)),
                            con(res.row_sort, P(emb.axis)),
                            res.hot_pos, res.hot_w)
            return {"ex": con(ex, P(None, emb.axis)),
                    "row": con(row, P(emb.axis)), "res": res}

        def prefetch_fn(emb_params, cats):
            ex, row, res = emb.apply(emb_params, list(cats),
                                     return_residuals=True,
                                     residual_sort=sort_arg,
                                     _want_exchange=True)
            return constrain_carry(ex, row, res)

        def run_stages(params, opt_state, ex, row, res, numerical, cats,
                       labels, next_cats):
            # ---- prefetch stage (batch N+1): traced FIRST and reading
            # only params + next_cats — no data dependency on the dense
            # stage below, which is the whole point (the overlap arm of
            # tools/hlo_audit.py asserts it on the lowered module)
            nex, nrow, nres = emb.apply(params["embedding"],
                                        list(next_cats),
                                        return_residuals=True,
                                        residual_sort=sort_arg,
                                        _want_exchange=True)

            # ---- dense stage (batch N) over the carried activations
            def loss_staged(dense0, ex_in, row_in):
                p = _merge_dense(dense0, params)
                with emb.staged_exchange_scope(ex_in, row_in):
                    return model.loss_fn(p, numerical, list(cats), labels)

            dense0 = _dense_part(params)
            loss, (g_dense, g_ex, g_row) = jax.value_and_grad(
                loss_staged, argnums=(0, 1, 2))(dense0, ex, row)

            # ---- drain stage: explicit dp->mp gradient transpose (the
            # monolithic step's bwd collectives) + row-sparse update
            g_taps = emb.exchange_transpose(g_ex, g_row, res.key)
            sopt_t = sopt_for(opt_state)
            new_emb, new_emb_state, _ = drain_sparse_apply(
                emb, params["embedding"], opt_state["emb"], g_taps, res,
                sopt_t)
            updates, new_dense_state = dense_optimizer.update(
                g_dense, opt_state["dense"], dense0)
            new_dense = apply_updates(dense0, updates)
            new_params = _merge_dense(
                new_dense, {**params, "embedding": new_emb})
            new_state = {"emb": new_emb_state, "dense": new_dense_state}
            if scheduled:
                new_state["count"] = opt_state["count"] + 1
            return (new_params, new_state, loss,
                    constrain_carry(nex, nrow, nres))

        if self.stale_ok:
            def fused_fn(params, opt_state, carry, numerical, cats,
                         labels, next_cats):
                return run_stages(params, opt_state, carry["ex"],
                                  carry["row"], carry["res"], numerical,
                                  cats, labels, next_cats)
        else:
            def fused_fn(params, opt_state, carry, patch_cats, patch_idx,
                         numerical, cats, labels, next_cats):
                # ---- patch stage: re-exchange the stale samples against
                # the CURRENT tables (they carry the previous batch's
                # update) and overwrite their carried activations — the
                # bit-exactness seam. residual_sort=False: the patch is a
                # plain activation recompute, zero extra sort ops.
                ex, row, res = carry["ex"], carry["row"], carry["res"]
                pex, prow, _ = emb.apply(params["embedding"],
                                         list(patch_cats),
                                         return_residuals=True,
                                         residual_sort=False,
                                         _want_exchange=True)
                batch = (ex[0].shape[1] if ex else row[0].shape[0])
                ex, row = emb.patch_staged_carry(ex, row, pex, prow,
                                                 patch_idx, batch)
                return run_stages(params, opt_state, ex, row, res,
                                  numerical, cats, labels, next_cats)

        self._prefetch = jax.jit(prefetch_fn)
        self._fused = jax.jit(fused_fn,
                              donate_argnums=(0, 1, 2) if donate else ())
        self._slots = DoubleBufferSlots()
        self._prev_touched = None

    # ------------------------------------------------------------ state
    def init(self, params):
        """Sparse+dense optimizer state (same pytree as
        `make_sparse_train_step`'s init_fn — states are interchangeable
        between lookahead depths)."""
        return self._init_fn(params)

    def reset(self):
        """Flush the pipeline: drop the carried prefetch and touched-row
        memory. Call after mutating params/tables OUTSIDE the engine
        (checkpoint restore, store.apply_published, manual edits) — the
        next step re-fills the carry from the new tables."""
        if self._slots is not None:
            self._slots.clear()
        self._prev_touched = None

    def compile_counts(self) -> dict:
        """Executable-cache sizes per stage — the compile-count
        stability gate reads these (one entry per (plan, batch-shape),
        regardless of how many steps ran)."""
        if self.lookahead == 0:
            return {}
        return {"prefetch": self._prefetch._cache_size(),
                "fused": self._fused._cache_size()}

    # ------------------------------------------------------------ step
    @staticmethod
    def _canon(c):
        if isinstance(c, (RaggedIds, SparseIds)):
            raise NotImplementedError(
                "lookahead>0 supports dense id inputs (and (ids, "
                "weights) tuples) only: ragged/sparse per-sample patch "
                "selection would be shape-dynamic and recompile the "
                "fused step every batch")
        if isinstance(c, tuple):
            return tuple(jnp.asarray(e) for e in c)
        return jnp.asarray(c)

    def _capacity(self, batch: int) -> int:
        cap = (self.patch_capacity if self.patch_capacity is not None
               else max(1, batch // 8))
        world = self.emb.world_size
        cap = max(cap, world)
        return -(-cap // world) * world      # round up to a world multiple

    @staticmethod
    def _host_cats(cats):
        """ONE device->host materialization of the id inputs per step,
        shared by the stale mask, the patch gather and the touched-row
        accounting (each would otherwise fetch the same tensors again —
        real host-path time at DLRM id volumes)."""
        def h(x):
            return np.asarray(jax.device_get(x))
        return [tuple(h(e) for e in c) if isinstance(c, tuple) else h(c)
                for c in cats]

    def _build_patch(self, host_cats, idx_np, cap: int, batch: int):
        """Fixed-shape patch sub-batch: rows `idx_np` of every
        (host-materialized) input, padded to `cap` with sample 0
        (scatter index `batch` => padding lanes drop device-side)."""
        idx = np.full((cap,), batch, np.int64)
        idx[:len(idx_np)] = idx_np
        safe = np.zeros((cap,), np.int64)
        safe[:len(idx_np)] = idx_np
        pcats = []
        for x in host_cats:
            if isinstance(x, tuple):
                pcats.append(tuple(jnp.asarray(a[safe]) for a in x))
            else:
                pcats.append(jnp.asarray(x[safe]))
        return pcats, jnp.asarray(idx, jnp.int32)

    def step(self, params, opt_state, batch, next_batch=None):
        """One optimizer step over `batch`; `next_batch` is the batch
        the engine prefetches for (None at the tail — the step then
        feeds the current cats as a throwaway prefetch operand so the
        compiled executable never re-specializes).

        The pipeline contract: the object passed as `next_batch` here
        must be the object passed as `batch` on the NEXT call — the
        carry is tagged with its identity and a mismatch (or a cold
        start) falls back to a fresh, bit-exact prefetch on the current
        tables.

        Returns (params, opt_state, loss)."""
        num, cats, labels = batch
        if self.lookahead == 0:
            return self._base_step(params, opt_state, jnp.asarray(num),
                                   [self._canon(c) for c in cats],
                                   jnp.asarray(labels))
        cats = [self._canon(c) for c in cats]
        first = cats[0][0] if isinstance(cats[0], tuple) else cats[0]
        batch_n = int(first.shape[0])
        cap = self._capacity(batch_n)
        emb = self.emb

        host_cats = None if self.stale_ok else self._host_cats(cats)
        idx_np = np.zeros((0,), np.int64)
        cold = None
        if self._slots.current is None or self._slots.tag is not batch:
            cold = "cold_fills"
        elif not self.stale_ok and self._prev_touched is not None:
            mask = emb.prefetch_stale_mask(host_cats, self._prev_touched)
            idx_np = np.nonzero(mask)[0]
            if len(idx_np) > cap:
                cold = "patch_overflows"
        if cold is not None:
            # fresh prefetch on the CURRENT tables — bit-exact by
            # definition (it is the sequential computation), and it
            # reuses the already-compiled warmup executable
            self._slots.clear()
            carry = self._prefetch(params["embedding"], cats)
            idx_np = np.zeros((0,), np.int64)
            self.stats[cold] += 1
            self._metrics.counter(f"lookahead/{cold}").inc()
        else:
            carry = self._slots.take()

        nb_cats = (cats if next_batch is None
                   else [self._canon(c) for c in next_batch[1]])
        if self.stale_ok:
            params, opt_state, loss, new_carry = self._fused(
                params, opt_state, carry, jnp.asarray(num), cats,
                jnp.asarray(labels), nb_cats)
        else:
            patch_cats, patch_idx = self._build_patch(host_cats, idx_np,
                                                      cap, batch_n)
            params, opt_state, loss, new_carry = self._fused(
                params, opt_state, carry, patch_cats, patch_idx,
                jnp.asarray(num), cats, jnp.asarray(labels), nb_cats)
        self._slots.stage(new_carry,
                          tag=next_batch if next_batch is not None else None)
        if not self.stale_ok:
            # host-side id accounting for the NEXT step's patch (on the
            # already-materialized host arrays); runs while the device
            # chews on the dispatched step
            self._prev_touched = emb.touched_row_keys(host_cats)
        self.stats["steps"] += 1
        n_patched = int(len(idx_np))
        if n_patched:
            self.stats["patched_steps"] += 1
            self.stats["patched_samples"] += n_patched
            self.stats["patched_samples_max"] = max(
                self.stats["patched_samples_max"], n_patched)
        m = self._metrics
        m.counter("lookahead/steps").inc()
        if n_patched:
            m.counter("lookahead/patched_steps").inc()
            m.counter("lookahead/patched_samples").inc(n_patched)
            m.gauge("lookahead/patched_samples_max").set(
                self.stats["patched_samples_max"])
        # executable-cache sizes as gauges — the compile-count SLO
        # ("must stay 1 per (plan, batch-shape)") reads these
        m.gauge("lookahead/compiles", stage="prefetch").set(
            self._prefetch._cache_size())
        m.gauge("lookahead/compiles", stage="fused").set(
            self._fused._cache_size())
        return params, opt_state, loss

    # ------------------------------------------------------- lowering
    def lower_prefetch(self, params, cats):
        """`jax.jit(...).lower` of the prefetch stage (audit/bench)."""
        return self._prefetch.lower(params["embedding"],
                                    [self._canon(c) for c in cats])

    def lower_fused(self, params, opt_state, batch, next_batch=None):
        """Lower (don't compile) the fused staged step for one batch —
        the module tools/hlo_audit.py's overlap arm analyzes."""
        num, cats, labels = batch
        cats = [self._canon(c) for c in cats]
        first = cats[0][0] if isinstance(cats[0], tuple) else cats[0]
        batch_n = int(first.shape[0])
        carry = jax.eval_shape(self._prefetch, params["embedding"], cats)
        nb_cats = (cats if next_batch is None
                   else [self._canon(c) for c in next_batch[1]])
        if self.stale_ok:
            return self._fused.lower(params, opt_state, carry,
                                     jnp.asarray(num), cats,
                                     jnp.asarray(labels), nb_cats)
        cap = self._capacity(batch_n)
        patch_cats, patch_idx = self._build_patch(
            self._host_cats(cats), np.zeros((0,), np.int64), cap, batch_n)
        return self._fused.lower(params, opt_state, carry, patch_cats,
                                 patch_idx, jnp.asarray(num), cats,
                                 jnp.asarray(labels), nb_cats)
