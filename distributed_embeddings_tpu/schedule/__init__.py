"""Device-side execution scheduling (ISSUE 9).

The fourth subsystem, alongside `serving/`, `store/` and `vocab/`: where
those manage the *state* of the embedding system (queries, versions,
bindings), `schedule/` manages the *shape of a training step in time* —
restructuring the monolithic jitted step into an explicit multi-stage
device pipeline whose exchange collectives overlap the dense compute.
"""

from distributed_embeddings_tpu.schedule.lookahead import (
    LookaheadEngine, default_lookahead)

__all__ = ["LookaheadEngine", "default_lookahead"]
