"""Flight recorder: bounded in-memory trace of structured events
(ISSUE 14).

`obs.span` gave every host-side region two outputs — a registry
histogram and an XPlane `TraceAnnotation` — but both are lossy in the
direction a postmortem needs: the histogram keeps only the
distribution, and the XPlane trace exists only while a profiler session
is running (and never on CI or a serving replica). The
`FlightRecorder` is the third output: a BOUNDED ring of begin/end/
instant events that is always on (a flight recorder that must be
switched on before the incident is a black box that records nothing),
cheap enough to feed from every span (one lock + deque append per
edge), and exportable at any moment as Chrome-trace-format JSON that
loads directly in Perfetto (ui.perfetto.dev) or chrome://tracing.

Event kinds (Chrome trace `ph` phases on export):

  * ``begin``/``end`` (B/E) — span edges, appended by `obs.span` on
    entry/exit with the composed span path, so the exported timeline
    reproduces the nesting `span_seconds{span=}` paths describe,
    per thread (publisher loop, pipeline workers, consumer pollers
    each get their own track).
  * ``instant`` (i) — point annotations (degraded-entry, SLO breach,
    fault injection...).
  * ``lineage`` (b/n/e nestable-async, ``cat="version"``) — a store
    version's LIFE as one async track keyed by the version number:
    ``commit`` opens the track, ``publish``/``scan``/``apply`` land as
    async instants on it, and the FIRST ``serve`` (a predict answered
    at >= that version) closes it. Because publisher and replica
    report into one process-wide recorder, the track spans threads and
    components: the scalar ``store/publish_to_apply_seconds``
    histogram becomes an inspectable per-version breakdown of where
    commit->predict latency went. Later phases on a closed track (a
    second replica applying the same version) record as instants, so
    the async begin/end pairing stays balanced.

The ring is bounded (``DET_OBS_TRACE_EVENTS``, default 16384 events):
old events fall off the front and the drop count is kept, so a
week-long soak holds the LAST window of activity in constant memory —
exactly the flight-recorder contract. `export()` re-balances on the
way out (an `end` whose `begin` was evicted is dropped; a still-open
`begin` gets a synthetic close at the export timestamp), so the
exported JSON always validates regardless of where the ring was cut.

`dump_postmortem` is the incident artifact: ring + registry snapshot +
caller context in one timestamped JSON file. `InferenceEngine.
poll_updates` calls it on every degraded-mode ENTRY when
``DET_OBS_POSTMORTEM_DIR`` is set, and `bench.py` dumps on SLO breach
— see docs/observability.md "Flight recorder & postmortems".
"""

import collections
import json
import os
import threading
import time
from typing import Dict, List, Optional

__all__ = ["FlightRecorder", "default_recorder", "reset_default_recorder",
           "dump_postmortem", "DEFAULT_CAPACITY"]

DEFAULT_CAPACITY = 16384

# lineage phases in life order; "commit" opens the async track and
# "serve" closes it (first occurrence only — see class docstring)
LINEAGE_PHASES = ("commit", "publish", "scan", "apply", "serve")


class FlightRecorder:
    """Bounded ring of trace events; see module docstring.

    Args:
      capacity: max events held (oldest evicted first). Default:
        ``DET_OBS_TRACE_EVENTS`` or 16384.

    Every mutator is thread-safe (one lock around the deque); the
    recording cost is one `time.perf_counter()` read plus an append,
    so spans can feed it unconditionally.
    """

    def __init__(self, capacity: Optional[int] = None):
        if capacity is None:
            capacity = int(os.environ.get("DET_OBS_TRACE_EVENTS",
                                          DEFAULT_CAPACITY))
        if capacity < 2:
            raise ValueError(f"capacity must be >= 2, got {capacity}")
        self.capacity = int(capacity)
        self._lock = threading.Lock()
        self._events = collections.deque(maxlen=self.capacity)
        self._dropped = 0
        self._thread_names: Dict[int, str] = {}
        # lineage state: version -> "open" | "closed" (versions the ring
        # has begun an async track for; bounded by eviction reconcile at
        # export, and by being integers — a few bytes per version)
        self._lineage: Dict[int, str] = {}
        # perf_counter at construction: export timestamps are relative
        # to this origin (Chrome trace ts is an arbitrary-epoch us)
        self._t0 = time.perf_counter()
        # wall-clock twin of _t0 so exported args can carry absolute time
        self._wall0 = time.time()

    # ------------------------------------------------------------ record
    def _append_locked(self, ph: str, name: str, ts: float, tid: int,
                       cat: Optional[str] = None,
                       eid: Optional[int] = None,
                       args: Optional[dict] = None):
        """Caller holds self._lock. Split out so `lineage` can make its
        state transition AND its event append one atomic step — a
        check-then-act gap there lets two threads first-sighting the
        same version emit a duplicate async begin (or land an 'n'
        before its 'b'), breaking the balanced-export contract."""
        if tid not in self._thread_names:
            self._thread_names[tid] = threading.current_thread().name
        if len(self._events) == self.capacity:
            self._dropped += 1
        self._events.append((ph, name, ts, tid, cat, eid, args))

    def _append(self, ph: str, name: str, cat: Optional[str] = None,
                eid: Optional[int] = None, args: Optional[dict] = None):
        tid = threading.get_ident()
        ts = time.perf_counter() - self._t0
        with self._lock:
            self._append_locked(ph, name, ts, tid, cat, eid, args)

    def begin(self, name: str) -> None:
        """Open a region (span entry). Paired with `end(name)`."""
        self._append("B", name)

    def end(self, name: str) -> None:
        """Close a region (span exit)."""
        self._append("E", name)

    def instant(self, name: str, **args) -> None:
        """A point event (degraded entry, SLO breach, fault fired...)."""
        self._append("i", name, args=args or None)

    def lineage(self, version: int, phase: str, **args) -> None:
        """One step of store version `version`'s life (see module
        docstring). Unknown-to-the-recorder versions auto-open (a
        consumer can watch a stream whose publisher lives elsewhere);
        the first ``serve`` closes the track, later phases on a closed
        version record as async instants."""
        if phase not in LINEAGE_PHASES:
            raise ValueError(
                f"lineage phase {phase!r} not in {LINEAGE_PHASES}")
        version = int(version)
        name = f"v{version}"
        tid = threading.get_ident()
        # state transition + event append under ONE lock hold: two
        # threads first-sighting a version must serialize into exactly
        # one 'b' followed by the other's 'n'/'e'
        with self._lock:
            ts = time.perf_counter() - self._t0
            state = self._lineage.get(version)
            if state is None:
                # open the async track (commit, or first sight on a
                # consumer that never saw the publisher's commit)
                self._lineage[version] = "open"
                self._append_locked(
                    "b", name, ts, tid, cat="version", eid=version,
                    args={"phase": "commit"} if phase == "commit"
                    else None)
                if phase == "commit":
                    return
                state = "open"
            if phase == "serve" and state == "open":
                self._lineage[version] = "closed"
                self._append_locked(
                    "e", name, ts, tid, cat="version", eid=version,
                    args={"phase": "serve", **args} if args
                    else {"phase": "serve"})
                return
            self._append_locked("n", name, ts, tid, cat="version",
                                eid=version,
                                args={"phase": phase, **args})

    # ------------------------------------------------------------- views
    def events(self) -> List[tuple]:
        """The current ring contents, oldest first (tuples of
        (ph, name, ts_seconds, tid, cat, id, args))."""
        with self._lock:
            return list(self._events)

    @property
    def dropped(self) -> int:
        """Events evicted from the ring so far (0 = nothing lost)."""
        with self._lock:
            return self._dropped

    def lineage_versions(self) -> List[int]:
        """Versions whose lineage track this ring has opened, sorted."""
        with self._lock:
            return sorted(self._lineage)

    def lineage_open_versions(self) -> List[int]:
        """Versions whose track is begun but not yet closed by a
        ``serve`` phase, sorted — the serving seam closes every open
        version <= the version a predict was answered at (a predict at
        V is also the first predict at >= every version below it)."""
        with self._lock:
            return sorted(v for v, s in self._lineage.items()
                          if s == "open")

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._dropped = 0
            self._lineage.clear()

    # ------------------------------------------------------------ export
    def to_chrome_trace(self) -> dict:
        """The ring as a Chrome-trace-format dict (`traceEvents` JSON
        object form — what Perfetto and chrome://tracing load).

        Balanced by construction: per-thread `E` events whose `B` was
        evicted from the ring are dropped, still-open `B` events get a
        synthetic close at the export timestamp, and lineage tracks
        likewise (an evicted async begin is re-synthesized at the
        track's first surviving event; an open track closes at export).
        Span timestamps are microseconds relative to the recorder's
        construction.
        """
        with self._lock:
            events = list(self._events)
            thread_names = dict(self._thread_names)
            wall0 = self._wall0
        pid = os.getpid()
        now_us = (time.perf_counter() - self._t0) * 1e6
        out: List[dict] = [{
            "ph": "M", "pid": pid, "name": "process_name",
            "args": {"name": "flight_recorder"}}]
        for tid, tname in thread_names.items():
            out.append({"ph": "M", "pid": pid, "tid": tid,
                        "name": "thread_name", "args": {"name": tname}})
        open_spans: Dict[int, List[dict]] = {}
        open_async: Dict[int, dict] = {}
        for ph, name, ts, tid, cat, eid, args in events:
            ev = {"ph": ph, "name": name, "pid": pid, "tid": tid,
                  "ts": round(ts * 1e6, 3)}
            if cat is not None:
                ev["cat"] = cat
            if eid is not None:
                ev["id"] = eid
            if args:
                ev["args"] = dict(args)
            if ph == "B":
                open_spans.setdefault(tid, []).append(ev)
                out.append(ev)
            elif ph == "E":
                stack = open_spans.get(tid)
                if not stack:
                    continue             # begin evicted: drop the orphan
                stack.pop()
                out.append(ev)
            elif ph == "b":
                open_async[eid] = ev
                out.append(ev)
            elif ph in ("n", "e"):
                if eid not in open_async:
                    # async begin evicted: re-open the track just before
                    # this first surviving event so the id still groups
                    synth = {"ph": "b", "name": name, "pid": pid,
                             "tid": tid, "cat": cat or "version",
                             "id": eid, "ts": ev["ts"],
                             "args": {"synthesized": "begin-evicted"}}
                    open_async[eid] = synth
                    out.append(synth)
                if ph == "e":
                    open_async[eid] = None   # closed
                out.append(ev)
            else:                            # "i" and any future phases
                ev["s"] = "t"
                out.append(ev)
        # close whatever export caught mid-flight, deepest first
        for tid, stack in open_spans.items():
            for ev in reversed(stack):
                out.append({"ph": "E", "name": ev["name"], "pid": pid,
                            "tid": tid, "ts": round(now_us, 3),
                            "args": {"synthesized": "open-at-export"}})
        for eid, ev in open_async.items():
            if ev is not None:
                out.append({"ph": "e", "name": ev["name"], "pid": pid,
                            "tid": ev["tid"], "cat": ev.get("cat",
                                                            "version"),
                            "id": eid, "ts": round(now_us, 3),
                            "args": {"synthesized": "open-at-export"}})
        return {
            "displayTimeUnit": "ms",
            "metadata": {"source": "distributed_embeddings_tpu.obs.trace",
                         "wall_time_origin": wall0,
                         "dropped_events": self._dropped},
            "traceEvents": out,
        }

    def export(self, path: str) -> dict:
        """Write `to_chrome_trace()` to `path` (overwrite; the ring is
        a window, not a log — repeated exports supersede). Returns the
        exported dict."""
        doc = self.to_chrome_trace()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, "w") as f:
            json.dump(doc, f)
        return doc


_default_lock = threading.Lock()
_default: Optional[FlightRecorder] = None


def default_recorder() -> FlightRecorder:
    """The process-wide recorder `obs.span`, the store/consumer lineage
    seams, and the serving engine feed — one ring so a postmortem sees
    publisher, pipeline and replica activity on one timeline."""
    global _default
    with _default_lock:
        if _default is None:
            _default = FlightRecorder()
        return _default


def reset_default_recorder() -> None:
    """Drop the process-wide recorder (tests)."""
    global _default
    with _default_lock:
        _default = None


def dump_postmortem(directory: str, reason: str, registry=None,
                    recorder: Optional[FlightRecorder] = None,
                    extra: Optional[dict] = None) -> str:
    """Write the incident artifact: flight-recorder ring (as a chrome
    trace) + registry snapshot + caller context, one timestamped JSON
    file in `directory`. Returns the artifact path.

    The filename carries a monotonic-per-process sequence number so two
    dumps in the same second (two reasons activating on one poll) never
    collide or overwrite."""
    rec = recorder if recorder is not None else default_recorder()
    os.makedirs(directory, exist_ok=True)
    with _default_lock:
        global _postmortem_seq
        _postmortem_seq += 1
        seq = _postmortem_seq
    safe = "".join(c if c.isalnum() or c in "-_" else "_"
                   for c in str(reason))[:60]
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
    path = os.path.join(directory,
                        f"postmortem_{stamp}_{seq:04d}_{safe}.json")
    doc = {
        "ts": round(time.time(), 3),
        "reason": str(reason),
        "snapshot": (registry.snapshot() if registry is not None else None),
        "trace": rec.to_chrome_trace(),
        "lineage_versions": rec.lineage_versions(),
        "dropped_events": rec.dropped,
    }
    if extra:
        doc["extra"] = extra
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)       # atomic: a watcher never sees a torn dump
    if registry is not None:
        registry.counter("obs/postmortems_total", reason=safe).inc()
    return path


_postmortem_seq = 0
