"""Unified runtime telemetry (ISSUE 11): one metric registry, host-side
step-span tracing, and declarative SLO evaluation across
train/serve/vocab/store/lookahead.

See docs/observability.md for the full API and schema; the short form:

    from distributed_embeddings_tpu import obs

    reg = obs.MetricRegistry()            # or obs.default_registry()
    reg.counter("train/steps").inc()
    with obs.span("train/step", reg):
        ...
    snap = reg.snapshot()
    findings = obs.evaluate_rules(obs.load_rules("slo.json"), snap)
"""

from distributed_embeddings_tpu.obs.registry import (  # noqa: F401
    Counter, Gauge, LatencyHistogram, MetricRegistry, default_registry,
    metric_key, reset_default_registry)
from distributed_embeddings_tpu.obs.slo import (  # noqa: F401
    evaluate_rules, load_rules, metric_value, summarize)
from distributed_embeddings_tpu.obs.spans import (  # noqa: F401
    annotation, current_span, span)
from distributed_embeddings_tpu.obs.instrument import (  # noqa: F401
    export_exchange_gauges, export_kernel_gauges)

__all__ = [
    "Counter", "Gauge", "LatencyHistogram", "MetricRegistry",
    "default_registry", "reset_default_registry", "metric_key",
    "span", "annotation", "current_span",
    "load_rules", "evaluate_rules", "metric_value", "summarize",
    "export_exchange_gauges", "export_kernel_gauges",
]
