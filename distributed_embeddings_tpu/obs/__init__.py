"""Unified runtime telemetry (ISSUE 11/14): one metric registry,
host-side step-span tracing, declarative SLO evaluation, a bounded
flight recorder with version-lineage tracks, and device-time
attribution from profiler captures — across
train/serve/vocab/store/lookahead.

See docs/observability.md for the full API and schema; the short form:

    from distributed_embeddings_tpu import obs

    reg = obs.MetricRegistry()            # or obs.default_registry()
    reg.counter("train/steps").inc()
    with obs.span("train/step", reg):
        ...
    snap = reg.snapshot()
    findings = obs.evaluate_rules(obs.load_rules("slo.json"), snap)
    obs.default_recorder().export("trace.json")   # Perfetto-loadable
    obs.attribution.attribute_logdir(profiler_logdir, registry=reg)
"""

from distributed_embeddings_tpu.obs.registry import (  # noqa: F401
    Counter, Gauge, LatencyHistogram, MetricRegistry, default_registry,
    metric_key, reset_default_registry)
from distributed_embeddings_tpu.obs.slo import (  # noqa: F401
    evaluate_rules, load_rules, metric_value, summarize)
from distributed_embeddings_tpu.obs.spans import (  # noqa: F401
    annotation, current_span, span)
from distributed_embeddings_tpu.obs.instrument import (  # noqa: F401
    export_exchange_gauges, export_kernel_gauges)
from distributed_embeddings_tpu.obs.trace import (  # noqa: F401
    FlightRecorder, default_recorder, dump_postmortem,
    reset_default_recorder)
from distributed_embeddings_tpu.obs import attribution  # noqa: F401

__all__ = [
    "Counter", "Gauge", "LatencyHistogram", "MetricRegistry",
    "default_registry", "reset_default_registry", "metric_key",
    "span", "annotation", "current_span",
    "load_rules", "evaluate_rules", "metric_value", "summarize",
    "export_exchange_gauges", "export_kernel_gauges",
    "FlightRecorder", "default_recorder", "reset_default_recorder",
    "dump_postmortem", "attribution",
]
