"""Host-side step-span tracing (ISSUE 11).

``span("train/step")`` times a host-side region into a registry
histogram AND opens a ``jax.profiler.TraceAnnotation`` for the same
region, so the spans that structure a training/serving loop show up in
two places at once: the registry snapshot (wall-time percentiles per
span path, SLO-gateable) and the XPlane trace (TensorBoard/Perfetto,
next to the device ops the span dispatched).

Nesting composes paths: a ``span("publish")`` opened inside
``span("train")`` records as ``train/publish`` — the per-thread span
stack supplies the prefix, so instrumented helpers don't need to know
where they are called from. The stack is thread-local: pipeline worker
threads and the consumer each get their own nesting.

This module is HOST-side by design: spans read the wall clock, which is
exactly what `tools/lint_invariants.py`'s ``wallclock-in-jit`` rule
bans from jitted-code modules (ops/, layers/, parallel/, schedule/).
``obs/`` is deliberately NOT in that module set — it is the sanctioned
home for wall-clock accounting — and instrumented call sites in jitted
modules must stay in their host-side driver methods (e.g.
`LookaheadEngine.step`'s Python body, never inside a traced function:
a traced span would freeze one timestamp into the compiled program and
time nothing).

The `annotation()` helper is the shared tolerant wrapper around
`utils.profiling.annotate`: the works/doesn't-work probe is cached
module-wide, so backends with no profiler configured pay one failed
construction per process instead of one exception per region
(`utils.pipeline` delegates here — its per-stage-invocation re-probe
was measurable ingest overhead).

Since ISSUE 14 a span has a THIRD output: its begin/end edges land in
the process-wide flight recorder (`obs.trace.default_recorder`), so
the last window of loop structure is exportable as a Perfetto-loadable
timeline at any moment — including from a postmortem dump on a box
where no profiler session ever ran.
"""

import contextlib
import threading
import time
from typing import Optional

from distributed_embeddings_tpu.obs.registry import (MetricRegistry,
                                                     default_registry)
from distributed_embeddings_tpu.obs.trace import default_recorder

__all__ = ["span", "annotation", "current_span"]

_state = threading.local()

# cached annotate probe: None = untried, False = profiler unavailable
# (never retried), True = construction known to work
_ANNOTATE_OK = None


def annotation(name: str):
    """`utils.profiling.annotate(name)`, tolerating backends with no
    profiler — the probe result is cached process-wide so the failure
    path costs one exception total, not one per region."""
    global _ANNOTATE_OK
    if _ANNOTATE_OK is False:
        return contextlib.nullcontext()
    from distributed_embeddings_tpu.utils import profiling
    try:
        cm = profiling.annotate(name)
        _ANNOTATE_OK = True
        return cm
    except Exception:  # noqa: BLE001 - accounting must never break the run
        _ANNOTATE_OK = False
        return contextlib.nullcontext()


def current_span() -> Optional[str]:
    """The innermost open span path on this thread (None outside any)."""
    stack = getattr(_state, "stack", None)
    return stack[-1] if stack else None


@contextlib.contextmanager
def span(name: str, registry: Optional[MetricRegistry] = None):
    """Time a host-side region into ``span_seconds{span=<path>}``.

    Args:
      name: span name; joined onto the enclosing span's path with ``/``
        (top-level spans may themselves be pre-pathed: "train/step").
      registry: target registry (default: the process-local one).

    The duration records even when the body raises — a failing step is
    still a step that took time — and the annotation scope closes with
    the region, so XPlane nesting matches the histogram paths.
    """
    reg = registry if registry is not None else default_registry()
    stack = getattr(_state, "stack", None)
    if stack is None:
        stack = _state.stack = []
    path = f"{stack[-1]}/{name}" if stack else name
    stack.append(path)
    rec = default_recorder()
    rec.begin(path)
    t0 = time.perf_counter()
    try:
        with annotation(path):
            yield path
    finally:
        dt = time.perf_counter() - t0
        stack.pop()
        rec.end(path)
        reg.histogram("span_seconds", span=path).record(dt)
