"""Declarative SLO rules over registry snapshots (ISSUE 11).

A rule is data, not code — checked into a JSON file next to the CI
config so the gate that scripts ROADMAP item 5's soak scenarios ("serve
p99 during churn", "compile count must stay 1", "zero audit findings")
is reviewable and diffable:

    {"name": "one-compile", "metric": "lookahead/compiles{stage=fused}",
     "op": "==", "threshold": 1}
    {"name": "serve-p99", "metric": "serve/request_seconds:p99_ms",
     "op": "<=", "threshold": 250, "window": 5, "severity": "warning"}

``metric`` addresses a snapshot entry by its flat registry key
(`obs.registry.metric_key` form, labels included); a ``:field`` suffix
selects a histogram summary field (``p50_ms``/``p95_ms``/``p99_ms``/
``mean_ms``/``max_ms``/``count``). ``window=N`` evaluates the rule over
the last N snapshots of a sequence (e.g. the parsed lines of a
`MetricRegistry.export_jsonl` file) — the rule must hold in EVERY
snapshot of the window; a single snapshot is a window of one.

An absent metric is a violation by default (an SLO over a signal that
never materialized must fail loudly, not vacuously pass).
``"if_present": true`` opts a rule out of that: it gates the metric
only when it exists, for rule files shared across runs where the gated
subsystem is legitimately optional (e.g. one soak rule file covering
both lookahead and vocab-maintenance scenarios — the two compose
mutually exclusively, so ``lookahead/compiles`` is absent from half
the runs by design, not by failure).

Violations come back in `analysis.passes.Finding` shape — the same
typed finding `bench.py` and CI already gate audit results through —
with stable content-derived ids (``slo:<name>``), so an SLO breach and
a static-invariant breach flow through one reporting path.
"""

import json
import operator
from typing import Dict, List, Optional, Sequence, Union

from distributed_embeddings_tpu.analysis.passes import Finding

__all__ = ["load_rules", "validate_rule", "metric_value",
           "evaluate_rules", "summarize"]

_OPS = {"<": operator.lt, "<=": operator.le, "==": operator.eq,
        "!=": operator.ne, ">=": operator.ge, ">": operator.gt}

_HIST_FIELDS = ("count", "mean_ms", "p50_ms", "p95_ms", "p99_ms",
                "max_ms")


def validate_rule(rule: dict) -> dict:
    """Shape-check one rule; returns it. Fails LOUDLY at load time —
    a malformed rule that silently never fires is a gate that cannot
    gate."""
    for field in ("name", "metric", "op", "threshold"):
        if field not in rule:
            raise ValueError(f"SLO rule missing {field!r}: {rule}")
    if rule["op"] not in _OPS:
        raise ValueError(
            f"SLO rule {rule['name']!r}: op {rule['op']!r} not in "
            f"{sorted(_OPS)}")
    if not isinstance(rule["threshold"], (int, float)):
        raise ValueError(
            f"SLO rule {rule['name']!r}: threshold must be a number")
    window = rule.get("window", 1)
    if not (isinstance(window, int) and window >= 1):
        raise ValueError(
            f"SLO rule {rule['name']!r}: window must be an int >= 1")
    sev = rule.get("severity", "error")
    if sev not in ("error", "warning"):
        raise ValueError(
            f"SLO rule {rule['name']!r}: severity {sev!r} not in "
            "('error', 'warning')")
    if not isinstance(rule.get("if_present", False), bool):
        raise ValueError(
            f"SLO rule {rule['name']!r}: if_present must be a bool")
    return rule


def load_rules(path: str) -> List[dict]:
    """Load + validate a JSON rule file: either a bare list of rules or
    ``{"rules": [...]}`` (room for future file-level fields)."""
    with open(path) as f:
        doc = json.load(f)
    rules = doc["rules"] if isinstance(doc, dict) else doc
    if not isinstance(rules, list):
        raise ValueError(f"{path}: expected a rule list")
    return [validate_rule(r) for r in rules]


def metric_value(snapshot: dict, metric: str) -> Optional[float]:
    """Resolve a rule's metric address against one snapshot; None when
    absent. Counters/gauges resolve by flat key; histograms need a
    ``:field`` suffix (addressing a histogram without one is a rule
    bug, raised not hidden)."""
    name, _, field = metric.partition(":")
    for section in ("counters", "gauges"):
        if name in snapshot.get(section, {}):
            if field:
                raise ValueError(
                    f"metric {metric!r}: field suffix on a {section[:-1]}"
                    " (only histograms have summary fields)")
            return float(snapshot[section][name])
    hist = snapshot.get("histograms", {}).get(name)
    if hist is not None:
        if not field:
            raise ValueError(
                f"metric {metric!r} is a histogram: address a summary "
                f"field ({', '.join(_HIST_FIELDS)})")
        if field not in hist:
            raise ValueError(
                f"metric {metric!r}: no field {field!r} in "
                f"{sorted(hist)}")
        return float(hist[field])
    return None


def evaluate_rules(rules: Sequence[dict],
                   snapshots: Union[dict, Sequence[dict]]) -> List[Finding]:
    """Evaluate every rule; return one Finding per violated (or
    unresolvable) rule, `analysis.passes.Finding`-shaped so callers
    gate SLO breaches exactly like audit findings.

    `snapshots` is one snapshot dict or an ordered sequence (oldest
    first); each rule reads its last ``window`` snapshots and must hold
    in all of them. A metric missing from any windowed snapshot is a
    violation — an SLO over a signal that never materialized must fail
    loudly, not vacuously pass — unless the rule opts out with
    ``"if_present": true`` (see module docstring).
    """
    if isinstance(snapshots, dict):
        snapshots = [snapshots]
    snapshots = list(snapshots)
    if not snapshots:
        raise ValueError("evaluate_rules needs at least one snapshot")
    findings: List[Finding] = []
    for rule in rules:
        rule = validate_rule(dict(rule))
        window = snapshots[-int(rule.get("window", 1)):]
        op = _OPS[rule["op"]]
        optional = bool(rule.get("if_present", False))
        worst: Optional[float] = None
        missing = False
        for snap in window:
            v = metric_value(snap, rule["metric"])
            if v is None:
                if optional:
                    # if_present: absent snapshots are skipped, but the
                    # rule still gates every snapshot where the metric
                    # DID materialize — a breach observed before the
                    # subsystem went quiet must not be silenced
                    continue
                missing = True
                break
            if not op(v, rule["threshold"]) and (
                    worst is None or abs(v - rule["threshold"])
                    > abs(worst - rule["threshold"])):
                worst = v
        if missing:
            findings.append(Finding(
                pass_name="slo", fid=f"slo:{rule['name']}:absent",
                severity=rule.get("severity", "error"),
                message=(f"SLO {rule['name']!r}: metric "
                         f"{rule['metric']!r} absent from snapshot"),
                func=rule["metric"], op=rule["op"]))
        elif worst is not None:
            findings.append(Finding(
                pass_name="slo", fid=f"slo:{rule['name']}",
                severity=rule.get("severity", "error"),
                message=(f"SLO {rule['name']!r}: {rule['metric']} = "
                         f"{worst:g}, want {rule['op']} "
                         f"{rule['threshold']:g} over window of "
                         f"{len(window)}"),
                func=rule["metric"], op=rule["op"]))
    return findings


def summarize(findings: Sequence[Finding]) -> Dict[str, object]:
    """The ``{"count", "ids"}`` bundle bench records embed — the same
    shape as their ``audit_findings`` stamp."""
    return {"count": len(findings),
            "ids": sorted({f.fid for f in findings})}
