"""Bridging helpers: existing accounting surfaces -> registry (ISSUE 11).

The padding/byte/touched-row report (`DistributedEmbedding.
exchange_padding_report`) is the repo's static model of every per-step
volume; `export_exchange_gauges` publishes its headline fields as
registry gauges so SLO rules and bench snapshots address them the same
way they address runtime counters — and so the consistency test
(tests/test_exchange.py) can assert the gauges a driven run exported
EQUAL a fresh report's fields (the wiring, not the model, is what can
silently rot).
"""

from typing import Optional

from distributed_embeddings_tpu.obs.registry import MetricRegistry

__all__ = ["export_exchange_gauges", "export_kernel_gauges",
           "EXCHANGE_GAUGE_FIELDS", "EXCHANGE_GROUP_GAUGE_FIELDS"]


def export_kernel_gauges(registry: MetricRegistry) -> dict:
    """Set ``kernels/gate_verdict{impl=}`` gauges from the sparse-update
    kernel gates (ISSUE 12): 1 = hardware-validated, 0 = probe failed,
    -1 = never probed (off-TPU interpret mode / impl never requested).
    ``tools/slo_tier1.json`` requires the pallas verdict's PRESENCE, so
    a run that forgot this wiring fails the smoke loudly rather than
    shipping a snapshot that cannot say which kernel family ran.
    Returns the verdict dict."""
    from distributed_embeddings_tpu.ops.sparse_update import gate_verdicts
    verdicts = gate_verdicts()
    for impl, verdict in verdicts.items():
        registry.gauge("kernels/gate_verdict", impl=impl).set(verdict)
    return verdicts

# top-level report fields exported as exchange/<field> gauges
EXCHANGE_GAUGE_FIELDS = (
    "true_ids", "exchanged_ids", "ratio",
    "exchanged_bytes", "true_bytes", "act_wire_reduction",
    "touched_rows_per_step", "delta_bytes_per_step",
    "occupancy", "slack_rows", "evictions_per_step",
    "prefetch_patch_rows_per_step", "prefetch_patch_bytes_per_step",
)

# per-group fields exported with a group= label
EXCHANGE_GROUP_GAUGE_FIELDS = (
    "touched_rows_per_step", "occupancy",
    "prefetch_patch_rows_per_step",
)


def export_exchange_gauges(registry: MetricRegistry, emb, *,
                           batch: int = 1, vocab=None, lookahead: int = 0,
                           hot_hit_rate=None,
                           hotness: Optional[list] = None) -> dict:
    """Set ``exchange/*`` gauges from one `exchange_padding_report`
    call (same arguments, same numbers); per-group entries land under a
    ``group=<index>`` label with the bucket index alongside. Returns
    the report so callers embedding it (bench records, fit history)
    don't recompute it."""
    rep = emb.exchange_padding_report(hotness=hotness,
                                      hot_hit_rate=hot_hit_rate,
                                      batch=batch, vocab=vocab,
                                      lookahead=lookahead)
    for field in EXCHANGE_GAUGE_FIELDS:
        registry.gauge(f"exchange/{field}").set(rep[field])
    for gi, entry in enumerate(rep["groups"]):
        for field in EXCHANGE_GROUP_GAUGE_FIELDS:
            registry.gauge(f"exchange/{field}", group=gi,
                           bucket=entry["bucket"]).set(entry[field])
    return rep
