"""Device-time attribution: profiler trace -> per-span device seconds
(ISSUE 14).

`obs.span` opens a `jax.profiler.TraceAnnotation` for every span path
(PR 11), so a profiler capture (`utils.profiling.trace`) already
contains the span windows AND the device-op events side by side — but
nothing ever consumed the match. This module closes the loop: parse
the capture's Chrome-trace export (`plugins/profile/<run>/*.trace.
json.gz`, written by jax's profiler on `stop_trace`), classify events
into span windows (host-side annotation events whose names are span
paths) and device ops (events carrying ``hlo_op``/``hlo_module`` args,
or living in a ``/device:*`` process — TPU op tracks and XLA:CPU thunk
executions both match), and attribute every device op to the INNERMOST
span window containing its midpoint. The result answers the question
every bench record since r03 has begged: where did this step's DEVICE
time actually go, per phase?

Attribution is exhaustive by construction: every device op lands in
exactly one span bucket or in ``unattributed`` (dispatched outside any
open span — async-dispatch tail on TPU, profiler warmup, compile-time
autotuning), so ``sum(spans) + unattributed == total`` exactly. The
collective breakdown additionally classifies exchange ops
(all-to-all / all-gather / reduce-scatter / collective-permute /
all-reduce) and measures how much of their device time is EXPOSED
(not covered by concurrent dense-compute ops on other device tracks) —
the lookahead arm's headline metric (docs/perf_model.md "Lookahead
prefetch": projected speedup = (E + D) / max(E, D) where E is exactly
this exposed fraction times the exchange term).

Outputs:
  * `attribute_logdir(logdir, registry=)` — the ``device_attribution``
    dict bench records embed, and (with a registry) the
    ``device/span_seconds{span=}`` / ``device/unattributed_seconds`` /
    ``device/total_seconds`` gauges SLO rules can address.
  * `reconciliation_table(att, projections)` — measured-vs-perf_model
    rows: each projection either SETTLES (within tolerance) or
    FALSIFIES, the tunnel-window record of docs/perf_model.md.
  * `tools/device_attribution.py` — the CLI over both.
"""

import glob
import gzip
import json
import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

__all__ = ["find_trace_file", "load_trace_events", "attribute_device_time",
           "export_device_gauges", "attribute_logdir",
           "reconciliation_table", "span_paths_from_snapshot",
           "COLLECTIVE_RE", "COMPUTE_RE"]

# HLO op-name fingerprints. Collectives match the exchange family the
# wire/overlap audits track (`utils.profiling._COLLECTIVES`, dash form
# as HLO spells them); compute matches the dense ops the overlap audit
# treats as hideable-under (dot/conv and the fusions XLA folds them
# into).
COLLECTIVE_RE = re.compile(
    r"(ragged-)?all-to-all|all-gather|all-reduce|reduce-scatter"
    r"|collective-permute", re.IGNORECASE)
COMPUTE_RE = re.compile(r"\b(dot|convolution|cudnn|fusion)", re.IGNORECASE)


def find_trace_file(logdir: str) -> str:
    """The newest profiler run's ``*.trace.json(.gz)`` under `logdir`
    (jax writes ``plugins/profile/<timestamp>/<host>.trace.json.gz``
    on `stop_trace`). Raises FileNotFoundError when no capture
    landed."""
    pats = [os.path.join(logdir, "plugins", "profile", "*", p)
            for p in ("*.trace.json.gz", "*.trace.json")]
    pats += [os.path.join(logdir, p)
             for p in ("*.trace.json.gz", "*.trace.json")]
    hits: List[str] = []
    for pat in pats:
        hits.extend(glob.glob(pat))
    if not hits:
        raise FileNotFoundError(
            f"no profiler chrome trace (*.trace.json[.gz]) under "
            f"{logdir!r} — did the capture run?")
    return max(hits, key=os.path.getmtime)


def load_trace_events(path: str) -> List[dict]:
    """The `traceEvents` list of one Chrome-trace JSON file (gzipped or
    plain; object form or bare event list)."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        doc = json.loads(f.read().decode("utf-8", errors="replace"))
    return doc["traceEvents"] if isinstance(doc, dict) else doc


def _device_pids(events: Sequence[dict]) -> set:
    """Process ids whose metadata names them a device timeline."""
    pids = set()
    for e in events:
        if (e.get("ph") == "M" and e.get("name") == "process_name"
                and str(e.get("args", {}).get("name", ""))
                .startswith("/device:")):
            pids.add(e.get("pid"))
    return pids


def _is_device_op(e: dict, device_pids: set) -> bool:
    args = e.get("args")
    if isinstance(args, dict) and ("hlo_op" in args
                                   or "hlo_module" in args
                                   or "hlo_category" in args):
        return True
    return e.get("pid") in device_pids


def _span_windows(events: Sequence[dict], span_paths,
                  device_pids: set
                  ) -> List[Tuple[float, float, str, object]]:
    """(start_us, end_us, path, host_tid) for every span-annotation
    event.

    With `span_paths` (the registry's recorded span set) the match is
    exact. Without, fall back to the shape of an annotation: a
    complete host event whose name contains ``/`` and is neither a
    python-tracer frame (``$``-prefixed) nor a device op."""
    wins = []
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        name = e.get("name", "")
        if span_paths is not None:
            if name not in span_paths:
                continue
        else:
            if ("/" not in name or name.startswith("$")
                    or "::" in name
                    or _is_device_op(e, device_pids)):
                continue
        ts = float(e["ts"])
        wins.append((ts, ts + float(e["dur"]), name, e.get("tid")))
    return wins


def _merged_intervals(ivs: List[Tuple[float, float]]
                      ) -> List[Tuple[float, float]]:
    out: List[Tuple[float, float]] = []
    for s, t in sorted(ivs):
        if out and s <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], t))
        else:
            out.append((s, t))
    return out


def _overlap(s: float, t: float,
             merged: List[Tuple[float, float]]) -> float:
    """Length of [s, t] covered by the merged interval list (us)."""
    cov = 0.0
    for a, b in merged:
        if b <= s:
            continue
        if a >= t:
            break
        cov += min(b, t) - max(a, s)
    return cov


def attribute_device_time(events: Sequence[dict],
                          span_paths: Optional[set] = None) -> dict:
    """Attribute device-op time to enclosing span annotations.

    Args:
      events: Chrome-trace `traceEvents` (from `load_trace_events`).
      span_paths: the span paths to treat as attribution windows
        (typically the registry's ``span_seconds{span=}`` key set);
        None = shape-based fallback (see `_span_windows`).

    Returns the ``device_attribution`` dict: ``total_device_seconds``,
    ``spans`` {path: seconds}, ``unattributed_seconds``,
    ``coverage_frac``, op/window counts, a per-op-category split, and
    the ``collective`` exposure block (global and per-span) —
    seconds rounded to 9 places; the sum identity holds exactly in the
    unrounded accumulators and within 1e-6 after rounding.

    Concurrent-span honesty: time-midpoint containment cannot tell
    WHICH host thread dispatched a device op, so when windows from
    more than one host thread contain an op's midpoint (e.g. a serving
    span overlapping a background trainer's step span in wall time)
    the shortest-window assignment is a guess. ``ambiguous_seconds``
    totals the device time in that state — a large value means the
    per-span split should be read as approximate, not that the
    measurement failed (the sum identity is unaffected).
    """
    events = [e for e in events if isinstance(e, dict)]
    device_pids = _device_pids(events)
    wins = _span_windows(events, span_paths, device_pids)
    # innermost-first candidate order: shortest window wins a midpoint
    wins_sorted = sorted(wins, key=lambda w: w[1] - w[0])
    # ambiguity zones: time ranges where windows from DIFFERENT host
    # threads coexist (precomputed once — a per-op full window scan
    # would make big traces quadratic)
    by_tid: Dict[object, List[Tuple[float, float]]] = {}
    for s, t, _, wtid in wins:
        by_tid.setdefault(wtid, []).append((s, t))
    amb_zones: List[Tuple[float, float]] = []
    if len(by_tid) > 1:
        merged = {tid: _merged_intervals(iv) for tid, iv in by_tid.items()}
        tids = list(merged)
        for i, ta in enumerate(tids):
            for tb in tids[i + 1:]:
                for a1, b1 in merged[ta]:
                    for a2, b2 in merged[tb]:
                        lo, hi = max(a1, a2), min(b1, b2)
                        if lo < hi:
                            amb_zones.append((lo, hi))
        amb_zones = _merged_intervals(amb_zones)

    ops = [e for e in events
           if e.get("ph") == "X" and "dur" in e
           and _is_device_op(e, device_pids)]
    total = 0.0
    per_span: Dict[str, float] = {}
    unattributed = 0.0
    ambiguous = 0.0
    categories: Dict[str, float] = {}
    compute_ivs: List[Tuple[float, float]] = []
    coll_ops: List[Tuple[float, float, Optional[str]]] = []
    for e in ops:
        ts, dur = float(e["ts"]), float(e["dur"])
        total += dur
        mid = ts + dur / 2.0
        name = str(e.get("name", ""))
        hlo = str((e.get("args") or {}).get("hlo_op", name))
        assigned = None
        for s, t, path, _ in wins_sorted:
            if s <= mid <= t:
                assigned = path
                break
        if assigned is not None and _overlap(mid, mid + 1e-9,
                                             amb_zones) > 0:
            ambiguous += dur
        if assigned is None:
            unattributed += dur
        else:
            per_span[assigned] = per_span.get(assigned, 0.0) + dur
        if COLLECTIVE_RE.search(hlo) or COLLECTIVE_RE.search(name):
            categories["collective"] = (categories.get("collective", 0.0)
                                        + dur)
            coll_ops.append((ts, ts + dur, assigned))
        elif COMPUTE_RE.search(hlo) or COMPUTE_RE.search(name):
            categories["compute"] = categories.get("compute", 0.0) + dur
            compute_ivs.append((ts, ts + dur))
        else:
            categories["other"] = categories.get("other", 0.0) + dur

    merged_compute = _merged_intervals(compute_ivs)
    coll_total = 0.0
    coll_exposed = 0.0
    per_span_coll: Dict[str, Dict[str, float]] = {}
    for s, t, path in coll_ops:
        dur = t - s
        exp = dur - _overlap(s, t, merged_compute)
        coll_total += dur
        coll_exposed += exp
        if path is not None:
            d = per_span_coll.setdefault(path, {"seconds": 0.0,
                                                "exposed_seconds": 0.0})
            d["seconds"] += dur
            d["exposed_seconds"] += exp

    us = 1e-6

    def sec(v):
        return round(v * us, 9)

    att = {
        "total_device_seconds": sec(total),
        "spans": {p: sec(v) for p, v in sorted(per_span.items())},
        "unattributed_seconds": sec(unattributed),
        "ambiguous_seconds": sec(ambiguous),
        "coverage_frac": round((total - unattributed) / total, 6)
        if total else 0.0,
        "device_op_count": len(ops),
        "span_window_count": len(wins),
        "categories_seconds": {k: sec(v)
                               for k, v in sorted(categories.items())},
        "collective": {
            "device_seconds": sec(coll_total),
            "exposed_seconds": sec(coll_exposed),
            "overlapped_seconds": sec(coll_total - coll_exposed),
            "exposed_fraction": round(coll_exposed / coll_total, 6)
            if coll_total else None,
            "per_span": {
                p: {"seconds": sec(d["seconds"]),
                    "exposed_seconds": sec(d["exposed_seconds"]),
                    "exposed_fraction": round(
                        d["exposed_seconds"] / d["seconds"], 6)
                    if d["seconds"] else None}
                for p, d in sorted(per_span_coll.items())},
        },
    }
    return att


def export_device_gauges(att: dict, registry) -> None:
    """Publish an attribution onto a registry: one
    ``device/span_seconds{span=}`` gauge per attributed span, plus
    ``device/unattributed_seconds`` and ``device/total_seconds`` — the
    device-true twins of the host-side ``span_seconds`` histograms,
    SLO-addressable like everything else."""
    for path, seconds in att.get("spans", {}).items():
        registry.gauge("device/span_seconds", span=path).set(seconds)
    registry.gauge("device/unattributed_seconds").set(
        att.get("unattributed_seconds", 0.0))
    registry.gauge("device/total_seconds").set(
        att.get("total_device_seconds", 0.0))
    coll = att.get("collective", {})
    if coll.get("exposed_fraction") is not None:
        registry.gauge("device/exposed_exchange_fraction").set(
            coll["exposed_fraction"])


def span_paths_from_snapshot(snapshot: dict) -> Optional[set]:
    """The span paths a registry snapshot (or a bench record carrying a
    ``metrics_snapshot``) has recorded — the ``span_seconds{span=}``
    histogram keys, parsed ONCE here for every consumer (the
    `attribute_logdir` registry path and the CLI's ``--snapshot``
    mode must never drift on the key format)."""
    snap = snapshot.get("metrics_snapshot", snapshot)
    paths = set()
    for key in snap.get("histograms", {}):
        m = re.match(r"^span_seconds\{span=(.+)\}$", key)
        if m:
            paths.add(m.group(1))
    return paths or None


def _registry_span_paths(registry) -> Optional[set]:
    if registry is None:
        return None
    return span_paths_from_snapshot(registry.snapshot())


def attribute_logdir(logdir: str, registry=None,
                     span_paths: Optional[set] = None) -> dict:
    """Parse the newest capture under `logdir` and attribute it. With a
    `registry`: the span window set defaults to the registry's recorded
    span paths and the ``device/*`` gauges are exported onto it.
    Returns the attribution dict (plus ``trace_file``)."""
    path = find_trace_file(logdir)
    if span_paths is None:
        span_paths = _registry_span_paths(registry)
    att = attribute_device_time(load_trace_events(path),
                                span_paths=span_paths)
    att["trace_file"] = os.path.basename(path)
    if registry is not None:
        export_device_gauges(att, registry)
    return att


def reconciliation_table(att: dict, projections: Dict[str, float],
                         tolerance_frac: float = 0.5) -> List[dict]:
    """Measured-vs-projection rows: for each perf_model projection
    ``{phase_or_span: projected_ms}``, find the measured per-span
    device milliseconds (exact span-path match, else substring match
    over attributed spans, else the total) and mark it ``settled``
    (within ``tolerance_frac`` relative) or ``falsified``. Rows with no
    measured signal are ``unmeasured`` — a projection the capture
    cannot speak to stays open rather than silently passing."""
    spans_ms = {p: s * 1e3 for p, s in att.get("spans", {}).items()}
    rows = []
    for phase, projected_ms in sorted(projections.items()):
        measured = spans_ms.get(phase)
        if measured is None:
            hits = [v for p, v in spans_ms.items() if phase in p]
            measured = sum(hits) if hits else None
        if measured is None and phase in ("total", "step"):
            measured = att.get("total_device_seconds", 0.0) * 1e3
        if measured is None or projected_ms is None:
            verdict = "unmeasured"
        else:
            rel = (abs(measured - float(projected_ms))
                   / max(abs(float(projected_ms)), 1e-9))
            verdict = "settled" if rel <= tolerance_frac else "falsified"
        rows.append({
            "phase": phase,
            "projected_ms": (round(float(projected_ms), 3)
                             if projected_ms is not None else None),
            "measured_ms": (round(measured, 3)
                            if measured is not None else None),
            "verdict": verdict,
        })
    return rows
