"""Process-local metric registry: ONE namespace for runtime telemetry
(ISSUE 11).

Six subsystems grew their own accounting — serving kept a private
latency histogram, the ingest pipeline its per-stage timings, the delta
consumer its staleness lists, the vocab manager its occupancy counters,
the lookahead engine its compile counts — and nothing could read,
export, or gate any of it in one place. `MetricRegistry` is that place:
named counters, gauges, and histograms with labeled families
(``table=``, ``group=``, ``stage=``), a point-in-time ``snapshot()``
dict every driver can embed (``bench.py`` records, ``fit`` history, the
tier-1 smoke), JSONL append export for soak runs, and a
Prometheus-style text dump for scraping.

`LatencyHistogram` — the geometric-bucket histogram `serving` and the
ingest pipeline always used — moved here and IS the registry's
histogram type (``utils.metrics`` re-exports it, so existing imports
are unchanged). Construction outside ``obs/`` is lint-banned
(``tools/lint_invariants.py`` rule ``shadow-metric``): components
obtain instruments through a registry, so a composed run has exactly
one metric namespace and no shadow accounting.

Sharing model: `MetricRegistry()` is instantiable — a component given
no registry creates a private one (per-instance accounting, the
historical behavior) — and `default_registry()` is the process-local
instance drivers use to unify a run (`training.fit` threads ONE
registry through the pipeline, engine, store, and vocab manager it
drives; `bench.py` stamps ``metrics_snapshot`` from the default
registry into every record). Instruments are plain Python objects
updated from host-side driver code only — nothing here may run under a
jit trace.
"""

import json
import os
import re
import threading
import time
from typing import Dict, Optional, Tuple

import numpy as np

__all__ = ["Counter", "Gauge", "LatencyHistogram", "MetricRegistry",
           "default_registry", "reset_default_registry", "metric_key"]


def metric_key(name: str, labels: Dict[str, object]) -> str:
    """Canonical flat key: ``name`` or ``name{k=v,...}`` with labels
    sorted — the snapshot/export key AND the address SLO rules use."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


class Counter:
    """Monotonic count (requests, admissions, publish bytes...)."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Dict[str, object]):
        self.name = name
        self.labels = dict(labels)
        self.value = 0

    def inc(self, n: int = 1) -> None:
        self.value += n


class Gauge:
    """Point-in-time value (occupancy, version lag, compile count...)."""

    __slots__ = ("name", "labels", "value")

    def __init__(self, name: str, labels: Dict[str, object]):
        self.name = name
        self.labels = dict(labels)
        self.value = 0.0

    def set(self, v: float) -> None:
        self.value = float(v)


class LatencyHistogram:
    """Geometric-bucket latency histogram with percentile estimates.

    O(1) `record`, fixed memory (`~bins_per_decade * decades` int64 slots),
    so a long-lived server can keep one per metric without unbounded
    per-request lists. Percentiles interpolate within the winning bucket —
    with the default 32 buckets/decade the edge-quantization error is
    < 7.5%, far below the run-to-run variance of real serving latencies.

    Usage (through a registry — direct construction is lint-banned
    outside ``obs/``):
      h = registry.histogram("serve/request_seconds")
      h.record(0.0123)                  # seconds
      h.percentile(99)                  # seconds
      h.summary()                       # {"count", "p50_ms", ...}
    """

    def __init__(self, lo: float = 1e-6, hi: float = 120.0,
                 bins_per_decade: int = 32):
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got {lo}, {hi}")
        self.lo = float(lo)
        decades = np.log10(hi / lo)
        self.bins = int(np.ceil(decades * bins_per_decade)) + 1
        self._ratio = 10.0 ** (1.0 / bins_per_decade)
        # edges[i] = lo * ratio^i; bucket i holds (edges[i-1], edges[i]]
        self._edges = lo * self._ratio ** np.arange(self.bins)
        self._counts = np.zeros((self.bins + 1,), np.int64)  # +overflow
        self._total = 0.0
        self._max = 0.0

    def record(self, seconds: float) -> None:
        s = max(float(seconds), 0.0)
        idx = int(np.searchsorted(self._edges, s, side="left"))
        self._counts[min(idx, self.bins)] += 1
        self._total += s
        self._max = max(self._max, s)

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold another histogram's counts into this one (in place;
        returns self for chaining). Lets per-rep/per-stage histograms
        aggregate into one distribution — e.g. the ingest bench's
        per-stage timings across interleaved repetitions — instead of
        only the last rep surviving. Bucket layouts must match exactly
        (same lo/hi/bins_per_decade): merging differently-edged
        histograms would silently misfile counts."""
        if (self.lo, self.bins, self._ratio) != (other.lo, other.bins,
                                                 other._ratio):
            raise ValueError(
                "cannot merge LatencyHistograms with different bucket "
                f"layouts: (lo={self.lo}, bins={self.bins}, "
                f"ratio={self._ratio}) vs (lo={other.lo}, "
                f"bins={other.bins}, ratio={other._ratio})")
        self._counts += other._counts
        self._total += other._total
        self._max = max(self._max, other._max)
        return self

    @property
    def count(self) -> int:
        return int(self._counts.sum())

    def percentile(self, p: float) -> float:
        """The p-th percentile (0..100) in seconds; 0.0 when empty."""
        n = self.count
        if n == 0:
            return 0.0
        rank = np.ceil(n * min(max(p, 0.0), 100.0) / 100.0)
        cum = np.cumsum(self._counts)
        idx = int(np.searchsorted(cum, max(rank, 1)))
        if idx >= self.bins:
            return self._max
        hi = self._edges[idx]
        lo = self._edges[idx - 1] if idx else 0.0
        # linear interpolation inside the bucket by rank position, capped
        # by the true max so a wide top bucket cannot report p99 > max
        prev = cum[idx - 1] if idx else 0
        frac = (rank - prev) / max(self._counts[idx], 1)
        return float(min(lo + (hi - lo) * frac, self._max))

    def summary(self) -> dict:
        n = self.count
        return {
            "count": n,
            "mean_ms": round(self._total / n * 1e3, 3) if n else 0.0,
            "p50_ms": round(self.percentile(50) * 1e3, 3),
            "p95_ms": round(self.percentile(95) * 1e3, 3),
            "p99_ms": round(self.percentile(99) * 1e3, 3),
            "max_ms": round(self._max * 1e3, 3),
        }


_LabelKey = Tuple[Tuple[str, object], ...]


class MetricRegistry:
    """Named counters/gauges/histograms with labeled families.

    ``counter(name, **labels)`` (and gauge/histogram) returns the ONE
    instrument for that (name, labels) — repeated calls are a lookup,
    so components can resolve their instruments per event without
    holding references. Kinds live in separate namespaces (requesting a
    gauge where a counter exists raises: one name means one thing).
    For histograms the first creation's bucket layout wins; a later
    request with a different layout raises rather than silently
    misfiling.

    Instrument updates are single-writer-cheap plain attribute writes;
    the registry's own map is lock-protected so pipeline worker threads
    can resolve instruments concurrently.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, str, _LabelKey], object] = {}

    def _resolve(self, kind: str, name: str, labels: dict, factory):
        key = (kind, name, tuple(sorted(labels.items())))
        with self._lock:
            for other in ("counter", "gauge", "histogram"):
                if other != kind and (other, name,
                                      key[2]) in self._metrics:
                    raise ValueError(
                        f"metric {metric_key(name, labels)!r} already "
                        f"registered as a {other}, requested as {kind}")
            m = self._metrics.get(key)
            if m is None:
                m = factory()
                self._metrics[key] = m
            return m

    def counter(self, name: str, **labels) -> Counter:
        return self._resolve("counter", name, labels,
                             lambda: Counter(name, labels))

    def gauge(self, name: str, **labels) -> Gauge:
        return self._resolve("gauge", name, labels,
                             lambda: Gauge(name, labels))

    def histogram(self, name: str, lo: float = 1e-6, hi: float = 120.0,
                  bins_per_decade: int = 32, **labels) -> LatencyHistogram:
        h = self._resolve("histogram", name, labels,
                          lambda: LatencyHistogram(
                              lo=lo, hi=hi,
                              bins_per_decade=bins_per_decade))
        # full layout check (lo, ratio AND bin count — bins derive from
        # hi, so a differing hi alone must also refuse): the same triple
        # merge() guards on
        want_bins = int(np.ceil(np.log10(hi / lo) * bins_per_decade)) + 1
        if (h.lo, h.bins, h._ratio) != (float(lo), want_bins,
                                        10.0 ** (1.0 / bins_per_decade)):
            raise ValueError(
                f"histogram {metric_key(name, labels)!r} exists with a "
                "different bucket layout (first creation wins; merging "
                "layouts would misfile counts)")
        return h

    # ------------------------------------------------------------ views
    def _by_kind(self, kind: str):
        with self._lock:
            items = [(name, key_labels, m) for (k, name, key_labels), m
                     in self._metrics.items() if k == kind]
        return sorted(items, key=lambda t: (t[0], t[1]))

    def snapshot(self) -> dict:
        """Point-in-time dict of every instrument: ``{"counters":
        {key: int}, "gauges": {key: float}, "histograms": {key:
        summary-dict}}`` with ``name{label=value,...}`` flat keys —
        the schema `obs.slo` rules address and bench records embed."""
        return {
            "counters": {metric_key(n, dict(kl)): m.value
                         for n, kl, m in self._by_kind("counter")},
            "gauges": {metric_key(n, dict(kl)): m.value
                       for n, kl, m in self._by_kind("gauge")},
            "histograms": {metric_key(n, dict(kl)): m.summary()
                           for n, kl, m in self._by_kind("histogram")},
        }

    def export_jsonl(self, path: str, extra: Optional[dict] = None,
                     fsync: bool = False) -> dict:
        """Append one timestamped snapshot line to `path` (creating it);
        the soak-run export format: one JSON object per line, so a
        watcher can tail it and `obs.slo.evaluate_rules` can window
        over the parsed lines. Returns the line's dict.

        The line is FLUSHED to the OS before the file closes — a
        crashed soak must not lose the tail lines its SLO window
        evaluates over (the postmortem reads the last written step).
        ``fsync=True`` additionally fsyncs, for the final/explicit
        export of a run (per-line fsync would put a disk barrier on the
        snapshot cadence; per-line flush already survives a process
        crash, and the closing export survives power loss)."""
        line = {"ts": round(time.time(), 3), **(extra or {}),
                **self.snapshot()}
        with open(path, "a") as f:
            f.write(json.dumps(line) + "\n")
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        return line

    def to_prometheus(self) -> str:
        """Prometheus text exposition of the registry: counters as
        ``*_total``, gauges verbatim, histograms as summaries
        (quantile series + ``_count``/``_sum``). Metric names sanitize
        ``/`` and other non-identifier characters to ``_``; label
        VALUES escape per the text-format spec (backslash, double
        quote, newline) — degraded reasons and quarantine paths put
        arbitrary filesystem strings into labels, and one unescaped
        quote makes the whole exposition unparseable."""
        def sane(name: str) -> str:
            return re.sub(r"[^a-zA-Z0-9_:]", "_", name)

        def esc(value: object) -> str:
            # the exposition-format escape set, in spec order:
            # backslash first (or the others' escapes double-escape)
            return (str(value).replace("\\", "\\\\")
                    .replace('"', '\\"').replace("\n", "\\n"))

        def fmt_labels(labels: dict, extra: Optional[dict] = None) -> str:
            merged = {**labels, **(extra or {})}
            if not merged:
                return ""
            inner = ",".join(f'{sane(str(k))}="{esc(merged[k])}"'
                             for k in sorted(merged))
            return "{" + inner + "}"

        out = []
        for name, kl, m in self._by_kind("counter"):
            mn = sane(name) + "_total"
            out.append(f"# TYPE {mn} counter")
            out.append(f"{mn}{fmt_labels(dict(kl))} {m.value}")
        for name, kl, m in self._by_kind("gauge"):
            mn = sane(name)
            out.append(f"# TYPE {mn} gauge")
            out.append(f"{mn}{fmt_labels(dict(kl))} {m.value}")
        for name, kl, m in self._by_kind("histogram"):
            mn = sane(name)
            labels = dict(kl)
            out.append(f"# TYPE {mn} summary")
            for q in (0.5, 0.95, 0.99):
                v = m.percentile(q * 100)
                out.append(f"{mn}{fmt_labels(labels, {'quantile': q})} "
                           f"{v:.9f}")
            out.append(f"{mn}_count{fmt_labels(labels)} {m.count}")
            out.append(f"{mn}_sum{fmt_labels(labels)} {m._total:.9f}")
        return "\n".join(out) + ("\n" if out else "")


_default_lock = threading.Lock()
_default: Optional[MetricRegistry] = None


def default_registry() -> MetricRegistry:
    """The process-local registry drivers share (`bench.py` snapshot
    stamping, the tier-1 obs smoke). Long-lived processes composing
    several independent runs should create per-run `MetricRegistry`
    instances instead — counts here accumulate for the process
    lifetime (that is the point)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = MetricRegistry()
        return _default


def reset_default_registry() -> None:
    """Drop the process-local registry (tests)."""
    global _default
    with _default_lock:
        _default = None
