"""Per-process input staging for multi-host training.

The reference's data path is per-rank: each Horovod process reads its own
batch shard (dp input) or its own features (mp input) straight from disk
(reference examples/dlrm/utils.py:260-266). Under SPMD the analogous
contract is: each process loads only its local slice as numpy, and these
helpers assemble the global-view `jax.Array`s the jitted step consumes —
no cross-host gathering of input data, ever.
"""

from typing import Any, Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.parallel.mesh import DEFAULT_AXIS

__all__ = ["stage_dp_batch", "stage_replicated"]


def stage_dp_batch(mesh: Mesh, batch: Any,
                   axis_name: Optional[str] = None) -> Any:
    """Assemble batch-sharded global arrays from process-local shards.

    Args:
      mesh: the 1-D device mesh.
      batch: pytree of numpy/jax arrays, each this process's batch slice
        [B_local, ...] (B_local = global_batch / process_count).

    Returns the same pytree as global jax.Arrays sharded P(axis) over dim 0.
    Single-process: a plain sharded device_put.
    """
    axis = axis_name or mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))

    def stage(x):
        x = np.asarray(x)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(stage, batch)


def stage_replicated(mesh: Mesh, tree: Any) -> Any:
    """Replicate per-process identical arrays (labels of a shared eval set,
    hyperparameter tensors) across the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(np.asarray(x), sharding),
                        tree)
