"""Per-process input staging for multi-host training.

The reference's data path is per-rank: each Horovod process reads its own
batch shard (dp input) or its own features (mp input) straight from disk
(reference examples/dlrm/utils.py:260-266). Under SPMD the analogous
contract is: each process loads only its local slice as numpy, and these
helpers assemble the global-view `jax.Array`s the jitted step consumes —
no cross-host gathering of input data, ever.
"""

from typing import Any, Optional

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.parallel.mesh import DEFAULT_AXIS

__all__ = ["stage_dp_batch", "stage_replicated", "DoubleBufferSlots"]


def stage_dp_batch(mesh: Mesh, batch: Any,
                   axis_name: Optional[str] = None) -> Any:
    """Assemble batch-sharded global arrays from process-local shards.

    Args:
      mesh: the 1-D device mesh.
      batch: pytree of numpy/jax arrays, each this process's batch slice
        [B_local, ...] (B_local = global_batch / process_count).

    Returns the same pytree as global jax.Arrays sharded P(axis) over dim 0.
    Single-process: a plain sharded device_put.
    """
    axis = axis_name or mesh.axis_names[0]
    sharding = NamedSharding(mesh, P(axis))

    def stage(x):
        x = np.asarray(x)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(stage, batch)


def stage_replicated(mesh: Mesh, tree: Any) -> Any:
    """Replicate per-process identical arrays (labels of a shared eval set,
    hyperparameter tensors) across the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree.map(lambda x: jax.device_put(np.asarray(x), sharding),
                        tree)


class DoubleBufferSlots:
    """Two-slot device carry for the lookahead pipeline (ISSUE 9).

    The `schedule.LookaheadEngine` keeps one batch's prefetched exchange
    artifacts on device while the fused step produces the next batch's —
    a classic double buffer. This helper owns the slot discipline:

      * `stage(tree, tag)` installs a freshly produced carry (and returns
        the evicted one, if any — with step donation on, that pytree's
        buffers were CONSUMED by the producing call and must not be
        touched again; holding it only here makes accidental host reuse
        structurally visible).
      * `current` / `tag` read the live slot; `take()` pops it for the
        consuming call (the donation hand-off point).
      * `clear()` invalidates both slots (pipeline flush — e.g. params
        were rewritten outside the engine and every prefetch is stale).

    Tags are opaque identities (the engine uses the upcoming batch
    object) so a consumer can verify the staged carry belongs to the
    batch it is about to run.
    """

    def __init__(self):
        self._live = None        # (tag, tree)
        self._retired = None     # previous (tag, tree), donation-dead

    def stage(self, tree: Any, tag: Any = None) -> Optional[Any]:
        """Install `tree` as the live carry; returns the evicted tree."""
        evicted = self._retired[1] if self._retired is not None else None
        self._retired = self._live
        self._live = (tag, tree)
        return evicted

    @property
    def current(self) -> Optional[Any]:
        return self._live[1] if self._live is not None else None

    @property
    def tag(self) -> Optional[Any]:
        return self._live[0] if self._live is not None else None

    def take(self) -> Optional[Any]:
        """Pop the live carry for consumption (it moves to the retired
        slot: its buffers may be donated by the consuming call)."""
        if self._live is None:
            return None
        tag_tree = self._live
        self._retired = tag_tree
        self._live = None
        return tag_tree[1]

    def clear(self) -> None:
        self._live = None
        self._retired = None
