"""Sharding planner: decides where every embedding table (or slice) lives.

Behavioral port of the reference planner `DistEmbeddingStrategy`
(reference: distributed_embeddings/python/layers/dist_model_parallel.py:301-709).
Every rank computes the identical global plan deterministically — which on TPU
becomes simply: the plan is trace-time Python constants baked into one SPMD
program. The planner is pure Python over config dicts (the same "config IR"
idea as the reference, which manipulates keras get_config() dicts).

Groups (reference :479-495):
  group 0 — data-parallel: tables with <= data_parallel_threshold elements,
            replicated on every device.
  group 1 — column-slice + table-parallel (the core): tables optionally split
            along output_dim into power-of-2 slices, then whole slices placed
            onto devices by one of three strategies.
  group 2 — row-slice: tables with >= row_slice_threshold elements, split
            evenly along input_dim across *all* devices.
"""

import dataclasses
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple

# the float wire formats of the exchange collectives (ISSUE 5) live in
# ops/wire.py — the planner only records the REQUEST (validated against
# that one registry), and lowering (parallel/plan.py) gates it per bucket
from distributed_embeddings_tpu.ops.wire import (
    WIRE_FORMATS as EXCHANGE_WIRE_FORMATS, default_exchange_wire,
    default_store_dtype, resolve_store_dtype)
from distributed_embeddings_tpu.utils.initializers import ConcatInitializer

Config = Dict[str, Any]


def default_vocab_slack() -> int:
    """The `DET_VOCAB_SLACK` environment default for dynamic-vocabulary
    slack (ISSUE 7): extra physical rows pre-reserved per model-parallel
    table beyond its configured input_dim. Slack rows are dead weight to
    a static-vocab model but are what lets a `vocab.VocabManager` ADMIT
    new keys at runtime without changing any array shape (and therefore
    without recompiling the jitted step): admission binds a key to a
    pre-allocated free row, eviction frees one. 0 (the default) reserves
    nothing — plans are bit-identical to pre-slack plans; an explicit
    ``vocab_slack=`` argument always wins."""
    try:
        return max(0, int(os.environ.get("DET_VOCAB_SLACK", "0")))
    except ValueError:
        return 0


def default_hot_rows() -> int:
    """The `DET_HOT_ROWS` environment default for hot-row replication
    (rows per model-parallel bucket whose top-H hottest rows are
    replicated data-parallel in the training step — see
    layers/dist_model_parallel.py). 0 (the default) disables the hot
    shard; an explicit ``hot_rows=`` argument always wins."""
    from distributed_embeddings_tpu.tune import resolve as _tune_resolve
    try:
        return max(0, int(_tune_resolve.knob_value("DET_HOT_ROWS", "0")))
    except ValueError:
        return 0


def _table_size(config: Config) -> int:
    return config["input_dim"] * config["output_dim"]


def _stable_argsort(values, key=None, reverse=False):
    if key is None:
        key = lambda v: v
    order = sorted(range(len(values)), key=lambda i: key(values[i]), reverse=reverse)
    return [values[i] for i in order], order


class DistEmbeddingStrategy:
    """Computes the global placement plan for a list of embedding tables.

    Args / attributes mirror the reference class (dist_model_parallel.py:301-345)
    so that user code written against the reference maps one-to-one.
    """

    def __init__(self,
                 embeddings: Sequence,
                 world_size: int,
                 strategy: str = "basic",
                 input_table_map: Optional[Sequence[int]] = None,
                 column_slice_threshold: Optional[int] = None,
                 row_slice_threshold: Optional[int] = None,
                 data_parallel_threshold: Optional[int] = None,
                 gpu_embedding_size: Optional[int] = None,
                 input_hotness: Optional[Sequence[Optional[int]]] = None,
                 hot_rows: Optional[int] = None,
                 exchange_wire: Optional[str] = None,
                 vocab_slack: Optional[int] = None,
                 storage_dtype: Optional[str] = None):
        if strategy not in ("auto", "basic", "memory_balanced",
                            "memory_optimized", "comm_balanced"):
            raise ValueError(f"Unsupported shard strategy {strategy}")
        if strategy == "auto":
            # multi-hot models (any hotness hint > 1) pay real exchange
            # padding — minimize it; one-hot models exchange exactly one id
            # per feature, so placement only matters for memory -> the
            # reference's default ('basic', :345)
            strategy = ("comm_balanced"
                        if input_hotness is not None
                        and any((h or 1) > 1 for h in input_hotness)
                        else "basic")
        # single process: plan degenerates like the reference (:357)
        self.strategy = "basic" if world_size == 1 else strategy
        self.world_size = world_size
        self.column_slice_threshold = column_slice_threshold
        self.row_slice_threshold = row_slice_threshold
        self.data_parallel_threshold = data_parallel_threshold
        self.gpu_embedding_size = gpu_embedding_size
        # hot-row replication capacity (rows per MP bucket); None defers
        # to the DET_HOT_ROWS environment default. Eligibility per bucket
        # (combiner, offload, key-space bounds) is decided at lowering
        # time (parallel/plan.py lower_strategy).
        self.hot_rows = (default_hot_rows() if hot_rows is None
                         else max(0, int(hot_rows)))
        # float exchange-wire request (ISSUE 5); None defers to the
        # DET_EXCHANGE_WIRE environment default. Per-bucket eligibility
        # (combiner, offload) is decided at lowering time
        # (parallel/plan.py lower_strategy), like hot_rows above.
        if exchange_wire is None:
            exchange_wire = default_exchange_wire()
        elif exchange_wire not in EXCHANGE_WIRE_FORMATS:
            raise ValueError(
                f"exchange_wire={exchange_wire!r}: expected one of "
                f"{EXCHANGE_WIRE_FORMATS}")
        self.exchange_wire = exchange_wire
        # at-rest row storage request (ISSUE 15); None defers to the
        # DET_STORE_DTYPE environment default. Per-bucket eligibility
        # (only cold/offloaded buckets quantize — hot HBM shards stay
        # f32) is decided at lowering time, like exchange_wire above.
        self.storage_dtype = (default_store_dtype() if storage_dtype is None
                              else resolve_store_dtype(storage_dtype))

        self.global_configs = []
        for emb in embeddings:
            cfg = dict(emb.get_config())
            cfg["layer_class"] = type(emb)
            self.global_configs.append(cfg)
        if input_table_map is None:
            input_table_map = list(range(len(self.global_configs)))
        self.input_table_map = list(input_table_map)
        # optional per-input hotness hints (comm_balanced placement): None
        # entries / no list at all degrade to hotness-1 assumptions
        if input_hotness is not None and \
                len(input_hotness) != len(self.input_table_map):
            raise ValueError(
                f"input_hotness has {len(input_hotness)} entries but there "
                f"are {len(self.input_table_map)} inputs")
        self.input_hotness = (list(input_hotness)
                              if input_hotness is not None
                              else [None] * len(self.input_table_map))

        self.table_groups = self.init_table_groups(self.global_configs)
        # dynamic-vocabulary slack (ISSUE 7): inflate every table-parallel
        # (group 1) table by `vocab_slack` pre-reserved rows AFTER the
        # dp/col/row grouping (so grouping thresholds keep their configured
        # meaning) and BEFORE slicing/fusion/lowering (so every downstream
        # structure — column slices, concat fusion, bucket rows_max, init
        # segments, weight placements, id-wire proofs — sees the physical
        # capacity). `vocab_base_rows` keeps the configured vocab so the
        # vocab manager knows where the build rows end. dp tables
        # (replicated, dense-trained) and row-sliced tables are not
        # managed and keep their exact configured shapes.
        # NOTE: slack is PHYSICAL rows, so it counts toward the
        # gpu_embedding_size offload budget like any other row — a big
        # slack can push a table over the budget into host offload,
        # where the vocab manager refuses to manage it (its slack then
        # sits unusable in host RAM and the padding report counts it as
        # dead capacity). Budget slack per table when offload budgets
        # are in play.
        self.vocab_slack = (default_vocab_slack() if vocab_slack is None
                            else max(0, int(vocab_slack)))
        if self.vocab_slack:
            for i in self.table_groups[1]:
                cfg = self.global_configs[i]
                cfg["vocab_base_rows"] = cfg["input_dim"]
                cfg["vocab_slack"] = self.vocab_slack
                cfg["input_dim"] += self.vocab_slack
        (self.input_groups, self.map_groups,
         self.rev_group_ids) = self.init_input_and_map_groups(
            self.table_groups, self.input_table_map)

        # group 0: data parallel
        self.dp_configs = [self.global_configs[i] for i in self.table_groups[0]]

        # group 2: row slice
        if self.table_groups[2]:
            self.row_sliced_configs, self.row_inputs_offsets = (
                self.create_row_sliced_configs(
                    [self.global_configs[i] for i in self.table_groups[2]],
                    world_size))
        else:
            self.row_sliced_configs = [[] for _ in range(world_size)]
            self.row_inputs_offsets = [[] for _ in range(world_size)]

        # group 1: column slice + table parallel
        self.sliced_out_ranges: List[List[int]] = []
        self.input_ids_list: List[List[int]] = []
        self.local_maps: List[List[int]] = []
        self.local_configs: List[List[Config]] = []
        self.local_input_offsets: List[List[int]] = []
        self.local_weight_offsets: List[List[List[int]]] = []
        self.local_group_list: List[List[List[int]]] = []
        self.table_ids: List[List[int]] = []
        # per-rank slice configs after merge+offload, before concat fusion —
        # the SPMD lowering (parallel/plan.py) builds its stacked buckets and
        # weight-placement records from these.
        self.local_preconcat_configs: List[List[Config]] = []
        self.widths_list_flat: List[int] = []
        self.rev_tp_ids: List[int] = []
        if not self.table_groups[1]:
            return

        sliced_configs, self.sliced_out_ranges = self.create_col_sliced_configs(
            [self.global_configs[i] for i in self.table_groups[1]],
            world_size, self.column_slice_threshold, self.map_groups[1])

        divided_ids = self.apply_strategy(self.strategy, world_size, sliced_configs)

        # every rank computes the full global view (reference :407-434)
        for rank_table_ids in divided_ids:
            rank_table_ids, rank_configs = self._merge_slices(rank_table_ids,
                                                              sliced_configs)
            self.table_ids.append(rank_table_ids)

            rank_input_ids, rank_input_map = [], []
            for local_pos, table_idx in enumerate(rank_table_ids):
                for inp_pos, mapped_idx in enumerate(self.map_groups[1]):
                    if table_idx == mapped_idx:
                        rank_input_ids.append(inp_pos)
                        rank_input_map.append(local_pos)

            rank_configs = self._maybe_offload(rank_configs)
            self.local_preconcat_configs.append([dict(c) for c in rank_configs])
            (rank_configs, rank_input_map, input_offsets, group,
             weight_offsets) = self._create_concat(rank_configs, rank_input_map)

            self.input_ids_list.append(rank_input_ids)
            self.local_configs.append(rank_configs)
            self.local_maps.append(rank_input_map)
            self.local_input_offsets.append(input_offsets)
            self.local_group_list.append(group)
            self.local_weight_offsets.append(weight_offsets)

        for configs, input_map in zip(self.local_configs, self.local_maps):
            self.widths_list_flat += [configs[m]["output_dim"] for m in input_map]

        worker_order = [i for rank_ids in self.input_ids_list for i in rank_ids]
        self.rev_tp_ids = [
            pos for _, pos in sorted(zip(worker_order, range(len(worker_order))))
        ]

    # ---------------------------------------------------------------- groups
    def init_table_groups(self, configs: Sequence[Config]) -> List[List[int]]:
        """Partition tables into [dp, col, row] id groups by element count
        (reference :479-495)."""
        dp, col, row = [], [], []
        for i, config in enumerate(configs):
            n = _table_size(config)
            if self.data_parallel_threshold and n <= self.data_parallel_threshold:
                dp.append(i)
            elif self.row_slice_threshold and n >= self.row_slice_threshold:
                row.append(i)
            else:
                col.append(i)
        return [dp, col, row]

    def init_input_and_map_groups(self, table_groups, input_table_map):
        """Split inputs along the same grouping; compute reorder indices to
        restore original input order (reference :497-516)."""
        dp, col, row = table_groups
        inputs = [[], [], []]
        maps = [[], [], []]
        for inp_pos, table_idx in enumerate(input_table_map):
            for gid, group in enumerate((dp, col, row)):
                if table_idx in group:
                    inputs[gid].append(inp_pos)
                    maps[gid].append(group.index(table_idx))
                    break
            else:
                raise ValueError("input_table_map entry matches no table group")
        flat = inputs[0] + inputs[1] + inputs[2]
        rev = [pos for _, pos in sorted(zip(flat, range(len(flat))))]
        return inputs, maps, rev

    # ------------------------------------------------------------- col slice
    def maybe_slice_table_column(self, orig_config: Config,
                                 column_slice_threshold: Optional[int],
                                 world_size: int) -> List[Config]:
        """Split a table along output_dim into the smallest power-of-2 number
        of even slices that puts each slice under the threshold, capped at
        min(N, world_size, output_dim) (reference :518-549)."""
        if column_slice_threshold is None:
            column_slice_threshold = float("inf")
        size = _table_size(orig_config)
        num_slices = 1
        while size > column_slice_threshold:
            num_slices *= 2
            size /= 2
        if num_slices == 1:
            return [dict(orig_config)]
        num_slices = min(num_slices, world_size, orig_config["output_dim"])
        base = orig_config["output_dim"] // num_slices
        rem = orig_config["output_dim"] % num_slices
        slices = []
        for i in range(num_slices):
            cfg = dict(orig_config)
            cfg["output_dim"] = base + (1 if i < rem else 0)
            slices.append(cfg)
        return slices

    def create_col_sliced_configs(self, global_col_configs, world_size,
                                  column_slice_threshold, input_table_map):
        """Maybe-slice every col-group table; also compute which output ranges
        must be re-concatenated after the exchange (reference :551-586).

        When there are fewer tables than workers and no explicit threshold,
        auto-pick a threshold by repeatedly halving the largest table until
        there are at least world_size slices (reference :567-573).
        """
        if column_slice_threshold is None:
            sizes = [_table_size(c) for c in global_col_configs]
            while world_size > len(sizes):
                sizes.sort()
                column_slice_threshold = sizes[-1] - 1
                largest = sizes.pop()
                sizes += [largest // 2, largest // 2]

        sliced_configs = [
            self.maybe_slice_table_column(cfg, column_slice_threshold, world_size)
            for cfg in global_col_configs
        ]

        sliced_out_ranges = []
        for input_id, table_id in enumerate(input_table_map):
            if len(sliced_configs[table_id]) > 1:
                sliced_out_ranges.append(
                    [input_id, input_id + len(sliced_configs[table_id])])
        return sliced_configs, sliced_out_ranges

    # ------------------------------------------------------------- row slice
    def create_row_sliced_configs(self, global_row_configs, world_size):
        """Evenly split each row-group table along input_dim across all ranks;
        offsets are the (negative) global row base so that
        `global_id + offset` is the local row, OOB for non-owned rows
        (reference :588-609)."""
        per_table_configs, per_table_offsets = [], []
        for orig in global_row_configs:
            base = orig["input_dim"] // world_size
            rem = orig["input_dim"] % world_size
            configs, offsets, cursor = [], [], 0
            for i in range(world_size):
                cfg = dict(orig)
                cfg["input_dim"] = base + (1 if i < rem else 0)
                configs.append(cfg)
                offsets.append(cursor)
                cursor -= cfg["input_dim"]
            per_table_configs.append(configs)
            per_table_offsets.append(offsets)
        # transpose to rank-major
        by_rank_configs = [list(t) for t in zip(*per_table_configs)]
        by_rank_offsets = [list(t) for t in zip(*per_table_offsets)]
        return by_rank_configs, by_rank_offsets

    # -------------------------------------------------------------- strategy
    def apply_strategy(self, mode: str, world_size: int,
                       sliced_configs) -> List[List[int]]:
        """Assign table slices to ranks (reference :612-648).

        Returns per-rank lists of table ids (indices into the col group);
        a table id appears once per slice assigned to that rank.
        """
        flat_ids, flat_sizes = [], []
        for table_id, slices in enumerate(sliced_configs):
            for cfg in slices:
                flat_ids.append(table_id)
                flat_sizes.append(_table_size(cfg))

        if mode == "basic":
            return [flat_ids[r::world_size] for r in range(world_size)]

        if mode == "memory_balanced":
            ordered = [tid for _, tid in
                       sorted(zip(flat_sizes, flat_ids), reverse=True)]
            return [
                ordered[r::2 * world_size]
                + ordered[(2 * world_size - 1 - r)::2 * world_size]
                for r in range(world_size)
            ]

        if mode == "memory_optimized":
            # greedy: hand the largest remaining slice to the least-loaded rank
            remaining = sorted(zip(flat_sizes, flat_ids))
            bins: List[List[Any]] = [[0, []] for _ in range(world_size)]
            while remaining:
                size, tid = remaining.pop()
                bins[0][0] += size
                bins[0][1].append(tid)
                bins = sorted(bins)
            return [b[1] for b in bins]

        if mode == "comm_balanced":
            return self._comm_balanced(world_size, sliced_configs)

        raise ValueError(f"Unsupported strategy {mode}")

    def _comm_balanced(self, world_size: int,
                       sliced_configs) -> List[List[int]]:
        """Beyond-reference placement: minimize exchange-volume padding.

        The runtime exchanges one dense [world, B, f_max, k] block per
        (width, combiner, hotness) class, where f_max is the MAX per-rank
        feature count in the class — so per-destination id traffic is
        world x f_max x k regardless of how few features the other ranks
        own (see layers/dist_model_parallel.py exchange groups). The
        size-only reference strategies leave 2.5-5x padding on the
        synthetic zoo; this greedy pass assigns each slice (largest first)
        to the rank where it increases Σ_class k·f_max the least, with
        per-rank bytes as the tie-break (memory_balanced's objective).
        Hotness comes from `input_hotness` hints (unhinted inputs count
        as hotness 1).
        """
        table_ks: List[List[int]] = [[] for _ in sliced_configs]
        for inp_pos, tidx in enumerate(self.map_groups[1]):
            orig = self.input_groups[1][inp_pos]
            table_ks[tidx].append(self.input_hotness[orig] or 1)

        flat = []
        for tid, slices in enumerate(sliced_configs):
            for cfg in slices:
                flat.append((_table_size(cfg), tid, cfg))
        flat.sort(key=lambda t: t[0], reverse=True)

        counts: List[Dict] = [{} for _ in range(world_size)]
        bytes_ = [0] * world_size
        out: List[List[int]] = [[] for _ in range(world_size)]
        cls_max: Dict = {}
        for size, tid, cfg in flat:
            tally: Dict = {}
            for k in (table_ks[tid] or [1]):
                c = (cfg["output_dim"], cfg.get("combiner"), k)
                tally[c] = tally.get(c, 0) + 1
            best, best_cost = 0, None
            for r in range(world_size):
                pad = sum(
                    c[2] * max(0, counts[r].get(c, 0) + n
                               - cls_max.get(c, 0))
                    for c, n in tally.items())
                cost = (pad, bytes_[r], len(out[r]))
                if best_cost is None or cost < best_cost:
                    best, best_cost = r, cost
            for c, n in tally.items():
                counts[best][c] = counts[best].get(c, 0) + n
                cls_max[c] = max(cls_max.get(c, 0), counts[best][c])
            bytes_[best] += size
            out[best].append(tid)
        return out

    # --------------------------------------------------------------- offload
    def _maybe_offload(self, configs: List[Config]) -> List[Config]:
        """Flag the largest tables for host offload so the on-device total
        stays within gpu_embedding_size (reference :449-476). On TPU this
        drives host-memory placement rather than /CPU:0 device scope."""
        configs = [dict(c) for c in configs]
        if self.gpu_embedding_size is None:
            for c in configs:
                c["cpu_offload"] = False
            return configs
        total = 0
        _, order = _stable_argsort(configs, key=_table_size)
        for idx in order:
            total += _table_size(configs[idx])
            configs[idx]["cpu_offload"] = total > self.gpu_embedding_size
        return configs

    # ---------------------------------------------------------------- concat
    def _create_concat(self, table_configs: List[Config], input_maps: List[int]):
        """Fuse a rank's same-width same-combiner tables into one tall table
        (reference :651-691). On TPU this is doubly important: it is also what
        makes the stacked SPMD parameterization dense (one gather per bucket).
        """
        grouped_ids: List[List[int]] = []
        concat_configs: List[Config] = []
        for table_id, config in enumerate(table_configs):
            merged = False
            for group, ccfg in zip(grouped_ids, concat_configs):
                if (config["output_dim"] == ccfg["output_dim"]
                        and config.get("combiner") == ccfg.get("combiner")
                        and not (config["cpu_offload"] or ccfg["cpu_offload"])):
                    group.append(table_id)
                    ccfg["input_dim"] += config["input_dim"]
                    ccfg["input_dims"].append(config["input_dim"])
                    ccfg["offsets"].append(ccfg["offsets"][-1] + config["input_dim"])
                    merged = True
                    break
            if not merged:
                cfg = dict(config)
                cfg["input_dims"] = [config["input_dim"]]
                cfg["offsets"] = [0, config["input_dim"]]
                grouped_ids.append([table_id])
                concat_configs.append(cfg)

        new_input_map, input_offsets = [], []
        for m in input_maps:
            for gid, (group, ccfg) in enumerate(zip(grouped_ids, concat_configs)):
                if m in group:
                    new_input_map.append(gid)
                    input_offsets.append(ccfg["offsets"][group.index(m)])
                    break

        for ccfg in concat_configs:
            input_dims = ccfg.pop("input_dims")
            if len(input_dims) > 1 and "embeddings_initializer" in ccfg:
                ccfg["embeddings_initializer"] = ConcatInitializer(
                    ccfg["embeddings_initializer"], input_dims)

        weight_offsets = [ccfg.pop("offsets", None) for ccfg in concat_configs]
        return concat_configs, new_input_map, input_offsets, grouped_ids, weight_offsets

    # ----------------------------------------------------------- slice merge
    def _merge_slices(self, rank_table_ids: List[int], sliced_configs):
        """Re-merge column slices of the same table that landed on one rank
        (reference :694-709). Consumes slices from sliced_configs in rank
        visit order, so column ranges are rank-ordered."""
        merged_ids: List[int] = []
        rank_configs: List[Config] = []
        for table_idx in rank_table_ids:
            if table_idx in merged_ids:
                extra = sliced_configs[table_idx].pop(0)
                pos = merged_ids.index(table_idx)
                rank_configs[pos] = dict(rank_configs[pos])
                rank_configs[pos]["output_dim"] += extra["output_dim"]
                for out_range in self.sliced_out_ranges:
                    if out_range[0] == table_idx:
                        out_range[-1] -= 1
            else:
                merged_ids.append(table_idx)
                rank_configs.append(sliced_configs[table_idx].pop(0))
        return merged_ids, rank_configs
