"""Mesh helpers.

The reference's communication bootstrap is `hvd.init()` + one-GPU-per-process
pinning (reference examples/dlrm/main.py:152-157). The TPU equivalent is a
`jax.sharding.Mesh`: a single axis (default name "mp") plays both the
data-parallel and model-parallel role, exactly like the reference where
dp ranks == mp ranks (dist_model_parallel.py:757-762). Multi-host pods just
need `jax.distributed.initialize()` before building the mesh; the collectives
ride ICI within a slice and DCN across slices based on device order.
"""

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

DEFAULT_AXIS = "mp"


def create_mesh(devices: Optional[Sequence] = None, axis_name: str = DEFAULT_AXIS) -> Mesh:
    """Create a 1-D mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def default_mesh(axis_name: str = DEFAULT_AXIS) -> Mesh:
    return create_mesh(axis_name=axis_name)


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host bootstrap: the TPU analogue of `hvd.init()` + MPI env
    discovery (reference dist_model_parallel.py:759-762, dlrm/main.py:152).

    On TPU pods with standard launchers (GKE, gcloud, xmanager) all
    arguments auto-discover; pass them explicitly for bare-metal setups.
    Safe to call more than once (subsequent calls no-op). After this,
    `create_mesh()` spans every chip in the pod: jax device order puts
    ICI-connected chips of a slice adjacent, so the 1-D axis's collectives
    ride ICI within a slice and DCN across slices — the layout the
    scaling-book recipe prescribes for a single combined dp/mp axis.
    """
    # NOTE: must not touch jax.process_count()/jax.devices() here — any
    # backend-initializing call before jax.distributed.initialize() makes
    # the bootstrap fail ("must be called before any JAX calls ...")
    already = False
    try:
        from jax._src.distributed import global_state
        already = global_state.client is not None
    except Exception:  # noqa: BLE001 - internal layout differs by version
        try:  # public form on newer jax
            already = bool(jax.distributed.is_initialized())
        except Exception:  # noqa: BLE001
            already = False
    if already:
        return
    kwargs = {}
    if coordinator_address is not None:
        kwargs["coordinator_address"] = coordinator_address
    if num_processes is not None:
        kwargs["num_processes"] = num_processes
    if process_id is not None:
        kwargs["process_id"] = process_id
    try:
        jax.distributed.initialize(**kwargs)
    except (ValueError, RuntimeError) as e:
        # idempotence even when the already-initialized probe above had no
        # usable API: a repeat call is a no-op, not an error
        if "already" in str(e).lower() and "initialize" in str(e).lower():
            return
        # single-process runs (no coordinator discoverable) stay local
        if coordinator_address is not None:
            raise
        import logging
        logging.getLogger(__name__).info(
            "jax.distributed.initialize skipped (single process?): %s", e)
