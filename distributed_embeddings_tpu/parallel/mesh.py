"""Mesh helpers.

The reference's communication bootstrap is `hvd.init()` + one-GPU-per-process
pinning (reference examples/dlrm/main.py:152-157). The TPU equivalent is a
`jax.sharding.Mesh`: a single axis (default name "mp") plays both the
data-parallel and model-parallel role, exactly like the reference where
dp ranks == mp ranks (dist_model_parallel.py:757-762). Multi-host pods just
need `jax.distributed.initialize()` before building the mesh; the collectives
ride ICI within a slice and DCN across slices based on device order.
"""

from typing import Optional, Sequence

import numpy as np
import jax
from jax.sharding import Mesh

DEFAULT_AXIS = "mp"


def create_mesh(devices: Optional[Sequence] = None, axis_name: str = DEFAULT_AXIS) -> Mesh:
    """Create a 1-D mesh over the given (default: all) devices."""
    if devices is None:
        devices = jax.devices()
    return Mesh(np.asarray(devices), (axis_name,))


def default_mesh(axis_name: str = DEFAULT_AXIS) -> Mesh:
    return create_mesh(axis_name=axis_name)
