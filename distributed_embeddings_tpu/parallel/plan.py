"""SPMD lowering: planner output -> stacked, mesh-shardable parameterization.

The reference runtime materializes a *different* set of Keras layers on every
rank (dist_model_parallel.py:788-818) — natural for MPI, impossible for SPMD,
where every device must run the same program over same-shaped arrays. The
TPU-native representation chosen here:

  * Table-parallel group: all tables a rank owns with the same
    (width, combiner, offload) are concat-fused into one tall table (the
    reference does the same per-rank, :651-691). Fused tables are then padded
    to the max row count across ranks and stacked into one array
    ``[world, rows_max, width]`` sharded `P(axis)` — each device holds exactly
    its own fused table. One such "bucket" exists per distinct
    (width, combiner, offload) key.
  * Per-device differences (which features a device owns, each feature's row
    offset inside the fused table) are encoded as small integer constants
    ``[world, f_max]`` indexed by `lax.axis_index` at runtime — device-uniform
    program, device-varying data.
  * Row-slice group: each table becomes ``[world, slice_rows_max, width]``
    sharded on axis 0 (vocab sharding across *all* devices).
  * Weight (de/re)assembly is driven by flat placement records rather than the
    reference's chunked-allgather choreography (:1056-1137): on TPU, global
    weights are read/written through jax.Array shards directly.
"""

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from distributed_embeddings_tpu.parallel.planner import DistEmbeddingStrategy

Config = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class TPSlot:
    """One (device, bucket) lookup slot serving one table-parallel input."""
    tp_input: int     # index within the tp input group
    row_offset: int   # row offset of the backing table inside the fused bucket table


@dataclasses.dataclass(frozen=True)
class TPPlacement:
    """Where one column-slice of one tp table physically lives. Drives
    get/set_weights (reference get_col_sliced_weights :1056-1137)."""
    table_id: int     # index within the col (tp) table group
    rank: int
    bucket: int
    row_offset: int
    rows: int
    col_start: int
    col_end: int


@dataclasses.dataclass
class TPBucket:
    """One stacked parameter [world, rows_max, width]."""
    width: int
    combiner: Optional[str]
    offload: bool
    rows: List[int]                 # true (unpadded) rows per rank
    rows_max: int
    slots: List[List[TPSlot]]       # per rank, in exchange slot order
    f_max: int
    # per-rank list of (table_id, row_offset, rows, initializer, dtype)
    init_segments: List[List[Tuple[int, int, int, Any, Any]]]
    # hot-row replication capacity (ISSUE 4): the top-H hottest rows of
    # this bucket live in a replicated [H, width] shard during training;
    # 0 = no hot shard. Set by lower_strategy from the planner's
    # hot_rows config, gated on eligibility (see _hot_capacity).
    hot_rows: int = 0
    # wire formats of this bucket's exchange collectives (ISSUE 5):
    # `wire_dtype` ('f32' | 'bf16' | 'bf16-sr') covers the float wire —
    # the mp->dp activation all_to_all, its gradient transpose, and the
    # dp->mp weight exchange; `id_wire_dtype` ('int32' | 'int16') covers
    # the dp->mp id wire. Set by lower_strategy from the planner's
    # exchange_wire request, gated per bucket (see _wire_eligibility /
    # _id_wire_dtype); the defaults reproduce the pre-seam collectives
    # bit for bit.
    wire_dtype: str = "f32"
    id_wire_dtype: str = "int32"
    # at-rest row storage dtype (ISSUE 15/17): 'f32' (default — arrays
    # are byte-identical to pre-seam params), 'int8'/'fp8' (quantized
    # payload + per-row f32 scale, decoded at gather time). Set by
    # lower_strategy from the planner's storage_dtype request, gated
    # per bucket (see _storage_eligibility): both offloaded and
    # HBM-resident buckets quantize; hot-sharded buckets stay f32.
    storage_dtype: str = "f32"
    # dynamic-vocabulary slack (ISSUE 7): pre-reserved growth rows
    # folded into this bucket's rows_max (max over ranks of the summed
    # per-table vocab_slack placed on that rank). Informational — the
    # slack rows are physically indistinguishable from build rows; the
    # vocab manager owns which are bound. 0 = statically-planned bucket.
    slack_rows: int = 0
    # NOTE: runtime [world, f_max] sel/offset constants live on
    # _ExchangeGroup (dist_model_parallel._exchange_groups), grouped by
    # hotness — the bucket itself carries only placement structure.


@dataclasses.dataclass
class RowTablePlan:
    """One row-sliced (vocab-sharded) table [world, rows_max, width]."""
    table_id: int                   # index within the row table group
    width: int
    combiner: Optional[str]
    rows_per_rank: List[int]
    rows_max: int
    row_base: np.ndarray            # [world] global row base per rank
    initializer: Any
    dtype: Any
    # exchange wire formats (ISSUE 5), mirroring TPBucket: `wire_dtype`
    # covers the psum_scatter return / weight all_gather / their
    # gradient transposes, `id_wire_dtype` the id all_gather.
    wire_dtype: str = "f32"
    id_wire_dtype: str = "int32"
    # at-rest storage (ISSUE 15): row-sliced tables are device-resident
    # HBM shards on the training hot path — always 'f32' under the
    # cold-rows-only gate; the field exists so every byte report reads
    # ONE schema across table kinds.
    storage_dtype: str = "f32"


@dataclasses.dataclass
class ShardedPlan:
    world_size: int
    strategy: DistEmbeddingStrategy
    tp_buckets: List[TPBucket]
    tp_placements: List[TPPlacement]
    # per tp input: its shard-feature slots in rank order:
    # list of (rank, bucket_idx, slot_idx)
    tp_input_slots: List[List[Tuple[int, int, int]]]
    row_tables: List[RowTablePlan]


def _bucket_key(config: Config) -> Tuple[int, Optional[str], bool]:
    return (config["output_dim"], config.get("combiner"),
            bool(config.get("cpu_offload", False)))


def _hot_capacity(bucket: TPBucket, hot_rows: int, world: int) -> int:
    """Hot-shard capacity for one bucket, 0 when ineligible.

    Eligible: non-offloaded (offloaded buckets already have the serving
    HBM cache and their updates run out-of-jit host-side), a reducing
    combiner (the flatten path has no weighted-sum form to mask hits
    through), and a flat key space ``world * rows_max`` that fits int32
    (the membership searchsorted runs on int32 keys; x64 is off by
    default on TPU, so an overflowing key space silently corrupts the
    split — refuse instead). Capacity clamps to the bucket's true global
    row count."""
    if hot_rows <= 0 or bucket.offload or bucket.combiner is None:
        return 0
    rows_max = max(bucket.rows_max, 1)
    # (world + 1): the forward sentinel-masks hit lanes to rows_max
    # pre-offset, so post-offset ids reach up to 2 * rows_max on every
    # rank — the whole value range must stay inside int32
    if (world + 1) * rows_max + hot_rows >= 2**31 - 1:
        import warnings
        warnings.warn(
            f"hot_rows disabled for a width-{bucket.width} bucket: flat "
            f"key space world*rows_max = {world * rows_max} overflows "
            "int32 membership keys", RuntimeWarning, stacklevel=3)
        return 0
    return min(hot_rows, max(sum(bucket.rows), 1))


def _wire_eligibility(combiner: Optional[str], offload: bool,
                      requested: str) -> str:
    """Float wire format for one bucket/table, 'f32' when ineligible.

    Gated off (kept f32) where the planner knows bf16 round-off would be
    user-visible beyond the documented combine tolerance:

      * combiner-None passthrough buckets return RAW embedding rows to
        the user (no reduction to absorb the rounding) — a silently
        rounded row is a user-visible numerics change, so passthrough
        keeps the exact wire unless the user opts the whole layer into a
        bf16 compute_dtype (which already rounds those rows).
      * offloaded buckets: their mp->dp movement is a GSPMD host
        resharding, not a lax collective — there is no wire here to
        compress, and marking them f32 keeps the report honest.
    """
    if combiner is None or offload:
        return "f32"
    return requested


def _storage_eligibility(offload: bool, requested: str,
                         hot_rows: int = 0) -> str:
    """At-rest storage dtype for one bucket, 'f32' when ineligible.

    Both residencies quantize now (ISSUE 17): COLD (host-offloaded)
    buckets were the PR 15 capacity bottleneck (~4x more rows per host
    byte, decode folded into `_host_group_exchange`, SR re-encode a
    host-side apply epilogue); HBM-RESIDENT buckets gain the same seam
    — decode at gather time inside the jitted forward, and a
    master-weight-free sparse update (decode touched rows -> f32 math
    -> hash-SR re-encode) for the row-wise optimizers, so a quantized
    table costs ~1/4 the HBM with no resident f32 mirror.

    The one residual gate: a bucket with a HOT SHARD stays f32. The
    hot shard replicates raw f32 rows and its write-back/admission
    moves rows between the canonical table and the shard verbatim —
    re-encoding on every membership change would quantize hot rows
    repeatedly (unbounded drift), exactly the rows touched most.
    Capacity-wise the hot shard already holds the bucket's densest
    rows in f32, so quantizing the cold remainder under it is a
    different design, not a smaller diff."""
    if hot_rows > 0:
        return "f32"
    return requested


def _id_wire_dtype(rows_max: int, id_wire_mode: str) -> str:
    """Id wire for one bucket: 'int16' when the planner PROVES every
    value that can cross the wire fits (the int32-key-overflow gate
    style from PR 4, applied at the int16 boundary).

    The dp->mp wire carries PRE-offset ids — valid ids are < the lane's
    segment rows <= rows_max, and the hot split's sentinel is exactly
    rows_max — so the proof obligation is rows_max strictly below the
    int16 clip ceiling (the clip then keeps out-of-range user ids
    out of range AND distinct from the sentinel; see ops/wire.py
    encode_ids)."""
    from distributed_embeddings_tpu.ops.wire import int16_id_wire_ok
    if id_wire_mode == "auto" and int16_id_wire_ok(max(rows_max, 1)):
        return "int16"
    return "int32"


def lower_strategy(strategy: DistEmbeddingStrategy) -> ShardedPlan:
    """Lower a planner result to the stacked SPMD plan."""
    world = strategy.world_size

    # ---------------- table-parallel buckets --------------------------------
    bucket_index: Dict[Tuple, int] = {}
    buckets: List[TPBucket] = []
    placements: List[TPPlacement] = []

    # running column cursor per tp table (col slices are consumed in rank
    # order, matching the reference's rank-ordered weight slicing :921-936)
    col_cursor: Dict[int, int] = {}
    # per (rank, local_table_pos) -> (bucket_idx, row_offset)
    local_pos_info: List[List[Tuple[int, int]]] = []
    # per (bucket, rank): summed vocab_slack of the tables placed there
    slack_per: Dict[Tuple[int, int], int] = {}

    for rank in range(world):
        table_ids = strategy.table_ids[rank] if strategy.table_ids else []
        configs = (strategy.local_preconcat_configs[rank]
                   if strategy.local_preconcat_configs else [])
        rank_info = []
        for table_id, cfg in zip(table_ids, configs):
            key = _bucket_key(cfg)
            if key not in bucket_index:
                bucket_index[key] = len(buckets)
                buckets.append(TPBucket(
                    width=cfg["output_dim"], combiner=cfg.get("combiner"),
                    offload=bool(cfg.get("cpu_offload", False)),
                    rows=[0] * world, rows_max=0,
                    slots=[[] for _ in range(world)], f_max=0,
                    init_segments=[[] for _ in range(world)]))
            b = bucket_index[key]
            bucket = buckets[b]
            row_offset = bucket.rows[rank]
            bucket.rows[rank] += cfg["input_dim"]
            slack_per[(b, rank)] = (slack_per.get((b, rank), 0)
                                    + int(cfg.get("vocab_slack", 0)))
            bucket.init_segments[rank].append(
                (table_id, row_offset, cfg["input_dim"],
                 cfg.get("embeddings_initializer", "uniform"),
                 cfg.get("dtype")))
            col_start = col_cursor.get(table_id, 0)
            col_end = col_start + cfg["output_dim"]
            col_cursor[table_id] = col_end
            placements.append(TPPlacement(
                table_id=table_id, rank=rank, bucket=b,
                row_offset=row_offset, rows=cfg["input_dim"],
                col_start=col_start, col_end=col_end))
            rank_info.append((b, row_offset))
        local_pos_info.append(rank_info)

    for b, bucket in enumerate(buckets):
        bucket.rows_max = max(bucket.rows) if bucket.rows else 0
        bucket.slack_rows = max((slack_per.get((b, r), 0)
                                 for r in range(world)), default=0)

    # ---------------- input slots -------------------------------------------
    n_tp_inputs = len(strategy.input_groups[1]) if strategy.input_groups else 0
    tp_input_slots: List[List[Tuple[int, int, int]]] = [[] for _ in range(n_tp_inputs)]
    for rank in range(world):
        if not strategy.table_ids:
            break
        # reproduce the reference's per-rank input enumeration order
        # (tables outer, inputs inner — dist_model_parallel.py:414-419)
        for local_pos, table_idx in enumerate(strategy.table_ids[rank]):
            for inp_pos, mapped_idx in enumerate(strategy.map_groups[1]):
                if table_idx == mapped_idx:
                    b, row_offset = local_pos_info[rank][local_pos]
                    bucket = buckets[b]
                    slot_idx = len(bucket.slots[rank])
                    bucket.slots[rank].append(
                        TPSlot(tp_input=inp_pos, row_offset=row_offset))
                    tp_input_slots[inp_pos].append((rank, b, slot_idx))

    from distributed_embeddings_tpu.ops.wire import default_id_wire
    requested_wire = getattr(strategy, "exchange_wire", "f32")
    requested_store = getattr(strategy, "storage_dtype", "f32")
    id_wire_mode = default_id_wire()
    for bucket in buckets:
        bucket.f_max = max((len(s) for s in bucket.slots), default=0)
        bucket.hot_rows = _hot_capacity(
            bucket, getattr(strategy, "hot_rows", 0), world)
        bucket.wire_dtype = _wire_eligibility(
            bucket.combiner, bucket.offload, requested_wire)
        bucket.id_wire_dtype = _id_wire_dtype(bucket.rows_max, id_wire_mode)
        bucket.storage_dtype = _storage_eligibility(bucket.offload,
                                                    requested_store,
                                                    bucket.hot_rows)

    # ---------------- row-sliced tables -------------------------------------
    row_tables: List[RowTablePlan] = []
    n_row_tables = len(strategy.table_groups[2])
    for t in range(n_row_tables):
        per_rank = [strategy.row_sliced_configs[r][t] for r in range(world)]
        rows = [cfg["input_dim"] for cfg in per_rank]
        # reference keeps negative offsets (add to id); we store the positive
        # global base row of each rank's slice (subtract from id).
        base = np.asarray([-strategy.row_inputs_offsets[r][t]
                           for r in range(world)], dtype=np.int32)
        cfg0 = per_rank[0]
        # the row wire carries GLOBAL ids (base subtraction is local), so
        # the int16 proof obligation is the table's TOTAL row count
        row_tables.append(RowTablePlan(
            table_id=t, width=cfg0["output_dim"], combiner=cfg0.get("combiner"),
            rows_per_rank=rows, rows_max=max(rows), row_base=base,
            initializer=cfg0.get("embeddings_initializer", "uniform"),
            dtype=cfg0.get("dtype"),
            wire_dtype=_wire_eligibility(cfg0.get("combiner"), False,
                                         requested_wire),
            id_wire_dtype=_id_wire_dtype(sum(rows), id_wire_mode)))

    return ShardedPlan(
        world_size=world, strategy=strategy, tp_buckets=buckets,
        tp_placements=placements, tp_input_slots=tp_input_slots,
        row_tables=row_tables)
