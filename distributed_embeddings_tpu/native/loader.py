"""Build-on-demand ctypes loader for the native library."""

import ctypes
import os
import subprocess
import threading

_LIB = None
_LOCK = threading.Lock()
_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_det_native.so")


def load():
    global _LIB
    with _LOCK:
        if _LIB is not None:
            return _LIB
        srcs = [os.path.join(_DIR, f)
                for f in ("hashmap.cpp", "io.cpp", "host_apply.cpp")]
        have_so = os.path.exists(_SO)
        # missing sources (stripped install) are NOT stale — use the .so
        stale = (not have_so
                 or (all(os.path.exists(s) for s in srcs)
                     and any(os.path.getmtime(s) > os.path.getmtime(_SO)
                             for s in srcs)))
        if stale:
            # build to a temp name + atomic rename: concurrent processes
            # (multi-process tests) must never dlopen a half-written .so
            tmp = f"{_SO}.build.{os.getpid()}"
            cmd = ["g++", "-O3", "-std=c++17", "-fPIC", "-shared", "-Wall",
                   "-pthread", *srcs, "-o", tmp]
            try:
                subprocess.run(cmd, check=True, capture_output=True)
                os.replace(tmp, _SO)
            except Exception:
                # rebuild of a newer source failed (no g++?): a prebuilt
                # .so still beats the numpy fallback — warn and use it
                if not have_so:
                    raise
                import warnings
                warnings.warn(
                    "native rebuild failed; using the existing (possibly "
                    "stale) _det_native.so", RuntimeWarning, stacklevel=2)
        lib = ctypes.CDLL(_SO)

        i64 = ctypes.c_int64
        p = ctypes.c_void_p
        lib.il_create.restype = p
        lib.il_create.argtypes = [i64]
        lib.il_destroy.argtypes = [p]
        lib.il_size.restype = i64
        lib.il_size.argtypes = [p]
        lib.il_lookup_or_insert.argtypes = [p, ctypes.c_void_p, i64, ctypes.c_void_p]
        lib.il_lookup.argtypes = [p, ctypes.c_void_p, i64, ctypes.c_void_p]
        lib.il_export_keys.argtypes = [p, ctypes.c_void_p]
        lib.il_export_counts.argtypes = [p, ctypes.c_void_p]
        # erase/free-slot surface (ISSUE 7): a prebuilt .so from before
        # the erasable map may lack these — wrappers hasattr-guard
        if hasattr(lib, "il_erase"):
            lib.il_erase.argtypes = [p, ctypes.c_void_p, i64, ctypes.c_void_p]
            lib.il_high_water.restype = i64
            lib.il_high_water.argtypes = [p]
            lib.il_free_count.restype = i64
            lib.il_free_count.argtypes = [p]
            lib.il_export_free.argtypes = [p, ctypes.c_void_p]

        lib.pf_create.restype = p
        lib.pf_create.argtypes = [ctypes.POINTER(ctypes.c_char_p), i64, i64]
        lib.pf_destroy.argtypes = [p]
        lib.pf_submit.restype = p
        lib.pf_submit.argtypes = [p, i64, i64, i64, ctypes.c_void_p]
        lib.pf_wait.argtypes = [p, p]
        lib.pf_read.restype = i64
        lib.pf_read.argtypes = [p, i64, i64, i64, ctypes.c_void_p]

        # a prebuilt .so from before host_apply.cpp may lack these symbols
        # (stripped install with no g++): keep il_*/pf_* usable and let the
        # host-apply wrapper fall back to numpy
        if hasattr(lib, "ha_sgd"):
            f32 = ctypes.c_float
            lib.ha_sgd.argtypes = [p, i64, p, p, p, i64, f32]
            lib.ha_adagrad.argtypes = [p, p, i64, p, p, p, i64, f32, f32]
            lib.ha_adam.argtypes = [p, p, p, i64, p, p, p, i64, f32, f32,
                                    f32, f32, f32, f32]

        _LIB = lib
        return _LIB
