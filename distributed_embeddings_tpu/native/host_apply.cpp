// Host-memory sparse row optimizers for offloaded embedding buckets.
//
// Reference role: the reference keeps over-budget tables on the CPU and
// updates them with host TF ops (dist_model_parallel.py:449-476, :829-831,
// :971-1017).  Here the offloaded apply runs outside XLA entirely: the
// deduped update rows (rep/sums/valid, from prepare_safe_grad) are the only
// data fetched off-device; these kernels then update the pinned-host table
// and optimizer-state shards in place.  This sidesteps the SPMD
// partitioner's inability to shard host-placement side-effect custom-calls
// (XLA RET_CHECK "Side-effect ops cannot be replicated") — there is no XLA
// program to partition.
//
// Contract (matches ops/sparse_update.py host_sparse_*):
//  * rep[i] is in-bounds; slots with valid[i] == 0 are padding that aliases
//    row 0 with all-zero sums — skipped here (zero delta by construction).
//  * valid rows are unique (deduped), so a plain serial loop is exact; the
//    numerics mirror the jax rules row-for-row in float32.

#include <cmath>
#include <cstdint>

extern "C" {

void ha_sgd(float* table, int64_t w, const int32_t* rep, const float* sums,
            const float* valid, int64_t n, float lr) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid[i] == 0.0f) continue;
    float* t = table + (int64_t)rep[i] * w;
    const float* s = sums + i * w;
    for (int64_t j = 0; j < w; ++j) t[j] -= lr * s[j];
  }
}

void ha_adagrad(float* table, float* acc, int64_t w, const int32_t* rep,
                const float* sums, const float* valid, int64_t n, float lr,
                float eps) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid[i] == 0.0f) continue;
    const int64_t r = (int64_t)rep[i] * w;
    float* t = table + r;
    float* a = acc + r;
    const float* s = sums + i * w;
    for (int64_t j = 0; j < w; ++j) {
      a[j] += s[j] * s[j];
      t[j] -= lr * s[j] / std::sqrt(a[j] + eps);
    }
  }
}

// c1/c2 are the bias corrections 1-b1^t / 1-b2^t for the ALREADY
// incremented step count (the caller owns the scalar count update).
void ha_adam(float* table, float* mu, float* nu, int64_t w,
             const int32_t* rep, const float* sums, const float* valid,
             int64_t n, float lr, float b1, float b2, float c1, float c2,
             float eps) {
  for (int64_t i = 0; i < n; ++i) {
    if (valid[i] == 0.0f) continue;
    const int64_t r = (int64_t)rep[i] * w;
    float* t = table + r;
    float* m = mu + r;
    float* v = nu + r;
    const float* s = sums + i * w;
    for (int64_t j = 0; j < w; ++j) {
      m[j] = b1 * m[j] + (1.0f - b1) * s[j];
      v[j] = b2 * v[j] + (1.0f - b2) * s[j] * s[j];
      t[j] -= lr * (m[j] / c1) / (std::sqrt(v[j] / c2) + eps);
    }
  }
}

}  // extern "C"
