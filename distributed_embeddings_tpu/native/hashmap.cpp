// Host-side open-addressing hash map for on-the-fly vocabulary building.
//
// TPU-native replacement for the reference's cuCollections static_map GPU
// kernel (reference: cc/kernels/embedding_lookup_kernels.cu:383-516). TPUs
// have no device-side dynamic hash table; the TPU-native design runs the
// key->index mapping on the TPU-VM host (this library, called via ctypes)
// and keeps the device side a plain gather. Matches reference semantics:
// index 0 reserved for OOV, capacity = max_tokens + 1, per-key frequency
// counts, 1.5x slot load factor.
//
// Build: g++ -O3 -shared -fPIC hashmap.cpp -o _det_native.so

#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <functional>
#include <mutex>
#include <thread>
#include <vector>

namespace {

constexpr int64_t kEmpty = INT64_MIN;          // sentinel for empty slot
constexpr int64_t kTombstone = INT64_MIN + 1;  // erased slot (probe through)

// The two slot sentinels are RESERVED key values: a user key equal to
// either would corrupt probe chains (its occupied slot would read as
// empty/erased and be silently overwritten). Both map to OOV instead —
// the same graceful answer a full table gives (the numpy backend
// mirrors this; the Python layer documents the reservation).
inline bool reserved_key(int64_t key) {
  return key == kEmpty || key == kTombstone;
}

struct IntegerLookupMap {
  int64_t capacity;    // max distinct keys + 1 (index 0 = OOV)
  int64_t num_slots;   // power of two >= 1.5 * capacity
  int64_t mask;
  int64_t size;        // number of LIVE keys (erases decrement)
  int64_t tombstones;  // erased slots awaiting reuse/rehash
  std::vector<int64_t> slot_keys;
  std::vector<int64_t> slot_vals;      // index assigned to the key
  std::vector<int64_t> keys_by_index;  // reverse map: index-1 -> key
                                       // (kEmpty hole for erased indices)
  std::vector<int64_t> counts;         // per-index frequency (index 0 = OOV)
  std::vector<int64_t> free_idx;       // erased indices, reused LIFO

  explicit IntegerLookupMap(int64_t cap)
      : capacity(cap), size(0), tombstones(0) {
    int64_t want = static_cast<int64_t>(cap * 3 / 2) + 2;
    num_slots = 16;
    while (num_slots < want) num_slots <<= 1;
    mask = num_slots - 1;
    slot_keys.assign(num_slots, kEmpty);
    slot_vals.assign(num_slots, 0);
    keys_by_index.reserve(capacity);
    counts.assign(capacity, 0);
  }

  static inline uint64_t hash(int64_t key) {
    // splitmix64 finalizer
    uint64_t x = static_cast<uint64_t>(key) + 0x9e3779b97f4a7c15ull;
    x = (x ^ (x >> 30)) * 0xbf58476d1ce4e5b9ull;
    x = (x ^ (x >> 27)) * 0x94d049bb133111ebull;
    return x ^ (x >> 31);
  }

  inline int64_t find(int64_t key) const {
    if (reserved_key(key)) return 0;  // -> OOV
    uint64_t h = hash(key) & mask;
    while (true) {
      int64_t k = slot_keys[h];
      if (k == key) return slot_vals[h];
      if (k == kEmpty) return -1;  // tombstones probe through
      h = (h + 1) & mask;
    }
  }

  inline int64_t find_or_insert(int64_t key) {
    if (reserved_key(key)) return 0;  // -> OOV, never stored
    uint64_t h = hash(key) & mask;
    int64_t first_tomb = -1;
    while (true) {
      int64_t k = slot_keys[h];
      if (k == key) return slot_vals[h];
      if (k == kTombstone && first_tomb < 0) {
        first_tomb = static_cast<int64_t>(h);
      } else if (k == kEmpty) {
        if (size >= capacity - 1) return 0;  // table full -> OOV
        // indices: reuse an erased one (eviction freed its row slot)
        // before minting past the high-water mark
        int64_t idx;
        if (!free_idx.empty()) {
          idx = free_idx.back();
          free_idx.pop_back();
          keys_by_index[idx - 1] = key;
        } else {
          idx = static_cast<int64_t>(keys_by_index.size()) + 1;
          keys_by_index.push_back(key);
        }
        ++size;
        if (first_tomb >= 0) {
          h = static_cast<uint64_t>(first_tomb);
          --tombstones;
        }
        slot_keys[h] = key;
        slot_vals[h] = idx;
        // the probe loops terminate only on a kEmpty slot, so SOME
        // kEmpty slots must always survive: inserts that land on a
        // kEmpty slot (not a reused tombstone) consume one, and must
        // uphold the same occupancy bound erase() does — without this,
        // churn whose inserts keep missing the tombstones can fill the
        // last empty slot and the next absent-key lookup spins forever
        if (first_tomb < 0 && tombstones + size > (num_slots * 7) / 8)
          rehash();
        return idx;
      }
      h = (h + 1) & mask;
    }
  }

  // Erase a key: its index is freed for reuse, its slot becomes a
  // tombstone (probe chains through it stay intact), its frequency
  // count resets (a future key bound to this index must not inherit
  // it). Returns the freed index, 0 if the key was not present.
  inline int64_t erase(int64_t key) {
    if (reserved_key(key)) return 0;
    uint64_t h = hash(key) & mask;
    while (true) {
      int64_t k = slot_keys[h];
      if (k == key) {
        int64_t idx = slot_vals[h];
        slot_keys[h] = kTombstone;
        slot_vals[h] = 0;
        ++tombstones;
        keys_by_index[idx - 1] = kEmpty;
        counts[idx] = 0;
        free_idx.push_back(idx);
        --size;
        // erase-heavy churn can fill every kEmpty slot with tombstones,
        // degrading probes toward O(num_slots); rebuild from the live
        // reverse map well before that (live keys are bounded by
        // capacity <= 2/3 num_slots, so post-rehash load stays sane)
        if (tombstones + size > (num_slots * 7) / 8) rehash();
        return idx;
      }
      if (k == kEmpty) return 0;
      h = (h + 1) & mask;
    }
  }

  void rehash() {
    std::fill(slot_keys.begin(), slot_keys.end(), kEmpty);
    std::fill(slot_vals.begin(), slot_vals.end(), 0);
    tombstones = 0;
    for (size_t i = 0; i < keys_by_index.size(); ++i) {
      int64_t key = keys_by_index[i];
      if (key == kEmpty) continue;
      uint64_t h = hash(key) & mask;
      while (slot_keys[h] != kEmpty) h = (h + 1) & mask;
      slot_keys[h] = key;
      slot_vals[h] = static_cast<int64_t>(i) + 1;
    }
  }
};

// Persistent worker pool for the per-batch probe parallelism. The previous
// implementation spawned std::thread per batch (~10us each) — measurable
// against a ~1ms 16k-key probe, and paid on EVERY lookup call. Workers here
// are created once (lazily, hardware_concurrency - 1 of them: the caller
// always runs chunk 0 itself) and parked on a condition variable between
// batches; dispatch cost is one lock + notify (~1us).
//
// The pool object is intentionally leaked: the library is loaded via ctypes
// and never dlclosed, and joining detached workers from a static destructor
// during interpreter teardown is a known crash source. Workers exit with
// the process.
class WorkerPool {
 public:
  static WorkerPool& instance() {
    static WorkerPool* pool = new WorkerPool();  // leaked by design
    return *pool;
  }

  // Run fn(worker_index) on `nt - 1` pool workers (indices 1..nt-1) while
  // the caller runs index 0; returns when all are done. Serialized per
  // process (one batch in flight) — callers already hold the Python-side
  // map lock, and a single pool avoids oversubscribing the host.
  void run(int nt, const std::function<void(int)>& fn) {
    std::unique_lock<std::mutex> lk(run_mu_);
    // fork safety: a child inherits this object but none of its worker
    // THREADS — dispatching to them would wait on done_cv_ forever.
    // Workers are (re)spawned lazily on the first run() in each process
    // (also avoids paying hw-1 thread spawns in processes that only ever
    // do small single-threaded lookups). Residual risk: forking WHILE
    // another thread is inside a lookup is UB (inherited locked mutexes)
    // — the Python wrapper's per-map lock makes that a caller bug.
    if (pid_ != getpid()) {
      threads_.clear();  // detached std::threads: clearing is safe
      generation_ = 0;
      active_ = 0;
      task_workers_ = 0;
      for (int i = 0; i < max_workers_; ++i) {
        threads_.emplace_back([this, i] { Loop(i + 1); });
        threads_.back().detach();  // leaked pool: never joined
      }
      pid_ = getpid();
    }
    int workers = nt - 1;
    if (workers > static_cast<int>(threads_.size()))
      workers = static_cast<int>(threads_.size());
    if (workers > 0) {
      {
        std::lock_guard<std::mutex> g(mu_);
        task_ = &fn;
        task_workers_ = workers;
        active_ = workers;
        ++generation_;
      }
      cv_.notify_all();
    }
    fn(0);
    if (workers > 0) {
      std::unique_lock<std::mutex> g(mu_);
      done_cv_.wait(g, [&] { return active_ == 0; });
      task_ = nullptr;
    }
  }

  // Potential parallelism (caller + workers); workers spawn lazily on the
  // first run() so small-batch-only processes never pay the thread spawns.
  int max_threads() const { return max_workers_ + 1; }

 private:
  WorkerPool() {
    int hw = static_cast<int>(std::thread::hardware_concurrency());
    max_workers_ = hw > 1 ? hw - 1 : 0;
    if (max_workers_ > 31) max_workers_ = 31;  // caller + 31 = old 32 cap
  }

  void Loop(int index) {
    uint64_t seen = 0;
    for (;;) {
      const std::function<void(int)>* task = nullptr;
      {
        std::unique_lock<std::mutex> g(mu_);
        cv_.wait(g, [&] { return generation_ != seen; });
        seen = generation_;
        // not needed for this batch: only participants (index <=
        // task_workers_) touch active_, so just go back to sleep
        if (index > task_workers_) continue;
        task = task_;
      }
      (*task)(index);
      {
        std::lock_guard<std::mutex> g(mu_);
        if (--active_ == 0) done_cv_.notify_all();
      }
    }
  }

  std::mutex run_mu_;  // one batch in flight at a time
  std::mutex mu_;
  std::condition_variable cv_, done_cv_;
  const std::function<void(int)>* task_ = nullptr;
  int task_workers_ = 0;
  int active_ = 0;
  int max_workers_ = 0;
  pid_t pid_ = -1;   // owner process: workers respawn lazily after fork
  uint64_t generation_ = 0;
  std::vector<std::thread> threads_;
};

// Threads for an n-key batch. With the persistent pool, dispatch is ~1us
// (vs ~10us+ per spawned thread before), but parallel probing also fights
// cache sharing and the relaxed atomic hit-count adds on hot power-law
// keys — measured on the 2-vCPU reference host (docs/parity.md), the
// multi-thread probe only breaks even around 64k keys/batch and loses
// below it (e.g. 15.3 vs 18.9 M keys/s at 16k). So: single thread under
// 64k keys, then >=32k keys per thread, capped by the pool size.
inline int threads_for(int64_t n) {
  int hw = WorkerPool::instance().max_threads();
  if (hw <= 1 || n < (1 << 16)) return 1;
  int64_t want = n >> 15;  // ~32k keys per thread minimum
  if (want > hw) want = hw;
  if (want > 32) want = 32;
  return static_cast<int>(want);
}

template <typename Fn>
inline void parallel_chunks(int64_t n, Fn fn) {
  int nt = threads_for(n);
  if (nt <= 1) {
    fn(0, n);
    return;
  }
  int64_t chunk = (n + nt - 1) / nt;
  WorkerPool::instance().run(nt, [&](int t) {
    int64_t lo = t * chunk;
    int64_t hi = lo + chunk < n ? lo + chunk : n;
    if (lo < hi) fn(lo, hi);
  });
}

}  // namespace

extern "C" {

void* il_create(int64_t capacity) { return new IntegerLookupMap(capacity); }

void il_destroy(void* handle) {
  delete static_cast<IntegerLookupMap*>(handle);
}

int64_t il_size(void* handle) {
  return static_cast<IntegerLookupMap*>(handle)->size;
}

// Two-phase batch insert: phase 1 probes read-only IN PARALLEL (callers
// are serialized per map — the Python wrapper holds a lock across each
// call, so no writer is ever concurrent with the probe and plain reads of
// slot_keys/slot_vals are race-free; hit counts use relaxed atomic adds),
// phase 2 inserts the misses
// SEQUENTIALLY in batch order — preserving the exact first-appearance
// id-assignment contract of the sequential map (the property
// get_vocabulary() and the keras-parity tests pin). After vocabulary
// warmup nearly every key is a hit, so the parallel phase is ~all of the
// work; the reference gets the same effect from a massively-parallel GPU
// probe (embedding_lookup_kernels.cu:383-516).
void il_lookup_or_insert(void* handle, const int64_t* keys, int64_t n,
                         int64_t* out) {
  auto* m = static_cast<IntegerLookupMap*>(handle);
  int64_t* counts = m->counts.data();
  parallel_chunks(n, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int64_t idx = m->find(keys[i]);
      out[i] = idx;
      if (idx >= 0) __atomic_fetch_add(&counts[idx], 1, __ATOMIC_RELAXED);
    }
  });
  for (int64_t i = 0; i < n; ++i) {
    if (out[i] < 0) {
      int64_t idx = m->find_or_insert(keys[i]);
      out[i] = idx;
      counts[idx] += 1;
    }
  }
}

void il_lookup(void* handle, const int64_t* keys, int64_t n, int64_t* out) {
  auto* m = static_cast<IntegerLookupMap*>(handle);
  parallel_chunks(n, [&](int64_t lo, int64_t hi) {
    for (int64_t i = lo; i < hi; ++i) {
      int64_t idx = m->find(keys[i]);
      out[i] = idx < 0 ? 0 : idx;
    }
  });
}

// keys_out must have room for il_high_water() entries (index order,
// 1-based indices: keys_out[i] is the key mapped to index i+1; erased
// indices export INT64_MIN holes). high_water == size when no key was
// ever erased, so pre-erase callers see the original contract.
void il_export_keys(void* handle, int64_t* keys_out) {
  auto* m = static_cast<IntegerLookupMap*>(handle);
  std::memcpy(keys_out, m->keys_by_index.data(),
              sizeof(int64_t) * m->keys_by_index.size());
}

// Highest index ever assigned (= export_keys entry count).
int64_t il_high_water(void* handle) {
  return static_cast<int64_t>(
      static_cast<IntegerLookupMap*>(handle)->keys_by_index.size());
}

// Erase keys (ISSUE 7 eviction): out[i] = the freed index, 0 if the key
// was not bound. Sequential — erase batches are eviction-sized (small),
// and the tombstone/rehash writes need no probe parallelism.
void il_erase(void* handle, const int64_t* keys, int64_t n, int64_t* out) {
  auto* m = static_cast<IntegerLookupMap*>(handle);
  for (int64_t i = 0; i < n; ++i) out[i] = m->erase(keys[i]);
}

// Number of freed (reusable) indices.
int64_t il_free_count(void* handle) {
  return static_cast<int64_t>(
      static_cast<IntegerLookupMap*>(handle)->free_idx.size());
}

// free_out must have room for il_free_count() entries; exported in
// reuse order (the LAST entry is the next index lookup_or_insert mints).
void il_export_free(void* handle, int64_t* free_out) {
  auto* m = static_cast<IntegerLookupMap*>(handle);
  std::memcpy(free_out, m->free_idx.data(),
              sizeof(int64_t) * m->free_idx.size());
}

// counts_out must have room for capacity entries (index 0 = OOV count).
void il_export_counts(void* handle, int64_t* counts_out) {
  auto* m = static_cast<IntegerLookupMap*>(handle);
  std::memcpy(counts_out, m->counts.data(), sizeof(int64_t) * m->capacity);
}

}  // extern "C"
