// Native data-loader primitives: positional reads + background prefetch.
//
// TPU-native equivalent of the reference's RawBinaryDataset host path
// (reference: examples/dlrm/utils.py:231-266 — os.pread + single-thread
// prefetch executor). A small C++ thread pool issues pread()s ahead of the
// training step so the host input pipeline overlaps device compute.
//
// Built into _det_native.so together with hashmap.cpp.

#include <fcntl.h>
#include <unistd.h>

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct ReadRequest {
  int file;
  int64_t offset;
  int64_t size;
  uint8_t* dst;
  bool done = false;
};

struct Prefetcher {
  std::vector<int> fds;
  std::deque<ReadRequest*> queue;
  std::mutex mu;
  std::condition_variable cv_work, cv_done;
  std::vector<std::thread> workers;
  bool stop = false;

  explicit Prefetcher(const char** paths, int64_t n_files, int64_t n_threads) {
    for (int64_t i = 0; i < n_files; ++i) {
      fds.push_back(open(paths[i], O_RDONLY));
    }
    for (int64_t t = 0; t < n_threads; ++t) {
      workers.emplace_back([this] { this->worker(); });
    }
  }

  ~Prefetcher() {
    {
      std::lock_guard<std::mutex> lock(mu);
      stop = true;
    }
    cv_work.notify_all();
    for (auto& t : workers) t.join();
    for (int fd : fds) {
      if (fd >= 0) close(fd);
    }
  }

  void worker() {
    while (true) {
      ReadRequest* req = nullptr;
      {
        std::unique_lock<std::mutex> lock(mu);
        cv_work.wait(lock, [this] { return stop || !queue.empty(); });
        if (stop && queue.empty()) return;
        req = queue.front();
        queue.pop_front();
      }
      int64_t got = 0;
      while (got < req->size) {
        ssize_t r = pread(fds[req->file], req->dst + got, req->size - got,
                          req->offset + got);
        if (r <= 0) break;
        got += r;
      }
      {
        std::lock_guard<std::mutex> lock(mu);
        req->done = true;
      }
      cv_done.notify_all();
    }
  }

  ReadRequest* submit(int file, int64_t offset, int64_t size, uint8_t* dst) {
    auto* req = new ReadRequest{file, offset, size, dst};
    {
      std::lock_guard<std::mutex> lock(mu);
      queue.push_back(req);
    }
    cv_work.notify_one();
    return req;
  }

  void wait(ReadRequest* req) {
    std::unique_lock<std::mutex> lock(mu);
    cv_done.wait(lock, [req] { return req->done; });
  }
};

}  // namespace

extern "C" {

void* pf_create(const char** paths, int64_t n_files, int64_t n_threads) {
  return new Prefetcher(paths, n_files, n_threads);
}

void pf_destroy(void* handle) { delete static_cast<Prefetcher*>(handle); }

void* pf_submit(void* handle, int64_t file, int64_t offset, int64_t size,
                void* dst) {
  return static_cast<Prefetcher*>(handle)->submit(
      static_cast<int>(file), offset, size, static_cast<uint8_t*>(dst));
}

void pf_wait(void* handle, void* request) {
  auto* pf = static_cast<Prefetcher*>(handle);
  auto* req = static_cast<ReadRequest*>(request);
  pf->wait(req);
  delete req;
}

// synchronous convenience read
int64_t pf_read(void* handle, int64_t file, int64_t offset, int64_t size,
                void* dst) {
  auto* pf = static_cast<Prefetcher*>(handle);
  auto* req = pf->submit(static_cast<int>(file), offset, size,
                         static_cast<uint8_t*>(dst));
  pf->wait(req);
  delete req;
  return size;
}

}  // extern "C"
