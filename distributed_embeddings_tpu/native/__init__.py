"""Native (C++) runtime components, loaded via ctypes.

The shared object is built from hashmap.cpp + io.cpp by `make` in this
directory; if missing, it is compiled on first use with g++ (loader.py).
"""
