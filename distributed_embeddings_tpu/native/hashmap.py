"""ctypes wrapper for the native IntegerLookup hash map (hashmap.cpp)."""

import numpy as np

from distributed_embeddings_tpu.native import loader


class NativeIntegerLookup:
    """Host hash map: int64 keys -> contiguous indices (0 reserved for OOV).

    Backend for layers.embedding.IntegerLookup — the TPU-VM-host replacement
    for the reference's cuCollections GPU map (embedding_lookup_kernels.cu:383-516).
    """

    def __init__(self, capacity: int):
        import threading
        self._lib = loader.load()
        self.capacity = int(capacity)
        self._handle = self._lib.il_create(self.capacity)
        # ctypes releases the GIL during native calls; the C++ map's
        # internal probe threads assume no concurrent WRITER (phase-2
        # insert). Serialize whole calls so multi-threaded data pipelines
        # sharing one layer stay race-free (intra-call parallelism is
        # unaffected).
        self._call_lock = threading.Lock()

    def __del__(self):
        try:
            if getattr(self, "_handle", None):
                self._lib.il_destroy(self._handle)
                self._handle = None
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass

    @property
    def size(self) -> int:
        # locked like the mutating calls: an ingestion worker may be inside
        # phase-2 insert (non-atomic ++size) while a consumer thread polls
        # progress (e.g. the examples' vocab log line)
        with self._call_lock:
            return int(self._lib.il_size(self._handle))

    def lookup_or_insert(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        out = np.empty(keys.shape, dtype=np.int64)
        with self._call_lock:
            self._lib.il_lookup_or_insert(
                self._handle, keys.ctypes.data, keys.size, out.ctypes.data)
        return out

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        out = np.empty(keys.shape, dtype=np.int64)
        with self._call_lock:
            self._lib.il_lookup(
                self._handle, keys.ctypes.data, keys.size, out.ctypes.data)
        return out

    @property
    def supports_erase(self) -> bool:
        """False only with a stale prebuilt .so from before the erasable
        map (no g++ to rebuild) — then no erase can ever have happened,
        so the pre-erase export contracts below stay valid too."""
        return hasattr(self._lib, "il_erase")

    def erase(self, keys: np.ndarray) -> np.ndarray:
        """Unbind keys: returns the freed index per key (0 = was not
        bound). Freed indices are reused by later lookup_or_insert calls
        (LIFO) before new indices are minted."""
        if not self.supports_erase:
            raise NotImplementedError(
                "native _det_native.so predates il_erase and could not be "
                "rebuilt; rebuild with g++ or use the numpy backend")
        keys = np.ascontiguousarray(keys, dtype=np.int64)
        out = np.empty(keys.shape, dtype=np.int64)
        with self._call_lock:
            self._lib.il_erase(
                self._handle, keys.ctypes.data, keys.size, out.ctypes.data)
        return out

    def free_slots(self) -> np.ndarray:
        """Erased (reusable) indices, in reuse order — the binding-table
        free-list the vocab checkpoint round-trips."""
        if not self.supports_erase:
            return np.empty((0,), np.int64)
        with self._call_lock:
            n = int(self._lib.il_free_count(self._handle))
            out = np.empty((n,), dtype=np.int64)
            if n:
                self._lib.il_export_free(self._handle, out.ctypes.data)
        return out

    def keys_in_index_order(self):
        # one lock for the count read AND the export: racing an insert
        # could otherwise memcpy keys_by_index mid-realloc. The export is
        # high-water sized (== size pre-erase); erased indices hole as
        # INT64_MIN and are kept so positions stay 1-based-index-aligned.
        with self._call_lock:
            n = (int(self._lib.il_high_water(self._handle))
                 if self.supports_erase
                 else int(self._lib.il_size(self._handle)))
            out = np.empty((n,), dtype=np.int64)
            if n:
                self._lib.il_export_keys(self._handle, out.ctypes.data)
        return out.tolist()

    def counts(self) -> np.ndarray:
        out = np.zeros((self.capacity,), dtype=np.int64)
        with self._call_lock:
            self._lib.il_export_counts(self._handle, out.ctypes.data)
        return out
