"""Version/backend compatibility shims.

The library targets the moving jax API surface across the versions its
deployment environments actually carry. Two seams matter:

  * ``shard_map`` moved: old releases expose it as
    ``jax.experimental.shard_map.shard_map`` with a ``check_rep`` flag; new
    ones as ``jax.shard_map`` with ``check_vma``. ``shard_map`` here accepts
    the new-style signature and lowers to whichever the installed jax has.
  * Host memory spaces are backend-dependent: TPU backends expose
    ``pinned_host`` next to ``device``; the XLA:CPU backend of older
    releases exposes only ``unpinned_host`` (which is also its *default*
    space — host "offload" is then a placement no-op, but the whole
    offload/serving code path, including ``compute_on`` host regions, still
    compiles and runs, which is what the CPU test mesh needs).
    ``host_memory_kind`` picks the best available host space.
"""

from typing import Optional

import jax

__all__ = ["shard_map", "host_memory_kind", "default_memory_kind"]


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """New-style ``jax.shard_map`` signature on any supported jax."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def _memory_kinds(device) -> set:
    try:
        return {m.kind for m in device.addressable_memories()}
    except Exception:  # noqa: BLE001 - backend without the memories API
        return set()


def host_memory_kind(device) -> Optional[str]:
    """The backend's host memory space for table offload: ``pinned_host``
    where the runtime supports it (TPU; DMA-able), else ``unpinned_host``
    (older XLA:CPU), else None (no host space — offload must stay off)."""
    kinds = _memory_kinds(device)
    for kind in ("pinned_host", "unpinned_host"):
        if kind in kinds:
            return kind
    return None


def default_memory_kind(device) -> Optional[str]:
    """The memory space a plain array lands in on `device` ('device' on
    TPU/GPU; older XLA:CPU reports 'unpinned_host'). Lets tests assert
    offload placement without hardcoding a backend's space names."""
    try:
        return device.default_memory().kind
    except Exception:  # noqa: BLE001
        kinds = _memory_kinds(device)
        if "device" in kinds:
            return "device"
        return next(iter(kinds), None)
