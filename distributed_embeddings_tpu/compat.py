"""Version/backend compatibility shims.

The library targets the moving jax API surface across the versions its
deployment environments actually carry. Two seams matter:

  * ``shard_map`` moved: old releases expose it as
    ``jax.experimental.shard_map.shard_map`` with a ``check_rep`` flag; new
    ones as ``jax.shard_map`` with ``check_vma``. ``shard_map`` here accepts
    the new-style signature and lowers to whichever the installed jax has.
  * Host memory spaces are backend-dependent: TPU backends expose
    ``pinned_host`` next to ``device``; the XLA:CPU backend of older
    releases exposes only ``unpinned_host`` (which is also its *default*
    space — host "offload" is then a placement no-op, but the whole
    offload/serving code path, including ``compute_on`` host regions, still
    compiles and runs, which is what the CPU test mesh needs).
    ``host_memory_kind`` picks the best available host space.
"""

from typing import Optional

import jax

__all__ = ["shard_map", "host_memory_kind", "default_memory_kind",
           "install_cpu_donation_cache_guard"]


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """New-style ``jax.shard_map`` signature on any supported jax."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map
    return _shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                      check_rep=check_vma)


def _memory_kinds(device) -> set:
    try:
        return {m.kind for m in device.addressable_memories()}
    except Exception:  # noqa: BLE001 - backend without the memories API
        return set()


def host_memory_kind(device) -> Optional[str]:
    """The backend's host memory space for table offload: ``pinned_host``
    where the runtime supports it (TPU; DMA-able), else ``unpinned_host``
    (older XLA:CPU), else None (no host space — offload must stay off)."""
    kinds = _memory_kinds(device)
    for kind in ("pinned_host", "unpinned_host"):
        if kind in kinds:
            return kind
    return None


def default_memory_kind(device) -> Optional[str]:
    """The memory space a plain array lands in on `device` ('device' on
    TPU/GPU; older XLA:CPU reports 'unpinned_host'). Lets tests assert
    offload placement without hardcoding a backend's space names."""
    try:
        return device.default_memory().kind
    except Exception:  # noqa: BLE001
        kinds = _memory_kinds(device)
        if "device" in kinds:
            return "device"
        return next(iter(kinds), None)


_donation_cache_guard_installed = False


def install_cpu_donation_cache_guard() -> bool:
    """Bypass the persistent compilation cache for DONATED modules on the
    XLA:CPU backend (idempotent; returns True when the guard is active).

    jaxlib 0.4.36's CPU runtime intermittently mis-executes executables
    **deserialized from the persistent compilation cache** when the
    module carries input->output buffer donation (`tf.aliasing_output`
    on unsharded modules, `jax.buffer_donor` on sharded ones — the
    donated sharded train step lowers with the latter):
    roughly 1 in 5 cache-loaded donated train steps computes structurally
    wrong numerics (~7% off on a small training loss), consistently for
    the lifetime of that loaded executable, while the freshly-compiled
    twin of the SAME StableHLO is always correct. Isolated empirically
    (tests/conftest.py enables the cache; the wire-compression bit-exact
    A/B tests build identical donated steps twice per process, which
    made the load path hot): 225/225 builds correct with the cache off,
    135/135 correct with the cache on and donation off, ~20% of
    processes wrong with both on. Undonated programs (forwards, inits,
    set_weights) load correctly, so the guard scopes the bypass to
    donated modules on CPU: they always compile fresh — correctness over
    compile-time reuse — and everything else keeps the cache. TPU/GPU
    backends are untouched.
    """
    global _donation_cache_guard_installed
    if _donation_cache_guard_installed:
        return True
    try:
        from jax._src import compilation_cache as _comp_cache
        from jax._src import compiler as _compiler
        orig = _compiler.compile_or_get_cached
        backend_compile = _compiler.backend_compile
        cache_in_use = _comp_cache.is_cache_used
    except Exception:  # noqa: BLE001 - internal layout changed; newer
        return False   # jax releases carry the runtime fix anyway

    def _compile_or_get_cached(backend, computation, devices,
                               compile_options, host_callbacks,
                               *args, **kwargs):
        # the O(module-text) donation probe only runs where the hazard
        # exists: CPU backend AND the persistent cache actually enabled
        if (getattr(backend, "platform", None) == "cpu"
                and cache_in_use(backend)):
            try:
                text = str(computation)
                donated = ("tf.aliasing_output" in text
                           or "jax.buffer_donor" in text)
            except Exception:  # noqa: BLE001 - unprintable module
                donated = True  # fail safe: skip the cache
            if donated:
                return backend_compile(backend, computation,
                                       compile_options, host_callbacks)
        return orig(backend, computation, devices, compile_options,
                    host_callbacks, *args, **kwargs)

    _compiler.compile_or_get_cached = _compile_or_get_cached
    _donation_cache_guard_installed = True
    return True
