"""Single-device embedding layers (TPU-native, functional).

API mirror of the reference's Embedding / ConcatOneHotEmbedding / IntegerLookup
(reference: distributed_embeddings/python/layers/embedding.py:50-281), redesigned
as explicit-parameter functional modules: a layer object holds static config
only; ``init(key)`` returns a params pytree and ``__call__(params, inputs)``
is a pure function, so everything composes with jit / pjit / shard_map /
autodiff with no framework magic.
"""

import os
from typing import Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.ops import embedding_ops
from distributed_embeddings_tpu.utils.initializers import get_initializer


class Embedding:
    """Turns indices into fixed-size vectors, with optional built-in combine.

    Mirrors reference Embedding (embedding.py:50-170): a keras Embedding
    unified with embedding_lookup_sparse. Supported inputs when combiner is
    set: N-D dense ids, 2-D RaggedIds, 2-D SparseIds.

    Args:
      input_dim: vocabulary size.
      output_dim: embedding width.
      embeddings_initializer: initializer spec (see utils.initializers).
      combiner: None | 'sum' | 'mean'.
      use_custom_kernel: route the multi-hot path through the Pallas fused
        kernel when available (the reference's custom-CUDA-kernel toggle,
        embedding.py:80). XLA-native path otherwise.
      dtype: parameter dtype.
    """

    def __init__(self,
                 input_dim: int,
                 output_dim: int,
                 embeddings_initializer="uniform",
                 combiner: Optional[str] = None,
                 use_custom_kernel: bool = True,
                 dtype=jnp.float32,
                 name: Optional[str] = None):
        if input_dim <= 0 or output_dim <= 0:
            raise ValueError(
                f"Both input_dim and output_dim should be positive, "
                f"found {input_dim} and {output_dim}")
        if combiner not in (None, "sum", "mean"):
            raise ValueError(f"Unsupported combiner {combiner}")
        self.input_dim = input_dim
        self.output_dim = output_dim
        self.embeddings_initializer = embeddings_initializer
        self.combiner = combiner
        self.use_custom_kernel = use_custom_kernel
        self.dtype = dtype
        self.name = name

    def init(self, key) -> dict:
        init_fn = get_initializer(self.embeddings_initializer)
        return {
            "embeddings": init_fn(key, (self.input_dim, self.output_dim), self.dtype)
        }

    def __call__(self, params: dict, inputs):
        table = params["embeddings"]
        ids = inputs
        if isinstance(ids, (embedding_ops.RaggedIds, embedding_ops.SparseIds)):
            return embedding_ops.embedding_lookup(table, ids, combiner=self.combiner)
        ids = jnp.asarray(ids)
        out_shape = None
        if ids.ndim == 1:
            if self.combiner is not None:
                raise ValueError(
                    "1D input with combiner is ambiguous. Please create batch dimension.")
            ids = ids.reshape(-1, 1)
            out_shape = (-1, self.output_dim)
        elif ids.ndim > 2:
            # reduce over last dim only (reference embedding.py:124-138)
            if self.combiner is not None:
                out_shape = (-1,) + tuple(ids.shape[1:-1]) + (self.output_dim,)
            else:
                out_shape = (-1,) + tuple(ids.shape[1:]) + (self.output_dim,)
            ids = ids.reshape(-1, ids.shape[-1])
        if (self.combiner is not None and ids.ndim == 2 and ids.shape[1] > 1
                and self._pallas_enabled()):
            from distributed_embeddings_tpu.ops import pallas_lookup
            out = pallas_lookup.fused_embedding_lookup(
                table, ids, combiner=self.combiner)
        else:
            out = embedding_ops.embedding_lookup(table, ids,
                                                 combiner=self.combiner)
        if out_shape is not None:
            out = out.reshape(out_shape)
        return out

    def _pallas_enabled(self) -> bool:
        """Custom kernels compile only on real TPU; elsewhere the XLA path is
        both the fallback and the numerics reference (interpret mode is for
        tests, far too slow for training)."""
        if not self.use_custom_kernel:
            return False
        try:
            from distributed_embeddings_tpu.ops import pallas_lookup
        except ImportError:  # pallas unavailable on this jax build
            return False
        if os.environ.get("DET_FORCE_PALLAS", "0") == "1":
            return True
        return pallas_lookup.is_tpu_backend()

    def compute_output_shape(self, input_shape):
        if self.combiner is None:
            return tuple(input_shape) + (self.output_dim,)
        return tuple(input_shape[:-1]) + (self.output_dim,)

    def get_config(self) -> dict:
        return {
            "input_dim": self.input_dim,
            "output_dim": self.output_dim,
            "embeddings_initializer": self.embeddings_initializer,
            "combiner": self.combiner,
            "use_custom_kernel": self.use_custom_kernel,
            "dtype": self.dtype,
            "name": self.name,
        }

    @classmethod
    def from_config(cls, config: dict) -> "Embedding":
        config = dict(config)
        # accept stock-keras-style configs (reference embedding.py:163-170)
        config.pop("mask_zero", None)
        config.pop("input_length", None)
        config.pop("embeddings_regularizer", None)
        config.pop("activity_regularizer", None)
        config.pop("embeddings_constraint", None)
        return cls(**config)


class ConcatOneHotEmbedding:
    """Many one-hot tables fused into one tall table; a single offset gather.

    Mirror of reference ConcatOneHotEmbedding (embedding.py:173-198).
    """

    def __init__(self, feature_sizes: Sequence[int], embedding_width: int,
                 embeddings_initializer="uniform", dtype=jnp.float32):
        self.feature_sizes = list(feature_sizes)
        self.embedding_width = embedding_width
        self.embeddings_initializer = embeddings_initializer
        self.dtype = dtype
        self._offsets_np = np.concatenate([[0], np.cumsum(feature_sizes)])

    def init(self, key) -> dict:
        init_fn = get_initializer(self.embeddings_initializer)
        shape = (int(self._offsets_np[-1]), self.embedding_width)
        return {"params": init_fn(key, shape, self.dtype)}

    def __call__(self, params: dict, inputs):
        offsets = jnp.asarray(self._offsets_np[:-1], dtype=jnp.int32)
        offset_ids = jnp.asarray(inputs) + offsets
        return jnp.take(params["params"], offset_ids, axis=0)


class IntegerLookup:
    """Maps raw int64 keys to contiguous indices, building vocab on the fly.

    Mirror of reference IntegerLookup (embedding.py:202-281). The reference's
    GPU backend is a cuCollections hash map living in device memory
    (embedding_lookup_kernels.cu:383-516); TPUs have no device-side dynamic
    hash table, so the TPU-native design runs the hash on the TPU-VM host —
    a C++ open-addressing table (native/hashmap.cpp, loaded via ctypes) with a
    pure-numpy fallback — and keeps the device side a plain gather. Index 0 is
    reserved for OOV, matching the reference (embedding.py:219-220).

    This layer is stateful host-side preprocessing: call it outside jit (like
    a tf.data transform), or via `as_callback()` inside jit.

    Reserved keys: the two most negative int64 values (INT64_MIN and
    INT64_MIN+1 — the native map's empty/tombstone slot sentinels) are
    never bound; they translate to OOV (0) on every path, on both
    backends. No realistic hash or id space reaches them.
    """

    def __init__(self, max_tokens: int, use_native: Optional[bool] = None):
        max_tokens = int(max_tokens)
        self.max_tokens = max_tokens
        self.capacity = max_tokens + 1
        backend = None
        if use_native is None:
            use_native = os.environ.get("DET_DISABLE_NATIVE", "0") != "1"
        if use_native:
            try:
                from distributed_embeddings_tpu.native import hashmap as native_hashmap
                backend = native_hashmap.NativeIntegerLookup(self.capacity)
            except Exception as e:  # noqa: BLE001 - fall back to numpy backend
                import warnings
                warnings.warn(
                    "IntegerLookup native backend unavailable "
                    f"({type(e).__name__}: {e}); falling back to the pure-"
                    "Python per-key loop — expect orders of magnitude lower "
                    "keys/sec (host-bound). Set DET_DISABLE_NATIVE=1 to "
                    "silence.", RuntimeWarning, stacklevel=2)
                backend = None
        if backend is None:
            backend = _NumpyIntegerLookup(self.capacity)
        self._backend = backend

    @property
    def native(self) -> bool:
        """True when the C++ open-addressing backend is active."""
        return not isinstance(self._backend, _NumpyIntegerLookup)

    def __call__(self, inputs):
        arr = np.asarray(inputs, dtype=np.int64)
        flat = arr.reshape(-1)
        if self.native:
            # the native backend probes in parallel (O(n), multi-thread)
            # and its ordered sequential insert phase keeps first-
            # appearance id assignment with duplicates in the batch, so
            # it takes the raw stream — a numpy pre-unique would
            # serialize everything behind an O(n log n) sort
            out = self._backend.lookup_or_insert(flat)
        else:
            # numpy fallback: per-batch unique before the per-key dict
            # loop (the reference's CPU backend does exactly this,
            # embedding.py:246-252) — power-law id streams are duplicate-
            # heavy, so hashing |unique| << N keys wins. np.unique sorts;
            # reorder by first appearance so insertion ids (and
            # get_vocabulary order) match the sequential contract.
            uniq, first_idx, inv = np.unique(flat, return_index=True,
                                             return_inverse=True)
            if len(uniq) < len(flat):
                order = np.argsort(first_idx, kind="stable")
                out_u = self._backend.lookup_or_insert(uniq[order])
                rank = np.empty_like(order)
                rank[order] = np.arange(len(order))
                out = out_u[rank][inv]
            else:
                out = self._backend.lookup_or_insert(flat)
        if not self.native:
            # the dedup above hides duplicate occurrences from the numpy
            # backend; count the full stream here (the native backend
            # counts per occurrence inside its probe)
            self._backend.add_counts(out)
        res = out.reshape(arr.shape)
        if isinstance(inputs, jax.Array):
            return jnp.asarray(res)
        return res

    def lookup(self, inputs):
        """Query-only lookup (no vocabulary growth); unknown keys -> 0."""
        arr = np.asarray(inputs, dtype=np.int64)
        out = self._backend.lookup(arr.reshape(-1))
        return out.reshape(arr.shape)

    def as_callback(self, inputs: jax.Array) -> jax.Array:
        """Run the host hash under jit via io_callback (ordered: mutates state)."""
        import jax.experimental

        out_dtype = jnp.int64 if jax.config.jax_enable_x64 else jnp.int32

        def host_fn(x):
            out = np.asarray(self.__call__(np.asarray(x)))
            return out.astype(out_dtype)

        return jax.experimental.io_callback(
            host_fn, jax.ShapeDtypeStruct(inputs.shape, out_dtype), inputs,
            ordered=True)

    def counts(self) -> np.ndarray:
        """Per-index access frequencies: [capacity] int64, index 0 = OOV.

        counts()[i] is how many times translated index i was produced by
        `__call__`/`lookup_or_insert` — the natural frequency source for
        hot-row admission (`DistributedEmbedding.hot_keys_from_counts`
        consumes exactly this, truncated to the table's input_dim). The
        native backend counts with relaxed atomics in its parallel probe;
        the numpy fallback counts per batch."""
        return self._backend.counts()

    def erase(self, keys) -> np.ndarray:
        """Unbind keys from the vocabulary (ISSUE 7 eviction): each key's
        index is released and will be REUSED by a later insertion (LIFO),
        so a bounded table can follow an unbounded, drifting key space.
        Returns the freed index per key (0 = key was not bound). A later
        `lookup` of an erased key returns 0 (OOV) again, and its
        frequency count resets — a future tenant of the index must not
        inherit it."""
        arr = np.asarray(keys, dtype=np.int64)
        return self._backend.erase(arr.reshape(-1)).reshape(arr.shape)

    def free_slots(self) -> np.ndarray:
        """Erased (reusable) indices in reuse order — together with
        `get_vocabulary` this is the full binding state eviction-aware
        checkpoints round-trip."""
        return np.asarray(self._backend.free_slots(), np.int64)

    def get_vocabulary(self):
        """Keys in insertion (lookup-index) order, with -1 in the OOV slot
        (reference embedding.py:255-281 returns [-1] + keys). Erased
        indices appear as None holes (their positions must keep later
        keys index-aligned) until reused."""
        hole = np.iinfo(np.int64).min
        return [-1] + [None if k == hole else k
                       for k in self._backend.keys_in_index_order()]

    @property
    def size(self) -> int:
        """Live vocabulary size including the OOV slot (erases shrink)."""
        return self._backend.size + 1  # + OOV slot


class _NumpyIntegerLookup:
    """Pure-python fallback backend: dict-based, OOV (full table) -> 0.
    Mirrors the native contract including erase: freed indices reused
    LIFO before new ones are minted past the high-water mark, and the
    two RESERVED key values (the native map's slot sentinels,
    INT64_MIN and INT64_MIN+1) map to OOV without ever being stored —
    a dict would happily hold them, but the backends must agree."""

    _HOLE = np.iinfo(np.int64).min
    _RESERVED = (np.iinfo(np.int64).min, np.iinfo(np.int64).min + 1)

    def __init__(self, capacity: int):
        self.capacity = capacity
        self._map = {}
        self._counts = np.zeros((capacity,), np.int64)
        self._free = []           # erased indices, reuse order (LIFO)
        self._high = 0            # highest index ever assigned

    @property
    def size(self) -> int:
        return len(self._map)

    def lookup_or_insert(self, keys: np.ndarray) -> np.ndarray:
        out = np.zeros(keys.shape, dtype=np.int64)
        m = self._map
        cap = self.capacity - 1  # index 0 reserved for OOV
        for i, k in enumerate(keys.tolist()):
            if k in self._RESERVED:
                out[i] = 0
                continue
            idx = m.get(k)
            if idx is None:
                if len(m) < cap:
                    if self._free:
                        idx = self._free.pop()
                    else:
                        self._high += 1
                        idx = self._high
                    m[k] = idx
                else:
                    idx = 0
            out[i] = idx
        return out

    def erase(self, keys: np.ndarray) -> np.ndarray:
        out = np.zeros(keys.shape, dtype=np.int64)
        for i, k in enumerate(keys.tolist()):
            idx = self._map.pop(k, None)
            if idx is not None:
                out[i] = idx
                self._free.append(idx)
                self._counts[idx] = 0
        return out

    def free_slots(self) -> np.ndarray:
        return np.asarray(self._free, np.int64)

    def add_counts(self, indices: np.ndarray) -> None:
        """Per-OCCURRENCE frequency accounting (the class-level caller
        passes the full pre-dedup index stream, mirroring the native
        backend's in-probe counting)."""
        np.add.at(self._counts, indices.reshape(-1), 1)

    def lookup(self, keys: np.ndarray) -> np.ndarray:
        out = np.zeros(keys.shape, dtype=np.int64)
        m = self._map
        for i, k in enumerate(keys.tolist()):
            out[i] = m.get(k, 0)
        return out

    def keys_in_index_order(self):
        out = [self._HOLE] * self._high
        for k, idx in self._map.items():
            out[idx - 1] = k
        return out

    def counts(self) -> np.ndarray:
        return self._counts.copy()
