"""Hybrid data-parallel / model-parallel distributed embedding for TPU.

API mirror of the reference `DistributedEmbedding`
(reference: distributed_embeddings/python/layers/dist_model_parallel.py:712-1214),
re-designed SPMD-first:

  * One 1-D `jax.sharding.Mesh` axis plays both the dp and mp role (the
    reference likewise requires dp ranks == mp ranks, :757).
  * The forward is a single `shard_map` region: ids move dp->mp via a true
    `lax.all_to_all` — each device sends every destination only the ids of
    the features that destination owns, packed per (bucket, hotness)
    "exchange group" so per-device id traffic is
    O(owned features x true hotness), matching the reference's
    hvd.alltoall-with-splits (:169-288, :211) rather than replicating all
    ids everywhere. Embedding outputs move mp->dp the same way (:870-872).
  * Row-sliced tables: all_gather ids -> masked local lookup -> psum_scatter,
    the equivalent of hvd.grouped_allgather + grouped_reducescatter (:889-904).
    XLA gather clamps out-of-bounds instead of zero-filling like TF, so
    validity is masked explicitly.
  * There is no DistributedGradientTape/Optimizer monkey-patching layer:
    under sharded autodiff, grads of mp-sharded params stay local and grads of
    replicated (dp) params are psummed by the shard_map transpose — the
    behavioral contract of the reference's patched tape (:1242-1267) falls out
    for free.

Exchange-group design (the TPU answer to Horovod's variable `splits`):
XLA collectives need static shapes, so the variable per-destination split
sizes of hvd.alltoall are re-expressed as a *set* of fixed-shape all_to_alls.
Slots of one fused bucket are grouped by their input's hotness k; each group
exchanges a dense [world, B_local, f_max_g, k] block. Within a group there is
no hotness padding at all (every member has exactly k ids), and f_max_g
padding is bounded by per-destination feature-count imbalance, which the
planner's placement strategies already minimize.
"""

import math
from typing import Any, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.ops import embedding_ops, pallas_lookup
from distributed_embeddings_tpu.ops.embedding_ops import RaggedIds, SparseIds
from distributed_embeddings_tpu.parallel.mesh import DEFAULT_AXIS, create_mesh
from distributed_embeddings_tpu.parallel.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.parallel.plan import ShardedPlan, lower_strategy
from distributed_embeddings_tpu.utils.initializers import get_initializer

__all__ = [
    "DistEmbeddingStrategy",
    "DistributedEmbedding",
    "broadcast_variables",
]


def _combine(emb: jax.Array, weights: Optional[jax.Array],
             combiner: Optional[str]) -> jax.Array:
    """Reduce the hotness axis (second-to-last) of `emb` [..., K, w].

    weights [..., K] carries 0 for padded slots; mean divides by the true
    (weighted) count, matching tf.nn.embedding_lookup_sparse semantics.
    """
    if combiner is None:
        # flatten hotness into width; caller re-slices per-input
        return emb.reshape(emb.shape[:-2] + (emb.shape[-2] * emb.shape[-1],))
    if weights is None:
        if combiner == "sum":
            return jnp.sum(emb, axis=-2)
        return jnp.mean(emb, axis=-2)
    out = jnp.einsum("...k,...kw->...w", weights.astype(emb.dtype), emb)
    if combiner == "mean":
        denom = jnp.maximum(jnp.sum(weights, axis=-1), 1.0).astype(out.dtype)
        out = out / denom[..., None]
    return out


class _PreparedInput:
    """A normalized input: dense ids [B, k] (+ optional 0/1 weights [B, k])."""

    __slots__ = ("ids", "weights", "orig_1d", "k")

    def __init__(self, ids, weights, orig_1d, k):
        self.ids = ids
        self.weights = weights
        self.orig_1d = orig_1d
        self.k = k


class _ExchangeGroup:
    """The slots of one tp bucket whose inputs share hotness k — one
    fixed-shape all_to_all unit (see module docstring). Static planning data
    computed at trace time from the plan + each input's (static) hotness."""

    __slots__ = ("bucket", "k", "class_inputs", "sel", "offs", "f_max",
                 "need_w", "rank_slots")

    def __init__(self, bucket, k, class_inputs, sel, offs, f_max, need_w,
                 rank_slots):
        self.bucket = bucket            # index into plan.tp_buckets
        self.k = k                      # hotness shared by all member inputs
        self.class_inputs = class_inputs  # tp-input indices, stack order
        self.sel = sel                  # [world, f_max] -> class input pos
        self.offs = offs                # [world, f_max] fused-table row offsets
        self.f_max = f_max
        self.need_w = need_w
        self.rank_slots = rank_slots    # per rank: ordered member TPSlots


class DistributedEmbedding:
    """Distributed embedding wrapper: plans placement for a list of embedding
    tables and runs the hybrid-parallel lookup over a device mesh.

    Args (mirroring the reference :712-751):
      embeddings: list of `Embedding` layer objects (or anything exposing
        `get_config()` with input_dim/output_dim/combiner).
      strategy: 'basic' | 'memory_balanced' | 'memory_optimized'.
      column_slice_threshold: tables above this element count are split along
        output_dim into power-of-2 slices. None = auto only when there are
        fewer tables than devices.
      row_slice_threshold: tables above this element count are row-sliced
        evenly across all devices.
      dp_input: if True, `apply` takes data-parallel input — one global-batch
        array per feature. If False, takes model-parallel input (see
        `apply_mp`).
      input_table_map: input i -> table input_table_map[i] (shared tables).
      data_parallel_threshold: tables below this run replicated data-parallel.
      gpu_embedding_size: on-device element budget for table-parallel tables;
        overflow tables are flagged for host offload.
      mesh: jax Mesh with a single axis (default: all devices, axis "mp").
        world_size is taken from the mesh.
      input_max_hotness: optional per-input static max hotness, required to
        accept RaggedIds inputs (TPU needs static shapes).
    """

    def __init__(self,
                 embeddings: Sequence,
                 strategy: str = "basic",
                 column_slice_threshold: Optional[int] = None,
                 row_slice_threshold: Optional[int] = None,
                 dp_input: bool = True,
                 input_table_map: Optional[Sequence[int]] = None,
                 data_parallel_threshold: Optional[int] = None,
                 gpu_embedding_size: Optional[int] = None,
                 mesh: Optional[Mesh] = None,
                 world_size: Optional[int] = None,
                 input_max_hotness: Optional[Sequence[Optional[int]]] = None,
                 use_custom_kernel: bool = True,
                 compute_dtype: Optional[Any] = None):
        if mesh is None and world_size is not None and world_size > 1:
            mesh = create_mesh(jax.devices()[:world_size])
        self.mesh = mesh
        if mesh is not None:
            if len(mesh.axis_names) != 1:
                raise ValueError("DistributedEmbedding expects a 1-D mesh")
            self.axis = mesh.axis_names[0]
            self.world_size = mesh.devices.size
        else:
            self.axis = DEFAULT_AXIS
            self.world_size = 1

        self.dp_input = dp_input
        # single worker: fall back to pure table-parallel like the reference
        # (:764-774); mp-input mode also disables dp/row groups.
        if self.world_size > 1 and dp_input:
            row_thr, dp_thr = row_slice_threshold, data_parallel_threshold
        else:
            row_thr, dp_thr = None, None

        self.strategy = DistEmbeddingStrategy(
            embeddings, self.world_size, strategy,
            input_table_map=input_table_map,
            column_slice_threshold=column_slice_threshold,
            row_slice_threshold=row_thr,
            data_parallel_threshold=dp_thr,
            gpu_embedding_size=gpu_embedding_size)

        if self.strategy.table_groups[1]:
            if not all(self.strategy.local_configs):
                raise ValueError(
                    "Not enough tables after slicing to run on all devices. "
                    "Try decreasing column_slice_threshold or device count.")

        self.plan: ShardedPlan = lower_strategy(self.strategy)
        self.input_max_hotness = (list(input_max_hotness)
                                  if input_max_hotness is not None else None)
        self._n_inputs = len(self.strategy.input_table_map)
        # like the reference Embedding's use_custom_kernel (embedding.py:72):
        # route multi-hot fused-bucket lookups through the Pallas kernels when
        # on a TPU backend; plain XLA gather+reduce otherwise.
        self.use_custom_kernel = use_custom_kernel
        # mixed precision (reference tests' mixed_precision_policy,
        # dist_model_parallel_test.py:30-34): params stay fp32, the lookup
        # outputs / combines / collectives run in compute_dtype (e.g. bf16).
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        self._groups_cache: dict = {}
        if any(b.offload for b in self.plan.tp_buckets):
            import warnings
            warnings.warn(
                "gpu_embedding_size flagged table(s) for host offload, but "
                "physical host placement is not wired yet (jax memory-space "
                "propagation through shard_map): offloaded buckets remain "
                "device-resident and count against HBM.", RuntimeWarning,
                stacklevel=2)

    # ------------------------------------------------------------------ init
    def _tp_shard(self, key, b: int, rank: int) -> jax.Array:
        """One rank's fused bucket table [rows_max, width] (traced/jittable)."""
        bucket = self.plan.tp_buckets[b]
        tbl = jnp.zeros((max(bucket.rows_max, 1), bucket.width), jnp.float32)
        for seg_i, (table_id, row_offset, rows, init_spec, dtype) in enumerate(
                bucket.init_segments[rank]):
            seg_key = jax.random.fold_in(
                jax.random.fold_in(key, table_id), rank * 131071 + seg_i)
            init_fn = get_initializer(init_spec)
            block = init_fn(seg_key, (rows, bucket.width),
                            dtype or jnp.float32)
            tbl = tbl.at[row_offset:row_offset + rows].set(block)
        return tbl

    def _row_shard(self, key, t: int, rank: int) -> jax.Array:
        rt = self.plan.row_tables[t]
        init_fn = get_initializer(rt.initializer)
        tbl = jnp.zeros((max(rt.rows_max, 1), rt.width), jnp.float32)
        rows = rt.rows_per_rank[rank]
        seg_key = jax.random.fold_in(jax.random.fold_in(key, 7919 + t), rank)
        return tbl.at[:rows].set(init_fn(seg_key, (rows, rt.width),
                                         rt.dtype or jnp.float32))

    def _rank_of_device(self):
        """Map each addressable mesh device -> its rank index (axis position).

        Multi-process safe: iterates only devices this process can address."""
        flat = list(self.mesh.devices.flat)
        return [(flat.index(d), d) for d in flat
                if d.process_index == jax.process_index()]

    def _stack_sharded(self, shard_fn) -> jax.Array:
        """Assemble a [world, rows_max, w] P(axis)-sharded array by computing
        (or staging) each rank's shard directly on that rank's device — peak
        staging is one shard, never the global stack (round-1 gap: the
        reference chunks set_weights for the same reason, :977-1017, and
        CPU-inits to dodge init OOM, embedding.py:28-47).

        shard_fn(rank) -> [rows_max, w] array-like for that rank.
        """
        shards, shape = [], None
        for rank, dev in self._rank_of_device():
            with jax.default_device(dev):
                shard = jnp.asarray(shard_fn(rank))[None]
            shard = jax.device_put(shard, dev)
            shards.append(shard)
            shape = shard.shape
        global_shape = (self.world_size,) + tuple(shape[1:])
        sharding = NamedSharding(self.mesh, P(self.axis))
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards)

    def init(self, key) -> dict:
        """Create the parameter pytree:
          {'dp': [replicated [V,w]...],
           'tp': [stacked [world, rows_max, w] per bucket...],
           'row': [stacked [world, slice_rows_max, w] per row table...]}

        With a mesh bound, every tp/row shard is materialized per-device
        (shard-sized staging); without one, plain stacked arrays.
        """
        kd, kt, kr = jax.random.split(key, 3)
        params = {"dp": [], "tp": [], "row": []}
        for j, cfg in enumerate(self.strategy.dp_configs):
            init_fn = get_initializer(cfg.get("embeddings_initializer", "uniform"))
            params["dp"].append(init_fn(
                jax.random.fold_in(kd, j),
                (cfg["input_dim"], cfg["output_dim"]),
                cfg.get("dtype") or jnp.float32))
        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            params["dp"] = [jax.device_put(a, rep) for a in params["dp"]]
            tp_init = jax.jit(self._tp_shard, static_argnums=(1, 2))
            row_init = jax.jit(self._row_shard, static_argnums=(1, 2))
            for b in range(len(self.plan.tp_buckets)):
                params["tp"].append(self._stack_sharded(
                    lambda rank, b=b: tp_init(kt, b, rank)))
            for t in range(len(self.plan.row_tables)):
                params["row"].append(self._stack_sharded(
                    lambda rank, t=t: row_init(kr, t, rank)))
        else:
            for b in range(len(self.plan.tp_buckets)):
                params["tp"].append(jnp.stack(
                    [self._tp_shard(kt, b, r) for r in range(self.world_size)]))
            for t in range(len(self.plan.row_tables)):
                params["row"].append(jnp.stack(
                    [self._row_shard(kr, t, r) for r in range(self.world_size)]))
        return params

    def param_shardings(self, mesh: Optional[Mesh] = None) -> dict:
        """NamedSharding pytree matching `init` output — for pjit/device_put.

        Offload status: buckets flagged by the planner's gpu_embedding_size
        budget (reference _maybe_offload :449-476) are kept in separate
        buckets so they can be placed/streamed independently; physical
        pinned-host placement is not wired yet — as of jax 0.9, XLA's
        memory-space propagation does not reach through shard_map bodies, so
        host-resident tables cannot participate in the SPMD forward.
        """
        mesh = mesh or self.mesh
        if mesh is None:
            raise ValueError("No mesh bound")
        rep = NamedSharding(mesh, P())
        shard0 = NamedSharding(mesh, P(self.axis))
        return {
            "dp": [rep for _ in self.strategy.dp_configs],
            "tp": [shard0 for _ in self.plan.tp_buckets],
            "row": [shard0 for _ in self.plan.row_tables],
        }

    # ----------------------------------------------------------- input prep
    def _prepare_one(self, x, max_hotness: Optional[int]) -> _PreparedInput:
        if isinstance(x, tuple) and len(x) == 2 and not isinstance(x, RaggedIds):
            ids, weights = x
            return _PreparedInput(jnp.asarray(ids), jnp.asarray(weights),
                                  False, ids.shape[1])
        if isinstance(x, RaggedIds):
            if max_hotness is None:
                raise ValueError(
                    "RaggedIds input requires input_max_hotness (static shapes "
                    "are mandatory on TPU)")
            ids, weights = embedding_ops.ragged_to_padded(x, max_hotness)
            return _PreparedInput(ids, weights, False, max_hotness)
        if isinstance(x, SparseIds):
            batch, k = int(x.dense_shape[0]), int(x.dense_shape[1])
            rows, cols = x.indices[:, 0], x.indices[:, 1]
            ids = jnp.zeros((batch, k), x.values.dtype).at[rows, cols].set(x.values)
            weights = jnp.zeros((batch, k), jnp.float32).at[rows, cols].set(1.0)
            return _PreparedInput(ids, weights, False, k)
        ids = jnp.asarray(x)
        if ids.ndim == 1:
            return _PreparedInput(ids[:, None], None, True, 1)
        if ids.ndim != 2:
            raise ValueError(f"Expected 1-D or 2-D ids, got shape {ids.shape}")
        return _PreparedInput(ids, None, False, ids.shape[1])

    def _prepare_inputs(self, inputs) -> List[_PreparedInput]:
        if len(inputs) != self._n_inputs:
            raise ValueError(
                f"Expected {self._n_inputs} inputs, got {len(inputs)}")
        prepped = []
        for i, x in enumerate(inputs):
            mh = (self.input_max_hotness[i]
                  if self.input_max_hotness is not None else None)
            prepped.append(self._prepare_one(x, mh))
        return prepped

    def _exchange_groups(self, tp_prep: Sequence[_PreparedInput]):
        """Compute the (bucket, hotness) exchange groups and the per-input
        assembly map for a given set of prepared inputs.

        Returns (groups, assembly) where assembly[i] is the ordered list of
        (rank, group_idx, slot_in_group) triples for tp input i — the same
        rank-major slot order the plan's weight layout uses (col_cursor order,
        reference :921-936), so column-slice re-concat stays correct.
        Cached per hotness/weights signature (one entry per jit trace shape).
        """
        key = tuple((p.k, p.weights is not None) for p in tp_prep)
        hit = self._groups_cache.get(key)
        if hit is not None:
            return hit
        world = self.world_size
        per_bk: dict = {}   # (bucket, k) -> per-rank [(slot_idx, TPSlot)...]
        order: List[Tuple[int, int]] = []
        for b, bucket in enumerate(self.plan.tp_buckets):
            for r, slots in enumerate(bucket.slots):
                for j, s in enumerate(slots):
                    k = tp_prep[s.tp_input].k
                    if (b, k) not in per_bk:
                        per_bk[(b, k)] = [[] for _ in range(world)]
                        order.append((b, k))
                    per_bk[(b, k)][r].append((j, s))
        groups: List[_ExchangeGroup] = []
        slot_map: dict = {}  # (bucket, rank, slot_idx_in_bucket) -> (g, j_g)
        for g, (b, k) in enumerate(order):
            ranks = per_bk[(b, k)]
            class_inputs = sorted({s.tp_input for lst in ranks
                                   for (_, s) in lst})
            pos = {i: c for c, i in enumerate(class_inputs)}
            f_max = max(len(lst) for lst in ranks)
            sel = np.zeros((world, f_max), np.int32)
            offs = np.zeros((world, f_max), np.int32)
            rank_slots = []
            for r, lst in enumerate(ranks):
                for j_g, (j, s) in enumerate(lst):
                    sel[r, j_g] = pos[s.tp_input]
                    offs[r, j_g] = s.row_offset
                    slot_map[(b, r, j)] = (g, j_g)
                rank_slots.append([s for (_, s) in lst])
            need_w = any(tp_prep[i].weights is not None for i in class_inputs)
            groups.append(_ExchangeGroup(b, k, class_inputs, sel, offs,
                                         f_max, need_w, rank_slots))
        assembly = [
            [(rank, *slot_map[(bb, rank, jj)]) for (rank, bb, jj) in slots]
            for slots in self.plan.tp_input_slots
        ]
        self._groups_cache[key] = res = (groups, assembly)
        return res

    def _group_lookup(self, table: jax.Array, ids: jax.Array,
                      weights: Optional[jax.Array], combiner: Optional[str],
                      offload: bool) -> jax.Array:
        """Local fused-bucket lookup + combine: ids [B, f, k] -> [B, f, wf].

        Multi-hot sum/mean groups route through the Pallas fused kernel on
        TPU (the hot-loop equivalent of the reference's CUDA combiner,
        cu:175-336); everything else is XLA gather + reduce, which XLA fuses.

        `offload` marks buckets past the gpu_embedding_size budget; a true
        host-side gather (only looked-up rows crossing the host link, the
        reference's /CPU:0 lookup :829-831) needs memory-space propagation
        through shard_map, not available as of jax 0.9 — device-side for now.
        """
        del offload
        b_sz, f, k = ids.shape
        if (combiner in ("sum", "mean") and k > 1 and self.use_custom_kernel
                and pallas_lookup.is_tpu_backend()):
            w = (weights if weights is not None
                 else jnp.ones((b_sz, f, k), jnp.float32))
            out = pallas_lookup.fused_embedding_lookup(
                table, ids.reshape(b_sz * f, k), w.reshape(b_sz * f, k),
                combiner)
            return self._cast(out.reshape(b_sz, f, out.shape[-1]))
        emb = self._cast(jnp.take(table, ids, axis=0))   # [B, f, k, w]
        return _combine(emb, weights, combiner)

    def _cast(self, x: jax.Array) -> jax.Array:
        """Cast a lookup result to compute_dtype (mixed precision no-op when
        unset)."""
        if self.compute_dtype is not None and x.dtype != self.compute_dtype:
            return x.astype(self.compute_dtype)
        return x

    # -------------------------------------------------------------- forward
    def _my_index(self):
        if self.world_size == 1:
            return jnp.int32(0)
        return lax.axis_index(self.axis)

    def _device_const(self, const: np.ndarray):
        """Select this device's row of a [world, ...] planning constant."""
        return jnp.take(jnp.asarray(const), self._my_index(), axis=0)

    def _forward_local(self, dp_params, tp_params, row_params,
                       dp_in, group_ids, group_w, row_in, groups):
        """The per-device forward (shard_map body when world > 1).

        Args:
          dp_in / row_in: lists of (ids [B_l, k], weights or None) per input.
          group_ids: per exchange group, stacked ids [B_l, n_g, k_g].
          group_w: matching weights [B_l, n_g, k_g] or None per group.
          groups: the static _ExchangeGroup records.

        Returns (dp_outs, ex_list, row_outs):
          dp_outs: [B_l, w] (or [B_l, K, w]) per dp input
          ex_list: per group [world_src, B_l, f_max_g, wf]
          row_outs: [B_l, ...] partial sums scattered over batch.
        """
        world = self.world_size
        strat = self.strategy

        # ---- data-parallel tables: plain local lookup on replicated params
        dp_outs = []
        for j, (ids, weights) in enumerate(dp_in):
            cfg = strat.dp_configs[strat.map_groups[0][j]]
            table = dp_params[strat.map_groups[0][j]]
            emb = self._cast(jnp.take(table, ids, axis=0))   # [B_l, k, w]
            dp_outs.append(_combine(emb, weights, cfg.get("combiner")))

        # ---- table-parallel: per-group all_to_all id exchange (dp->mp),
        # local fused lookup, all_to_all back (mp->dp). Each destination
        # receives only ids for features it owns (reference hvd.alltoall
        # with splits, :211) — not an all_gather of everything.
        ex_list = []
        for g, grp in enumerate(groups):
            ids = group_ids[g]                               # [B_l, n_g, k]
            blocal = ids.shape[0]
            sel = jnp.asarray(grp.sel.reshape(-1))           # [world*f_max]
            send = jnp.take(ids, sel, axis=1).reshape(
                blocal, world, grp.f_max, grp.k)
            send = jnp.moveaxis(send, 1, 0)                  # [world, B_l, f, k]
            w_x = None
            if group_w[g] is not None:
                w_send = jnp.take(group_w[g], sel, axis=1).reshape(
                    blocal, world, grp.f_max, grp.k)
                w_send = jnp.moveaxis(w_send, 1, 0)
            if world > 1:
                recv = lax.all_to_all(send, self.axis, split_axis=0,
                                      concat_axis=0)
                if group_w[g] is not None:
                    w_recv = lax.all_to_all(w_send, self.axis, split_axis=0,
                                            concat_axis=0)
                    w_x = w_recv.reshape(-1, grp.f_max, grp.k)
            else:
                recv = send
                if group_w[g] is not None:
                    w_x = w_send.reshape(-1, grp.f_max, grp.k)
            ids_x = recv.reshape(-1, grp.f_max, grp.k)       # [B, f, k]
            offs = self._device_const(grp.offs)              # [f_max]
            ids_x = ids_x + offs[None, :, None].astype(ids_x.dtype)
            bucket = self.plan.tp_buckets[grp.bucket]
            out = self._group_lookup(tp_params[grp.bucket][0], ids_x, w_x,
                                     bucket.combiner, bucket.offload)
            ex_list.append(self._tp_bucket_exchange(out))

        # ---- row-sliced tables: all_gather ids, masked lookup, psum_scatter
        row_outs = self._row_slice_local(row_params, row_in)
        return dp_outs, ex_list, row_outs

    def _tp_bucket_exchange(self, out: jax.Array) -> jax.Array:
        """mp->dp movement of one bucket's outputs: [B, f, wf] ->
        [world_src, B_l, f, wf] (reference hvd.alltoall :870-872)."""
        world = self.world_size
        if world > 1:
            blocal = out.shape[0] // world
            x = out.reshape((world, blocal) + out.shape[1:])
            return lax.all_to_all(x, self.axis, split_axis=0, concat_axis=0)
        return out[None]

    def _row_slice_local(self, row_params, row_in):
        world = self.world_size
        strat = self.strategy
        row_outs = []
        for j, (ids, weights) in enumerate(row_in):
            t = strat.map_groups[2][j]
            rt = self.plan.row_tables[t]
            if world > 1:
                ids = lax.all_gather(ids, self.axis, axis=0, tiled=True)
                if weights is not None:
                    weights = lax.all_gather(weights, self.axis, axis=0, tiled=True)
            base = self._device_const(rt.row_base)
            nrows = self._device_const(np.asarray(rt.rows_per_rank, np.int32))
            local = ids - base.astype(ids.dtype)
            valid = (local >= 0) & (local < nrows.astype(ids.dtype))
            local = jnp.clip(local, 0, max(rt.rows_max - 1, 0))
            table = row_params[t][0]
            emb = self._cast(jnp.take(table, local, axis=0))
            emb = emb * valid[..., None].astype(emb.dtype)
            if rt.combiner is None:
                out = emb                                          # [B, k, w]
            elif weights is None:
                out = (jnp.sum(emb, axis=-2) if rt.combiner == "sum"
                       else jnp.mean(emb, axis=-2))
            else:
                out = jnp.einsum("bk,bkw->bw", weights.astype(emb.dtype), emb)
                if rt.combiner == "mean":
                    denom = jnp.maximum(jnp.sum(weights, axis=-1), 1.0)
                    out = out / denom[:, None].astype(out.dtype)
            if world > 1:
                out = lax.psum_scatter(out, self.axis, scatter_dimension=0,
                                       tiled=True)
            row_outs.append(out)
        return row_outs

    def apply(self, params: dict, inputs: Sequence) -> List[jax.Array]:
        """Forward pass with data-parallel input.

        Args:
          params: pytree from `init` (or `set_weights`).
          inputs: one per feature — global-batch arrays [B] / [B, k],
            RaggedIds, SparseIds or (ids, weights) tuples.

        Returns:
          One [B, width] array per input (or [B, k, width] for combiner=None
          multi-hot), in input order — batch-sharded over the mesh.
        """
        if not self.dp_input:
            raise ValueError("This layer was built with dp_input=False; "
                             "use apply_mp() instead")
        prepped = self._prepare_inputs(inputs)
        strat = self.strategy
        world = self.world_size

        batch = prepped[0].ids.shape[0]
        if world > 1 and batch % world != 0:
            raise ValueError(
                f"Global batch {batch} not divisible by device count {world}")

        dp_prep = [prepped[i] for i in strat.input_groups[0]]
        tp_prep = [prepped[i] for i in strat.input_groups[1]]
        row_prep = [prepped[i] for i in strat.input_groups[2]]

        # stack tp inputs per exchange group: [B, n_g, k_g] (+ weights where
        # any member input carries them — same-k members need no pad weights)
        groups, assembly = ([], [])
        group_ids: List[jax.Array] = []
        group_w: List[Optional[jax.Array]] = []
        if tp_prep:
            groups, assembly = self._exchange_groups(tp_prep)
            for grp in groups:
                members = [tp_prep[i] for i in grp.class_inputs]
                group_ids.append(jnp.stack(
                    [p.ids.astype(jnp.int32) for p in members], axis=1))
                if grp.need_w:
                    group_w.append(jnp.stack(
                        [(p.weights if p.weights is not None
                          else jnp.ones((batch, p.k), jnp.float32))
                         for p in members], axis=1))
                else:
                    group_w.append(None)

        dp_in = [(p.ids, p.weights) for p in dp_prep]
        row_in = [(p.ids, p.weights) for p in row_prep]

        if world > 1:
            specs = lambda tree, spec: jax.tree.map(lambda _: spec, tree)
            args = (params["dp"], params["tp"], params["row"],
                    dp_in, group_ids, group_w, row_in)
            in_specs = (specs(params["dp"], P()),
                        specs(params["tp"], P(self.axis)),
                        specs(params["row"], P(self.axis)),
                        specs(dp_in, P(self.axis)),
                        specs(group_ids, P(self.axis)),
                        specs(group_w, P(self.axis)),
                        specs(row_in, P(self.axis)))
            out_specs = (
                [P(self.axis)] * len(dp_in),
                [P(None, self.axis)] * len(groups),
                [P(self.axis)] * len(row_in),
            )
            dp_outs, ex_list, row_outs = jax.shard_map(
                lambda d, t, r, di, gi, gw, ri: self._forward_local(
                    d, t, r, di, gi, gw, ri, groups),
                mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False,
            )(*args)
        else:
            dp_outs, ex_list, row_outs = self._forward_local(
                params["dp"], params["tp"], params["row"],
                dp_in, group_ids, group_w, row_in, groups)

        # ---- assemble per-input outputs ------------------------------------
        dp_final = []
        for j, out in enumerate(dp_outs):
            p = dp_prep[j]
            cfg = strat.dp_configs[strat.map_groups[0][j]]
            dp_final.append(self._restore_shape(out, p, cfg.get("combiner"),
                                                cfg["output_dim"]))

        tp_final = self._assemble_tp_outputs(ex_list, tp_prep, batch,
                                             groups, assembly)

        row_final = []
        for j, out in enumerate(row_outs):
            p = row_prep[j]
            rt = self.plan.row_tables[strat.map_groups[2][j]]
            row_final.append(self._restore_shape(out, p, rt.combiner, rt.width))

        outputs = dp_final + tp_final + row_final
        return [outputs[idx] for idx in strat.rev_group_ids]

    def _assemble_tp_outputs(self, ex_list, tp_preps, batch, groups,
                             assembly) -> List[jax.Array]:
        """Slice the exchanged group outputs back into per-input arrays:
        reorder by slot, re-concat column slices (reference :876-886).

        Args:
          ex_list: per exchange group [world_src, B, f_max_g, wf] globals.
          tp_preps: _PreparedInput per tp-group input position.
          groups / assembly: from _exchange_groups (rank-major slot order).
        """
        strat = self.strategy
        tp_final = []
        for i, p in enumerate(tp_preps):
            parts = []
            for (rank, g, j_g) in assembly[i]:
                grp = groups[g]
                bucket = self.plan.tp_buckets[grp.bucket]
                part = ex_list[g][rank, :, j_g, :]          # [B, wf]
                if bucket.combiner is None:
                    part = part.reshape(batch, grp.k, bucket.width)
                parts.append(part)
            out = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=-1)
            cfg = strat.global_configs[
                strat.table_groups[1][strat.map_groups[1][i]]]
            tp_final.append(self._restore_shape(out, p, cfg.get("combiner"),
                                                out.shape[-1]))
        return tp_final

    def apply_mp(self, params: dict, inputs) -> List[jax.Array]:
        """Forward pass with model-parallel input (dp_input=False).

        The reference mp-input contract (:729-731, :846-851): each rank
        receives ids at *global* batch size for exactly the features it owns,
        in ``strategy.input_ids_list[rank]`` order, skipping the dp->mp
        exchange (the data loader already reads feature-sharded data, see
        models/data.py RawBinaryDataset).

        Args:
          params: pytree from `init`.
          inputs: nested per-rank lists — ``inputs[r][j]`` feeds the j-th
            local input of rank r (dense [B]/[B,k] ids, RaggedIds, SparseIds
            or (ids, weights)). With world_size == 1 a flat list is accepted.
            In multi-process runs, ``inputs[r]`` may be None for ranks whose
            devices this process cannot address (each process supplies only
            its own ranks' data); that mode requires `input_max_hotness` for
            every input so all processes trace identical shapes.

        Returns:
          One [B, width] array per input in original input order,
          batch-sharded over the mesh.
        """
        if self.dp_input:
            raise ValueError("This layer was built with dp_input=True; "
                             "use apply() instead")
        strat = self.strategy
        world = self.world_size
        if world == 1 and (not inputs or not isinstance(inputs[0], list)):
            inputs = [list(inputs)]
        if len(inputs) != world:
            raise ValueError(
                f"apply_mp expects {world} per-rank input lists, got {len(inputs)}")
        partial_ranks = any(x is None for x in inputs)
        if partial_ranks and (
                self.input_max_hotness is None
                or any(self.input_max_hotness[strat.input_groups[1][pos]]
                       is None
                       for pos in range(len(strat.input_groups[1])))):
            raise ValueError(
                "apply_mp with per-process inputs (None for remote ranks) "
                "requires input_max_hotness for every input: each process "
                "must trace the same static shapes")

        prepped: List[Optional[List[_PreparedInput]]] = []
        rank_pos: List[dict] = []   # per rank: tp input pos -> local index
        input_prep = {}             # tp input pos -> representative prep
        local_ranks = ({r for r, _ in self._rank_of_device()}
                       if self.mesh is not None else {0})
        for r in range(world):
            ids_list = strat.input_ids_list[r] if strat.input_ids_list else []
            if inputs[r] is None:
                if r in local_ranks:
                    raise ValueError(
                        f"rank {r} is addressable by this process; its "
                        "apply_mp inputs cannot be None")
                prepped.append(None)
                rank_pos.append({})
                continue
            if len(inputs[r]) != len(ids_list):
                raise ValueError(
                    f"rank {r}: expected {len(ids_list)} inputs "
                    f"(features {ids_list}), got {len(inputs[r])}")
            plist, pos = [], {}
            for j, (x, inp_pos) in enumerate(zip(inputs[r], ids_list)):
                orig = strat.input_groups[1][inp_pos]
                mh = (self.input_max_hotness[orig]
                      if self.input_max_hotness is not None else None)
                p = self._prepare_one(x, mh)
                if partial_ranks and p.k != mh:
                    raise ValueError(
                        f"rank {r} input {j}: hotness {p.k} != "
                        f"input_max_hotness {mh}; with per-process inputs "
                        "all ids must be padded to the declared max hotness")
                if partial_ranks and p.k == 1 and not p.orig_1d:
                    raise ValueError(
                        f"rank {r} input {j}: feed hotness-1 ids as 1-D [B] "
                        "arrays in per-process mode — every process must "
                        "agree on the restored output shape")
                if partial_ranks and p.weights is None:
                    # uniform weights-presence across processes keeps every
                    # process's exchange-group shapes identical
                    p = _PreparedInput(
                        p.ids, jnp.ones((p.ids.shape[0], p.k), jnp.float32),
                        p.orig_1d, p.k)
                plist.append(p)
                pos[inp_pos] = j
                input_prep.setdefault(inp_pos, p)
            prepped.append(plist)
            rank_pos.append(pos)
        if partial_ranks:
            # synthesize shape-only representatives for inputs that only
            # occur on remote ranks (content irrelevant: each device reads
            # its own shard)
            batches = [p.ids.shape[0] for p in input_prep.values()]
            if not batches:
                raise ValueError("no local rank inputs provided")
            b0 = batches[0]
            for inp_pos in range(len(strat.input_groups[1])):
                if inp_pos not in input_prep:
                    orig = strat.input_groups[1][inp_pos]
                    mh = self.input_max_hotness[orig]
                    # hotness-1 inputs are fed 1-D on their owning process
                    # (enforced above), so mirror orig_1d = (mh == 1) here to
                    # keep every process's restored shapes identical
                    input_prep[inp_pos] = _PreparedInput(
                        jnp.zeros((b0, mh), jnp.int32),
                        jnp.zeros((b0, mh), jnp.float32), mh == 1, mh)
        if not input_prep:
            return []
        batch = next(iter(input_prep.values())).ids.shape[0]
        if world > 1 and batch % world != 0:
            raise ValueError(
                f"Global batch {batch} not divisible by device count {world}")

        # mp input skips the dp->mp exchange entirely (the loader already
        # read feature-sharded data) — stack each rank's local features per
        # exchange group: ids [world, B, f_max_g, k_g] (+ weights). When
        # called eagerly with a mesh, each rank's block is staged directly on
        # that rank's device so only local shards materialize (not a
        # replicated [world, ...] host stack).
        tp_preps = [input_prep[i] for i in range(len(strat.input_groups[1]))]
        groups, assembly = self._exchange_groups(tp_preps)

        def rank_block(grp, r):
            """One rank's [B, f_max, k] ids (+ weights) for one group."""
            cols_i, cols_w = [], []
            for s in grp.rank_slots[r]:
                p = prepped[r][rank_pos[r][s.tp_input]]
                cols_i.append(p.ids.astype(jnp.int32))
                if grp.need_w:
                    cols_w.append(p.weights if p.weights is not None
                                  else jnp.ones((batch, p.k), jnp.float32))
            while len(cols_i) < grp.f_max:
                cols_i.append(jnp.zeros((batch, grp.k), jnp.int32))
                if grp.need_w:
                    cols_w.append(jnp.zeros((batch, grp.k), jnp.float32))
            ids_b = jnp.stack(cols_i, axis=1)               # [B, f, k]
            w_b = jnp.stack(cols_w, axis=1) if grp.need_w else None
            return ids_b, w_b

        def is_traced():
            for plist in prepped:
                for p in (plist or []):
                    if isinstance(p.ids, jax.core.Tracer):
                        return True
            return False

        group_ids, group_w = [], []
        if self.mesh is not None and not is_traced():
            id_shard = NamedSharding(self.mesh, P(self.axis))
            for grp in groups:
                i_shards, w_shards = [], []
                for r, dev in self._rank_of_device():
                    ids_b, w_b = rank_block(grp, r)
                    i_shards.append(jax.device_put(ids_b[None], dev))
                    if grp.need_w:
                        w_shards.append(jax.device_put(w_b[None], dev))
                gshape = (world,) + tuple(i_shards[0].shape[1:])
                group_ids.append(jax.make_array_from_single_device_arrays(
                    gshape, id_shard, i_shards))
                if grp.need_w:
                    wshape = (world,) + tuple(w_shards[0].shape[1:])
                    group_w.append(jax.make_array_from_single_device_arrays(
                        wshape, id_shard, w_shards))
                else:
                    group_w.append(None)
        else:
            if partial_ranks:
                raise ValueError(
                    "per-process (None) apply_mp inputs cannot be used under "
                    "jit/grad tracing; stage arrays eagerly first")
            for grp in groups:
                blocks = [rank_block(grp, r) for r in range(world)]
                group_ids.append(jnp.stack([b[0] for b in blocks]))
                group_w.append(jnp.stack([b[1] for b in blocks])
                               if grp.need_w else None)

        def body(tp_params, group_ids, group_w):
            ex_list = []
            for g, grp in enumerate(groups):
                ids_l = group_ids[g][0]                         # [B, f, k]
                offs = self._device_const(grp.offs)
                ids_l = ids_l + offs[None, :, None].astype(ids_l.dtype)
                w_l = group_w[g][0] if group_w[g] is not None else None
                bucket = self.plan.tp_buckets[grp.bucket]
                out = self._group_lookup(tp_params[grp.bucket][0], ids_l,
                                         w_l, bucket.combiner, bucket.offload)
                ex_list.append(self._tp_bucket_exchange(out))
            return ex_list

        if world > 1:
            specs = lambda tree, spec: jax.tree.map(lambda _: spec, tree)
            ex_list = jax.shard_map(
                body, mesh=self.mesh,
                in_specs=(specs(params["tp"], P(self.axis)),
                          specs(group_ids, P(self.axis)),
                          specs(group_w, P(self.axis))),
                out_specs=[P(None, self.axis)] * len(groups),
                check_vma=False,
            )(params["tp"], group_ids, group_w)
        else:
            ex_list = body(params["tp"], group_ids, group_w)

        outputs = self._assemble_tp_outputs(ex_list, tp_preps, batch,
                                            groups, assembly)
        return [outputs[idx] for idx in strat.rev_group_ids]

    @staticmethod
    def _restore_shape(out, p: _PreparedInput, combiner, width):
        if combiner is not None:
            return out
        # combiner None: canonical shape [B, k, w]; 1-D inputs drop the axis
        if out.ndim == 2:
            out = out.reshape(out.shape[0], -1, width)
        if p.orig_1d:
            out = out[:, 0, :]
        return out

    def __call__(self, params, inputs):
        if self.dp_input:
            return self.apply(params, inputs)
        return self.apply_mp(params, inputs)

    # --------------------------------------------------------- weights I/O
    def _shard_host(self, arr: jax.Array, rank: int) -> np.ndarray:
        """One rank's [rows_max, w] block of a stacked param, fetched
        shard-wise (never materializing the global stack on host)."""
        if hasattr(arr, "addressable_shards"):
            for sh in arr.addressable_shards:
                idx = sh.index[0]
                start = 0 if idx.start is None else idx.start
                stop = arr.shape[0] if idx.stop is None else idx.stop
                if start <= rank < stop:
                    return np.asarray(sh.data)[rank - start]
        return np.asarray(arr)[rank]

    def get_weights(self, params, all_ranks: bool = False) -> List[np.ndarray]:
        """Reassemble global per-table weights in original table order
        (reference get_weights :1139-1162), reading device shards one at a
        time. On a single host this is direct shard access; multi-host
        callers should wrap with process_allgather.
        """
        del all_ranks  # SPMD: every process sees the global jax.Array
        strat = self.strategy
        n = len(strat.global_configs)
        out: List[Optional[np.ndarray]] = [None] * n

        for j, gtid in enumerate(strat.table_groups[0]):
            out[gtid] = np.asarray(params["dp"][j])

        for t_local, gtid in enumerate(strat.table_groups[1]):
            cols = []
            for pl_ in sorted((p for p in self.plan.tp_placements
                               if p.table_id == t_local),
                              key=lambda p: p.col_start):
                shard = self._shard_host(params["tp"][pl_.bucket], pl_.rank)
                cols.append(shard[pl_.row_offset:pl_.row_offset + pl_.rows, :])
            out[gtid] = np.concatenate(cols, axis=1) if len(cols) > 1 else cols[0]

        for t_local, gtid in enumerate(strat.table_groups[2]):
            rt = self.plan.row_tables[t_local]
            parts = [self._shard_host(params["row"][t_local],
                                      r)[:rt.rows_per_rank[r], :]
                     for r in range(self.world_size)]
            out[gtid] = np.concatenate(parts, axis=0)
        return out

    def set_weights(self, weights: Sequence) -> dict:
        """Build a new params pytree from global per-table weights
        (numpy arrays or .npy file paths; reference set_weights :971-1022).
        Purely functional: returns new params with the same shardings.
        Each rank's shard is assembled and staged independently, so peak host
        memory is one shard — .npy paths are mmap'd and only the placed
        slices are read (reference np.load(mmap_mode='r') :911-950 and
        128M-element chunked scatter :1002-1017 serve the same purpose).
        """
        strat = self.strategy
        if len(weights) != len(strat.global_configs):
            raise ValueError(
                f"Expected {len(strat.global_configs)} weights, got {len(weights)}")
        weights = [np.load(w, mmap_mode="r") if isinstance(w, str) else np.asarray(w)
                   for w in weights]
        for w, cfg in zip(weights, strat.global_configs):
            expect = (cfg["input_dim"], cfg["output_dim"])
            if tuple(w.shape) != expect:
                raise ValueError(f"Weight shape {w.shape} != expected {expect}")

        new = {"dp": [], "tp": [], "row": []}
        for j, gtid in enumerate(strat.table_groups[0]):
            new["dp"].append(jnp.asarray(weights[gtid]))

        def tp_shard(rank: int, b: int) -> np.ndarray:
            bucket = self.plan.tp_buckets[b]
            arr = np.zeros((max(bucket.rows_max, 1), bucket.width), np.float32)
            for pl_ in self.plan.tp_placements:
                if pl_.bucket != b or pl_.rank != rank:
                    continue
                gtid = strat.table_groups[1][pl_.table_id]
                arr[pl_.row_offset:pl_.row_offset + pl_.rows, :] = (
                    weights[gtid][:, pl_.col_start:pl_.col_end])
            return arr

        def row_shard(rank: int, t_local: int, gtid: int) -> np.ndarray:
            rt = self.plan.row_tables[t_local]
            arr = np.zeros((max(rt.rows_max, 1), rt.width), np.float32)
            start = int(sum(rt.rows_per_rank[:rank]))
            rows = rt.rows_per_rank[rank]
            arr[:rows, :] = weights[gtid][start:start + rows, :]
            return arr

        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            new["dp"] = [jax.device_put(a, rep) for a in new["dp"]]
            for b in range(len(self.plan.tp_buckets)):
                new["tp"].append(self._stack_sharded(
                    lambda rank, b=b: tp_shard(rank, b)))
            for t_local, gtid in enumerate(strat.table_groups[2]):
                new["row"].append(self._stack_sharded(
                    lambda rank, t=t_local, g=gtid: row_shard(rank, t, g)))
        else:
            for b in range(len(self.plan.tp_buckets)):
                new["tp"].append(jnp.stack(
                    [jnp.asarray(tp_shard(r, b))
                     for r in range(self.world_size)]))
            for t_local, gtid in enumerate(strat.table_groups[2]):
                new["row"].append(jnp.stack(
                    [jnp.asarray(row_shard(r, t_local, gtid))
                     for r in range(self.world_size)]))
        return new


def broadcast_variables(params, root_rank: int = 0):
    """Reference-API shim (dist_model_parallel.py:1219-1239).

    Under SPMD there is nothing to broadcast: every process constructs the
    same global jax.Arrays (same program, same seed). For multi-process
    setups initializing from process-local data, broadcast from process 0.
    """
    if root_rank != 0:
        raise NotImplementedError(
            "broadcast_one_to_all always originates from process 0; "
            "root_rank != 0 is not supported")
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        return multihost_utils.broadcast_one_to_all(params)
    return params
