"""Hybrid data-parallel / model-parallel distributed embedding for TPU.

API mirror of the reference `DistributedEmbedding`
(reference: distributed_embeddings/python/layers/dist_model_parallel.py:712-1214),
re-designed SPMD-first:

  * One 1-D `jax.sharding.Mesh` axis plays both the dp and mp role (the
    reference likewise requires dp ranks == mp ranks, :757).
  * The forward is a single `shard_map` region: ids move dp->mp via a true
    `lax.all_to_all` — each device sends every destination only the ids of
    the features that destination owns, packed per (bucket, hotness)
    "exchange group" so per-device id traffic is
    O(owned features x true hotness), matching the reference's
    hvd.alltoall-with-splits (:169-288, :211) rather than replicating all
    ids everywhere. Embedding outputs move mp->dp the same way (:870-872).
  * Row-sliced tables: all_gather ids -> masked local lookup -> psum_scatter,
    the equivalent of hvd.grouped_allgather + grouped_reducescatter (:889-904).
    XLA gather clamps out-of-bounds instead of zero-filling like TF, so
    validity is masked explicitly.
  * There is no DistributedGradientTape/Optimizer monkey-patching layer:
    under sharded autodiff, grads of mp-sharded params stay local and grads of
    replicated (dp) params are psummed by the shard_map transpose — the
    behavioral contract of the reference's patched tape (:1242-1267) falls out
    for free.

Exchange-group design (the TPU answer to Horovod's variable `splits`):
XLA collectives need static shapes, so the variable per-destination split
sizes of hvd.alltoall are re-expressed as a *set* of fixed-shape all_to_alls.
Slots of one fused bucket are grouped by their input's hotness k; each group
exchanges a dense [world, B_local, f_max_g, k] block. Within a group there is
no hotness padding at all (every member has exactly k ids), and f_max_g
padding is bounded by per-destination feature-count imbalance, which the
planner's placement strategies already minimize.
"""

import contextlib
import functools
import logging
import math
import os
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu import compat
from distributed_embeddings_tpu.ops import embedding_ops, pallas_lookup
from distributed_embeddings_tpu.ops import sparse_update as sparse_update_ops
from distributed_embeddings_tpu.ops import wire as wire_ops
from distributed_embeddings_tpu.ops.embedding_ops import (GroupSort,
                                                          RaggedIds,
                                                          SparseIds,
                                                          canonical_id_sort)
from distributed_embeddings_tpu.ops.sparse_update import (SparseOptimizer,
                                                          SparseRowGrad,
                                                          concat_grads)
from distributed_embeddings_tpu.parallel.mesh import DEFAULT_AXIS, create_mesh
from distributed_embeddings_tpu.parallel.planner import DistEmbeddingStrategy
from distributed_embeddings_tpu.parallel.plan import ShardedPlan, lower_strategy
from distributed_embeddings_tpu.utils.hotness import HotnessTracker
from distributed_embeddings_tpu.utils.initializers import get_initializer

__all__ = [
    "DistEmbeddingStrategy",
    "DistributedEmbedding",
    "broadcast_variables",
]


def _combine(emb: jax.Array, weights: Optional[jax.Array],
             combiner: Optional[str]) -> jax.Array:
    """Reduce the hotness axis (second-to-last) of `emb` [..., K, w].

    weights [..., K] carries 0 for padded slots; mean divides by the true
    (weighted) count, matching tf.nn.embedding_lookup_sparse semantics.
    """
    if combiner is None:
        # flatten hotness into width; caller re-slices per-input
        return emb.reshape(emb.shape[:-2] + (emb.shape[-2] * emb.shape[-1],))
    if weights is None:
        if combiner == "sum":
            return jnp.sum(emb, axis=-2)
        return jnp.mean(emb, axis=-2)
    out = jnp.einsum("...k,...kw->...w", weights.astype(emb.dtype), emb)
    if combiner == "mean":
        denom = jnp.maximum(jnp.sum(weights, axis=-1), 1.0).astype(out.dtype)
        out = out / denom[..., None]
    return out


class _PreparedInput:
    """A normalized input: dense ids [B, k] (+ optional 0/1 weights [B, k])."""

    __slots__ = ("ids", "weights", "orig_1d", "k")

    def __init__(self, ids, weights, orig_1d, k):
        self.ids = ids
        self.weights = weights
        self.orig_1d = orig_1d
        self.k = k


class _ExchangeGroup:
    """The slots of one tp bucket whose inputs share hotness k — one
    fixed-shape all_to_all unit (see module docstring). Static planning data
    computed at trace time from the plan + each input's (static) hotness."""

    __slots__ = ("bucket", "k", "class_inputs", "sel", "offs", "f_max",
                 "need_w", "rank_slots", "f_per_rank", "flat_sel",
                 "in_offsets")

    def __init__(self, bucket, k, class_inputs, sel, offs, f_max, need_w,
                 rank_slots):
        self.bucket = bucket            # index into plan.tp_buckets
        self.k = k                      # hotness shared by all member inputs
        self.class_inputs = class_inputs  # tp-input indices, stack order
        self.sel = sel                  # [world, f_max] -> class input pos
        self.offs = offs                # [world, f_max] fused-table row offsets
        self.f_max = f_max
        self.need_w = need_w
        self.rank_slots = rank_slots    # per rank: ordered member TPSlots
        # true-splits (ragged) exchange metadata: per-destination feature
        # counts, the unpadded destination-major selector, and each
        # destination's start row in the flat send buffer
        self.f_per_rank = np.asarray([len(s) for s in rank_slots], np.int32)
        self.flat_sel = (np.concatenate(
            [sel[r, :n] for r, n in enumerate(self.f_per_rank)])
            if int(self.f_per_rank.sum()) else np.zeros((0,), np.int32))
        self.in_offsets = np.concatenate(
            [[0], np.cumsum(self.f_per_rank)[:-1]]).astype(np.int32)


class TapResiduals:
    """Residuals of a tapped forward pass, consumed by `sparse_update`:
    per exchange group the post-exchange absolute row ids and effective
    combine weights (None = uniform; the static scale is recomputed from the
    group metadata), and per row-sliced input the sentinel-masked local ids +
    effective weights. Registered as a pytree with the static exchange-group
    cache key as aux data so `sparse_update` can rebuild the group layout.

    `tp_sort` / `row_sort` (sort folding, ISSUE 2): optionally one
    `GroupSort` per exchange group / row input — the canonical sort of the
    SAME id stream `tp_ids`/`row_ids` carries, produced once in the forward
    (under `residual_sort_scope`) so the sparse update consumes the
    precomputed order instead of re-sorting (the reference CUDA backward's
    reuse of forward-sorted ids, embedding_lookup_kernels.cu:706-773).
    None entries (or None lists — every pre-fold producer) mean "no
    artifact"; consumers fall back to a fresh sort, so the field is
    strictly additive.

    `hot_pos` / `hot_w` (hot-row replication, ISSUE 4): per exchange group
    on a hot-sharded bucket, the pre-exchange hot-membership split —
    each lane's position in the replicated hot shard (sentinel H on miss)
    and its effective hit weight (0 on miss). The sparse update turns
    the hot-tap gradients into the replicated hot shard's dense row
    update from exactly these. None on non-hot groups / pre-hot
    residuals."""

    def __init__(self, key, tp_ids, tp_w, row_ids, row_w, tp_sort=None,
                 row_sort=None, hot_pos=None, hot_w=None):
        self.key = key          # static: ((k, has_w) per tp input)
        self.tp_ids = tp_ids    # per group [world, B, f_g, k_g] int32
        self.tp_w = tp_w        # per group [world, B, f_g, k_g] f32 or None
        self.row_ids = row_ids  # per row input [world, B, k] int32 (sentinel)
        self.row_w = row_w      # per row input [world, B, k] f32
        self.tp_sort = tp_sort    # per group GroupSort([world, N]...) | None
        self.row_sort = row_sort  # per row input GroupSort | None
        self.hot_pos = hot_pos  # per group [1, world, B_l, f_g, k_g] | None
        self.hot_w = hot_w      # per group [1, world, B_l, f_g, k_g] | None

    def tree_flatten(self):
        return ((self.tp_ids, self.tp_w, self.row_ids, self.row_w,
                 self.tp_sort, self.row_sort, self.hot_pos, self.hot_w),
                self.key)

    @classmethod
    def tree_unflatten(cls, key, children):
        return cls(key, *children)


jax.tree_util.register_pytree_node(
    TapResiduals, TapResiduals.tree_flatten, TapResiduals.tree_unflatten)


# The true-splits (ragged) exchange op lives behind the wire seam with
# every other exchange collective (ISSUE 10): `ops.wire.ragged_exchange`
# — native lax.ragged_all_to_all on TPU, the equal-shaped-collective
# emulation on CPU. Alias kept: this module's exchange paths call it by
# its historical name.
_ragged_exchange_op = wire_ops.ragged_exchange


# (backend, world_size) -> bool: did the 'native' (compute_on jit) host
# apply mode compile on this backend? Probed at most ONCE per process
# (VERDICT r5 weak #3): every further layer instance / bucket / optimizer
# reuses the verdict instead of re-compiling the known-failing program and
# re-spewing XLA's RET_CHECK stack trace to stderr.
_HOST_NATIVE_VERDICT: dict = {}


@contextlib.contextmanager
def _capture_fd2(out: dict):
    """Capture OS-level stderr (fd 2) for the duration of the block into
    ``out['data']`` — XLA's C++ status_macros LOG(ERROR) bypasses
    sys.stderr, so a Python-level redirect cannot catch it. The window is
    kept to a single probe call; callers replay the bytes when the error
    is unexpected so no diagnostics are ever lost."""
    import sys
    import tempfile
    sys.stderr.flush()
    saved = os.dup(2)
    cap = tempfile.TemporaryFile(mode="w+b")
    os.dup2(cap.fileno(), 2)
    try:
        yield
    finally:
        sys.stderr.flush()
        os.dup2(saved, 2)
        os.close(saved)
        cap.seek(0)
        out["data"] = cap.read()
        cap.close()


_INTERPRET_WARNED: set = set()


def _warn_interpret_once(path: str) -> None:
    """DET_LOOKUP_PATH=tiled/fused off-TPU runs the Pallas kernels in
    interpret mode — orders of magnitude slower than the XLA path. Fine
    for the equivalence tests that set it deliberately; say so once per
    path anywhere else (ADVICE r4)."""
    if path in _INTERPRET_WARNED:
        return
    _INTERPRET_WARNED.add(path)
    import warnings
    warnings.warn(
        f"DET_LOOKUP_PATH={path} on a non-TPU backend: this Pallas "
        "lookup runs in INTERPRET mode here (correct but very slow — "
        "intended for tests). Unset DET_LOOKUP_PATH or run on TPU.",
        RuntimeWarning, stacklevel=3)


# jit-of-named-function with static bounds: cached across chunks, calls
# and buckets (a fresh lambda per chunk would re-trace+compile every time)
def _slice_rows(a, lo: int, hi: int):
    return lax.slice_in_dim(a, lo, hi, axis=1)


_slice_rows_jit = jax.jit(_slice_rows, static_argnums=(1, 2))


def _overrides_forward(cls) -> bool:
    """True when a user embedding class carries its own forward semantics:
    it overrides Embedding.__call__ and does not declare
    `det_gather_semantics = True` (the opt-out for subclasses whose call is
    still a plain gather+combine, e.g. config-only extensions)."""
    from distributed_embeddings_tpu.layers.embedding import (
        ConcatOneHotEmbedding, Embedding)
    if cls is None or cls in (Embedding, ConcatOneHotEmbedding):
        return False
    if getattr(cls, "det_gather_semantics", False):
        return False
    # find the class that actually defines the instance __call__ — a
    # config-only layer with NO __call__ (reference CustomEmbedding test
    # contract, dist_model_parallel_test.py:48-66) has no forward of its
    # own and keeps gather semantics; plain attribute lookup would wrongly
    # return the metaclass's call here
    for base in cls.__mro__:
        if "__call__" in base.__dict__:
            return base.__dict__["__call__"] is not Embedding.__dict__.get(
                "__call__")
    return False


def _effective_weights(weights: Optional[jax.Array], k: int,
                       combiner: Optional[str]):
    """Rewrite a (weights, combiner) pair as an explicit weighted SUM:
    out[b] = scale * sum_k eff_w[b,k] * rows[b,k]  (eff_w None = all-ones).
    Returns (eff_w, scale). Matches `_combine` semantics exactly."""
    if combiner is None or combiner == "sum":
        return weights, 1.0
    if combiner != "mean":
        raise ValueError(f"Unknown combiner {combiner}")
    if weights is None:
        return None, 1.0 / max(k, 1)
    denom = jnp.maximum(jnp.sum(weights, axis=-1, keepdims=True), 1.0)
    return weights / denom, 1.0


class DistributedEmbedding:
    """Distributed embedding wrapper: plans placement for a list of embedding
    tables and runs the hybrid-parallel lookup over a device mesh.

    Args (mirroring the reference :712-751):
      embeddings: list of `Embedding` layer objects (or anything exposing
        `get_config()` with input_dim/output_dim/combiner).
      strategy: 'auto' (default) | 'basic' | 'memory_balanced' |
        'memory_optimized' | 'comm_balanced' (beyond-reference: minimizes
        exchange-group padding volume using `input_max_hotness` hints;
        memory as tie-break). 'auto' = comm_balanced when any
        input_max_hotness hint > 1 (multi-hot models pay real exchange
        padding), else the reference's 'basic'. See
        `exchange_padding_report` for the volume accounting.
      column_slice_threshold: tables above this element count are split along
        output_dim into power-of-2 slices. None = auto only when there are
        fewer tables than devices.
      row_slice_threshold: tables above this element count are row-sliced
        evenly across all devices.
      dp_input: if True, `apply` takes data-parallel input — one global-batch
        array per feature. If False, takes model-parallel input (see
        `apply_mp`).
      input_table_map: input i -> table input_table_map[i] (shared tables).
      data_parallel_threshold: tables below this run replicated data-parallel.
      gpu_embedding_size: on-device element budget for table-parallel tables;
        overflow tables are flagged for host offload.
      mesh: jax Mesh with a single axis (default: all devices, axis "mp").
        world_size is taken from the mesh.
      input_max_hotness: optional per-input static max hotness, required to
        accept RaggedIds inputs (TPU needs static shapes).
      exchange_wire: float wire format for the exchange collectives
        (ISSUE 5): 'f32' (default — the exact pre-seam collectives),
        'bf16' (half the activation/weight/gradient exchange bytes, f32
        math on both sides), or 'bf16-sr' (bf16 forward, stochastically
        rounded bf16 gradients). None defers to `DET_EXCHANGE_WIRE`.
        Gated off per bucket where the planner knows rounding would be
        user-visible (combiner-None passthrough buckets keep f32); see
        `exchange_padding_report` for the resulting byte accounting.
      vocab_slack: dynamic-vocabulary growth capacity (ISSUE 7): extra
        physical rows pre-reserved per table-parallel table beyond its
        configured input_dim, so a `vocab.VocabManager` can admit new
        raw keys at runtime by binding them to free rows — no array
        shape ever changes, so the jitted step never recompiles. None
        defers to `DET_VOCAB_SLACK` (default 0 = exactly the pre-slack
        plan). The slack inflates the table's physical shape: `init`,
        `get_weights`/`set_weights` and checkpoints all see
        ``input_dim + vocab_slack`` rows for managed tables.
      storage_dtype: at-rest row storage for COLD (host-offloaded)
        buckets (ISSUE 15): 'f32' (default — params byte-identical to
        the pre-seam layer, the `exchange_wire='f32'` contract applied
        to memory), 'int8' (per-row-scaled symmetric quantization: ~4x
        more rows per host byte, rows decode to f32 at gather time,
        training write-back rounds stochastically with the wire seam's
        keyless hash), or 'fp8' (float8_e4m3fn payload where the
        backend ships it). None defers to ``DET_STORE_DTYPE``.
        Quantized buckets carry their per-row scales in a
        ``params['tp_scale']`` leaf (present only when some bucket
        quantizes, so default pytrees are unchanged); device-resident
        buckets always stay f32 (parallel/plan._storage_eligibility).
    """

    def __init__(self,
                 embeddings: Sequence,
                 strategy: str = "auto",
                 column_slice_threshold: Optional[int] = None,
                 row_slice_threshold: Optional[int] = None,
                 dp_input: bool = True,
                 input_table_map: Optional[Sequence[int]] = None,
                 data_parallel_threshold: Optional[int] = None,
                 gpu_embedding_size: Optional[int] = None,
                 mesh: Optional[Mesh] = None,
                 world_size: Optional[int] = None,
                 input_max_hotness: Optional[Sequence[Optional[int]]] = None,
                 use_custom_kernel: bool = True,
                 compute_dtype: Optional[Any] = None,
                 hot_rows: Optional[int] = None,
                 exchange_wire: Optional[str] = None,
                 vocab_slack: Optional[int] = None,
                 storage_dtype: Optional[str] = None):
        if mesh is None and world_size is not None and world_size > 1:
            mesh = create_mesh(jax.devices()[:world_size])
        self.mesh = mesh
        if mesh is not None:
            if len(mesh.axis_names) != 1:
                raise ValueError("DistributedEmbedding expects a 1-D mesh")
            self.axis = mesh.axis_names[0]
            self.world_size = mesh.devices.size
        else:
            self.axis = DEFAULT_AXIS
            self.world_size = 1

        self.dp_input = dp_input
        # single worker: fall back to pure table-parallel like the reference
        # (:764-774); mp-input mode also disables dp/row groups.
        if self.world_size > 1 and dp_input:
            row_thr, dp_thr = row_slice_threshold, data_parallel_threshold
        else:
            row_thr, dp_thr = None, None

        # hot-row replication (ISSUE 4) needs the dp->mp exchange to skip:
        # mp-input mode has no exchange, so the hot shard is dp-input only
        self.strategy = DistEmbeddingStrategy(
            embeddings, self.world_size, strategy,
            input_table_map=input_table_map,
            column_slice_threshold=column_slice_threshold,
            row_slice_threshold=row_thr,
            data_parallel_threshold=dp_thr,
            gpu_embedding_size=gpu_embedding_size,
            input_hotness=input_max_hotness,
            hot_rows=(hot_rows if dp_input else 0),
            exchange_wire=exchange_wire,
            vocab_slack=vocab_slack,
            storage_dtype=storage_dtype)

        if self.strategy.table_groups[1]:
            if not all(self.strategy.local_configs):
                raise ValueError(
                    "Not enough tables after slicing to run on all devices. "
                    "Try decreasing column_slice_threshold or device count.")

        self.plan: ShardedPlan = lower_strategy(self.strategy)
        # Custom user layer classes (reference instantiates layer_class via
        # from_config and calls ITS forward, :820-834). Tables whose class
        # overrides the forward are honored per-table in the data-parallel
        # group; in the fused model-parallel groups the bucket machinery
        # executes plain gather+combine, so a custom forward there would be
        # silently ignored — reject at plan time instead (VERDICT r4 item 6).
        self._dp_custom_layers = {}
        for j, gtid in enumerate(self.strategy.table_groups[0]):
            cfg = self.strategy.global_configs[gtid]
            if _overrides_forward(cfg.get("layer_class")):
                kwargs = {k: v for k, v in cfg.items() if k != "layer_class"}
                self._dp_custom_layers[j] = (
                    cfg["layer_class"].from_config(kwargs))
        for group in (1, 2):
            for gtid in self.strategy.table_groups[group]:
                cls = self.strategy.global_configs[gtid].get("layer_class")
                if _overrides_forward(cls):
                    raise ValueError(
                        f"table {gtid}: custom embedding layer class "
                        f"{cls.__name__} overrides __call__, but it was "
                        "placed in a fused model-parallel group whose "
                        "executor implements plain gather+combine — its "
                        "custom forward would be silently ignored. Either "
                        "(a) raise data_parallel_threshold so this table "
                        "is data-parallel (custom forwards run per-table "
                        "there), or (b) set `det_gather_semantics = True` "
                        "on the class to assert its forward is equivalent "
                        "to a plain (weighted) gather+combine.")
        self.input_max_hotness = (list(input_max_hotness)
                                  if input_max_hotness is not None else None)
        self._n_inputs = len(self.strategy.input_table_map)
        # like the reference Embedding's use_custom_kernel (embedding.py:72):
        # route multi-hot fused-bucket lookups through the Pallas kernels when
        # on a TPU backend; plain XLA gather+reduce otherwise.
        self.use_custom_kernel = use_custom_kernel
        # DET_RAGGED_EXCHANGE: dp->mp ids (and weights, incl. the masks
        # synthesized for ragged/sparse inputs) can move via the
        # true-splits exchange (_ragged_exchange_op) instead of padded
        # [world, f_max] blocks — the reference's exact hvd.alltoall(splits)
        # wire volume. '1' forces it, '0' forces padded, 'auto' (default)
        # decides per exchange group from the static padding accounting
        # (see _use_ragged_exchange). DET_RAGGED_NATIVE overrides the
        # native-vs-emulation op choice (default: native iff TPU backend).
        # DET_LOOKUP_PATH=tiled must not be silently inert for flows that
        # never call make_sparse_train_step (inference, dense-grad optax):
        # __init__ runs eagerly, so validate the kernels on the chip here —
        # traced forwards then consult the cached verdict
        from distributed_embeddings_tpu.ops.sparse_update import (
            measured_default, prevalidate_active_impl)
        if measured_default("DET_LOOKUP_PATH", "auto") in ("tiled",
                                                          "fused"):
            prevalidate_active_impl(widths=self.plan_widths())
        # mixed precision (reference tests' mixed_precision_policy,
        # dist_model_parallel_test.py:30-34): params stay fp32, the lookup
        # outputs / combines / collectives run in compute_dtype (e.g. bf16).
        self.compute_dtype = (jnp.dtype(compute_dtype)
                              if compute_dtype is not None else None)
        self._groups_cache: dict = {}
        # sort folding (ISSUE 2): (optimizer_kind, dedup_strategy) spec set
        # by residual_sort_scope — when active, tapped forwards produce
        # per-group GroupSort residuals (see TapResiduals). None = off, the
        # strictly-additive default for every non-tapped path.
        self._residual_sort_spec = None
        # serving hook (see offload_lookup_scope): replaces the host-side
        # offloaded-bucket lookup in tapless forwards — the HBM hot-row
        # cache in `serving/` plugs in here
        self._offload_lookup_override = None
        # lookahead pipeline hook (ISSUE 9, see staged_exchange_scope):
        # when set, apply() consumes these prefetched (ex_list, row_outs)
        # instead of running the exchange — the dense stage of
        # schedule.LookaheadEngine's fused step plugs in here
        self._staged_exchange = None
        # (bucket, f_max, k) -> "ragged"|"padded": the exchange path each
        # group actually took (filled at trace time, see _use_ragged_exchange)
        self._exchange_path_taken: dict = {}
        self._host_fn_cache: dict = {}
        # hot-row replication (ISSUE 4): buckets with a replicated hot
        # shard, host-side frequency trackers (admission), and the jitted
        # sync helpers. Trackers are created lazily by observe_hot_ids /
        # sync_hot_rows; membership itself is carried in params["hot"].
        self._hot_buckets = [b for b, bk in enumerate(self.plan.tp_buckets)
                             if bk.hot_rows > 0]
        self._hot_trackers: dict = {}
        self._hot_fn_cache: dict = {}
        self._hot_meta_cache: dict = {}
        # physical host offload: buckets past the gpu_embedding_size budget
        # live in pinned host memory (the reference's /CPU:0 placement,
        # :829-831); their lookups run in a compute_on("device_host") region
        # outside the shard_map, streaming only combined rows device-ward.
        self._offload_enabled = False
        self._host_kind = None
        if any(b.offload for b in self.plan.tp_buckets):
            devs = (list(self.mesh.devices.flat) if self.mesh is not None
                    else jax.devices())
            # pinned_host on TPU; older XLA:CPU only has unpinned_host (its
            # default space — placement is then a no-op but the whole
            # offload path still runs, which the CPU test mesh relies on)
            self._host_kind = compat.host_memory_kind(devs[0])
            self._offload_enabled = self._host_kind is not None
            if not self._offload_enabled:
                import warnings
                warnings.warn(
                    "gpu_embedding_size flagged table(s) for host offload, "
                    "but this backend exposes no host memory space: "
                    "offloaded buckets remain device-resident and count "
                    "against device memory.", RuntimeWarning, stacklevel=2)
        # quantized at-rest storage for OFFLOADED buckets (ISSUE 15)
        # rides the offload lookup seam: with offload runtime-disabled
        # those gathers run INSIDE the shard_map through the plain f32
        # path with no host decode hook — demote them to f32 loudly
        # rather than serve raw int8 rows as embeddings. HBM-resident
        # quantized buckets (ISSUE 17) decode inside the jitted forward
        # and are untouched by the offload runtime gate.
        if not self._offload_enabled and any(
                b.offload and b.storage_dtype != "f32"
                for b in self.plan.tp_buckets):
            import warnings
            warnings.warn(
                "storage_dtype quantization demoted to f32 for offloaded "
                "bucket(s): host offload is disabled on this backend and "
                "offloaded quantized storage decodes at the "
                "offloaded-gather seam.", RuntimeWarning, stacklevel=2)
            for b in self.plan.tp_buckets:
                if b.offload:
                    b.storage_dtype = "f32"
        # jitted per-bucket storage codec fns (decode at gather /
        # SR re-encode at write-back), cached per bucket
        self._store_codec_cache: dict = {}
        # touched-rows quantized host-apply accounting (ISSUE 17): raw
        # totals mirrored into the default registry's
        # store/quantized_rows_applied_total counter per apply
        self.quantized_rows_applied_total: int = 0
        self.quantized_apply_bytes_total: int = 0

    def _bucket_store_dtype(self, b: int) -> str:
        """The at-rest storage dtype of tp bucket b ('f32' | 'int8' |
        'fp8') — THE one predicate every storage-seam branch keys on."""
        return self.plan.tp_buckets[b].storage_dtype

    @property
    def quantized_buckets(self) -> list:
        """Buckets whose rows are stored quantized (ISSUE 15)."""
        return [b for b, bk in enumerate(self.plan.tp_buckets)
                if bk.storage_dtype != "f32"]

    def _bucket_scale(self, params: dict, b: int):
        """The per-row scale leaf of bucket b, or None at f32 storage.
        A QUANTIZED bucket with no scale leaf fails loudly here — the
        read-side twin of `host_bucket_apply`'s drift guard; falling
        through to the f32 path would serve raw int8/fp8 payload codes
        as embedding values."""
        scales = params.get("tp_scale")
        scale = None if scales is None else scales[b]
        if scale is None and self._bucket_store_dtype(b) != "f32":
            raise ValueError(
                f"bucket {b} stores {self._bucket_store_dtype(b)} rows "
                "but params carries no tp_scale leaf for it — the "
                "pytree drifted from the plan (rebuild params via "
                "init/set_weights; a hand-stripped checkpoint cannot "
                "decode)")
        return scale

    def _device_bucket_scales(self, params: dict):
        """Per-bucket stacked scale leaves for quantized DEVICE-resident
        buckets (None elsewhere), or None when no bucket needs one — the
        forward/update shard_map threading of ISSUE 17. Host-offloaded
        scales stay OUT of shard_map bodies (XLA memory-space
        propagation does not reach through them); those decode at the
        offloaded-gather seam (`_host_group_exchange`) instead."""
        if not self.quantized_buckets:
            return None
        out = [(self._bucket_scale(params, b)
                if (self._bucket_store_dtype(b) != "f32"
                    and self._bucket_memory_kind(b) is None) else None)
               for b in range(len(self.plan.tp_buckets))]
        return out if any(s is not None for s in out) else None

    def _encoded_shard_fn(self, shard_fn, encoder):
        """(rank, b, part) accessor over quantized bucket shards with
        ONE encode per (bucket, rank): the payload (part 0) and scale
        (part 1) stack builders each ask for one half of the same
        encode. THE shared assembly core of `init` (jnp encoder) and
        `set_weights` (numpy encoder) — ISSUE 15."""
        cache: dict = {}

        def part(rank: int, b: int, idx: int):
            if (b, rank) not in cache:
                cache[(b, rank)] = encoder(shard_fn(rank, b),
                                           self._bucket_store_dtype(b))
            return cache[(b, rank)][idx]
        return part

    def plan_widths(self) -> tuple:
        """The distinct table lane widths of this plan (tp buckets + row
        slices) — THE one derivation of what `sparse_update.
        prevalidate_active_impl` must compile-probe the shape-classed
        pallas gate at (a width class never probed eagerly can never
        validate under the jit trace). Shared by this constructor and the
        train-step/engine factories."""
        return tuple(sorted({b.width for b in self.plan.tp_buckets}
                            | {rt.width for rt in self.plan.row_tables}))

    # ------------------------------------------------------------------ init
    def _tp_shard(self, key, b: int, rank: int) -> jax.Array:
        """One rank's fused bucket table [rows_max, width] (traced/jittable)."""
        bucket = self.plan.tp_buckets[b]
        tbl = jnp.zeros((max(bucket.rows_max, 1), bucket.width), jnp.float32)
        for seg_i, (table_id, row_offset, rows, init_spec, dtype) in enumerate(
                bucket.init_segments[rank]):
            seg_key = jax.random.fold_in(
                jax.random.fold_in(key, table_id), rank * 131071 + seg_i)
            init_fn = get_initializer(init_spec)
            block = init_fn(seg_key, (rows, bucket.width),
                            dtype or jnp.float32)
            tbl = tbl.at[row_offset:row_offset + rows].set(block)
        return tbl

    def _row_shard(self, key, t: int, rank: int) -> jax.Array:
        rt = self.plan.row_tables[t]
        init_fn = get_initializer(rt.initializer)
        tbl = jnp.zeros((max(rt.rows_max, 1), rt.width), jnp.float32)
        rows = rt.rows_per_rank[rank]
        seg_key = jax.random.fold_in(jax.random.fold_in(key, 7919 + t), rank)
        return tbl.at[:rows].set(init_fn(seg_key, (rows, rt.width),
                                         rt.dtype or jnp.float32))

    def _rank_of_device(self):
        """Map each addressable mesh device -> its rank index (axis position).

        Multi-process safe: iterates only devices this process can address."""
        flat = list(self.mesh.devices.flat)
        return [(flat.index(d), d) for d in flat
                if d.process_index == jax.process_index()]

    def _bucket_memory_kind(self, b: int) -> Optional[str]:
        """The backend's host memory kind (pinned_host on TPU) for
        physically-offloaded buckets, else None."""
        if self._offload_enabled and self.plan.tp_buckets[b].offload:
            return self._host_kind
        return None

    def _param_sharding(self, memory_kind: Optional[str] = None):
        kw = {"memory_kind": memory_kind} if memory_kind else {}
        return NamedSharding(self.mesh, P(self.axis), **kw)

    def _stack_sharded(self, shard_fn,
                       memory_kind: Optional[str] = None) -> jax.Array:
        """Assemble a [world, rows_max, w] P(axis)-sharded array by computing
        (or staging) each rank's shard directly on that rank's device — peak
        staging is one shard, never the global stack (round-1 gap: the
        reference chunks set_weights for the same reason, :977-1017, and
        CPU-inits to dodge init OOM, embedding.py:28-47).

        shard_fn(rank) -> [rows_max, w] array-like for that rank.
        memory_kind='pinned_host' stages each shard into that rank's host
        memory (offloaded buckets — reference /CPU:0 build, :1186-1189).
        """
        shards, shape = [], None
        for rank, dev in self._rank_of_device():
            with jax.default_device(dev):
                shard = jnp.asarray(shard_fn(rank))[None]
            target = (jax.sharding.SingleDeviceSharding(
                dev, memory_kind=memory_kind) if memory_kind else dev)
            shard = jax.device_put(shard, target)
            shards.append(shard)
            shape = shard.shape
        global_shape = (self.world_size,) + tuple(shape[1:])
        sharding = self._param_sharding(memory_kind)
        return jax.make_array_from_single_device_arrays(
            global_shape, sharding, shards)

    # -------------------------------------------------- hot-row replication
    def _hot_sentinel(self, b: int) -> int:
        """The membership sentinel key for bucket b: one past the flat key
        space ``world * rows_max`` — no valid (rank, row) key reaches it,
        and sentinel-padded slots keep the membership array sorted."""
        return self.world_size * max(self.plan.tp_buckets[b].rows_max, 1)

    def _empty_hot_entry(self, b: int) -> dict:
        """A hot-shard param entry with an EMPTY resident set: all-sentinel
        membership (every lookup misses — byte-identical behavior to no
        hot shard until `sync_hot_rows` admits rows) and zero rows."""
        bucket = self.plan.tp_buckets[b]
        ids = jnp.full((bucket.hot_rows,), self._hot_sentinel(b), jnp.int32)
        rows = jnp.zeros((bucket.hot_rows, bucket.width), jnp.float32)
        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            ids = jax.device_put(ids, rep)
            rows = jax.device_put(rows, rep)
        return {"ids": ids, "rows": rows}

    def _init_hot_params(self) -> list:
        return [self._empty_hot_entry(b) if b in self._hot_buckets else None
                for b in range(len(self.plan.tp_buckets))]

    def init(self, key) -> dict:
        """Create the parameter pytree:
          {'dp': [replicated [V,w]...],
           'tp': [stacked [world, rows_max, w] per bucket...],
           'row': [stacked [world, slice_rows_max, w] per row table...]}

        Layers built with `hot_rows` add
          {'hot': [None | {'ids': [H] int32 sorted membership keys,
                           'rows': [H, w] replicated hot rows} per bucket]}
        — initially EMPTY (all-sentinel membership), so the forward is
        behaviorally identical to a hot-less layer until `sync_hot_rows`
        admits rows.

        With a mesh bound, every tp/row shard is materialized per-device
        (shard-sized staging); without one, plain stacked arrays.
        """
        kd, kt, kr = jax.random.split(key, 3)
        params = {"dp": [], "tp": [], "row": []}
        for j, cfg in enumerate(self.strategy.dp_configs):
            init_fn = get_initializer(cfg.get("embeddings_initializer", "uniform"))
            params["dp"].append(init_fn(
                jax.random.fold_in(kd, j),
                (cfg["input_dim"], cfg["output_dim"]),
                cfg.get("dtype") or jnp.float32))
        qbs = self.quantized_buckets
        scales: Dict[int, jax.Array] = {}
        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            params["dp"] = [jax.device_put(a, rep) for a in params["dp"]]
            tp_init = jax.jit(self._tp_shard, static_argnums=(1, 2))
            row_init = jax.jit(self._row_shard, static_argnums=(1, 2))
            q_shard = self._encoded_shard_fn(
                lambda rank, b: tp_init(kt, b, rank), wire_ops.encode_rows)
            for b in range(len(self.plan.tp_buckets)):
                mk = self._bucket_memory_kind(b)
                if b in qbs:
                    params["tp"].append(self._stack_sharded(
                        lambda rank, b=b: q_shard(rank, b, 0),
                        memory_kind=mk))
                    scales[b] = self._stack_sharded(
                        lambda rank, b=b: q_shard(rank, b, 1),
                        memory_kind=mk)
                else:
                    params["tp"].append(self._stack_sharded(
                        lambda rank, b=b: tp_init(kt, b, rank),
                        memory_kind=mk))
            for t in range(len(self.plan.row_tables)):
                params["row"].append(self._stack_sharded(
                    lambda rank, t=t: row_init(kr, t, rank)))
        else:
            # jit the shard builders here too: eager .at[].set would copy
            # the whole bucket once per init segment (26 segments x 4.2 GiB
            # for the tiny model); jitted, XLA fuses them into one buffer
            tp_init = jax.jit(self._tp_shard, static_argnums=(1, 2))
            row_init = jax.jit(self._row_shard, static_argnums=(1, 2))
            for b in range(len(self.plan.tp_buckets)):
                arr = jnp.stack(
                    [tp_init(kt, b, r) for r in range(self.world_size)])
                mk = self._bucket_memory_kind(b)
                scale = None
                if b in qbs:
                    arr, scale = wire_ops.encode_rows(
                        arr, self._bucket_store_dtype(b))
                if mk:
                    hsh = jax.sharding.SingleDeviceSharding(
                        jax.devices()[0], memory_kind=mk)
                    arr = jax.device_put(arr, hsh)
                    if scale is not None:
                        scale = jax.device_put(scale, hsh)
                params["tp"].append(arr)
                if scale is not None:
                    scales[b] = scale
            for t in range(len(self.plan.row_tables)):
                params["row"].append(jnp.stack(
                    [row_init(kr, t, r) for r in range(self.world_size)]))
        if qbs:
            params["tp_scale"] = [scales.get(b)
                                  for b in range(len(self.plan.tp_buckets))]
        if self._hot_buckets:
            params["hot"] = self._init_hot_params()
        return params

    def param_shardings(self, mesh: Optional[Mesh] = None) -> dict:
        """NamedSharding pytree matching `init` output — for pjit/device_put.

        Buckets past the gpu_embedding_size budget carry
        memory_kind='pinned_host' (reference _maybe_offload :449-476 +
        /CPU:0 build :1186-1189): they live in host RAM and their lookups run
        host-side, outside the shard_map (XLA memory-space propagation does
        not reach through shard_map bodies as of jax 0.9)."""
        mesh = mesh or self.mesh
        if mesh is None:
            raise ValueError("No mesh bound")
        rep = NamedSharding(mesh, P())
        shard0 = NamedSharding(mesh, P(self.axis))
        def tp_shard(b):
            mk = self._bucket_memory_kind(b)
            return (NamedSharding(mesh, P(self.axis), memory_kind=mk)
                    if mk else shard0)
        out = {
            "dp": [rep for _ in self.strategy.dp_configs],
            "tp": [tp_shard(b) for b in range(len(self.plan.tp_buckets))],
            "row": [shard0 for _ in self.plan.row_tables],
        }
        if self.quantized_buckets:
            # per-row scales co-locate with their quantized bucket
            out["tp_scale"] = [tp_shard(b) if b in self.quantized_buckets
                               else None
                               for b in range(len(self.plan.tp_buckets))]
        if self._hot_buckets:
            out["hot"] = [({"ids": rep, "rows": rep}
                           if b in self._hot_buckets else None)
                          for b in range(len(self.plan.tp_buckets))]
        return out

    # ----------------------------------------------------------- input prep
    def _prepare_one(self, x, max_hotness: Optional[int]) -> _PreparedInput:
        if isinstance(x, tuple) and len(x) == 2 and not isinstance(x, RaggedIds):
            ids, weights = x
            return _PreparedInput(jnp.asarray(ids), jnp.asarray(weights),
                                  False, ids.shape[1])
        if isinstance(x, RaggedIds):
            if max_hotness is None:
                raise ValueError(
                    "RaggedIds input requires input_max_hotness (static shapes "
                    "are mandatory on TPU)")
            ids, weights = embedding_ops.ragged_to_padded(x, max_hotness)
            return _PreparedInput(ids, weights, False, max_hotness)
        if isinstance(x, SparseIds):
            batch, k = int(x.dense_shape[0]), int(x.dense_shape[1])
            rows, cols = x.indices[:, 0], x.indices[:, 1]
            ids = jnp.zeros((batch, k), x.values.dtype).at[rows, cols].set(x.values)
            weights = jnp.zeros((batch, k), jnp.float32).at[rows, cols].set(1.0)
            return _PreparedInput(ids, weights, False, k)
        ids = jnp.asarray(x)
        if ids.ndim == 1:
            return _PreparedInput(ids[:, None], None, True, 1)
        if ids.ndim != 2:
            raise ValueError(f"Expected 1-D or 2-D ids, got shape {ids.shape}")
        return _PreparedInput(ids, None, False, ids.shape[1])

    def _prepare_inputs(self, inputs) -> List[_PreparedInput]:
        if len(inputs) != self._n_inputs:
            raise ValueError(
                f"Expected {self._n_inputs} inputs, got {len(inputs)}")
        prepped = []
        for i, x in enumerate(inputs):
            mh = (self.input_max_hotness[i]
                  if self.input_max_hotness is not None else None)
            prepped.append(self._prepare_one(x, mh))
        return prepped

    def _exchange_groups(self, tp_prep: Sequence[_PreparedInput]):
        """Compute the (bucket, hotness) exchange groups and the per-input
        assembly map for a given set of prepared inputs.

        Returns (groups, assembly) where assembly[i] is the ordered list of
        (rank, group_idx, slot_in_group) triples for tp input i — the same
        rank-major slot order the plan's weight layout uses (col_cursor order,
        reference :921-936), so column-slice re-concat stays correct.
        Cached per hotness/weights signature (one entry per jit trace shape).
        """
        key = tuple((p.k, p.weights is not None) for p in tp_prep)
        return self._exchange_groups_for_key(key)

    def _exchange_groups_for_key(self, key):
        """Same as `_exchange_groups` but from the static (k, has_weights)
        signature alone — lets `sparse_update` rebuild the exact group layout
        a tapped forward used, via TapResiduals.key."""
        hit = self._groups_cache.get(key)
        if hit is not None:
            return hit
        world = self.world_size
        per_bk: dict = {}   # (bucket, k) -> per-rank [(slot_idx, TPSlot)...]
        order: List[Tuple[int, int]] = []
        for b, bucket in enumerate(self.plan.tp_buckets):
            for r, slots in enumerate(bucket.slots):
                for j, s in enumerate(slots):
                    k = key[s.tp_input][0]
                    if (b, k) not in per_bk:
                        per_bk[(b, k)] = [[] for _ in range(world)]
                        order.append((b, k))
                    per_bk[(b, k)][r].append((j, s))
        groups: List[_ExchangeGroup] = []
        slot_map: dict = {}  # (bucket, rank, slot_idx_in_bucket) -> (g, j_g)
        for g, (b, k) in enumerate(order):
            ranks = per_bk[(b, k)]
            class_inputs = sorted({s.tp_input for lst in ranks
                                   for (_, s) in lst})
            pos = {i: c for c, i in enumerate(class_inputs)}
            f_max = max(len(lst) for lst in ranks)
            sel = np.zeros((world, f_max), np.int32)
            offs = np.zeros((world, f_max), np.int32)
            rank_slots = []
            for r, lst in enumerate(ranks):
                for j_g, (j, s) in enumerate(lst):
                    sel[r, j_g] = pos[s.tp_input]
                    offs[r, j_g] = s.row_offset
                    slot_map[(b, r, j)] = (g, j_g)
                rank_slots.append([s for (_, s) in lst])
            need_w = any(key[i][1] for i in class_inputs)
            groups.append(_ExchangeGroup(b, k, class_inputs, sel, offs,
                                         f_max, need_w, rank_slots))
        assembly = [
            [(rank, *slot_map[(bb, rank, jj)]) for (rank, bb, jj) in slots]
            for slots in self.plan.tp_input_slots
        ]
        self._groups_cache[key] = res = (groups, assembly)
        return res

    def exchange_padding_report(self, hotness=None,
                                hot_hit_rate=None, batch: int = 1,
                                vocab=None, lookahead: int = 0,
                                delta_dtype: Optional[str] = None) -> dict:
        """Static accounting of the dp->mp id-exchange volume.

        The exchange sends one dense [world, f_max, k] id block per
        (bucket, hotness) group and sample (see `_exchange_groups_for_key`)
        where the reference's `hvd.alltoall` with per-destination splits
        (reference dist_model_parallel.py:169-288) sends exactly the true
        nnz. This report quantifies the gap for this plan, per sample:

          true_ids       sum over groups of sum_r f_r * k  (the reference's
                         splits volume)
          exchanged_ids  sum over groups of world * f_max * k (what the
                         fixed-shape lax.all_to_all moves)
          ratio          exchanged / true  (1.0 = zero padding)

        Hot-row replication (ISSUE 4): groups on hot-sharded buckets gain

          hot_hit_ids       expected ids served by the replicated hot
                            shard per sample (true_ids x hit rate) —
                            lanes that skip the exchange's useful volume
                            (sentinel-masked, zero weight; the WIRE shape
                            is static and unchanged: `exchanged_ids`
                            still counts the padded wire slots)
          true_ids_post_hot the residual USEFUL exchange volume,
                            true_ids - hot_hit_ids

        The hit rate comes from the layer's measured admission trackers
        (`observe_hot_ids`), WINDOWED to the current residency epoch —
        `sync_hot_rows` resets the hit/miss counters at each
        (re-)admission so the all-miss warmup stream never dilutes the
        rate. Pass `hot_hit_rate` (scalar or {bucket: rate}) to project
        for an assumed rate instead.

        Wire compression (ISSUE 5): every group entry also carries the
        BYTE-level accounting of its wire — `wire_dtype` /
        `id_wire_dtype` (the plan's per-bucket formats),
        `exchanged_bytes` / `true_bytes` (id wire + the mp->dp
        activation return, forward direction, per global sample) and
        `act_bytes` vs `act_bytes_f32` (the dominant activation term at
        the actual vs the f32 wire). Top-level `act_wire_reduction` is
        the statically auditable compression claim: 2.0 when every
        bucket rides bf16, 1.0 at the f32 default. The gradient
        transpose moves the same activation volume again (same ratio);
        weighted inputs add `weight_bytes_if_weighted` per group —
        FORWARD-only (weights are inputs, not params: no gradient
        crosses the weight wire). Id fields charge the NARROWED id
        dtype (an int16 bucket's wire moves 2 B/id, exactly what the
        lowered operand carries). `analysis.programs.
        expected_collective_bytes` converts these per-sample fields
        into the exact per-device HLO payload bytes, and the
        collective-bytes audit pass + tests/test_wire.py assert the
        compiled program matches the model byte-for-byte on every wire
        config (ISSUE 10 reconciliation).

        Touched-row accounting (ISSUE 6): every group also carries
        `touched_rows_per_step` — the dedup'd post-sentinel-mask ids the
        sparse update actually writes per step at global batch size
        ``batch`` (hot-HIT lanes are sentinel-masked and skip the
        canonical scatter, so the post-hot volume is the base; the
        dedup bound is the bucket's total row count) — and
        `delta_bytes_per_step`, the row-delta size model built on it:
        ``(touched + republished hot hits) *
        wire.delta_row_bytes(width, delta_dtype)`` — 8 id bytes plus
        the width-element payload at the STREAM's storage dtype plus
        its per-row scale (`delta_dtype=None` defers to
        ``DET_DELTA_DTYPE``; 'f32' reproduces the historical
        ``8 + 4*width`` exactly). Hot-HIT rows skip the canonical
        scatter but still move the replicated hot shard, so the
        published delta republishes their merged values (bounded by
        the hot capacity). `wire.delta_row_bytes` is THE shared byte
        model: `TableStore.publish`'s payload accounting and the bench
        reconcile against the same formula, the
        `expected_collective_bytes` discipline applied to the stream
        (docs/perf_model.md "Weight streaming"). Each group also
        reports its bucket's at-rest `storage_dtype` (ISSUE 15).

        Dynamic vocabulary (ISSUE 7): every group also carries the
        bucket's capacity accounting — `slack_rows` (growth rows the
        planner pre-reserved in this bucket, folded into rows_max),
        `occupancy` (live rows / capacity rows over the bucket's
        tables: managed tables report their binding's bound count when
        a `vocab.VocabManager` is passed, 1.0 means every row is live —
        the static-vocabulary reading), and `evictions_per_step`
        (measured demotions per maintain cycle from the manager, 0.0
        without one). Top-level totals aggregate the same three.

        Lookahead prefetch (ISSUE 9): with ``lookahead > 0`` every group
        also carries the overlap-window accounting of the pipelined step:

          prefetch_patch_rows_per_step  worst-case rows the engine's
                            correctness patch re-publishes per step — the
                            previous batch's touched rows all reappearing
                            in the prefetched batch, i.e. exactly
                            `touched_rows_per_step` (the dedup bound
                            carries over; the measured intersection is
                            what `bench.py --mode lookahead` reports)
          prefetch_patch_bytes_per_step the patch recompute's wire cost
                            model at that bound: patched rows x (id wire
                            + one activation slot at the bucket's float
                            wire) — the EXTRA exchange traffic the
                            overlap window adds on top of the normal
                            (merely earlier) prefetched exchange

        Both are 0 at lookahead=0 (and under `stale_ok`, which skips the
        patch — the report models the bit-exact mode).

        Args:
          hotness: per-tp-input hotness override; defaults to the layer's
            input_max_hotness hints (unhinted inputs count as 1).
          hot_hit_rate: hot-shard hit-rate override (see above).
          batch: global batch size for the touched-row/delta-size model
            (default 1 = per-sample accounting, matching the id fields).
          vocab: optional `vocab.VocabManager` supplying measured
            occupancy/eviction numbers for managed tables.
          lookahead: pipeline depth for the prefetch-patch model (0 = the
            sequential step, patch fields report 0).
        Returns {"groups": [...], "true_ids", "exchanged_ids", "ratio",
        "exchanged_bytes", "true_bytes", "act_bytes", "act_bytes_f32",
        "act_wire_reduction", "wire_dtypes", "id_narrowed_groups",
        "hot_hit_ids", "true_ids_post_hot", "hot_hit_rates",
        "touched_rows_per_step", "delta_bytes_per_step", "occupancy",
        "slack_rows", "evictions_per_step", "lookahead",
        "prefetch_patch_rows_per_step", "prefetch_patch_bytes_per_step"}.
        """
        tp_inputs = self.strategy.input_groups[1]
        delta_dtype = (wire_ops.default_delta_dtype() if delta_dtype is None
                       else wire_ops.resolve_store_dtype(delta_dtype))
        if hotness is None:
            mh = self.input_max_hotness or [None] * self._n_inputs
            hotness = [mh[i] or 1 for i in tp_inputs]
        if len(hotness) != len(tp_inputs):
            raise ValueError(
                f"hotness has {len(hotness)} entries, expected "
                f"{len(tp_inputs)} (one per tp input)")

        def rate_for(b):
            if b not in self._hot_buckets:
                return None
            if isinstance(hot_hit_rate, dict):
                return float(hot_hit_rate.get(b, 0.0))
            if hot_hit_rate is not None:
                return float(hot_hit_rate)
            tr = self._hot_trackers.get(b)
            return tr.hit_rate if tr is not None else 0.0

        def bucket_vocab(b):
            """(occupancy, slack_rows, evictions_per_step) of bucket b:
            live rows / capacity rows over the bucket's tables (managed
            tables read their binding; static tables are fully live)."""
            bucket = self.plan.tp_buckets[b]
            tids = sorted({self.strategy.table_groups[1][pl.table_id]
                           for pl in self.plan.tp_placements
                           if pl.bucket == b})
            live = cap = 0
            ev = 0.0
            # per-STEP denominator: observing translate() calls (one per
            # training step in the fit wiring); maintain cycles are the
            # fallback for managers driven without translation
            steps = max(getattr(vocab, "observe_steps", 0)
                        or getattr(vocab, "maintain_cycles", 0), 1) \
                if vocab is not None else 1
            for gtid in tids:
                cfg = self.strategy.global_configs[gtid]
                rows = int(cfg["input_dim"])
                cap += rows
                mv = (vocab.vocabs.get(gtid)
                      if vocab is not None else None)
                if mv is not None:
                    live += 1 + mv.bound    # fallback row is always live
                    ev += mv.evictions / steps
                else:
                    # no manager over this table: its build rows are
                    # live, but any pre-reserved slack is DEAD capacity
                    # (nothing can ever bind it) — counting it live
                    # would report a misleading 1.0 for slack plans run
                    # without (or outside) a manager
                    live += rows - int(cfg.get("vocab_slack", 0))
            return ((live / cap) if cap else 1.0, bucket.slack_rows, ev)

        vocab_by_bucket = {b: bucket_vocab(b)
                           for b in range(len(self.plan.tp_buckets))}
        key = tuple((int(h), False) for h in hotness)
        groups, _ = self._exchange_groups_for_key(key)
        report, true_tot, ex_tot, hot_tot = [], 0, 0, 0
        touched_tot, delta_bytes_tot = 0, 0
        patch_rows_tot, patch_bytes_tot = 0, 0
        ex_bytes_tot, true_bytes_tot = 0, 0
        act_bytes_tot, act_bytes_f32_tot = 0, 0
        id_narrowed = []
        for gi, g in enumerate(groups):
            bucket = self.plan.tp_buckets[g.bucket]
            true_ids = sum(len(s) for s in g.rank_slots) * g.k
            ex_ids = self.world_size * g.f_max * g.k
            true_tot += true_ids
            ex_tot += ex_ids
            # byte-level accounting (ISSUE 5), per global sample: the id
            # wire at the bucket's (possibly int16-narrowed) id dtype
            # plus the mp->dp combined-activation return — one slot is
            # width elements combined (width*k for passthrough) — at the
            # bucket's float wire. FORWARD volume; the gradient
            # transpose doubles the activation term, and weighted inputs
            # add one more id-shaped float block at the same wire
            # (`weight_bytes_if_weighted`).
            w_out = bucket.width * (1 if bucket.combiner is not None
                                    else g.k)
            id_b = wire_ops.id_wire_itemsize(bucket.id_wire_dtype)
            wire_b = wire_ops.wire_itemsize(bucket.wire_dtype)
            act_ex = self.world_size * g.f_max * w_out
            act_true = sum(len(s) for s in g.rank_slots) * w_out
            ex_bytes = ex_ids * id_b + act_ex * wire_b
            true_bytes = true_ids * id_b + act_true * wire_b
            ex_bytes_tot += ex_bytes
            true_bytes_tot += true_bytes
            act_bytes_tot += act_ex * wire_b
            act_bytes_f32_tot += act_ex * 4
            if bucket.id_wire_dtype == "int16":
                id_narrowed.append(gi)
            entry = {
                "bucket": g.bucket, "hotness": g.k, "f_max": g.f_max,
                "features_per_rank": [len(s) for s in g.rank_slots],
                "true_ids": true_ids, "exchanged_ids": ex_ids,
                "wire_dtype": bucket.wire_dtype,
                "id_wire_dtype": bucket.id_wire_dtype,
                "storage_dtype": bucket.storage_dtype,
                "act_width": w_out,
                "act_bytes": act_ex * wire_b,
                "act_bytes_f32": act_ex * 4,
                "exchanged_bytes": ex_bytes,
                "true_bytes": true_bytes,
                "weight_bytes_if_weighted": ex_ids * wire_b,
                "occupancy": round(vocab_by_bucket[g.bucket][0], 4),
                "slack_rows": vocab_by_bucket[g.bucket][1],
                "evictions_per_step": round(vocab_by_bucket[g.bucket][2],
                                            4),
                "path_taken": self._exchange_path_taken.get(
                    (g.bucket, g.f_max, g.k)),
            }
            rate = rate_for(g.bucket)
            if rate is not None:
                hot_ids = int(round(true_ids * rate))
                hot_tot += hot_ids
                entry["hot_hit_ids"] = hot_ids
                entry["true_ids_post_hot"] = true_ids - hot_ids
            # touched-row / delta-size model (ISSUE 6): rows this group's
            # sparse update writes per step — post-hot ids scaled to the
            # batch, dedup-bounded by the bucket's total rows. The BYTE
            # model adds the hot-HIT rows back in: they skip the
            # canonical scatter but move the replicated hot shard, and
            # the published delta republishes their MERGED values
            # (touched_row_keys includes them) — bounded by the hot
            # shard's capacity, the most rows the merged view can move.
            post_hot = entry.get("true_ids_post_hot", true_ids)
            touched = min(int(batch) * post_hot,
                          self.world_size * max(bucket.rows_max, 1))
            hot_pub = min(int(batch) * entry.get("hot_hit_ids", 0),
                          bucket.hot_rows)
            entry["touched_rows_per_step"] = touched
            entry["delta_bytes_per_step"] = (
                (touched + hot_pub)
                * wire_ops.delta_row_bytes(bucket.width, delta_dtype))
            touched_tot += touched
            delta_bytes_tot += entry["delta_bytes_per_step"]
            # lookahead overlap-window model (ISSUE 9): worst case, every
            # row the previous step touched reappears in the prefetched
            # batch and is re-exchanged by the correctness patch — one id
            # + one activation slot per patched row at this bucket's wire
            patch_rows = touched if lookahead > 0 else 0
            entry["prefetch_patch_rows_per_step"] = patch_rows
            entry["prefetch_patch_bytes_per_step"] = (
                patch_rows * (id_b + w_out * wire_b))
            patch_rows_tot += patch_rows
            patch_bytes_tot += entry["prefetch_patch_bytes_per_step"]
            report.append(entry)
        return {"groups": report, "true_ids": true_tot,
                "exchanged_ids": ex_tot,
                "ratio": (ex_tot / true_tot) if true_tot else 1.0,
                "exchanged_bytes": ex_bytes_tot,
                "true_bytes": true_bytes_tot,
                "act_bytes": act_bytes_tot,
                "act_bytes_f32": act_bytes_f32_tot,
                # f32-wire bytes / actual-wire bytes of the dominant
                # (activation) exchange: 1.0 all-f32, 2.0 all-bf16 — the
                # statically auditable half-the-wire claim
                "act_wire_reduction": (act_bytes_f32_tot / act_bytes_tot
                                       if act_bytes_tot else 1.0),
                "wire_dtypes": {b: bk.wire_dtype for b, bk in
                                enumerate(self.plan.tp_buckets)},
                "id_narrowed_groups": id_narrowed,
                "hot_hit_ids": hot_tot,
                "true_ids_post_hot": true_tot - hot_tot,
                "hot_hit_rates": {b: rate_for(b) for b in self._hot_buckets},
                "touched_rows_per_step": touched_tot,
                "delta_bytes_per_step": delta_bytes_tot,
                "delta_dtype": delta_dtype,
                "storage_dtypes": {b: bk.storage_dtype for b, bk in
                                   enumerate(self.plan.tp_buckets)},
                "lookahead": int(lookahead),
                "prefetch_patch_rows_per_step": patch_rows_tot,
                "prefetch_patch_bytes_per_step": patch_bytes_tot,
                # capacity accounting (ISSUE 7), each bucket counted ONCE
                # (a bucket can serve several hotness groups): occupancy
                # capacity-weighted over buckets, slack/evictions summed
                "occupancy": round(
                    sum(vocab_by_bucket[b][0]
                        * max(self.plan.tp_buckets[b].rows_max, 1)
                        for b in vocab_by_bucket)
                    / max(sum(max(self.plan.tp_buckets[b].rows_max, 1)
                              for b in vocab_by_bucket), 1), 4)
                if vocab_by_bucket else 1.0,
                "slack_rows": sum(v[1] for v in vocab_by_bucket.values()),
                # top-level evictions come from the MANAGER, not a
                # bucket sum: a column-sliced table spanning several
                # buckets (unequal slice widths land in different
                # width-keyed buckets) would otherwise count each
                # logical eviction once per bucket. Per-group entries
                # keep the per-bucket view — each bucket genuinely
                # rewrites its slice of a rebound row.
                "evictions_per_step": round(
                    sum(mv.evictions for mv in vocab.vocabs.values())
                    / max(getattr(vocab, "observe_steps", 0)
                          or getattr(vocab, "maintain_cycles", 0), 1),
                    4) if vocab is not None else 0.0,
                "exchange_paths": dict(self._exchange_path_taken)}

    def residual_sort_scope(self, spec):
        """Scope the sort-folding spec over forwards traced inside it.

        ``spec = (optimizer_kind, dedup_strategy)`` — e.g. ("adagrad",
        "sort") — tells tapped forwards (``return_residuals=True``) to
        produce per-group/per-row-input `GroupSort` residual artifacts
        wherever `sparse_update`'s dispatch (mirrored statically by
        `sparse_update.update_consumes_sort`) or the tiled forward gather
        will consume them; ``None`` disables. `make_sparse_train_step`
        wraps its loss+grad region in this scope, so the production train
        step sorts each exchange group's ids exactly once (ISSUE 2). The
        scope is trace-time state on this layer instance — like
        `offload_lookup_scope`, re-entrant but not thread-safe."""

        @contextlib.contextmanager
        def scope():
            prev = self._residual_sort_spec
            self._residual_sort_spec = spec
            try:
                yield self
            finally:
                self._residual_sort_spec = prev
        return scope()

    def _fwd_tiled_active(self, bucket, k: int) -> bool:
        """Will `_group_lookup` take a sorted-gather Pallas path (tiled
        or the ISSUE 12 fused gather->combine) for this (bucket,
        hotness)? Mirrors its dispatch statically (trace-safe) — both
        paths consume the residual sort's inverse permutation."""
        path = sparse_update_ops.measured_default("DET_LOOKUP_PATH", "auto")
        if path not in ("tiled", "fused") or not self.use_custom_kernel:
            return False
        if bucket.combiner is None and k != 1:
            return False       # flatten path; no sorted gather
        if path == "fused":
            return sparse_update_ops.pallas_fwd_ok_static(bucket.width)
        return sparse_update_ops.tiled_fwd_ok_static()

    def _sort_plan(self, groups, spec) -> List[Optional[str]]:
        """Per exchange group: None (no artifact), "plain" (sid/perm/
        seg_start for the sparse update) or "inv" (+ inverse permutation,
        consumed by the tiled forward gather's unpermute). Buckets whose
        update concatenates several groups keep None — a per-group sort
        cannot serve the concatenated dedup, and applying the optimizer
        per group instead would change adagrad/adam numerics."""
        if spec is None:
            return [None] * len(groups)
        opt_kind, strategy = spec
        per_bucket: dict = {}
        for grp in groups:
            per_bucket[grp.bucket] = per_bucket.get(grp.bucket, 0) + 1
        plan: List[Optional[str]] = []
        for grp in groups:
            bucket = self.plan.tp_buckets[grp.bucket]
            if bucket.offload and self._offload_enabled:
                plan.append(None)    # host apply path keeps its own dedup
                continue
            fwd_inv = self._fwd_tiled_active(bucket, grp.k)
            upd = (per_bucket[grp.bucket] == 1
                   and sparse_update_ops.update_consumes_sort(
                       opt_kind, strategy, max(bucket.rows_max, 1),
                       bucket.width))
            plan.append("inv" if fwd_inv else ("plain" if upd else None))
        return plan

    def _row_sort_plan(self, spec) -> List[Optional[str]]:
        """Per row-sliced input: "plain" when its table's update will
        consume the artifact (single-input tables only — shared tables
        concatenate, see `_sort_plan`)."""
        n = len(self.strategy.input_groups[2])
        if spec is None:
            return [None] * n
        opt_kind, strategy = spec
        counts: dict = {}
        for j in range(n):
            t = self.strategy.map_groups[2][j]
            counts[t] = counts.get(t, 0) + 1
        plan: List[Optional[str]] = []
        for j in range(n):
            t = self.strategy.map_groups[2][j]
            rt = self.plan.row_tables[t]
            ok = (counts[t] == 1
                  and sparse_update_ops.update_consumes_sort(
                      opt_kind, strategy, max(rt.rows_max, 1), rt.width))
            plan.append("plain" if ok else None)
        return plan

    @staticmethod
    def _stack_sort(sort_g: Optional[GroupSort]) -> Optional[GroupSort]:
        """Add the leading per-device axis residual arrays carry."""
        if sort_g is None:
            return None
        return GroupSort(
            sort_g.sid[None], sort_g.perm[None], sort_g.seg_start[None],
            None if sort_g.inv is None else sort_g.inv[None])

    def _group_lookup(self, table: jax.Array, ids: jax.Array,
                      weights: Optional[jax.Array],
                      combiner: Optional[str],
                      presorted: Optional[GroupSort] = None) -> jax.Array:
        """Local fused-bucket lookup + combine: ids [B, f, k] -> [B, f, wf].

        Path selection (overridable via DET_LOOKUP_PATH=auto|xla|pallas for
        hardware A/B): combined sum/mean groups route through the Pallas
        fused kernel on TPU (the hot-loop equivalent of the reference's CUDA
        combiner, cu:175-336) — in 'auto' only for multi-hot (k > 1), under
        'pallas' for one-hot gathers as well; 'xla' forces take + reduce,
        which XLA fuses. (Offloaded buckets never reach here — their lookups
        run host-side in `_host_group_exchange`.)

        `presorted`: a GroupSort of this group's flattened ids (the tapped
        forward's residual artifact). Only the tiled gather consumes it
        (and only when it carries `inv`) — the sort + inverse-permute it
        would otherwise compute itself fold onto the residual sort.
        """
        b_sz, f, k = ids.shape
        path = sparse_update_ops.measured_default("DET_LOOKUP_PATH", "auto")
        if combiner is None and k == 1 and path in ("pallas", "tiled",
                                                    "fused"):
            combiner = "sum"     # identical result at hotness 1
        if (path == "fused" and combiner in ("sum", "mean")
                and self.use_custom_kernel):
            # ISSUE 12 fused gather->combine (ops/pallas_tiled.
            # fused_lookup_combine): one weighted-gather kernel pass +
            # scatter-free unpermute + plain hotness sum, replacing the
            # descriptor-bound XLA table gather AND the separate combine
            # einsum. Compiled use requires the eager shape-class gate
            # (prevalidate_active_impl); off-TPU it runs in interpret
            # mode (tests). The constructor opt-out wins over the knob.
            from distributed_embeddings_tpu.ops import (pallas_tiled,
                                                        sparse_update)
            if not pallas_lookup.is_tpu_backend():
                _warn_interpret_once("fused")
            if sparse_update.pallas_kernels_ok(table):
                w = (weights if weights is not None
                     else jnp.ones((b_sz, f, k), jnp.float32))
                ps = None
                if presorted is not None and presorted.inv is not None:
                    ps = (presorted.sid, presorted.perm, presorted.inv)
                out = pallas_tiled.fused_lookup_combine(
                    table, ids.reshape(b_sz * f, k), w.reshape(b_sz * f, k),
                    combiner, presorted=ps)
                return self._cast(out.reshape(b_sz, f, out.shape[-1]))
        if (path == "tiled" and combiner in ("sum", "mean")
                and self.use_custom_kernel):
            # round-4 tiled one-hot-matmul gather (ops/pallas_tiled.py):
            # sort + block-streamed table walk, replacing the ~22 ns/row
            # descriptor-bound XLA row gather. Compiled use requires the
            # eager hardware validation (prevalidate_active_impl); off-TPU
            # it runs in interpret mode (tests). Gated on use_custom_kernel
            # like the pallas path — the constructor opt-out wins over the
            # env knob (ADVICE r4).
            from distributed_embeddings_tpu.ops import (pallas_tiled,
                                                        sparse_update)
            if not pallas_lookup.is_tpu_backend():
                _warn_interpret_once("tiled")
            if sparse_update.tiled_kernels_ok(table):
                w = (weights if weights is not None
                     else jnp.ones((b_sz, f, k), jnp.float32))
                ps = None
                if presorted is not None and presorted.inv is not None:
                    ps = (presorted.sid, presorted.perm, presorted.inv)
                out = pallas_tiled.tiled_embedding_lookup(
                    table, ids.reshape(b_sz * f, k), w.reshape(b_sz * f, k),
                    combiner, presorted=ps)
                return self._cast(out.reshape(b_sz, f, out.shape[-1]))
        want_pallas = (self.use_custom_kernel
                       and pallas_lookup.is_tpu_backend()
                       and combiner in ("sum", "mean")
                       and path != "xla"
                       and (k > 1 or path == "pallas"))
        if want_pallas:
            w = (weights if weights is not None
                 else jnp.ones((b_sz, f, k), jnp.float32))
            out = pallas_lookup.fused_embedding_lookup(
                table, ids.reshape(b_sz * f, k), w.reshape(b_sz * f, k),
                combiner)
            return self._cast(out.reshape(b_sz, f, out.shape[-1]))
        # (The round-3 DET_SORTED_GATHER sort+sorted-gather+unpermute
        # variant was removed in round 5: DET_LOOKUP_PATH=tiled IS that
        # composite done properly — sort + block-streamed tiled gather +
        # scatter-free unpermute — and the knob never earned its own
        # hardware number. The 'sort+sortedgather+unperm' prim composite in
        # tools/tpu_scatter_probe.py still measures the hypothesis.)
        emb = self._cast(jnp.take(table, ids, axis=0))      # [B, f, k, w]
        return _combine(emb, weights, combiner)

    def _cast(self, x: jax.Array) -> jax.Array:
        """Cast a lookup result to compute_dtype (mixed precision no-op when
        unset)."""
        if self.compute_dtype is not None and x.dtype != self.compute_dtype:
            return x.astype(self.compute_dtype)
        return x

    # -------------------------------------------------------------- forward
    def _my_index(self):
        if self.world_size == 1:
            return jnp.int32(0)
        return lax.axis_index(self.axis)

    def _device_const(self, const: np.ndarray):
        """Select this device's row of a [world, ...] planning constant."""
        return jnp.take(jnp.asarray(const), self._my_index(), axis=0)

    def _forward_local(self, dp_params, tp_params, row_params,
                       dp_in, group_ids, group_w, row_in, groups,
                       taps=None, want_res=False, sort_plan=None,
                       row_sort_plan=None, hot_params=None, tp_scales=None):
        """The per-device forward (shard_map body when world > 1).

        Args:
          dp_in / row_in: lists of (ids [B_l, k], weights or None) per input.
          group_ids: per exchange group, stacked ids [B_l, n_g, k_g].
          group_w: matching weights [B_l, n_g, k_g] or None per group.
          groups: the static _ExchangeGroup records.
          taps: optional {'tp': [[1, B, f, w_out]...], 'row': [...]} zero
            arrays added to each bucket-lookup / row-partial output; their
            cotangents under autodiff are exactly the per-device output
            gradients `sparse_update` consumes (no dense table grads).
          want_res: also return TapResiduals arrays (post-exchange ids +
            effective weights).
          sort_plan / row_sort_plan: static per-group / per-row-input sort
            production plan (see `_sort_plan`) — which GroupSort residuals
            to build, and whether the tiled forward consumes them.
          tp_scales: per-bucket stacked per-row scale shards (None at f32
            or host-offloaded buckets) — quantized HBM-resident buckets
            (ISSUE 17) decode at gather time via `_tp_group_out`.

        Returns (dp_outs, ex_list, row_outs, off_ids, off_w, res):
          dp_outs: [B_l, w] (or [B_l, K, w]) per dp input
          ex_list: per group [world_src, B_l, f_max_g, wf]; None at offloaded
            groups (filled by the caller via _host_group_exchange)
          row_outs: [B_l, ...] partial sums scattered over batch.
          off_ids / off_w: per group the exchanged ids / effective weights
            ([1, ...]-stacked) for offloaded groups, None elsewhere.
          res: (tp_ids, tp_w, row_ids, row_w, tp_sort, row_sort) lists
            ([1, ...]-stacked) or None when want_res is False.
        """
        world = self.world_size
        strat = self.strategy

        # ---- data-parallel tables: plain local lookup on replicated params
        dp_outs = []
        for j, (ids, weights) in enumerate(dp_in):
            t_dp = strat.map_groups[0][j]
            cfg = strat.dp_configs[t_dp]
            table = dp_params[t_dp]
            layer = self._dp_custom_layers.get(t_dp)
            if layer is not None:
                # custom layer_class: run the USER's forward on the prepared
                # [B_l, k] ids (reference :820-834 semantics). Contract:
                # params stay {"embeddings": [V, w]}; output rank must match
                # the stock layer ([B, w] with a combiner, [B, k, w] without)
                # so the shard_map out_specs hold.
                if weights is not None:
                    raise NotImplementedError(
                        f"dp table {t_dp}: (ids, weights) inputs are not "
                        "supported for custom embedding layer classes — "
                        "the layer's own __call__ defines its semantics")
                out = layer({"embeddings": table}, ids)
                want_rank = 2 if cfg.get("combiner") else 3
                if out.ndim != want_rank:
                    raise ValueError(
                        f"dp table {t_dp}: custom layer forward returned "
                        f"rank-{out.ndim} output, expected rank "
                        f"{want_rank} ([batch, width] with a combiner, "
                        "[batch, hotness, width] without)")
                # custom outputs honor the compute_dtype policy like stock
                # tables (ADVICE r5): without the cast, a mixed-precision
                # model would see f32 here and bf16 everywhere else
                dp_outs.append(self._cast(out))
                continue
            emb = self._cast(jnp.take(table, ids, axis=0))   # [B_l, k, w]
            dp_outs.append(_combine(emb, weights, cfg.get("combiner")))

        # ---- table-parallel: per-group all_to_all id exchange (dp->mp),
        # local fused lookup, all_to_all back (mp->dp). Each destination
        # receives only ids for features it owns (reference hvd.alltoall
        # with splits, :211) — not an all_gather of everything.
        ex_list = []
        off_ids: List[Optional[jax.Array]] = []
        off_w: List[Optional[jax.Array]] = []
        tp_res_ids: List[jax.Array] = []
        tp_res_w: List[Optional[jax.Array]] = []
        tp_res_sort: List[Optional[GroupSort]] = []
        hot_res_pos: List[Optional[jax.Array]] = []
        hot_res_w: List[Optional[jax.Array]] = []
        hot_taps = (taps or {}).get("hot") if taps is not None else None
        for g, grp in enumerate(groups):
            ids = group_ids[g]                               # [B_l, n_g, k]
            blocal = ids.shape[0]
            bucket = self.plan.tp_buckets[grp.bucket]
            offloaded = bucket.offload and self._offload_enabled
            # hot-row replication (ISSUE 4): split the id stream against
            # the bucket's replicated hot shard BEFORE the exchange — hit
            # lanes are served locally from the [H, w] hot param (no
            # all_to_all, no big-table gather); miss lanes take the stock
            # exchange with hits masked to zero-weight id-0 lanes
            hot = (hot_params[grp.bucket]
                   if (hot_params is not None and bucket.hot_rows > 0
                       and not offloaded) else None)
            hot_info = None
            if hot is not None:
                send_m, w_send_m, hot_pos, hot_w = self._hot_split_send(
                    grp, ids, group_w[g], world, blocal, hot)
                ids_x, w_x = self._exchange_send(grp, send_m, w_send_m,
                                                 world, blocal)
                if w_x is None:
                    # unweighted input: the sentinel is receiver-
                    # detectable — real ids are < their lane's segment
                    # rows <= rows_max and hit lanes are EXACTLY rows_max
                    # — so the 0/scale effective weights reconstruct
                    # locally, bit-identical to exchanging them. (An
                    # INVALID input id == rows_max reads as weight 0 here
                    # where the baseline clamps it onto the last row;
                    # ids past rows_max keep the baseline clamp.)
                    _, scale = _effective_weights(None, grp.k,
                                                  bucket.combiner)
                    w_x = jnp.where(
                        ids_x == jnp.int32(max(bucket.rows_max, 1)),
                        jnp.float32(0.0), jnp.float32(scale))
                hot_info = (hot_pos, hot_w)
            elif self._use_ragged_exchange(grp, world):
                ids_x, w_x = self._ragged_id_exchange(
                    grp, ids, group_w[g], world, blocal)
            else:
                ids_x, w_x = self._padded_id_exchange(
                    grp, ids, group_w[g], world, blocal)
            offs = self._device_const(grp.offs)              # [f_max]
            ids_x = ids_x + offs[None, :, None].astype(ids_x.dtype)
            # sort folding: ONE canonical sort of this group's exchanged id
            # stream, consumed by the tiled forward gather below (when the
            # plan says "inv") and by the sparse update via the residuals
            sort_g = None
            if (want_res and sort_plan is not None and sort_plan[g]
                    and not offloaded):
                sort_g = canonical_id_sort(
                    ids_x, max(bucket.rows_max, 1),
                    want_inv=(sort_plan[g] == "inv"))
            if offloaded:
                # id exchange happens on-device (above); the lookup itself
                # runs host-side outside the shard_map (reference /CPU:0
                # lookup :829-831) — export the exchanged ids/weights
                eff_w, _ = _effective_weights(w_x, grp.k, bucket.combiner)
                off_ids.append(ids_x[None].astype(jnp.int32))
                off_w.append(None if eff_w is None else eff_w[None])
                ex_list.append(None)
            elif hot_info is not None:
                off_ids.append(None)
                off_w.append(None)
                # miss path: w_x is already the EFFECTIVE weight (scale
                # folded, hits zeroed) — plain weighted sum, tap as usual.
                # The gather gets sentinel lanes CLAMPED: jnp.take's
                # default OOB mode is fill-with-NaN, and 0 * NaN = NaN —
                # the residual/sort streams keep the raw sentinel so the
                # update still drops those lanes outright.
                ids_lu = jnp.minimum(ids_x, max(bucket.rows_max, 1) - 1)
                out = self._group_lookup(tp_params[grp.bucket][0], ids_lu,
                                         w_x, "sum", presorted=sort_g)
                tap_g = None if taps is None else taps["tp"][g]
                if tap_g is not None:
                    out = out + tap_g[0].astype(out.dtype)
                ex = self._tp_bucket_exchange(out, bucket.wire_dtype)
                hot_tap = None if hot_taps is None else hot_taps[g]
                contrib = self._hot_contrib(grp, bucket, hot, hot_info[0],
                                            hot_info[1], hot_tap)
                ex_list.append(ex + contrib.astype(ex.dtype))
            else:
                off_ids.append(None)
                off_w.append(None)
                out = self._tp_group_out(
                    tp_params, grp, ids_x, w_x,
                    None if taps is None else taps["tp"][g],
                    presorted=sort_g,
                    scale_s=(None if tp_scales is None
                             else tp_scales[grp.bucket]))
                ex_list.append(self._tp_bucket_exchange(
                    out, bucket.wire_dtype))
            if want_res:
                if hot_info is not None:
                    # w_x IS the effective weight stream (see above)
                    eff_w = w_x
                else:
                    eff_w, _ = _effective_weights(w_x, grp.k,
                                                  bucket.combiner)
                tp_res_ids.append(ids_x[None].astype(jnp.int32))
                tp_res_w.append(None if eff_w is None else eff_w[None])
                tp_res_sort.append(self._stack_sort(sort_g))
                hot_res_pos.append(None if hot_info is None
                                   else hot_info[0][None])
                hot_res_w.append(None if hot_info is None
                                 else hot_info[1][None])

        # ---- row-sliced tables: all_gather ids, masked lookup, psum_scatter
        row_outs, row_res = self._row_slice_local(
            row_params, row_in,
            None if taps is None else taps["row"], want_res,
            sort_plan=row_sort_plan)
        res = ((tp_res_ids, tp_res_w) + row_res[:2]
               + (tp_res_sort, row_res[2])
               + (hot_res_pos, hot_res_w)) if want_res else None
        return dp_outs, ex_list, row_outs, off_ids, off_w, res

    def _use_ragged_exchange(self, grp, world: int) -> bool:
        """Per-group dp->mp exchange policy. DET_RAGGED_EXCHANGE '1'
        forces the true-splits exchange, '0' forces padded; 'auto' (the
        default) takes true-splits on the TPU backend when the group's
        padded wire volume exceeds 1.5x its true id volume (static
        accounting, same arithmetic as exchange_padding_report — e.g.
        tiny/comm_balanced pads 2.54x, jumbo 1.16x). The ragged op's TPU
        lowering+semantics are hardware-verified (r03 'ragged' stage); a
        padded-vs-ragged wall-clock A/B needs a real pod and is recorded
        as pending in docs/round4_notes.md."""
        if world <= 1:
            return False
        mode = os.environ.get("DET_RAGGED_EXCHANGE", "auto")
        if mode in ("0", "1"):
            ragged = mode == "1"
        elif jax.default_backend() != "tpu":
            ragged = False    # CPU emulation path is for tests only
        else:
            true_ids = sum(len(s) for s in grp.rank_slots) * grp.k
            padded_ids = world * grp.f_max * grp.k
            ragged = padded_ids > 1.5 * max(true_ids, 1)
        # attributable perf (ADVICE r4): record the decision per group so a
        # hardware regression can be traced to the path that ran — surfaced
        # in exchange_padding_report()["exchange_paths"] and the debug log
        decision = "ragged" if ragged else "padded"
        key = (grp.bucket, grp.f_max, grp.k)
        if self._exchange_path_taken.get(key) != decision:
            self._exchange_path_taken[key] = decision
            logging.getLogger(__name__).debug(
                "exchange group bucket=%d f_max=%d k=%d -> %s "
                "(DET_RAGGED_EXCHANGE=%s)", grp.bucket, grp.f_max, grp.k,
                decision, mode)
        return ragged

    def _padded_id_exchange(self, grp, ids, w, world, blocal):
        """Fixed-shape dp->mp id (+weight) exchange: dense
        [world, B_l, f_max, k] blocks through lax.all_to_all (padding
        bounded by the comm_balanced placement).

        Wire formats (ISSUE 5, from the bucket's plan fields): the id
        block narrows to int16 where the planner proved the key space
        fits (losslessly — see ops/wire.py encode_ids), and the weight
        block rides the bucket's float wire. Both decode back to full
        width before any local math."""
        bucket = self.plan.tp_buckets[grp.bucket]
        sel = jnp.asarray(grp.sel.reshape(-1))           # [world*f_max]
        send = jnp.take(ids, sel, axis=1).reshape(
            blocal, world, grp.f_max, grp.k)
        send = jnp.moveaxis(send, 1, 0)                  # [world, B_l, f, k]
        w_x = None
        if w is not None:
            w_send = jnp.take(w, sel, axis=1).reshape(
                blocal, world, grp.f_max, grp.k)
            w_send = jnp.moveaxis(w_send, 1, 0)
        if world > 1:
            recv = wire_ops.wire_id_all_to_all(send, self.axis,
                                               bucket.id_wire_dtype)
            if w is not None:
                w_recv = wire_ops.wire_all_to_all(w_send, self.axis,
                                                  bucket.wire_dtype)
                w_x = w_recv.reshape(-1, grp.f_max, grp.k)
        else:
            recv = send
            if w is not None:
                w_x = w_send.reshape(-1, grp.f_max, grp.k)
        return recv.reshape(-1, grp.f_max, grp.k), w_x   # [B, f, k]

    def _ragged_exchange_rows(self, grp, operand, world, blocal):
        """One true-splits exchange of destination-major flat rows
        ``operand [S, blocal*k]`` -> receive layout [B, f_max, k] — the
        shared core of `_ragged_id_exchange` and the hot split's
        `_exchange_send` (ONE copy of the split metadata, the
        DET_RAGGED_NATIVE choice and the receive-layout reassembly, so
        the two callers cannot drift).

        The operand crosses at its bucket's wire format (ISSUE 5),
        dispatched by dtype: int operands take the id wire (int16 where
        the planner proved the range), float operands the float wire.
        The float encode/decode pair is differentiable, so the reverse
        ragged exchange of the weight gradient rides the same wire
        (no custom_vjp needed — the cast transposes bound it)."""
        bucket = self.plan.tp_buckets[grp.bucket]
        orig_dtype = operand.dtype
        is_int = jnp.issubdtype(orig_dtype, jnp.integer)
        if is_int:
            operand = wire_ops.encode_ids(operand, bucket.id_wire_dtype)
        else:
            operand = wire_ops.encode_fwd(operand, bucket.wire_dtype)
        me = self._my_index()
        f_pr = jnp.asarray(grp.f_per_rank)
        in_off = jnp.asarray(grp.in_offsets)
        out_off = jnp.full((world,), me * grp.f_max, jnp.int32)
        recv_sz = jnp.full((world,), jnp.take(f_pr, me), jnp.int32)
        native_env = os.environ.get("DET_RAGGED_NATIVE", "auto")
        native = (pallas_lookup.is_tpu_backend() if native_env == "auto"
                  else native_env == "1")
        out_buf = jnp.zeros((world * grp.f_max, blocal * grp.k),
                            operand.dtype)
        recv = _ragged_exchange_op(operand, out_buf, in_off, f_pr,
                                   out_off, recv_sz, self.axis, native)
        if is_int:
            recv = wire_ops.decode_ids(recv, bucket.id_wire_dtype,
                                       orig_dtype)
        else:
            recv = recv.astype(orig_dtype)
        recv = recv.reshape(world, grp.f_max, blocal, grp.k)
        return jnp.moveaxis(recv, 2, 1).reshape(-1, grp.f_max, grp.k)

    def _ragged_id_exchange(self, grp, ids, w, world, blocal):
        """True-splits dp->mp exchange (DET_RAGGED_EXCHANGE=1): each
        destination's features travel unpadded — sum_r f_r rows on the
        wire instead of world*f_max (the reference's hvd.alltoall(splits)
        volume, dist_model_parallel.py:169-288). Weights (explicit or the
        synthesized ragged/sparse masks) ride the same metadata;
        `lax.ragged_all_to_all` carries jvp+transpose rules, so the weight
        gradient flows back through the reverse exchange. The receive
        buffer keeps the [world, f_max] layout (static shapes; unwritten
        slots read as id/weight 0 and are never consumed downstream), so
        everything after the exchange — offsets, lookup, output exchange,
        residuals — is byte-identical to the padded path."""
        flat_sel = jnp.asarray(grp.flat_sel)             # [S]
        s_rows = int(grp.f_per_rank.sum())

        def exchange(x):                                 # [B_l, n_g, k]
            send = jnp.take(x, flat_sel, axis=1)         # [B_l, S, k]
            send = jnp.moveaxis(send, 1, 0).reshape(
                s_rows, blocal * grp.k)
            return self._ragged_exchange_rows(grp, send, world, blocal)

        return exchange(ids), None if w is None else exchange(w)

    # ------------------------------------------- hot-row split (ISSUE 4)
    def _hot_group_meta(self, grp):
        """Static per-group hot-split constants: ``base [world, f_max]``
        — each send lane's flat key base ``rank * rows_max + row_offset``
        — ``lane_valid [world, f_max]`` masking the f_max padding lanes
        (their sel replicates input 0; without the mask a padding lane
        could alias a hot key and pollute the split), and ``lane_rows
        [world, f_max]`` — each lane's backing table-segment row count,
        bounding which ids are in range for THAT lane (an over-range id
        would fold onto a neighboring segment's or the next rank's key
        space and could falsely hit a foreign resident row). Memoized per
        group object (groups live forever in _groups_cache)."""
        hit = self._hot_meta_cache.get(id(grp))
        if hit is not None:
            return hit
        bucket = self.plan.tp_buckets[grp.bucket]
        rows_max = max(bucket.rows_max, 1)
        world = self.world_size
        rows_of = {(pl.rank, pl.row_offset): pl.rows
                   for pl in self.plan.tp_placements
                   if pl.bucket == grp.bucket}
        base = np.zeros((world, grp.f_max), np.int64)
        lane_valid = np.zeros((world, grp.f_max), bool)
        lane_rows = np.zeros((world, grp.f_max), np.int32)
        for r in range(world):
            base[r, :] = r * rows_max
            for j in range(int(grp.f_per_rank[r])):
                base[r, j] += int(grp.offs[r, j])
                lane_valid[r, j] = True
                lane_rows[r, j] = rows_of.get((r, int(grp.offs[r, j])), 0)
        res = (base.astype(np.int32), lane_valid, lane_rows)
        self._hot_meta_cache[id(grp)] = res
        return res

    def _hot_split_send(self, grp, ids, w, world, blocal, hot):
        """Pre-exchange hot-membership split of one exchange group.

        Builds the destination-major send block [world, B_l, f_max, k]
        (ids + EFFECTIVE weights — the explicit weighted-sum form with the
        static mean scale folded in, so hit and miss contributions share
        the baseline's denominators), classifies every lane against the
        bucket's sorted hot membership (`sorted_member_positions`: a
        searchsorted — zero sort ops), and SENTINEL-masks hit lanes out
        of the miss path: their ids go to `rows_max` (post-offset ids
        land >= rows_max — the canonical OOB sentinel every lookup path
        clamps and the sparse update DROPS outright) and their weights to
        0. The canonical rows of resident ids are therefore never even
        touched by the update — which matters for lazy adam, whose
        moment decay runs on every *touched* row regardless of the
        gradient value (a zero-contribution touch at a real row would
        silently diverge its moments from the hot-less baseline).

        Returns (send_ids_m, send_w_m, hot_pos, hot_w): masked send block
        plus, per lane, the hot-shard row position (sentinel H on miss)
        and the effective hit weight (0 on miss).
        """
        bucket = self.plan.tp_buckets[grp.bucket]
        h_cap = bucket.hot_rows
        rows_max = max(bucket.rows_max, 1)
        eff, scale = _effective_weights(w, grp.k, bucket.combiner)
        sel = jnp.asarray(grp.sel.reshape(-1))
        send = jnp.take(ids, sel, axis=1).reshape(
            blocal, world, grp.f_max, grp.k)
        send = jnp.moveaxis(send, 1, 0).astype(jnp.int32)
        if eff is None:
            # unweighted input: every lane's effective weight is the
            # static `scale`, so there is nothing worth exchanging — hit
            # weights below are the scale constant, and the miss weights
            # reconstruct receiver-side from the sentinel (see the
            # caller), sparing a dense f32 all_to_all the stock
            # unweighted exchange never pays
            w_send = None
        else:
            wsum = eff * jnp.asarray(scale, jnp.float32)  # [B_l, n_g, k]
            w_send = jnp.moveaxis(jnp.take(wsum, sel, axis=1).reshape(
                blocal, world, grp.f_max, grp.k), 1, 0)
        base, lane_valid, lane_rows = self._hot_group_meta(grp)
        keys = send + jnp.asarray(base)[:, None, :, None]
        pos, hit = embedding_ops.sorted_member_positions(hot["ids"], keys)
        # out-of-range input ids fold onto a NEIGHBORING segment's (or the
        # next/previous rank's) key range and could alias a resident key
        # there — serving a foreign table's hot row with full weight where
        # the baseline gather handles the invalid id deterministically.
        # Invalid ids always miss: 0 <= id < this lane's segment rows.
        hit = (hit & jnp.asarray(lane_valid)[:, None, :, None]
               & (send >= 0)
               & (send < jnp.asarray(lane_rows)[:, None, :, None]))
        send_m = jnp.where(hit, jnp.int32(rows_max), send)
        hot_pos = jnp.where(hit, pos, jnp.int32(h_cap))
        if w_send is None:
            w_send_m = None
            hot_w = jnp.where(hit, jnp.float32(scale), jnp.float32(0.0))
        else:
            w_send_m = jnp.where(hit, 0.0, w_send)
            hot_w = jnp.where(hit, w_send, 0.0)
        return send_m, w_send_m, hot_pos, hot_w

    def _exchange_send(self, grp, send, w_send, world, blocal):
        """dp->mp exchange of a pre-built destination-major send block
        [world, B_l, f_max, k] (+ weights) — the hot-split form of
        `_padded_id_exchange` / `_ragged_id_exchange` (the split must mask
        per (destination, slot) lane, which only exists post-`sel`).
        Returns (ids_x [B, f, k], w_x [B, f, k]) matching the stock
        exchanges byte for byte (incl. their wire formats, ISSUE 5)."""
        bucket = self.plan.tp_buckets[grp.bucket]
        if not self._use_ragged_exchange(grp, world):
            if world > 1:
                recv = wire_ops.wire_id_all_to_all(send, self.axis,
                                                   bucket.id_wire_dtype)
                w_recv = (None if w_send is None else
                          wire_ops.wire_all_to_all(w_send, self.axis,
                                                   bucket.wire_dtype))
            else:
                recv, w_recv = send, w_send
            return (recv.reshape(-1, grp.f_max, grp.k),
                    None if w_recv is None else
                    w_recv.reshape(-1, grp.f_max, grp.k))
        # ragged: destination-major flat rows (r, j < f_r) selected out of
        # the send block — same operand the stock ragged path builds
        s_rows = int(grp.f_per_rank.sum())
        flat_rows = (np.concatenate(
            [r * grp.f_max + np.arange(n, dtype=np.int64)
             for r, n in enumerate(grp.f_per_rank)]).astype(np.int32)
            if s_rows else np.zeros((0,), np.int32))

        def exchange(x):                          # [world, B_l, f_max, k]
            flat = jnp.transpose(x, (0, 2, 1, 3)).reshape(
                world * grp.f_max, blocal * grp.k)
            op = jnp.take(flat, jnp.asarray(flat_rows), axis=0)
            return self._ragged_exchange_rows(grp, op, world, blocal)

        return exchange(send), (None if w_send is None
                                else exchange(w_send))

    def _hot_contrib(self, grp, bucket, hot, hot_pos, hot_w, hot_tap):
        """The hit lanes' locally-computed output contribution
        [world, B_l, f_max, w]: gather from the replicated hot shard,
        weighted-sum over hotness — added to the returned exchange block
        (same layout), so hits never touch the exchange or the big table.
        `hot_tap` (the hot-shard tap) rides the addition; its cotangent is
        exactly the per-(serving-rank, sample, slot) output gradient the
        replicated hot update consumes."""
        ph = jnp.minimum(hot_pos, bucket.hot_rows - 1)
        rows = self._cast(jnp.take(hot["rows"], ph, axis=0))
        contrib = jnp.einsum("rbfk,rbfkw->rbfw",
                             hot_w.astype(rows.dtype), rows)
        if hot_tap is not None:
            contrib = contrib + hot_tap.astype(contrib.dtype)
        return contrib

    def _tp_group_out(self, tp_params, grp, ids_x, w_x, tap, presorted=None,
                      scale_s=None):
        """One exchange group's local bucket output [B, f, w_out], via the
        explicit weighted-sum form (so tapped and untapped paths share
        numerics), plus the optional tap perturbation.

        scale_s: the bucket's stacked per-row scale shard for quantized
        HBM-RESIDENT storage (ISSUE 17) — the payload rows and their
        scales gather together and decode right here, inside the jitted
        program (the device twin of `_host_group_exchange`'s
        decode-at-gather). The kernel lookup paths (pallas/tiled/fused)
        are f32-table programs, so quantized buckets take the explicit
        gather+combine form — the same numerics as `_group_lookup`'s XLA
        route with one decode inserted before the cast."""
        bucket = self.plan.tp_buckets[grp.bucket]
        eff_w, scale = _effective_weights(w_x, grp.k, bucket.combiner)
        if scale_s is not None:
            emb = jnp.take(tp_params[grp.bucket][0], ids_x, axis=0)
            srow = jnp.take(scale_s[0], ids_x, axis=0)
            emb = self._cast(wire_ops.decode_rows(
                emb, srow, bucket.storage_dtype))
            out = _combine(emb, eff_w,
                           None if bucket.combiner is None else "sum")
        else:
            out = self._group_lookup(
                tp_params[grp.bucket][0], ids_x, eff_w,
                None if bucket.combiner is None else "sum",
                presorted=presorted)
        if scale != 1.0:
            out = out * jnp.asarray(scale, out.dtype)
        if tap is not None:
            out = out + tap[0].astype(out.dtype)
        return out

    def _host_group_exchange(self, table_h: jax.Array, grp, ids_g, w_g, tap,
                             g: int, scale_h=None):
        """Offloaded-bucket lookup: gather+combine in pinned host memory
        (compute_on 'device_host'), stream only combined [B, f, w_out] rows
        to the device, then reshard owner-major -> batch-major (the GSPMD
        form of the mp->dp all_to_all). Output layout matches
        `_tp_bucket_exchange` exactly. Reference: /CPU:0 tables with native
        kernels (dist_model_parallel.py:829-831).

        ids_g: [world, B, f, k] device-sharded exchanged absolute rows;
        w_g: matching effective weights or None; tap: optional perturbation;
        scale_h: the bucket's per-row scale stack for quantized storage
        (ISSUE 15) — rows gather at the stored dtype and DECODE here, in
        the same host region as the gather, so only the touched rows'
        payloads+scales ever move and only f32 combined rows go device-ward.
        """
        bucket = self.plan.tp_buckets[grp.bucket]
        world = self.world_size
        k, wf = grp.k, bucket.width
        store_dtype = bucket.storage_dtype
        # bucket identity must key the cache: the same group index can map
        # to a different bucket under another hotness signature, and the
        # closure bakes in rows_max / combiner / scale
        key = (g, grp.bucket, bucket.combiner, ids_g.shape,
               None if w_g is None else w_g.shape,
               None if tap is None else tap.shape,
               None if scale_h is None else store_dtype)
        fn = self._host_fn_cache.get(key)
        if fn is None:
            combiner = bucket.combiner
            # the static mean scale applies only to the uniform-weights case;
            # explicit weights arrive already normalized (_effective_weights'
            # scale-1.0 branch) — mirroring _tp_group_out exactly
            if w_g is None:
                _, scale = _effective_weights(None, k, combiner)
            else:
                scale = 1.0
            rows_max = max(bucket.rows_max, 1)
            if self.mesh is not None:
                host_sh = lambda: NamedSharding(self.mesh, P(self.axis),
                                                memory_kind=self._host_kind)
                dev_sh = NamedSharding(self.mesh, P(self.axis))
            else:
                dev0 = jax.devices()[0]
                host_sh = lambda: jax.sharding.SingleDeviceSharding(
                    dev0, memory_kind=self._host_kind)
                dev_sh = jax.sharding.SingleDeviceSharding(dev0)

            def run(table_h, scale_h, ids_g, w_g, tap):
                B, f = ids_g.shape[1], ids_g.shape[2]
                ids = jnp.clip(ids_g, 0, rows_max - 1).reshape(world, -1)
                ids_h = jax.device_put(ids, host_sh())
                w_h = (None if w_g is None
                       else jax.device_put(
                           w_g.reshape(world, B * f, k), host_sh()))
                from jax.experimental import compute_on
                with compute_on.compute_on("device_host"):
                    rows = jax.vmap(sparse_update_ops.take_rows)(
                        table_h, ids_h)                    # [world, N, wf]
                    if scale_h is not None:
                        # decode-at-gather (ISSUE 15): per-row scales
                        # gather beside their payload rows, all inside
                        # the host region — device-ward traffic stays
                        # the combined f32 rows, exactly the f32 path's
                        srow = jax.vmap(sparse_update_ops.take_rows)(
                            scale_h, ids_h)                # [world, N, 1]
                        rows = wire_ops.decode_rows(rows, srow,
                                                    store_dtype)
                    if combiner is None:
                        out_h = rows.reshape(world, B, f, k * wf)
                    else:
                        rows = rows.reshape(world, B * f, k, wf)
                        out_h = (rows if w_h is None
                                 else rows * w_h[..., None]).sum(axis=2)
                        out_h = out_h.reshape(world, B, f, wf)
                out = jax.device_put(out_h, dev_sh)
                out = self._cast(out)
                if scale != 1.0:
                    out = out * jnp.asarray(scale, out.dtype)
                if tap is not None:
                    out = out + tap.astype(out.dtype)
                if self.mesh is not None and world > 1:
                    out = lax.with_sharding_constraint(
                        out, NamedSharding(self.mesh, P(None, self.axis)))
                return out

            fn = jax.jit(run)
            self._host_fn_cache[key] = fn
        return fn(table_h, scale_h, ids_g, w_g, tap)

    def offload_lookup_scope(self, lookup_fn):
        """Scope an offloaded-bucket lookup override over forwards.

        ``lookup_fn(g, grp, table, ids_g, w_g) -> out | None`` is consulted
        for every offloaded exchange group of a TAPLESS forward (training
        forwards with taps always take the host path — the tap gradient
        contract depends on it). Returning None falls back to the stock
        host-memory lookup. `ids_g`/`w_g` and the required output layout
        are exactly `_host_group_exchange`'s. This is the seam the serving
        subsystem's HBM hot-row cache uses (serving/cache.py); the scope is
        re-entrant per layer instance, not thread-safe.
        """
        import contextlib

        @contextlib.contextmanager
        def scope():
            prev = self._offload_lookup_override
            self._offload_lookup_override = lookup_fn
            try:
                yield self
            finally:
                self._offload_lookup_override = prev
        return scope()

    def _offload_group_out(self, g, grp, table, scale, off_id, off_w,
                           tap_g):
        """One offloaded group's output: the serving override when scoped
        (and tapless), else the host-memory gather+combine
        (decode-at-gather for quantized storage). The override receives
        the AT-REST table leaf — raw f32 rows, or the quantized payload
        whose decode (via the bucket's scale leaf) is the override's
        job; the serving cache's decode seam (ISSUE 17) fetches that
        scale itself from the same traced params."""
        if tap_g is None and self._offload_lookup_override is not None:
            out = self._offload_lookup_override(g, grp, table, off_id, off_w)
            if out is not None:
                return out
        return self._host_group_exchange(table, grp, off_id, off_w, tap_g,
                                         g, scale_h=scale)

    def _tp_bucket_exchange(self, out: jax.Array,
                            wire: str = "f32") -> jax.Array:
        """mp->dp movement of one bucket's outputs: [B, f, wf] ->
        [world_src, B_l, f, wf] (reference hvd.alltoall :870-872).

        `wire` (the bucket's plan `wire_dtype`, ISSUE 5) compresses the
        activation block on the wire — and, through the custom-vjp
        transpose, the dp->mp GRADIENT block of the backward pass —
        while the math on both sides stays at the caller's dtype. 'f32'
        lowers to the exact pre-seam `lax.all_to_all`."""
        world = self.world_size
        if world > 1:
            blocal = out.shape[0] // world
            x = out.reshape((world, blocal) + out.shape[1:])
            return wire_ops.wire_all_to_all(x, self.axis, wire)
        return out[None]

    def _row_slice_local(self, row_params, row_in, row_taps=None,
                         want_res=False, sort_plan=None):
        world = self.world_size
        strat = self.strategy
        row_outs = []
        res_ids: List[jax.Array] = []
        res_w: List[jax.Array] = []
        res_sort: List[Optional[GroupSort]] = []
        for j, (ids, weights) in enumerate(row_in):
            t = strat.map_groups[2][j]
            rt = self.plan.row_tables[t]
            if world > 1:
                # wire formats (ISSUE 5) from the row-table plan: int16
                # id wire where the TOTAL row count provably fits, the
                # float wire on the weight broadcast
                ids = wire_ops.wire_id_all_gather(ids, self.axis,
                                                  rt.id_wire_dtype)
                if weights is not None:
                    weights = wire_ops.wire_all_gather(
                        weights, self.axis, rt.wire_dtype, world)
            base = self._device_const(rt.row_base)
            nrows = self._device_const(np.asarray(rt.rows_per_rank, np.int32))
            local = ids - base.astype(ids.dtype)
            valid = (local >= 0) & (local < nrows.astype(ids.dtype))
            local = jnp.clip(local, 0, max(rt.rows_max - 1, 0))
            table = row_params[t][0]
            emb = self._cast(jnp.take(table, local, axis=0))
            vmask = valid.astype(jnp.float32)
            # explicit weighted-sum form (see _effective_weights): the valid
            # mask folds into the weights so the tapped backward sees the
            # exact per-contribution coefficients
            eff_w, scale = _effective_weights(weights, ids.shape[-1],
                                              rt.combiner)
            w_full = vmask if eff_w is None else eff_w * vmask
            if rt.combiner is None:
                out = emb * vmask[..., None].astype(emb.dtype)     # [B, k, w]
            else:
                out = jnp.einsum("bk,bkw->bw", w_full.astype(emb.dtype), emb)
                if scale != 1.0:
                    out = out * jnp.asarray(scale, out.dtype)
            if row_taps is not None:
                out = out + row_taps[j][0].astype(out.dtype)
            if world > 1:
                # the partial-sum return rides the float wire; under a
                # compressed wire the reduce-scatter re-expresses as
                # all_to_all + LOCAL f32 accumulation, so cross-device
                # adds never run at wire precision (ops/wire.py)
                out = wire_ops.wire_psum_scatter(out, self.axis,
                                                 rt.wire_dtype, world)
            row_outs.append(out)
            if want_res:
                # OOB sentinel rows_max: dropped by the sparse scatter
                sent = jnp.where(valid, local, rt.rows_max).astype(jnp.int32)
                res_ids.append(sent[None])
                res_w.append((w_full * scale)[None])
                sort_j = None
                if sort_plan is not None and sort_plan[j]:
                    sort_j = canonical_id_sort(sent, max(rt.rows_max, 1))
                res_sort.append(self._stack_sort(sort_j))
        return row_outs, (res_ids, res_w, res_sort)

    def apply(self, params: dict, inputs: Sequence, taps=None,
              return_residuals: bool = False, residual_sort=None,
              _want_exchange: bool = False):
        """Forward pass with data-parallel input.

        Args:
          params: pytree from `init` (or `set_weights`).
          inputs: one per feature — global-batch arrays [B] / [B, k],
            RaggedIds, SparseIds or (ids, weights) tuples.
          taps: optional zero pytree from `make_taps(inputs)`. When supplied,
            differentiating the loss w.r.t. `taps` yields the per-device
            bucket-output gradients that `sparse_update` turns into row-wise
            table updates — the TPU equivalent of the reference's sparse
            IndexedSlices backward (embedding_lookup_ops.py:105-122), with
            no dense [V, w] gradient ever materialized.
          return_residuals: also return the TapResiduals for `sparse_update`.
          residual_sort: sort-folding control. None (default) defers to the
            ambient `residual_sort_scope` (off unless scoped — non-tapped
            and host-offload paths keep their exact pre-fold behavior);
            False forces off; an (optimizer_kind, strategy) tuple forces
            the spec. Only consulted when return_residuals is True.
          _want_exchange: lookahead prefetch mode (ISSUE 9, used by
            `schedule.LookaheadEngine`): return the RAW exchange-stage
            artifacts `(ex_list, row_outs, residuals)` instead of
            assembled per-input outputs — ex_list is the post-all_to_all
            per-group activation block `[world_src, B, f_max_g, wf]`,
            row_outs the post-psum_scatter row-table partials. The
            exchange computation is the IDENTICAL code path the normal
            forward runs (the dp lookup and assembly are traced but
            unused, so XLA drops them); a later `staged_exchange_scope`
            forward re-attaches these artifacts bit-exactly.

        Returns:
          One [B, width] array per input (or [B, k, width] for combiner=None
          multi-hot), in input order — batch-sharded over the mesh.
          With return_residuals, a (outputs, TapResiduals) tuple.
        """
        if not self.dp_input:
            raise ValueError("This layer was built with dp_input=False; "
                             "use apply_mp() instead")
        if self._staged_exchange is not None and not _want_exchange:
            return self._apply_staged(params, inputs, taps=taps,
                                      return_residuals=return_residuals)
        if _want_exchange:
            return_residuals = True
            if taps is not None:
                raise ValueError("_want_exchange is a tapless prefetch "
                                 "mode; gradients reach the tables via "
                                 "the drain-stage transpose, not taps")
        if residual_sort is None:
            sort_spec = self._residual_sort_spec
        else:
            sort_spec = None if residual_sort is False else residual_sort
        prepped = self._prepare_inputs(inputs)
        strat = self.strategy
        world = self.world_size

        batch = prepped[0].ids.shape[0]
        if world > 1 and batch % world != 0:
            raise ValueError(
                f"Global batch {batch} not divisible by device count {world}")

        dp_prep = [prepped[i] for i in strat.input_groups[0]]
        tp_prep = [prepped[i] for i in strat.input_groups[1]]
        row_prep = [prepped[i] for i in strat.input_groups[2]]

        # stack tp inputs per exchange group: [B, n_g, k_g] (+ weights where
        # any member input carries them — same-k members need no pad weights)
        groups, assembly = ([], [])
        group_ids: List[jax.Array] = []
        group_w: List[Optional[jax.Array]] = []
        if tp_prep:
            groups, assembly = self._exchange_groups(tp_prep)
            for grp in groups:
                members = [tp_prep[i] for i in grp.class_inputs]
                group_ids.append(jnp.stack(
                    [p.ids.astype(jnp.int32) for p in members], axis=1))
                if grp.need_w:
                    group_w.append(jnp.stack(
                        [(p.weights if p.weights is not None
                          else jnp.ones((batch, p.k), jnp.float32))
                         for p in members], axis=1))
                else:
                    group_w.append(None)

        dp_in = [(p.ids, p.weights) for p in dp_prep]
        row_in = [(p.ids, p.weights) for p in row_prep]

        want_res = bool(return_residuals)
        sort_plan = (self._sort_plan(groups, sort_spec) if want_res
                     else [None] * len(groups))
        row_sort_plan = (self._row_sort_plan(sort_spec) if want_res
                         else [None] * len(row_in))
        offloaded_groups = [
            g for g, grp in enumerate(groups)
            if self.plan.tp_buckets[grp.bucket].offload
            and self._offload_enabled]
        # taps of offloaded groups are applied outside the shard_map (at the
        # host-lookup output); mask them from the inner forward
        inner_taps = taps
        if taps is not None and offloaded_groups:
            inner_taps = {
                "tp": [None if g in offloaded_groups else t
                       for g, t in enumerate(taps["tp"])],
                "row": taps["row"]}
            if "hot" in taps:
                inner_taps["hot"] = taps["hot"]
        hot_params = (params.get("hot")
                      if self._hot_buckets and self.plan.tp_buckets else None)
        # which groups take the hot split (static): mirrors _forward_local
        hot_groups = set()
        if hot_params is not None:
            for g, grp in enumerate(groups):
                if (self.plan.tp_buckets[grp.bucket].hot_rows > 0
                        and hot_params[grp.bucket] is not None
                        and g not in offloaded_groups):
                    hot_groups.add(g)
        if hot_groups and taps is not None and "hot" not in taps:
            # the split masks resident rows' canonical gradients to ZERO
            # by design — their updates flow only through the hot taps, so
            # a hand-built tap pytree without them would silently freeze
            # the hottest rows (tapless forwards are fine: no gradients)
            raise ValueError(
                "tapped hot-split forward needs taps['hot'] — build the "
                "tap pytree with make_taps() (it adds the hot entry when "
                "hot_rows is active), or pass taps=None")
        dev_scales = self._device_bucket_scales(params)
        if world > 1:
            specs = lambda tree, spec: jax.tree.map(lambda _: spec, tree)
            args = (params["dp"], params["tp"], params["row"],
                    dp_in, group_ids, group_w, row_in, inner_taps,
                    hot_params, dev_scales)
            # the hot-shard taps enter batch-sharded with the serving-rank
            # axis intact (P(None, axis)) — each device adds the hot
            # contribution for its OWN batch slice across all source ranks
            tap_specs = None
            if inner_taps is not None:
                tap_specs = {
                    "tp": specs(inner_taps["tp"], P(self.axis)),
                    "row": specs(inner_taps["row"], P(self.axis))}
                if "hot" in inner_taps:
                    tap_specs["hot"] = [
                        None if t is None else P(None, self.axis)
                        for t in inner_taps["hot"]]
            in_specs = (specs(params["dp"], P()),
                        specs(params["tp"], P(self.axis)),
                        specs(params["row"], P(self.axis)),
                        specs(dp_in, P(self.axis)),
                        specs(group_ids, P(self.axis)),
                        specs(group_w, P(self.axis)),
                        specs(row_in, P(self.axis)),
                        tap_specs,
                        specs(hot_params, P()),
                        specs(dev_scales, P(self.axis)))
            off_id_specs = [P(self.axis) if g in offloaded_groups else None
                            for g in range(len(groups))]
            off_w_specs = [
                (P(self.axis) if (g in offloaded_groups
                                  and group_w[g] is not None) else None)
                for g in range(len(groups))]
            out_specs = (
                [P(self.axis)] * len(dp_in),
                [None if g in offloaded_groups else P(None, self.axis)
                 for g in range(len(groups))],
                [P(self.axis)] * len(row_in),
                off_id_specs,
                off_w_specs,
            )
            res_specs = ((
                [P(self.axis)] * len(groups),
                # hot-split groups always carry effective weights, even
                # when the raw input had none
                [P(self.axis) if (w is not None or g in hot_groups)
                 else None for g, w in enumerate(group_w)],
                [P(self.axis)] * len(row_in),
                [P(self.axis)] * len(row_in),
                # GroupSort subtrees take P(axis) as a pytree-prefix spec
                [None if p is None else P(self.axis) for p in sort_plan],
                [None if p is None else P(self.axis)
                 for p in row_sort_plan],
                [P(self.axis) if g in hot_groups else None
                 for g in range(len(groups))],
                [P(self.axis) if g in hot_groups else None
                 for g in range(len(groups))]) if want_res else None,)
            dp_outs, ex_list, row_outs, off_ids, off_w, res = compat.shard_map(
                lambda d, t, r, di, gi, gw, ri, tp, hp, sc:
                self._forward_local(
                    d, t, r, di, gi, gw, ri, groups, taps=tp,
                    want_res=want_res, sort_plan=sort_plan,
                    row_sort_plan=row_sort_plan, hot_params=hp,
                    tp_scales=sc),
                mesh=self.mesh, in_specs=in_specs,
                out_specs=out_specs + res_specs,
                check_vma=False,
            )(*args)
        else:
            dp_outs, ex_list, row_outs, off_ids, off_w, res = (
                self._forward_local(
                    params["dp"], params["tp"], params["row"],
                    dp_in, group_ids, group_w, row_in, groups,
                    taps=inner_taps, want_res=want_res,
                    sort_plan=sort_plan, row_sort_plan=row_sort_plan,
                    hot_params=hot_params, tp_scales=dev_scales))

        if _want_exchange:
            # lookahead prefetch return (ISSUE 9): the raw exchange-stage
            # artifacts. Offloaded buckets are refused — their lookup runs
            # host-side OUTSIDE the jitted stage, so there is no device
            # artifact to carry across the pipeline boundary.
            if offloaded_groups:
                raise NotImplementedError(
                    "lookahead prefetch (_want_exchange) does not support "
                    "host-offloaded buckets: their lookups run outside the "
                    "jitted stage and cannot be carried/patched")
            key = tuple((p.k, p.weights is not None) for p in tp_prep)
            return ex_list, row_outs, TapResiduals(
                key, res[0], res[1], res[2], res[3], res[4], res[5],
                res[6], res[7])

        # offloaded buckets: host-side lookup + GSPMD exchange (or the
        # scoped serving override — see offload_lookup_scope)
        for g in offloaded_groups:
            grp = groups[g]
            tap_g = taps["tp"][g] if taps is not None else None
            ex_list[g] = self._offload_group_out(
                g, grp, params["tp"][grp.bucket],
                self._bucket_scale(params, grp.bucket),
                off_ids[g], off_w[g], tap_g)

        # ---- assemble per-input outputs ------------------------------------
        dp_final = []
        for j, out in enumerate(dp_outs):
            p = dp_prep[j]
            cfg = strat.dp_configs[strat.map_groups[0][j]]
            dp_final.append(self._restore_shape(out, p, cfg.get("combiner"),
                                                cfg["output_dim"]))

        tp_final = self._assemble_tp_outputs(ex_list, tp_prep, batch,
                                             groups, assembly)

        row_final = []
        for j, out in enumerate(row_outs):
            p = row_prep[j]
            rt = self.plan.row_tables[strat.map_groups[2][j]]
            row_final.append(self._restore_shape(out, p, rt.combiner, rt.width))

        outputs = dp_final + tp_final + row_final
        outputs = [outputs[idx] for idx in strat.rev_group_ids]
        if want_res:
            key = tuple((p.k, p.weights is not None) for p in tp_prep)
            return outputs, TapResiduals(key, res[0], res[1], res[2], res[3],
                                         res[4], res[5], res[6], res[7])
        return outputs

    def _assemble_tp_outputs(self, ex_list, tp_preps, batch, groups,
                             assembly) -> List[jax.Array]:
        """Slice the exchanged group outputs back into per-input arrays:
        reorder by slot, re-concat column slices (reference :876-886).

        Args:
          ex_list: per exchange group [world_src, B, f_max_g, wf] globals.
          tp_preps: _PreparedInput per tp-group input position.
          groups / assembly: from _exchange_groups (rank-major slot order).
        """
        strat = self.strategy
        tp_final = []
        for i, p in enumerate(tp_preps):
            parts = []
            for (rank, g, j_g) in assembly[i]:
                grp = groups[g]
                bucket = self.plan.tp_buckets[grp.bucket]
                part = ex_list[g][rank, :, j_g, :]          # [B, wf]
                if bucket.combiner is None:
                    part = part.reshape(batch, grp.k, bucket.width)
                parts.append(part)
            out = parts[0] if len(parts) == 1 else jnp.concatenate(parts, axis=-1)
            cfg = strat.global_configs[
                strat.table_groups[1][strat.map_groups[1][i]]]
            tp_final.append(self._restore_shape(out, p, cfg.get("combiner"),
                                                out.shape[-1]))
        return tp_final

    # ------------------------------------------ lookahead staging (ISSUE 9)
    @contextlib.contextmanager
    def staged_exchange_scope(self, ex_list, row_outs):
        """Scope forwards over PREFETCHED exchange artifacts.

        Inside the scope, `apply(params, inputs)` skips the id exchange /
        table gather / activation all_to_all and consumes the provided
        per-group activation blocks (`ex_list`, from a prior
        `apply(..., _want_exchange=True)`) and row-table partials
        (`row_outs`) instead — only the dp lookups and the output
        assembly run live. This is the dense stage of the lookahead
        pipeline (schedule.LookaheadEngine): differentiating the scoped
        forward w.r.t. `ex_list`/`row_outs` yields exactly the
        activation cotangents whose explicit dp->mp transpose
        (`exchange_transpose`) reproduces the monolithic step's tap
        gradients bit-exactly."""
        prev = self._staged_exchange
        self._staged_exchange = (list(ex_list), list(row_outs))
        try:
            yield
        finally:
            self._staged_exchange = prev

    def _apply_staged(self, params, inputs, taps=None,
                      return_residuals=False):
        """apply() body under `staged_exchange_scope`: live dp lookups +
        assembly over the carried exchange artifacts (same code path the
        stock forward's tail runs, so values are bit-identical given
        bit-identical artifacts)."""
        if taps is not None or return_residuals:
            raise ValueError(
                "staged_exchange_scope forwards are tapless by design — "
                "table gradients reach the sparse update through the "
                "engine's drain-stage transpose, not taps")
        if self._hot_buckets:
            raise NotImplementedError(
                "staged_exchange_scope does not support hot-row "
                "replicated buckets (the replicated hot shard updates "
                "densely every step, so prefetched activations cannot be "
                "patched from the touched-row set)")
        prepped = self._prepare_inputs(inputs)
        strat = self.strategy
        batch = prepped[0].ids.shape[0]
        dp_prep = [prepped[i] for i in strat.input_groups[0]]
        tp_prep = [prepped[i] for i in strat.input_groups[1]]
        row_prep = [prepped[i] for i in strat.input_groups[2]]
        groups, assembly = ([], [])
        if tp_prep:
            groups, assembly = self._exchange_groups(tp_prep)
        ex_list, row_outs = self._staged_exchange
        if len(ex_list) != len(groups) or len(row_outs) != len(row_prep):
            raise ValueError(
                f"staged exchange artifacts do not match this batch's "
                f"plan: got {len(ex_list)} group blocks / {len(row_outs)} "
                f"row partials, expected {len(groups)} / {len(row_prep)}")
        # dp lookups run live (dense-trained tables must see CURRENT
        # params): replicated table + per-sample gather/combine — the
        # identical math the shard_map body's dp section runs per shard
        dp_outs = []
        for j, p in enumerate(dp_prep):
            t_dp = strat.map_groups[0][j]
            cfg = strat.dp_configs[t_dp]
            if self._dp_custom_layers.get(t_dp) is not None:
                raise NotImplementedError(
                    "staged_exchange_scope does not support custom "
                    "embedding layer classes on dp tables (their forward "
                    "is defined per-device under shard_map)")
            rows = self._cast(jnp.take(params["dp"][t_dp], p.ids, axis=0))
            dp_outs.append(_combine(rows, p.weights, cfg.get("combiner")))
        dp_final = []
        for j, out in enumerate(dp_outs):
            cfg = strat.dp_configs[strat.map_groups[0][j]]
            dp_final.append(self._restore_shape(out, dp_prep[j],
                                                cfg.get("combiner"),
                                                cfg["output_dim"]))
        tp_final = self._assemble_tp_outputs(ex_list, tp_prep, batch,
                                             groups, assembly)
        row_final = []
        for j, out in enumerate(row_outs):
            rt = self.plan.row_tables[strat.map_groups[2][j]]
            row_final.append(self._restore_shape(out, row_prep[j],
                                                 rt.combiner, rt.width))
        outputs = dp_final + tp_final + row_final
        return [outputs[idx] for idx in strat.rev_group_ids]

    def exchange_transpose(self, g_ex, g_row, key) -> dict:
        """Drain-stage gradient transpose (ISSUE 9): move the dense
        stage's activation cotangents dp->mp, producing the exact
        `make_taps`-shaped gradient pytree `sparse_update` consumes.

        In the monolithic step this movement happens inside autodiff (the
        custom-vjp backward of the forward exchange); in the lookahead
        pipeline the forward exchange ran one step earlier in a different
        traced region, so the transpose is invoked explicitly — via
        `ops.wire.wire_all_to_all_t` / `wire_psum_scatter_t`, the same
        bwd rules, which is what keeps lookahead=1 bit-exact.

        Args:
          g_ex: per exchange group, cotangent of the carried activation
            block [world_src, B, f_max_g, wf].
          g_row: per row-sliced input, cotangent of the carried partial
            [B, (k,) w].
          key: the carried TapResiduals.key (selects the group layout).

        Returns {"tp": [[world, B, f, w] ...], "row": [[world, B, ...]]}.
        """
        groups, _ = self._exchange_groups_for_key(key)
        if len(g_ex) != len(groups):
            raise ValueError(f"got {len(g_ex)} group cotangents, plan has "
                             f"{len(groups)} exchange groups")
        wires = [self.plan.tp_buckets[grp.bucket].wire_dtype
                 for grp in groups]
        row_wires = [self.plan.row_tables[t].wire_dtype
                     for t in self.strategy.map_groups[2]]
        world = self.world_size
        if world == 1:
            # forward: ex = out[None]; row partials pass through — the
            # transpose is a leading-axis relabel
            return {"tp": list(g_ex), "row": [g[None] for g in g_row]}

        def body(g_ex_l, g_row_l):
            tp_taps = []
            for g, ge in enumerate(g_ex_l):       # [world_src, B_l, f, w]
                h = wire_ops.wire_all_to_all_t(ge, self.axis, wires[g])
                tp_taps.append(h.reshape((h.shape[0] * h.shape[1],)
                                         + h.shape[2:])[None])
            row_taps = []
            for j, gr in enumerate(g_row_l):      # [B_l, (k,) w]
                h = wire_ops.wire_psum_scatter_t(gr, self.axis,
                                                 row_wires[j], world)
                row_taps.append(h[None])
            return tp_taps, row_taps

        tp_taps, row_taps = compat.shard_map(
            body, mesh=self.mesh,
            in_specs=([P(None, self.axis)] * len(g_ex),
                      [P(self.axis)] * len(g_row)),
            out_specs=([P(self.axis)] * len(g_ex),
                       [P(self.axis)] * len(g_row)),
            check_vma=False,
        )(list(g_ex), list(g_row))
        return {"tp": tp_taps, "row": row_taps}

    def patch_staged_carry(self, ex_list, row_outs, patch_ex, patch_row,
                           patch_idx, batch: int):
        """Overwrite the carried exchange artifacts for the patched
        samples (ISSUE 9): sample `patch_idx[i]` of the carry takes the
        freshly re-exchanged values at patch position i. Out-of-range
        indices (the padding convention: index == batch) drop.

        The scatter runs per shard (each device patches only the rows of
        its own batch slice) so the batch-sharded carry never regathers.
        """
        if self.world_size == 1:
            ex = [e.at[:, patch_idx].set(pe, mode="drop")
                  for e, pe in zip(ex_list, patch_ex)]
            row = [r.at[patch_idx].set(pr, mode="drop")
                   for r, pr in zip(row_outs, patch_row)]
            return ex, row
        blocal = batch // self.world_size

        def body(ex_l, row_l, pex_l, prow_l, idx):
            rank = lax.axis_index(self.axis)
            lidx = idx.astype(jnp.int32) - rank * jnp.int32(blocal)
            # foreign-shard and padding rows land on the OOB slot -> drop
            lidx = jnp.where((lidx >= 0) & (lidx < blocal), lidx,
                             jnp.int32(blocal))
            ex2 = [e.at[:, lidx].set(pe, mode="drop")
                   for e, pe in zip(ex_l, pex_l)]
            row2 = [r.at[lidx].set(pr, mode="drop")
                    for r, pr in zip(row_l, prow_l)]
            return ex2, row2

        return compat.shard_map(
            body, mesh=self.mesh,
            in_specs=([P(None, self.axis)] * len(ex_list),
                      [P(self.axis)] * len(row_outs),
                      # patch blocks replicate: every shard sees every
                      # patched sample and keeps only its own rows
                      [P()] * len(ex_list), [P()] * len(row_outs), P()),
            out_specs=([P(None, self.axis)] * len(ex_list),
                       [P(self.axis)] * len(row_outs)),
            check_vma=False,
        )(list(ex_list), list(row_outs), list(patch_ex), list(patch_row),
          patch_idx)

    def prefetch_stale_mask(self, inputs, touched) -> np.ndarray:
        """Host-side [B] bool mask: which samples of a PREFETCHED batch
        contain at least one id whose row the previous batch's sparse
        update touched (`touched` = that batch's `touched_row_keys`) —
        i.e. which prefetched activations are stale and must be patched
        against the post-update tables (ISSUE 9).

        Same key-space walk as `touched_row_keys`, kept per-sample:
        tp ids map to ``rank * rows_max + row_offset + id`` flat keys,
        row-sliced ids are global rows; out-of-range ids are
        sentinel-dropped by the update and never match. Dense/(ids,
        weights) input forms only (the engine refuses ragged/sparse
        inputs — their per-sample selection would be shape-dynamic)."""
        if len(inputs) != self._n_inputs:
            raise ValueError(
                f"Expected {self._n_inputs} inputs, got {len(inputs)}")

        def host_2d(x):
            if (isinstance(x, tuple) and len(x) == 2
                    and not isinstance(x, RaggedIds)):
                x = x[0]
            if isinstance(x, (RaggedIds, SparseIds)):
                raise NotImplementedError(
                    "prefetch_stale_mask supports dense id inputs only")
            a = np.asarray(jax.device_get(x)).astype(np.int64)
            return a.reshape(a.shape[0], -1)

        seg_rows = {(pl.bucket, pl.rank, pl.row_offset): pl.rows
                    for pl in self.plan.tp_placements}
        mask = None
        for pos, i in enumerate(self.strategy.input_groups[1]):
            ids = host_2d(inputs[i])
            if mask is None:
                mask = np.zeros(ids.shape[0], bool)
            for (rank, b, slot_idx) in self.plan.tp_input_slots[pos]:
                t = touched.get(("tp", b))
                if t is None or not len(t):
                    continue
                bucket = self.plan.tp_buckets[b]
                off = bucket.slots[rank][slot_idx].row_offset
                rows = seg_rows.get((b, rank, off), 0)
                valid = (ids >= 0) & (ids < rows)
                keys = rank * max(bucket.rows_max, 1) + off + ids
                mask |= (valid & np.isin(keys, t)).any(axis=1)
        for j, i in enumerate(self.strategy.input_groups[2]):
            t_id = self.strategy.map_groups[2][j]
            t = touched.get(("row", t_id))
            ids = host_2d(inputs[i])
            if mask is None:
                mask = np.zeros(ids.shape[0], bool)
            if t is None or not len(t):
                continue
            total = int(sum(self.plan.row_tables[t_id].rows_per_rank))
            valid = (ids >= 0) & (ids < total)
            mask |= (valid & np.isin(ids, t)).any(axis=1)
        if mask is None:
            # no mp inputs at all — nothing prefetched, nothing stale
            x = inputs[0]
            n = (np.asarray(x[0]).shape[0] if isinstance(x, tuple)
                 else np.asarray(x).shape[0])
            mask = np.zeros(n, bool)
        return mask

    def apply_mp(self, params: dict, inputs, taps=None,
                 return_residuals: bool = False, residual_sort=None):
        """Forward pass with model-parallel input (dp_input=False).

        The reference mp-input contract (:729-731, :846-851): each rank
        receives ids at *global* batch size for exactly the features it owns,
        in ``strategy.input_ids_list[rank]`` order, skipping the dp->mp
        exchange (the data loader already reads feature-sharded data, see
        models/data.py RawBinaryDataset).

        Args:
          params: pytree from `init`.
          inputs: nested per-rank lists — ``inputs[r][j]`` feeds the j-th
            local input of rank r (dense [B]/[B,k] ids, RaggedIds, SparseIds
            or (ids, weights)). With world_size == 1 a flat list is accepted.
            In multi-process runs, ``inputs[r]`` may be None for ranks whose
            devices this process cannot address (each process supplies only
            its own ranks' data); that mode requires `input_max_hotness` for
            every input so all processes trace identical shapes.

        Returns:
          One [B, width] array per input in original input order,
          batch-sharded over the mesh.
        """
        if self.dp_input:
            raise ValueError("This layer was built with dp_input=True; "
                             "use apply() instead")
        strat = self.strategy
        world = self.world_size
        if world == 1 and (not inputs or not isinstance(inputs[0], list)):
            inputs = [list(inputs)]
        if len(inputs) != world:
            raise ValueError(
                f"apply_mp expects {world} per-rank input lists, got {len(inputs)}")
        partial_ranks = any(x is None for x in inputs)
        if partial_ranks and (
                self.input_max_hotness is None
                or any(self.input_max_hotness[strat.input_groups[1][pos]]
                       is None
                       for pos in range(len(strat.input_groups[1])))):
            raise ValueError(
                "apply_mp with per-process inputs (None for remote ranks) "
                "requires input_max_hotness for every input: each process "
                "must trace the same static shapes")

        prepped: List[Optional[List[_PreparedInput]]] = []
        rank_pos: List[dict] = []   # per rank: tp input pos -> local index
        input_prep = {}             # tp input pos -> representative prep
        local_ranks = ({r for r, _ in self._rank_of_device()}
                       if self.mesh is not None else {0})
        for r in range(world):
            ids_list = strat.input_ids_list[r] if strat.input_ids_list else []
            if inputs[r] is None:
                if r in local_ranks:
                    raise ValueError(
                        f"rank {r} is addressable by this process; its "
                        "apply_mp inputs cannot be None")
                prepped.append(None)
                rank_pos.append({})
                continue
            if len(inputs[r]) != len(ids_list):
                raise ValueError(
                    f"rank {r}: expected {len(ids_list)} inputs "
                    f"(features {ids_list}), got {len(inputs[r])}")
            plist, pos = [], {}
            for j, (x, inp_pos) in enumerate(zip(inputs[r], ids_list)):
                orig = strat.input_groups[1][inp_pos]
                mh = (self.input_max_hotness[orig]
                      if self.input_max_hotness is not None else None)
                p = self._prepare_one(x, mh)
                if partial_ranks and p.k != mh:
                    raise ValueError(
                        f"rank {r} input {j}: hotness {p.k} != "
                        f"input_max_hotness {mh}; with per-process inputs "
                        "all ids must be padded to the declared max hotness")
                if partial_ranks and p.k == 1 and not p.orig_1d:
                    raise ValueError(
                        f"rank {r} input {j}: feed hotness-1 ids as 1-D [B] "
                        "arrays in per-process mode — every process must "
                        "agree on the restored output shape")
                if partial_ranks and p.weights is None:
                    # uniform weights-presence across processes keeps every
                    # process's exchange-group shapes identical
                    p = _PreparedInput(
                        p.ids, jnp.ones((p.ids.shape[0], p.k), jnp.float32),
                        p.orig_1d, p.k)
                plist.append(p)
                pos[inp_pos] = j
                input_prep.setdefault(inp_pos, p)
            prepped.append(plist)
            rank_pos.append(pos)
        if partial_ranks:
            # synthesize shape-only representatives for inputs that only
            # occur on remote ranks (content irrelevant: each device reads
            # its own shard)
            batches = [p.ids.shape[0] for p in input_prep.values()]
            if not batches:
                raise ValueError("no local rank inputs provided")
            b0 = batches[0]
            for inp_pos in range(len(strat.input_groups[1])):
                if inp_pos not in input_prep:
                    orig = strat.input_groups[1][inp_pos]
                    mh = self.input_max_hotness[orig]
                    # hotness-1 inputs are fed 1-D on their owning process
                    # (enforced above), so mirror orig_1d = (mh == 1) here to
                    # keep every process's restored shapes identical
                    input_prep[inp_pos] = _PreparedInput(
                        jnp.zeros((b0, mh), jnp.int32),
                        jnp.zeros((b0, mh), jnp.float32), mh == 1, mh)
        if not input_prep:
            return []
        batch = next(iter(input_prep.values())).ids.shape[0]
        if world > 1 and batch % world != 0:
            raise ValueError(
                f"Global batch {batch} not divisible by device count {world}")

        # mp input skips the dp->mp exchange entirely (the loader already
        # read feature-sharded data) — stack each rank's local features per
        # exchange group: ids [world, B, f_max_g, k_g] (+ weights). When
        # called eagerly with a mesh, each rank's block is staged directly on
        # that rank's device so only local shards materialize (not a
        # replicated [world, ...] host stack).
        tp_preps = [input_prep[i] for i in range(len(strat.input_groups[1]))]
        groups, assembly = self._exchange_groups(tp_preps)

        def rank_block(grp, r):
            """One rank's [B, f_max, k] ids (+ weights) for one group."""
            cols_i, cols_w = [], []
            for s in grp.rank_slots[r]:
                p = prepped[r][rank_pos[r][s.tp_input]]
                cols_i.append(p.ids.astype(jnp.int32))
                if grp.need_w:
                    cols_w.append(p.weights if p.weights is not None
                                  else jnp.ones((batch, p.k), jnp.float32))
            while len(cols_i) < grp.f_max:
                cols_i.append(jnp.zeros((batch, grp.k), jnp.int32))
                if grp.need_w:
                    cols_w.append(jnp.zeros((batch, grp.k), jnp.float32))
            ids_b = jnp.stack(cols_i, axis=1)               # [B, f, k]
            w_b = jnp.stack(cols_w, axis=1) if grp.need_w else None
            return ids_b, w_b

        def is_traced():
            for plist in prepped:
                for p in (plist or []):
                    if isinstance(p.ids, jax.core.Tracer):
                        return True
            return False

        group_ids, group_w = [], []
        if self.mesh is not None and not is_traced():
            id_shard = NamedSharding(self.mesh, P(self.axis))
            for grp in groups:
                i_shards, w_shards = [], []
                for r, dev in self._rank_of_device():
                    ids_b, w_b = rank_block(grp, r)
                    i_shards.append(jax.device_put(ids_b[None], dev))
                    if grp.need_w:
                        w_shards.append(jax.device_put(w_b[None], dev))
                gshape = (world,) + tuple(i_shards[0].shape[1:])
                group_ids.append(jax.make_array_from_single_device_arrays(
                    gshape, id_shard, i_shards))
                if grp.need_w:
                    wshape = (world,) + tuple(w_shards[0].shape[1:])
                    group_w.append(jax.make_array_from_single_device_arrays(
                        wshape, id_shard, w_shards))
                else:
                    group_w.append(None)
        else:
            if partial_ranks:
                raise ValueError(
                    "per-process (None) apply_mp inputs cannot be used under "
                    "jit/grad tracing; stage arrays eagerly first")
            for grp in groups:
                blocks = [rank_block(grp, r) for r in range(world)]
                group_ids.append(jnp.stack([b[0] for b in blocks]))
                group_w.append(jnp.stack([b[1] for b in blocks])
                               if grp.need_w else None)

        offloaded_groups = [
            g for g, grp in enumerate(groups)
            if self.plan.tp_buckets[grp.bucket].offload
            and self._offload_enabled]
        inner_taps = taps
        if taps is not None and offloaded_groups:
            inner_taps = {"tp": [None if g in offloaded_groups else t
                                 for g, t in enumerate(taps["tp"])],
                          "row": taps.get("row", [])}

        if residual_sort is None:
            sort_spec = self._residual_sort_spec
        else:
            sort_spec = None if residual_sort is False else residual_sort
        sort_plan = (self._sort_plan(groups, sort_spec) if return_residuals
                     else [None] * len(groups))

        def body(tp_params, group_ids, group_w, taps_l, tp_scales):
            ex_list, off_ids, off_w = [], [], []
            res_ids, res_w, res_sort = [], [], []
            for g, grp in enumerate(groups):
                ids_l = group_ids[g][0]                         # [B, f, k]
                offs = self._device_const(grp.offs)
                ids_l = ids_l + offs[None, :, None].astype(ids_l.dtype)
                w_l = group_w[g][0] if group_w[g] is not None else None
                bucket = self.plan.tp_buckets[grp.bucket]
                sort_g = None
                if return_residuals and sort_plan[g]:
                    sort_g = canonical_id_sort(
                        ids_l, max(bucket.rows_max, 1),
                        want_inv=(sort_plan[g] == "inv"))
                if g in offloaded_groups:
                    eff_w, _ = _effective_weights(w_l, grp.k, bucket.combiner)
                    off_ids.append(ids_l[None].astype(jnp.int32))
                    off_w.append(None if eff_w is None else eff_w[None])
                    ex_list.append(None)
                else:
                    off_ids.append(None)
                    off_w.append(None)
                    out = self._tp_group_out(
                        tp_params, grp, ids_l, w_l,
                        None if taps_l is None else taps_l["tp"][g],
                        presorted=sort_g,
                        scale_s=(None if tp_scales is None
                                 else tp_scales[grp.bucket]))
                    ex_list.append(self._tp_bucket_exchange(
                        out, bucket.wire_dtype))
                if return_residuals:
                    eff_w, _ = _effective_weights(w_l, grp.k, bucket.combiner)
                    res_ids.append(ids_l[None].astype(jnp.int32))
                    res_w.append(None if eff_w is None else eff_w[None])
                    res_sort.append(self._stack_sort(sort_g))
            res = ((res_ids, res_w, res_sort) if return_residuals
                   else None)
            return ex_list, off_ids, off_w, res

        dev_scales = self._device_bucket_scales(params)

        if world > 1:
            specs = lambda tree, spec: jax.tree.map(lambda _: spec, tree)
            out_specs = (
                [None if g in offloaded_groups else P(None, self.axis)
                 for g in range(len(groups))],
                [P(self.axis) if g in offloaded_groups else None
                 for g in range(len(groups))],
                [(P(self.axis) if (g in offloaded_groups
                                   and group_w[g] is not None) else None)
                 for g in range(len(groups))],
                (([P(self.axis)] * len(groups),
                  [None if g is None else P(self.axis) for g in group_w],
                  [None if p is None else P(self.axis) for p in sort_plan])
                 if return_residuals else None),
            )
            ex_list, off_ids, off_w, res = compat.shard_map(
                body, mesh=self.mesh,
                in_specs=(specs(params["tp"], P(self.axis)),
                          specs(group_ids, P(self.axis)),
                          specs(group_w, P(self.axis)),
                          specs(inner_taps, P(self.axis)),
                          specs(dev_scales, P(self.axis))),
                out_specs=out_specs,
                check_vma=False,
            )(params["tp"], group_ids, group_w, inner_taps, dev_scales)
        else:
            ex_list, off_ids, off_w, res = body(params["tp"], group_ids,
                                                group_w, inner_taps,
                                                dev_scales)

        for g in offloaded_groups:
            grp = groups[g]
            tap_g = taps["tp"][g] if taps is not None else None
            ex_list[g] = self._offload_group_out(
                g, grp, params["tp"][grp.bucket],
                self._bucket_scale(params, grp.bucket),
                off_ids[g], off_w[g], tap_g)

        outputs = self._assemble_tp_outputs(ex_list, tp_preps, batch,
                                            groups, assembly)
        outputs = [outputs[idx] for idx in strat.rev_group_ids]
        if return_residuals:
            key = tuple((p.k, p.weights is not None) for p in tp_preps)
            return outputs, TapResiduals(key, res[0], res[1], [], [],
                                         res[2], [])
        return outputs

    # ------------------------------------------------- sparse training path
    def make_taps(self, inputs) -> dict:
        """Zero perturbation pytree for `apply(..., taps=...)`: one
        [world, B, f_max_g, w_out] array per exchange group and one
        [world, B, (k,) w] array per row-sliced input. Create inside the
        jitted train step — XLA folds the zero adds away in the forward while
        autodiff still delivers their cotangents. Accepts dp-form flat inputs
        (dp_input=True) or the nested per-rank lists of apply_mp."""
        strat = self.strategy
        dtype = self.compute_dtype or jnp.float32
        taps = {"tp": [], "row": []}
        if self.dp_input:
            prepped = self._prepare_inputs(inputs)
            batch = prepped[0].ids.shape[0]
            tp_prep = [prepped[i] for i in strat.input_groups[1]]
        else:
            tp_prep, batch = self._mp_tp_preps(inputs)
            prepped = None
        if tp_prep:
            groups, _ = self._exchange_groups(tp_prep)
            for grp in groups:
                bucket = self.plan.tp_buckets[grp.bucket]
                w_out = (bucket.width if bucket.combiner is not None
                         else bucket.width * grp.k)
                taps["tp"].append(jnp.zeros(
                    (self.world_size, batch, grp.f_max, w_out), dtype))
            if self._hot_buckets and self.dp_input:
                # hot-shard taps (ISSUE 4): one per hot-split group, added
                # at the hit-contribution merge — their cotangents are the
                # per-(serving rank, sample, slot) output grads the
                # replicated hot update consumes
                taps["hot"] = [
                    (jnp.zeros((self.world_size, batch, grp.f_max,
                                self.plan.tp_buckets[grp.bucket].width),
                               dtype)
                     if self.plan.tp_buckets[grp.bucket].hot_rows > 0
                     else None)
                    for grp in groups]
        for pos, j in enumerate(strat.input_groups[2]):
            p = prepped[j]
            rt = self.plan.row_tables[strat.map_groups[2][pos]]
            shape = ((self.world_size, batch, rt.width)
                     if rt.combiner is not None
                     else (self.world_size, batch, p.k, rt.width))
            taps["row"].append(jnp.zeros(shape, dtype))
        return taps

    def _mp_tp_preps(self, inputs):
        """Representative _PreparedInputs per tp input from nested per-rank
        apply_mp inputs (None ranks allowed when input_max_hotness covers
        their inputs). Returns (tp_preps, global_batch)."""
        strat = self.strategy
        if self.world_size == 1 and (not inputs
                                     or not isinstance(inputs[0], list)):
            inputs = [list(inputs)]
        input_prep: dict = {}
        for r, ids_list in enumerate(strat.input_ids_list or []):
            if r >= len(inputs) or inputs[r] is None:
                continue
            for x, inp_pos in zip(inputs[r], ids_list):
                orig = strat.input_groups[1][inp_pos]
                mh = (self.input_max_hotness[orig]
                      if self.input_max_hotness is not None else None)
                input_prep.setdefault(inp_pos, self._prepare_one(x, mh))
        if not input_prep:
            return [], 0
        batch = next(iter(input_prep.values())).ids.shape[0]
        for pos in range(len(strat.input_groups[1])):
            if pos not in input_prep:
                orig = strat.input_groups[1][pos]
                if self.input_max_hotness is None or \
                        self.input_max_hotness[orig] is None:
                    raise ValueError(
                        "make_taps with per-process mp inputs requires "
                        "input_max_hotness for remote-rank features")
                mh = self.input_max_hotness[orig]
                input_prep[pos] = _PreparedInput(
                    jnp.zeros((batch, mh), jnp.int32),
                    jnp.zeros((batch, mh), jnp.float32), mh == 1, mh)
        return ([input_prep[i] for i in range(len(strat.input_groups[1]))],
                batch)

    def _state_spec(self, leaf):
        """Sharding spec rule for sparse-optimizer state leaves: table-shaped
        stacked arrays ([world, rows, w]) shard over the axis, scalars (adam
        step count) replicate."""
        return P(self.axis) if getattr(leaf, "ndim", 0) == 3 else P()

    def _group_contrib(self, g, grp, res_tp_ids, res_tp_w, tp_g,
                       stacked: bool) -> SparseRowGrad:
        """Build one exchange group's SparseRowGrad from residual ids /
        effective weights and the tap gradient. stacked=False squeezes the
        leading [1] device axis (shard_map body); True keeps the [world]
        axis (global host-offload path)."""
        bucket = self.plan.tp_buckets[grp.bucket]
        ids_x = res_tp_ids[g] if stacked else res_tp_ids[g][0]
        gtap = tp_g[g] if stacked else tp_g[g][0]
        k, wf = grp.k, bucket.width
        lead = gtap.shape[:-1]                        # [..., B, f]
        if bucket.combiner is None:
            gk = gtap.reshape(lead + (k, wf))
        else:
            gk = gtap[..., None, :]
        eff = res_tp_w[g]
        if eff is None:
            _, scale = _effective_weights(None, k, bucket.combiner)
            contrib = jnp.broadcast_to(gk.astype(jnp.float32) * scale,
                                       ids_x.shape + (wf,))
        else:
            eff = eff if stacked else eff[0]
            contrib = gk.astype(jnp.float32) * eff[..., None]
        if stacked:
            world = ids_x.shape[0]
            return SparseRowGrad(ids_x.reshape(world, -1),
                                 contrib.reshape(world, -1, wf))
        return SparseRowGrad(ids_x.reshape(-1), contrib.reshape(-1, wf))

    @staticmethod
    def _unstack_sort(s: Optional[GroupSort]) -> Optional[GroupSort]:
        """Strip the leading per-device axis of a residual GroupSort."""
        if s is None:
            return None
        return GroupSort(s.sid[0], s.perm[0], s.seg_start[0],
                         None if s.inv is None else s.inv[0])

    def _sparse_update_body(self, tp_params, row_params, tp_states,
                            row_states, tp_g, row_g, res_tp_ids, res_tp_w,
                            res_row_ids, res_row_w, res_tp_sort,
                            res_row_sort, hot_tabs, hot_states, hot_g,
                            res_hot_pos, res_hot_w, tp_scales, groups, opt,
                            dev_buckets):
        """Per-device sparse updates (stacked [1, rows, w] shards in/out).
        tp_params/tp_states hold only the non-offloaded buckets, in
        dev_buckets order. res_tp_sort / res_row_sort carry the forward's
        per-group sort artifacts (sort folding) — consumed only where a
        bucket's grad comes from a single group, so the folded update is
        bit-identical to the fresh-sort one.

        hot_tabs/hot_states (ISSUE 4): the replicated [H, w] hot shards in
        self._hot_buckets order; hot_g the hot-tap gradients and
        res_hot_pos/res_hot_w the forward's membership split. Hot grads
        aggregate into a dense [H, w] partial per device (H is small by
        construction), psum to the global gradient, then apply the SAME
        optimizer rule dense-masked (`sparse_update.apply_dense_rows`) on
        every device — replicated in, replicated out, no sort ops."""

        def split_state(state):
            return tuple(x[0] if getattr(x, "ndim", 0) == 3 else x
                         for x in state)

        def stack_state(state):
            return tuple(x[None] if getattr(x, "ndim", 0) == 2 else x
                         for x in state)

        bucket_groups: dict = {}
        for g, grp in enumerate(groups):
            bucket_groups.setdefault(grp.bucket, []).append(g)

        new_tp, new_tp_s = [], []
        new_tp_sc = []
        for pos, b in enumerate(dev_buckets):
            scale_s = None if tp_scales is None else tp_scales[pos]
            gs = bucket_groups.get(b, [])
            grads = [self._group_contrib(g, groups[g], res_tp_ids, res_tp_w,
                                         tp_g, stacked=False)
                     for g in gs]
            if not grads:
                new_tp.append(tp_params[pos])
                new_tp_s.append(tp_states[pos])
                new_tp_sc.append(scale_s)
                continue
            sort_b = (self._unstack_sort(res_tp_sort[gs[0]])
                      if len(gs) == 1 else None)
            # kwarg only when an artifact exists: pre-fold user-built
            # SparseOptimizers with 3-arg update callables keep working
            # whenever no fold is active
            kw = {} if sort_b is None else {"presorted": sort_b}
            if scale_s is not None:
                # master-weight-free quantized row update (ISSUE 17):
                # decode touched rows -> f32 math -> hash-SR re-encode,
                # no resident f32 mirror of the table
                hp = dict(opt.hp)
                if opt.kind == "adagrad" and "eps" in hp:
                    kw["eps"] = hp["eps"]
                p_new, s_new_sc, st_new = \
                    sparse_update_ops.quantized_row_update(
                        opt.kind, tp_params[pos][0], scale_s[0],
                        split_state(tp_states[pos]), concat_grads(grads),
                        self._bucket_store_dtype(b), opt.lr, **kw)
                new_tp.append(p_new[None])
                new_tp_sc.append(s_new_sc[None])
                new_tp_s.append(stack_state(st_new))
                continue
            t_new, s_new = opt.update(tp_params[pos][0],
                                      split_state(tp_states[pos]),
                                      concat_grads(grads), **kw)
            new_tp.append(t_new[None])
            new_tp_s.append(stack_state(s_new))
            new_tp_sc.append(None)

        # row-sliced tables: multiple inputs may share one table
        table_inputs: dict = {}
        for j in range(len(res_row_ids)):
            t = self.strategy.map_groups[2][j]
            table_inputs.setdefault(t, []).append(j)
        new_row = list(row_params)
        new_row_s = list(row_states)
        for t, js in table_inputs.items():
            rt = self.plan.row_tables[t]
            grads = []
            for j in js:
                ids = res_row_ids[j][0]                   # [B, k]
                w = res_row_w[j][0]                       # [B, k]
                gtap = row_g[j][0]                        # [B, w] | [B, k, w]
                gk = (gtap[:, None, :] if rt.combiner is not None else gtap)
                contrib = gk.astype(jnp.float32) * w[..., None]
                grads.append(SparseRowGrad(
                    ids.reshape(-1), contrib.reshape(-1, rt.width)))
            sort_t = (self._unstack_sort(res_row_sort[js[0]])
                      if len(js) == 1 else None)
            kw = {} if sort_t is None else {"presorted": sort_t}
            t_new, s_new = opt.update(row_params[t][0],
                                      split_state(row_states[t]),
                                      concat_grads(grads), **kw)
            new_row[t] = t_new[None]
            new_row_s[t] = stack_state(s_new)

        # hot shards: dense local aggregate -> psum -> replicated apply
        new_hot_t, new_hot_s = [], []
        hp = dict(opt.hp)
        hot_kw = {k: hp[k] for k in ("eps", "b1", "b2") if k in hp}
        for pos_h, b in enumerate(self._hot_buckets):
            bucket = self.plan.tp_buckets[b]
            h_cap, wf = bucket.hot_rows, bucket.width
            gs = [g for g, grp in enumerate(groups)
                  if grp.bucket == b and res_hot_pos[g] is not None
                  and hot_g[g] is not None]
            if not gs:
                new_hot_t.append(hot_tabs[pos_h])
                new_hot_s.append(hot_states[pos_h])
                continue
            ids_l, con_l = [], []
            for g in gs:
                pos = res_hot_pos[g][0]            # [world, B_l, f, k]
                wv = res_hot_w[g][0]
                gh = hot_g[g]                      # [world, B_l, f, wf]
                contrib = gh[..., None, :].astype(jnp.float32) \
                    * wv[..., None]
                ids_l.append(pos.reshape(-1))
                con_l.append(contrib.reshape(-1, wf))
            g_dense, counts = sparse_update_ops._dense_sum(
                jnp.concatenate(ids_l), jnp.concatenate(con_l), h_cap)
            if self.world_size > 1:
                g_dense = lax.psum(g_dense, self.axis)
                counts = lax.psum(counts, self.axis)
            t_new, s_new = sparse_update_ops.apply_dense_rows(
                opt.kind, hot_tabs[pos_h], hot_states[pos_h], g_dense,
                counts > 0, opt.lr, **hot_kw)
            new_hot_t.append(t_new)
            new_hot_s.append(tuple(s_new))
        return (new_tp, new_row, new_tp_s, new_row_s, new_hot_t, new_hot_s,
                new_tp_sc if tp_scales is not None else None)

    def init_sparse_state(self, params: dict, opt: SparseOptimizer) -> dict:
        """Sparse-optimizer state for the tp/row tables (dp tables train
        dense). Table-shaped state leaves (adagrad accumulator, adam moments)
        are created directly with the tables' shardings — never materialized
        unsharded (the init-OOM concern behind the reference's CPU-side init,
        embedding.py:28-47)."""
        def init_host(stack):
            # constant-fill leaves staged shard-wise straight into pinned
            # host memory via numpy (XLA cannot emit host-placed outputs on
            # every backend, and a device-side init would need HBM the
            # offloaded bucket was too big for in the first place)
            # f32 probe regardless of the stack's storage dtype: the
            # optimizer state of a quantized (int8/fp8) bucket is f32 —
            # only the TABLE is stored compressed (ISSUE 15)
            tiny = opt.init(jnp.zeros((1, stack.shape[-1]), jnp.float32))
            out = []
            for x in tiny:
                if getattr(x, "ndim", 0) == 2:
                    fill = float(np.asarray(x)[0, 0])
                    if self.mesh is None:
                        host = jax.sharding.SingleDeviceSharding(
                            jax.devices()[0], memory_kind=self._host_kind)
                        out.append(jax.device_put(
                            np.full(stack.shape, fill, np.float32), host))
                    else:
                        out.append(self._stack_sharded(
                            lambda rank: np.full(stack.shape[1:], fill,
                                                 np.float32),
                            memory_kind=self._host_kind))
                else:
                    out.append(x)
            return tuple(out)

        def init_one(stack, memory_kind=None):
            if memory_kind:
                return init_host(stack)
            if self.mesh is None:
                return opt.init(stack)
            shard = NamedSharding(self.mesh, P(self.axis))
            rep = NamedSharding(self.mesh, P())
            probe = jax.eval_shape(opt.init, stack)
            out_sh = tuple(shard if x.ndim == 3 else rep for x in probe)
            return jax.jit(opt.init, out_shardings=out_sh)(stack)
        out = {"tp": [init_one(t, self._bucket_memory_kind(b))
                      for b, t in enumerate(params["tp"])],
               "row": [init_one(t) for t in params["row"]]}
        if self._hot_buckets and "hot" in params:
            # replicated optimizer state over the replicated hot shards
            # (ISSUE 4): every device applies the identical (psummed)
            # dense update, so the state never shards
            def init_hot(entry):
                st = opt.init(entry["rows"])
                if self.mesh is not None:
                    rep = NamedSharding(self.mesh, P())
                    st = tuple(jax.device_put(x, rep) for x in st)
                return st
            out["hot"] = [init_hot(params["hot"][b])
                          for b in self._hot_buckets]
        return out

    def sparse_update(self, params: dict, opt_states: dict, tap_grads: dict,
                      residuals: "TapResiduals", opt: SparseOptimizer):
        """Row-wise sparse optimizer step for tp/row tables.

        Args:
          params: full param pytree (dp untouched, returned as-is).
          opt_states: from `init_sparse_state`.
          tap_grads: gradient w.r.t. the `make_taps` pytree.
          residuals: TapResiduals from `apply(..., return_residuals=True)`.
          opt: a SparseOptimizer (make_sparse_optimizer).

        Returns (new_params, new_opt_states). The O(touched rows) analogue
        of the reference backward + IndexedSlices apply
        (embedding_lookup_kernels.cu:603-775): no [V, w] dense gradient, no
        full-table optimizer pass.
        """
        n_buckets = len(self.plan.tp_buckets)
        off_buckets = [b for b in range(n_buckets)
                       if self._bucket_memory_kind(b)]
        dev_buckets = [b for b in range(n_buckets) if b not in off_buckets]
        if off_buckets and opt.kind not in sparse_update_ops.HOST_SPARSE_APPLY:
            raise NotImplementedError(
                f"sparse optimizer {opt.kind!r} has no host-memory apply "
                "rule for offloaded buckets (available: "
                f"{sorted(sparse_update_ops.HOST_SPARSE_APPLY)})")
        q_dev = [b for b in dev_buckets
                 if self._bucket_store_dtype(b) != "f32"]
        if q_dev and opt.kind not in sparse_update_ops.QUANTIZED_ROW_KINDS:
            raise NotImplementedError(
                f"sparse optimizer {opt.kind!r} has no master-weight-free "
                f"quantized row-update rule (HBM-quantized buckets "
                f"{q_dev}; available: "
                f"{sorted(sparse_update_ops.QUANTIZED_ROW_KINDS)}). adam's "
                "moment-normalized steps fall below the per-row "
                "quantization grid during bias correction and are "
                "systematically lost even under stochastic rounding; its "
                "f32 moments also dwarf the table saving. Keep such "
                "buckets at storage_dtype='f32', or offload them "
                "(host apply keeps f32 math end-to-end).")
        groups, _ = self._exchange_groups_for_key(residuals.key)
        tp_dev_sc = ([self._bucket_scale(params, b)
                      if self._bucket_store_dtype(b) != "f32" else None
                      for b in dev_buckets] if q_dev else None)
        tp_dev = [params["tp"][b] for b in dev_buckets]
        tp_dev_s = [opt_states["tp"][b] for b in dev_buckets]
        # sort-folding artifacts (absent on pre-fold / residual_sort-off
        # residual pytrees: normalize to per-entry None)
        tp_sort = residuals.tp_sort or [None] * len(residuals.tp_ids)
        row_sort = residuals.row_sort or [None] * len(residuals.row_ids)
        # hot-shard inputs (ISSUE 4): replicated [H, w] tables/state in
        # self._hot_buckets order; residual membership split + hot-tap
        # grads per group (None everywhere on hot-less layers/residuals)
        n_groups = len(residuals.tp_ids)
        hot_on = bool(self._hot_buckets and "hot" in params
                      and residuals.hot_pos is not None)
        hot_tabs = ([params["hot"][b]["rows"] for b in self._hot_buckets]
                    if hot_on else [])
        hot_states = list(opt_states.get("hot", [])) if hot_on else []
        hot_g = (list(tap_grads.get("hot") or [None] * n_groups)
                 if hot_on else [None] * n_groups)
        res_hot_pos = (residuals.hot_pos if hot_on else [None] * n_groups)
        res_hot_w = (residuals.hot_w if hot_on else [None] * n_groups)

        args = (tp_dev, params["row"], tp_dev_s,
                opt_states["row"], tap_grads["tp"], tap_grads["row"],
                residuals.tp_ids, residuals.tp_w, residuals.row_ids,
                residuals.row_w, tp_sort, row_sort,
                hot_tabs, hot_states, hot_g, res_hot_pos, res_hot_w,
                tp_dev_sc)
        if self.world_size > 1:
            sspec = lambda tree: jax.tree.map(self._state_spec, tree)
            pspec = lambda tree, s: jax.tree.map(lambda _: s, tree)
            in_specs = (pspec(tp_dev, P(self.axis)),
                        pspec(params["row"], P(self.axis)),
                        sspec(tp_dev_s), sspec(opt_states["row"]),
                        pspec(tap_grads["tp"], P(self.axis)),
                        pspec(tap_grads["row"], P(self.axis)),
                        pspec(residuals.tp_ids, P(self.axis)),
                        pspec(residuals.tp_w, P(self.axis)),
                        pspec(residuals.row_ids, P(self.axis)),
                        pspec(residuals.row_w, P(self.axis)),
                        pspec(tp_sort, P(self.axis)),
                        pspec(row_sort, P(self.axis)),
                        pspec(hot_tabs, P()),
                        sspec(hot_states),
                        [None if g is None else P(None, self.axis)
                         for g in hot_g],
                        pspec(res_hot_pos, P(self.axis)),
                        pspec(res_hot_w, P(self.axis)),
                        pspec(tp_dev_sc, P(self.axis)))
            out_specs = (pspec(tp_dev, P(self.axis)),
                         pspec(params["row"], P(self.axis)),
                         sspec(tp_dev_s), sspec(opt_states["row"]),
                         pspec(hot_tabs, P()), sspec(hot_states),
                         pspec(tp_dev_sc, P(self.axis)))
            (new_tp_dev, new_row, new_tp_dev_s, new_row_s, new_hot_t,
             new_hot_s, new_tp_sc) = compat.shard_map(
                lambda *a: self._sparse_update_body(*a, groups, opt,
                                                    dev_buckets),
                mesh=self.mesh, in_specs=in_specs, out_specs=out_specs,
                check_vma=False)(*args)
        else:
            (new_tp_dev, new_row, new_tp_dev_s, new_row_s, new_hot_t,
             new_hot_s, new_tp_sc) = (
                self._sparse_update_body(*args, groups, opt, dev_buckets))

        new_tp = list(params["tp"])
        new_tp_s = list(opt_states["tp"])
        for pos, b in enumerate(dev_buckets):
            new_tp[b] = new_tp_dev[pos]
            new_tp_s[b] = new_tp_dev_s[pos]
        # offloaded buckets: dedup to (rep, sums) here (device-side, inside
        # the caller's jit); the host-memory apply happens OUTSIDE the step
        # jit (host_bucket_apply) — XLA only honors host placement of
        # outputs at top level, and host params must stay read-only inside
        # the SPMD program
        pending = {b: self._host_bucket_pending(b, groups, tap_grads["tp"],
                                                residuals)
                   for b in off_buckets}
        new_params = {"dp": params["dp"], "tp": new_tp, "row": new_row}
        if "tp_scale" in params:
            # offloaded-bucket scales are read-only inside the jitted
            # step (the out-of-jit host apply refreshes them);
            # HBM-resident quantized buckets re-derive theirs in the
            # master-weight-free row update above (ISSUE 17)
            new_scales = list(params["tp_scale"])
            if new_tp_sc is not None:
                for pos, b in enumerate(dev_buckets):
                    if new_tp_sc[pos] is not None:
                        new_scales[b] = new_tp_sc[pos]
            new_params["tp_scale"] = new_scales
        new_states = {"tp": new_tp_s, "row": new_row_s}
        if "hot" in params:
            new_hot = list(params["hot"])
            if hot_on:
                for pos_h, b in enumerate(self._hot_buckets):
                    new_hot[b] = {"ids": params["hot"][b]["ids"],
                                  "rows": new_hot_t[pos_h]}
                new_states["hot"] = list(new_hot_s)
            elif "hot" in opt_states:
                new_states["hot"] = opt_states["hot"]
            new_params["hot"] = new_hot
        return new_params, new_states, pending

    def _host_bucket_pending(self, b, groups, tp_g, residuals):
        """Deduped (rep, sums) update rows for one offloaded bucket,
        computed on device: [world, N] / [world, N, w] arrays sharded over
        the mesh axis (vmap over the world axis keeps each shard's sort
        local — no cross-device traffic)."""
        bucket = self.plan.tp_buckets[b]
        rows = max(bucket.rows_max, 1)
        gs = [g for g, grp in enumerate(groups) if grp.bucket == b]
        grad = concat_grads([
            self._group_contrib(g, groups[g], residuals.tp_ids,
                                residuals.tp_w, tp_g, stacked=True)
            for g in gs])
        return jax.vmap(
            lambda i, c: sparse_update_ops.prepare_safe_grad(i, c, rows))(
                grad.ids, grad.contribs)

    def host_bucket_apply(self, b, table_h, state_h, rep, sums, valid,
                          opt: SparseOptimizer, lr_value=None,
                          scale_h=None):
        """Storage-dtype dispatch over `_host_bucket_apply_f32` (ISSUE
        15). f32 buckets pass straight through (bit-exact, the
        early-return contract). Quantized buckets update
        TOUCHED-ROWS-ONLY (ISSUE 17): per local shard, decode exactly
        the rows the pending delta names into a compact f32 block, run
        the stock host row kernels on it, and hash-SR re-encode those
        rows back in place — O(touched rows) bytes moved per apply, vs
        the v1 whole-bucket f32 round-trip (kept behind
        DET_HOST_APPLY=roundtrip for hardware A/B; it transits device
        memory and needs the decoded f32 bucket to fit there).
        Keyless hash-SR on the write-back centers the rounding error
        on zero across a step's many updated values instead of
        accumulating RNE bias. Returns (table, state) at f32 and
        (payload, scale, state) when `scale_h` is given."""
        sd = self._bucket_store_dtype(b)
        if sd == "f32":
            if scale_h is not None:
                raise ValueError(
                    f"bucket {b} stores f32 rows but a scale leaf was "
                    "passed — params['tp_scale'] drifted from the plan")
            return self._host_bucket_apply_f32(
                b, table_h, state_h, rep, sums, valid, opt,
                lr_value=lr_value)
        if scale_h is None:
            raise ValueError(
                f"bucket {b} stores {sd} rows: host_bucket_apply needs "
                "the params['tp_scale'] leaf alongside the payload")
        if os.environ.get("DET_HOST_APPLY") == "roundtrip":
            ckey = ("store_codec", b, sd)
            codec = self._store_codec_cache.get(ckey)
            if codec is None:
                codec = (jax.jit(functools.partial(wire_ops.decode_rows,
                                                   store_dtype=sd)),
                         jax.jit(functools.partial(wire_ops.encode_rows,
                                                   store_dtype=sd,
                                                   sr=True)))
                self._store_codec_cache[ckey] = codec
            decode, encode_sr = codec
            back = table_h.sharding
            self._host_fn_cache[("host_apply_mode", b, opt.kind)] = \
                "roundtrip"
            table_f = jax.device_put(decode(table_h, scale_h), back)
            new_f, new_state = self._host_bucket_apply_f32(
                b, table_f, state_h, rep, sums, valid, opt,
                lr_value=lr_value)
            payload, scale = encode_sr(new_f)
            return (jax.device_put(payload, back),
                    jax.device_put(scale, back), new_state)
        return self._host_quantized_touched_apply(
            b, sd, table_h, scale_h, state_h, rep, sums, valid, opt,
            lr_value=lr_value)

    def _host_quantized_touched_apply(self, b, sd, table_h, scale_h,
                                      state_h, rep, sums, valid,
                                      opt: SparseOptimizer, lr_value=None):
        """Touched-rows-only quantized host apply (ISSUE 17): the
        `_host_pershard_apply` walk specialized to (payload, scale)
        buckets. Per local shard and world slice, fetch the deduped
        update rows off device (the native wire volume), decode ONLY
        those rows to a compact f32 block, apply them with the
        C++/numpy row kernels against the f32 optimizer state, then
        hash-SR re-encode the block back into the payload/scale
        buffers in place. Bytes moved per apply are
        O(touched rows x delta_row_bytes), independent of bucket
        size; `store/quantized_rows_applied_total` (default registry)
        and the layer's raw totals record the volume."""
        apply_fn = sparse_update_ops.HOST_SPARSE_APPLY[opt.kind]
        hp = dict(opt.hp)
        kw = {k: hp[k] for k in ("eps", "b1", "b2")
              if k in hp and opt.kind in ("adagrad", "adam")}
        lr = float(jax.device_get(opt.lr if lr_value is None
                                  else lr_value))
        self._host_fn_cache[("host_apply_mode", b, opt.kind)] = "pershard"

        def by_device(x):
            return {s.device: s.data for s in x.addressable_shards}

        p_shards = list(table_h.addressable_shards)
        sc_d = by_device(scale_h)
        rep_d, sums_d, valid_d = by_device(rep), by_device(sums), \
            by_device(valid)
        arr_state = [x for x in state_h if getattr(x, "ndim", 0) >= 1]
        state_d = [by_device(x) for x in arr_state]
        scalar_after = {
            i: jax.device_get(x) + (1 if opt.kind == "adam" else 0)
            for i, x in enumerate(state_h)
            if getattr(x, "ndim", 0) == 0}

        rows_applied = 0
        new_p, new_sc, new_s = [], [], [[] for _ in arr_state]
        for sh in p_shards:
            dev = sh.device
            p_np = np.array(sh.data)            # host->host copy, mutable
            sc_np = np.array(sc_d[dev])
            s_nps = [np.array(sd_[dev]) for sd_ in state_d]
            rep_np = np.asarray(rep_d[dev])     # rows only cross the wire
            sums_np = np.asarray(sums_d[dev])
            valid_np = np.asarray(valid_d[dev])
            nw = p_np.shape[0]
            drift = [(name, a.shape) for name, a in
                     (("scale", sc_np), ("rep", rep_np), ("sums", sums_np),
                      ("valid", valid_np),
                      *((f"state[{i}]", s) for i, s in enumerate(s_nps)))
                     if a.shape[0] != nw]
            if drift:
                raise RuntimeError(
                    f"quantized per-shard apply: device {dev} holds "
                    f"{nw} world slice(s) of the payload but the update "
                    f"arrays have mismatched leading dims {drift} — "
                    "sharding layout drifted between the step jit's "
                    "pending outputs and the pinned-host bucket")
            for j in range(nw):                 # world slices on this shard
                ok = valid_np[j] > 0
                ru = rep_np[j][ok]
                m = int(ru.shape[0])
                if m == 0:
                    continue
                # compact f32 block of exactly the touched rows
                sub = np.ascontiguousarray(wire_ops.decode_rows_np(
                    p_np[j][ru], sc_np[j][ru], sd))
                st_subs = [np.ascontiguousarray(s[j][ru]) for s in s_nps]
                if opt.kind == "adam":
                    st = (st_subs[0], st_subs[1],
                          next(iter(scalar_after.values())))
                else:
                    st = tuple(st_subs)
                sparse_update_ops.host_apply_rows_inplace(
                    opt.kind, sub, st,
                    np.arange(m, dtype=rep_np.dtype),
                    np.ascontiguousarray(sums_np[j][ok]),
                    np.ones(m, dtype=valid_np.dtype), lr, **kw)
                for s, st_sub in zip(s_nps, st_subs):
                    s[j][ru] = st_sub           # fancy-index wrote a copy
                pay, scl = wire_ops.encode_rows_np(sub, sd, sr=True)
                p_np[j][ru] = pay
                sc_np[j][ru] = scl
                rows_applied += m
            new_p.append(jax.device_put(p_np, sh.data.sharding))
            new_sc.append(jax.device_put(sc_np, sc_d[dev].sharding))
            for i, s_np in enumerate(s_nps):
                new_s[i].append(
                    jax.device_put(s_np, state_d[i][dev].sharding))

        self.quantized_rows_applied_total += rows_applied
        self.quantized_apply_bytes_total += rows_applied * \
            wire_ops.delta_row_bytes(table_h.shape[-1], sd)
        from distributed_embeddings_tpu.obs.registry import default_registry
        default_registry().counter(
            "store/quantized_rows_applied_total").inc(rows_applied)

        def assemble(global_ref, shards):
            return jax.make_array_from_single_device_arrays(
                global_ref.shape, global_ref.sharding, shards)

        out_state, ai = [], 0
        for i, x in enumerate(state_h):
            if getattr(x, "ndim", 0) >= 1:
                out_state.append(assemble(x, new_s[ai]))
                ai += 1
            else:
                out_state.append(jax.device_put(
                    jnp.asarray(scalar_after[i], dtype=x.dtype),
                    x.sharding))
        return (assemble(table_h, new_p), assemble(scale_h, new_sc),
                tuple(out_state))

    def _host_bucket_apply_f32(self, b, table_h, state_h, rep, sums, valid,
                               opt: SparseOptimizer, lr_value=None):
        """Apply deduped rows to an offloaded bucket's host-resident table.

        Three implementations, best-available (force with DET_HOST_APPLY=
        native|pershard|roundtrip):

        * 'native' — a top-level jit whose outputs are pinned host memory,
          with the row scatter in a compute_on host region (zero full-table
          traffic, overlappable with device work). Preferred where the
          backend partitions host placements.
        * 'pershard' — XLA-free: per local shard, fetch ONLY the deduped
          update rows off-device (the native wire volume) and apply them to
          the pinned-host table/state buffers with the C++/numpy kernels
          (ops/sparse_update.host_apply_rows_inplace, native/host_apply.cpp).
          Sidesteps the SPMD partitioner entirely — there is no XLA program
          to partition — so it works at any world size on any backend.
          This is the reference's design point: host tables update with host
          ops (reference dist_model_parallel.py:829-831, :971-1017).
        * 'roundtrip' — pull the bucket shard to device, update, place back;
          a full-bucket transfer per step. Kept only as the last resort for
          non-f32 offloaded tables (the host kernels are f32) and for
          hardware A/B (tools/tpu_offload_probe.py).
        """
        apply_fn = sparse_update_ops.HOST_SPARSE_APPLY[opt.kind]
        hp = dict(opt.hp)
        kw = {k: hp[k] for k in ("eps", "b1", "b2")
              if k in hp and opt.kind in ("adagrad", "adam")}
        if self.mesh is not None:
            host_sh = NamedSharding(self.mesh, P(self.axis),
                                    memory_kind=self._host_kind)
            dev_sh = NamedSharding(self.mesh, P(self.axis))
        else:
            dev0 = jax.devices()[0]
            host_sh = jax.sharding.SingleDeviceSharding(
                dev0, memory_kind=self._host_kind)
            dev_sh = jax.sharding.SingleDeviceSharding(dev0)
        # per-world-shard state leaves map over axis 0; global scalars
        # (adam's step count) are shared across shards and stay unmapped
        state_axes = jax.tree.map(
            lambda x: 0 if getattr(x, "ndim", 0) >= 1 else None, state_h)
        vapply = jax.vmap(
            lambda t, s, r, sm, v, l: apply_fn(t, s, r, sm, v, l, **kw),
            in_axes=(0, state_axes, 0, 0, 0, None),
            out_axes=(0, state_axes))
        lr_in = opt.lr if lr_value is None else lr_value

        key = ("host_apply", b, opt.kind, rep.shape, sums.shape,
               lr_value is None)
        mode_key = ("host_apply_mode", b, opt.kind)
        fn = self._host_fn_cache.get(key)
        if fn is None:
            from jax.experimental import compute_on

            def run_native(table_h, state_h, rep, sums, valid, lr_a):
                rep_h = jax.device_put(rep, host_sh)
                sums_h = jax.device_put(sums, host_sh)
                valid_h = jax.device_put(valid, host_sh)
                with compute_on.compute_on("device_host"):
                    return vapply(table_h, state_h, rep_h, sums_h, valid_h,
                                  lr_a)

            if self.mesh is not None:
                scalar_sh = NamedSharding(self.mesh, P())
            else:
                scalar_sh = jax.sharding.SingleDeviceSharding(
                    jax.devices()[0])
            out_sh = jax.tree.map(
                lambda x: host_sh if getattr(x, "ndim", 0) >= 1
                else scalar_sh, (table_h, state_h))
            native = jax.jit(run_native, out_shardings=out_sh)
            roundtrip_core = jax.jit(vapply)

            def run_roundtrip(table_h, state_h, rep, sums, valid, lr_a):
                t_dev = jax.device_put(table_h, dev_sh)
                s_dev = jax.tree.map(
                    lambda x: jax.device_put(
                        x, dev_sh if x.ndim >= 1 else scalar_sh), state_h)
                new_t, new_s = roundtrip_core(t_dev, s_dev, rep, sums,
                                              valid, lr_a)
                return (jax.device_put(new_t, host_sh),
                        jax.tree.map(
                            lambda x: jax.device_put(
                                x, host_sh if x.ndim >= 1 else scalar_sh),
                            new_s))

            f32_ok = (table_h.dtype == jnp.float32 and all(
                x.dtype == jnp.float32
                for x in jax.tree.leaves(state_h)
                if getattr(x, "ndim", 0) >= 1))

            def run_pershard(table_h, state_h, rep, sums, valid, lr_a):
                return self._host_pershard_apply(
                    opt.kind, kw, table_h, state_h, rep, sums, valid, lr_a)

            forced = os.environ.get("DET_HOST_APPLY", "auto")
            if forced == "pershard" and not f32_ok:
                # the forced knob must not reach the f32-only host kernels
                # with a non-f32 bucket (heap corruption, not an error)
                import warnings
                warnings.warn(
                    f"DET_HOST_APPLY=pershard ignored for offloaded bucket "
                    f"{b}: the host kernels are float32-only and this "
                    "bucket is not; using the device round-trip",
                    RuntimeWarning, stacklevel=2)
                forced = "roundtrip"
            mode = (forced if forced in ("native", "pershard", "roundtrip")
                    else self._host_fn_cache.get(mode_key))
            if mode in ("native", "pershard", "roundtrip"):
                # forced modes must be visible to host_apply_modes() too
                self._host_fn_cache[mode_key] = mode
            if mode == "roundtrip":
                fn = run_roundtrip
            elif mode == "native":
                fn = native
            elif mode == "pershard":
                fn = run_pershard
            else:
                fallback = run_pershard if f32_ok else run_roundtrip
                fb_mode = ("pershard" if fallback is run_pershard
                           else "roundtrip")
                # the native-mode verdict is a property of (backend,
                # world_size), not of this layer/bucket/optimizer: consult
                # the process-wide cache before compiling the probe again
                # (VERDICT r5 weak #3 — re-probing spewed one XLA RET_CHECK
                # stack trace per offloaded init)
                vkey = (jax.default_backend(), self.world_size)
                verdict = _HOST_NATIVE_VERDICT.get(vkey)
                if verdict is True:
                    self._host_fn_cache[mode_key] = "native"
                    fn = native
                elif verdict is False:
                    if fb_mode == "roundtrip":
                        # the cached verdict must not silence the per-step
                        # perf-cliff signal the probe path emits
                        import warnings
                        warnings.warn(
                            "host-memory sparse apply unsupported on this "
                            "backend (cached verdict) and the bucket is "
                            "not f32; falling back to a device round-trip "
                            f"per step for offloaded bucket {b}",
                            RuntimeWarning, stacklevel=2)
                    self._host_fn_cache[mode_key] = fb_mode
                    fn = fallback

                def probe(table_h, state_h, rep, sums, valid, lr_a):
                    err, cap = None, {}
                    # fd-level capture: the partitioner RET_CHECK is
                    # LOG(ERROR)'d from C++ before the Python exception
                    # exists, so sys.stderr redirection cannot catch it
                    with _capture_fd2(cap):
                        try:
                            out = native(table_h, state_h, rep, sums,
                                         valid, lr_a)
                        except jax.errors.JaxRuntimeError as e:
                            err = e
                    if err is None:
                        _HOST_NATIVE_VERDICT[vkey] = True
                        if cap.get("data"):
                            os.write(2, cap["data"])   # replay non-error spew
                        self._host_fn_cache[mode_key] = "native"
                        self._host_fn_cache[key] = native
                        return out
                    # only the known backend gaps fall back: SPMD
                    # partitioners that cannot place host-memory outputs
                    # (two phrasings depending on whether the offender is
                    # an array or a scalar placement annotation) and
                    # backends with no host-placement custom-call at all
                    # (XLA:CPU single-device). Anything else replays the
                    # captured spew and re-raises — never hide an
                    # unexpected failure.
                    if ("cannot be replicated" not in str(err)
                            and "Side-effect HLO must have sharding"
                            not in str(err)
                            and "annotate_device_placement" not in
                            str(err)):
                        if cap.get("data"):
                            os.write(2, cap["data"])
                        raise err
                    _HOST_NATIVE_VERDICT[vkey] = False
                    first_line = str(err).splitlines()[0][:160]
                    if fallback is run_roundtrip:
                        import warnings
                        warnings.warn(
                            "host-memory sparse apply unsupported on "
                            "this backend and the bucket is not f32; "
                            "falling back to a device round-trip per "
                            f"step for offloaded bucket {b}",
                            RuntimeWarning, stacklevel=2)
                        self._host_fn_cache[mode_key] = "roundtrip"
                    else:
                        logging.getLogger(__name__).info(
                            "offloaded bucket %d: backend cannot partition "
                            "host-placement outputs (%s); using the "
                            "XLA-free per-shard host apply (row-only wire "
                            "traffic). Probe spew suppressed; verdict "
                            "cached for %s.", b, first_line, vkey)
                        self._host_fn_cache[mode_key] = "pershard"
                    self._host_fn_cache[key] = fallback
                    return fallback(table_h, state_h, rep, sums,
                                    valid, lr_a)
                if verdict is None:
                    fn = probe
            self._host_fn_cache.setdefault(key, fn)
        return fn(table_h, state_h, rep, sums, valid,
                  jnp.asarray(lr_in, jnp.float32))

    def host_apply_modes(self) -> dict:
        """{(bucket, optimizer_kind): 'native'|'pershard'|'roundtrip'} for
        every offloaded apply that has run (or been env-forced) in this
        process — keyed per BUCKET so a round-trip fallback on one bucket is
        never masked by another bucket's mode."""
        return {(k[1], k[2]): v for k, v in self._host_fn_cache.items()
                if isinstance(k, tuple) and k[0] == "host_apply_mode"}

    def _host_pershard_apply(self, kind, kw, table_h, state_h, rep, sums,
                             valid, lr_a):
        """XLA-free offloaded apply: for each LOCAL shard of the stacked
        pinned-host bucket, fetch that shard's deduped update rows from
        device (rows only — the bucket itself never crosses the wire),
        update the host buffers in place with the C++/numpy row kernels,
        and reassemble the global arrays shard-by-shard. Works at any world
        size on any backend because no XLA program ever sees the host
        placement. Scalar state leaves (adam's step count) increment here,
        mirroring host_sparse_adam's `count + 1`."""
        lr = float(jax.device_get(lr_a))

        def by_device(x):
            return {s.device: s.data for s in x.addressable_shards}

        t_shards = list(table_h.addressable_shards)
        rep_d, sums_d, valid_d = by_device(rep), by_device(sums), \
            by_device(valid)
        arr_state = [x for x in state_h if getattr(x, "ndim", 0) >= 1]
        state_d = [by_device(x) for x in arr_state]
        scalar_after = {
            i: jax.device_get(x) + (1 if kind == "adam" else 0)
            for i, x in enumerate(state_h)
            if getattr(x, "ndim", 0) == 0}

        new_t, new_s = [], [[] for _ in arr_state]
        for sh in t_shards:
            dev = sh.device
            t_np = np.array(sh.data)            # host->host copy, mutable
            s_nps = [np.array(sd[dev]) for sd in state_d]
            rep_np = np.asarray(rep_d[dev])     # rows only cross the wire
            sums_np = np.asarray(sums_d[dev])
            valid_np = np.asarray(valid_d[dev])
            # indexing below pairs world-slice j of the table shard with
            # world-slice j of the pending arrays — valid ONLY while both
            # carry the same P(axis) layout. If XLA ever materializes the
            # pending arrays differently (e.g. replicated), silently
            # applying the wrong slices would corrupt training (ADVICE r5).
            nw = t_np.shape[0]
            drift = [(name, a.shape) for name, a in
                     (("rep", rep_np), ("sums", sums_np), ("valid", valid_np),
                      *((f"state[{i}]", s) for i, s in enumerate(s_nps)))
                     if a.shape[0] != nw]
            if drift:
                raise RuntimeError(
                    f"offloaded per-shard apply: device {dev} holds "
                    f"{nw} world slice(s) of the table but the update "
                    f"arrays have mismatched leading dims {drift} — "
                    "sharding layout drifted between the step jit's "
                    "pending outputs and the pinned-host bucket")
            for j in range(nw):                 # world slices on this shard
                if kind == "adam":
                    st = (s_nps[0][j], s_nps[1][j],
                          next(iter(scalar_after.values())))
                else:
                    st = tuple(s[j] for s in s_nps)
                sparse_update_ops.host_apply_rows_inplace(
                    kind, t_np[j], st, rep_np[j], sums_np[j], valid_np[j],
                    lr, **kw)
            new_t.append(jax.device_put(t_np, sh.data.sharding))
            for i, s_np in enumerate(s_nps):
                new_s[i].append(
                    jax.device_put(s_np, state_d[i][dev].sharding))

        def assemble(global_ref, shards):
            return jax.make_array_from_single_device_arrays(
                global_ref.shape, global_ref.sharding, shards)

        out_table = assemble(table_h, new_t)
        out_state, ai = [], 0
        for i, x in enumerate(state_h):
            if getattr(x, "ndim", 0) >= 1:
                out_state.append(assemble(x, new_s[ai]))
                ai += 1
            else:
                out_state.append(jax.device_put(
                    jnp.asarray(scalar_after[i], dtype=x.dtype), x.sharding))
        return out_table, tuple(out_state)

    @staticmethod
    def _restore_shape(out, p: _PreparedInput, combiner, width):
        if combiner is not None:
            return out
        # combiner None: canonical shape [B, k, w]; 1-D inputs drop the axis
        if out.ndim == 2:
            out = out.reshape(out.shape[0], -1, width)
        if p.orig_1d:
            out = out[:, 0, :]
        return out

    def __call__(self, params, inputs, taps=None,
                 return_residuals: bool = False, residual_sort=None):
        if self.dp_input:
            return self.apply(params, inputs, taps=taps,
                              return_residuals=return_residuals,
                              residual_sort=residual_sort)
        return self.apply_mp(params, inputs, taps=taps,
                             return_residuals=return_residuals,
                             residual_sort=residual_sort)

    # ------------------------------------- hot-row admission + consistency
    @staticmethod
    def _host_flat_ids(x) -> np.ndarray:
        """Flatten one apply-style input (dense ids, (ids, weights)
        tuple, RaggedIds, SparseIds) to its locally-visible id stream as
        int64 numpy — the shared host-side mirror feeding both hot-row
        admission (`observe_hot_ids`) and touched-row accounting
        (`touched_row_keys`)."""

        def _local_parts(arr):
            # multi-process staged batches are global jax.Arrays that are
            # NOT fully addressable — device_get would raise. The local
            # batch shard is both available and exactly what this process
            # should observe (sync_hot_rows reconciles the per-process
            # counters by broadcasting the admitted set from process 0).
            if getattr(arr, "is_fully_addressable", True):
                return np.asarray(jax.device_get(arr)), 0
            shards = sorted(arr.addressable_shards,
                            key=lambda s: s.index[0].start or 0)
            start = shards[0].index[0].start or 0
            return np.concatenate(
                [np.asarray(s.data).reshape(-1) for s in shards]), start

        if (isinstance(x, tuple) and len(x) == 2
                and not isinstance(x, RaggedIds)):
            x = x[0]
        if isinstance(x, RaggedIds):
            # values past row_splits[-1] are padding by contract —
            # counting them would attribute phantom lookups to row 0.
            # Trim to the flat span the locally visible row_splits
            # cover: fully-addressable, that is exactly [0, n); on a
            # sharded batch it is always real values (padding lives
            # past the LAST split), at worst dropping a boundary
            # sliver of a row that straddles the shard edge — fine
            # for frequency statistics.
            vals, v0 = _local_parts(x.values)
            sp, _ = _local_parts(x.row_splits)
            sp = sp.reshape(-1)
            lo, hi = int(sp[0]), int(sp[-1])
            x = vals.reshape(-1)[max(lo - v0, 0):max(hi - v0, 0)]
        elif isinstance(x, SparseIds):
            x = x.values
        if not isinstance(x, np.ndarray):
            x = _local_parts(x)[0]
        return x.reshape(-1).astype(np.int64)

    def touched_row_keys(self, inputs) -> dict:
        """Host-side mirror of the rows one batch's sparse update may
        write (the weight-streaming producer's accounting, ISSUE 6):
        {("tp", b): sorted unique int64 flat keys
        (``rank * rows_max + row`` — the `HotRowCache`/hot-shard key
        space), ("row", t): sorted unique GLOBAL row ids}.

        The sets are deliberately a tight SUPERSET of the rows the
        update writes: sentinel-masked OOB ids are excluded (the update
        drops them), while hot-HIT lanes are included — they skip the
        canonical scatter but move the replicated hot shard, i.e. the
        MERGED row value a delta must republish. Zero-weight lanes are
        included too (lazy adam decays moments on id presence). A
        superset is the safe direction for SET-payload deltas: applying
        an unchanged row is a no-op, missing a changed one is silent
        divergence. dp tables never appear — they train densely and are
        published whole."""
        if len(inputs) != self._n_inputs:
            raise ValueError(
                f"Expected {self._n_inputs} inputs, got {len(inputs)}")
        seg_rows = {(pl.bucket, pl.rank, pl.row_offset): pl.rows
                    for pl in self.plan.tp_placements}
        per: dict = {}
        for pos, i in enumerate(self.strategy.input_groups[1]):
            ids = self._host_flat_ids(inputs[i])
            for (rank, b, slot_idx) in self.plan.tp_input_slots[pos]:
                bucket = self.plan.tp_buckets[b]
                off = bucket.slots[rank][slot_idx].row_offset
                rows = seg_rows.get((b, rank, off), 0)
                rows_max = max(bucket.rows_max, 1)
                v = ids[(ids >= 0) & (ids < rows)]
                if len(v):
                    per.setdefault(("tp", b), []).append(
                        rank * rows_max + off + v)
        for j, i in enumerate(self.strategy.input_groups[2]):
            t = self.strategy.map_groups[2][j]
            rt = self.plan.row_tables[t]
            total = int(sum(rt.rows_per_rank))
            ids = self._host_flat_ids(inputs[i])
            v = ids[(ids >= 0) & (ids < total)]
            if len(v):
                per.setdefault(("row", t), []).append(v)
        return {k: np.unique(np.concatenate(chunks))
                for k, chunks in per.items()}

    def hot_resident_rows(self, params) -> dict:
        """{bucket: (sorted valid int64 keys [n], rows [n, w])} — the
        AUTHORITATIVE hot-resident rows per hot bucket. This is the ONE
        source both consistency consumers read (ISSUE 6): the
        `get_weights` portable-dump overlay and the table store's
        versioned `read_rows` — so a stale overlay after
        `sync_hot_rows` cannot exist by construction (there is no second
        derivation to drift). Empty dict on hot-less layers/params."""
        out = {}
        if not (self._hot_buckets and "hot" in params):
            return out
        for b in self._hot_buckets:
            entry = params["hot"][b]
            if entry is None:
                continue
            keys = np.asarray(jax.device_get(entry["ids"])) \
                .astype(np.int64)
            rows = np.asarray(jax.device_get(entry["rows"]))
            valid = (keys >= 0) & (keys < self._hot_sentinel(b))
            if valid.any():
                out[b] = (keys[valid], rows[valid])
        return out

    def _hot_tracker(self, b: int) -> HotnessTracker:
        tr = self._hot_trackers.get(b)
        if tr is None:
            tr = HotnessTracker(self.plan.tp_buckets[b].hot_rows,
                                promote_threshold=1)
            self._hot_trackers[b] = tr
        return tr

    def observe_hot_ids(self, inputs) -> dict:
        """Host-side frequency observation for hot-row admission — the
        'warmup scan' feed (and the online counter feed between
        `sync_hot_rows` calls). `inputs` are the SAME per-feature arrays
        `apply` takes (dense ids, (ids, weights) tuples, RaggedIds,
        SparseIds); observation is pure numpy on this process's view — it
        never touches device state. Shares the counter/admission core with
        the serving cache (`utils.hotness.HotnessTracker`).

        Returns {bucket: hit_rate} of the stream observed so far against
        each tracker's CURRENT resident set (the measured rates
        `exchange_padding_report` folds into its post-hot accounting).
        """
        if not self._hot_buckets:
            return {}

        per_bucket: dict = {b: [] for b in self._hot_buckets}
        hot_set = set(self._hot_buckets)
        # the device split only ever hits ids inside the lane's backing
        # segment (`_hot_split_send` lane_rows guard) — mirror it here so
        # an over-range id can neither inflate a NEIGHBORING segment's
        # counts (aliased flat key) nor count as a hit the device forces
        # to miss
        seg_rows = {b: {(pl.rank, pl.row_offset): pl.rows
                        for pl in self.plan.tp_placements if pl.bucket == b}
                    for b in self._hot_buckets}
        for pos, i in enumerate(self.strategy.input_groups[1]):
            ids = self._host_flat_ids(inputs[i])
            for (rank, b, slot_idx) in self.plan.tp_input_slots[pos]:
                if b not in hot_set:
                    continue
                bucket = self.plan.tp_buckets[b]
                off = bucket.slots[rank][slot_idx].row_offset
                rows = seg_rows[b].get((rank, off), 0)
                rows_max = max(bucket.rows_max, 1)
                v = ids[(ids >= 0) & (ids < rows)]
                per_bucket[b].append(rank * rows_max + off + v)
        rates = {}
        for b, chunks in per_bucket.items():
            if not chunks:
                continue
            tr = self._hot_tracker(b)
            tr.lookup_slots(np.concatenate(chunks), observe=True)
            rates[b] = tr.hit_rate
        return rates

    def hot_keys_from_counts(self, counts: Sequence) -> dict:
        """Planner-driven admission input from per-input id frequencies
        (e.g. ``IntegerLookup.counts()`` after ingestion, truncated to the
        table's input_dim): ``counts[i]`` is a [input_dim_i] array for
        input i, or None for unobserved inputs. Duplicate keys (shared
        tables / column slices) aggregate. Returns {bucket: top-H keys}
        for `sync_hot_rows(new_keys=...)`."""
        if len(counts) != self._n_inputs:
            raise ValueError(
                f"counts has {len(counts)} entries, expected "
                f"{self._n_inputs} (one per input)")
        out = {}
        hot_set = set(self._hot_buckets)
        agg: dict = {b: ([], []) for b in self._hot_buckets}
        for pos, i in enumerate(self.strategy.input_groups[1]):
            if counts[i] is None:
                continue
            c = np.asarray(counts[i], np.int64).reshape(-1)
            # clamp to the table's row count: an over-length counts array
            # (e.g. IntegerLookup.counts() is [max_tokens + 1] — index 0
            # is the OOV slot, so it runs one past a table with
            # input_dim == max_tokens rows) would otherwise generate keys
            # past the slot's rows — aliasing NEIGHBORING tables'/ranks'
            # rows as "hot"
            table = self.strategy.input_table_map[i]
            in_dim = int(self.strategy.global_configs[table]["input_dim"])
            c = c[:in_dim]
            for (rank, b, slot_idx) in self.plan.tp_input_slots[pos]:
                if b not in hot_set:
                    continue
                bucket = self.plan.tp_buckets[b]
                off = bucket.slots[rank][slot_idx].row_offset
                rows_max = max(bucket.rows_max, 1)
                keys = (rank * rows_max + off
                        + np.arange(len(c), dtype=np.int64))
                agg[b][0].append(keys)
                agg[b][1].append(c)
        for b, (keys_l, counts_l) in agg.items():
            if not keys_l:
                continue
            keys = np.concatenate(keys_l)
            cnts = np.concatenate(counts_l)
            uniq, inv = np.unique(keys, return_inverse=True)
            tot = np.zeros(len(uniq), np.int64)
            np.add.at(tot, inv, cnts)
            h_cap = self.plan.tp_buckets[b].hot_rows
            nz = tot > 0
            order = np.argsort(-tot[nz], kind="stable")[:h_cap]
            out[b] = uniq[nz][order]
        return out

    def _hot_fn(self, b: int, kind: str):
        """Cached jitted scatter/gather between a stacked canonical param
        and a [H]-keyed hot array (keys = world_slice*rows_max + row;
        sentinel/OOB keys drop out)."""
        key = (b, kind)
        fn = self._hot_fn_cache.get(key)
        if fn is not None:
            return fn
        rows_max = max(self.plan.tp_buckets[b].rows_max, 1)
        world = self.world_size

        def scatter(stack, keys, rows):
            w_idx = keys // rows_max
            r_idx = keys % rows_max
            return stack.at[w_idx, r_idx].set(
                rows.astype(stack.dtype), mode="drop")

        def gather(stack, keys):
            valid = (keys >= 0) & (keys < world * rows_max)
            w_idx = jnp.clip(keys // rows_max, 0, world - 1)
            r_idx = jnp.clip(keys % rows_max, 0, rows_max - 1)
            picked = stack[w_idx, r_idx]
            return jnp.where(valid[:, None], picked,
                             jnp.zeros((), picked.dtype))

        fn = jax.jit(scatter if kind == "scatter" else gather)
        self._hot_fn_cache[key] = fn
        return fn

    def sync_hot_rows(self, params: dict, opt_states: Optional[dict] = None,
                      new_keys: Optional[dict] = None, admit: bool = False):
        """The hot shard's explicit consistency step (ISSUE 4).

        While rows are hot-resident, the replicated hot shard (and its
        replicated optimizer state) is AUTHORITATIVE for them — the
        canonical MP table rows receive zero gradient (the forward masks
        hit lanes out of the miss path). This step:

          1. writes every resident hot row (and its table-shaped optimizer
             state rows) back into the canonical stacked params, and
          2. optionally re-admits a new hot set: ``new_keys`` maps bucket
             -> flat row keys (``world_slice * rows_max + row``), or
             ``admit=True`` derives them from the observed frequency
             counters (`observe_hot_ids`); the new residents' rows AND
             state rows gather from the (just-synced) canonical arrays, so
             admission is numerically a no-op.

        Call it before checkpointing via `save_global_weights` semantics
        you derive from raw params, before a serving handoff, and whenever
        re-admission should happen. (`get_weights` overlays hot rows
        itself, so the portable dump is correct even mid-residency.)
        Purely functional: returns ``(params, opt_states)`` new pytrees.
        """
        if not self._hot_buckets or "hot" not in params:
            return params, opt_states
        if admit and new_keys is None:
            # each process's tracker only observed its local batch shard,
            # so per-process top keys differ — but the membership array is
            # consumed as a REPLICATED param, so every process must admit
            # the identical set or the sentinel masks feeding all_to_all
            # silently diverge. Broadcast process 0's choice (callers
            # passing `new_keys` explicitly own that same contract).
            new_keys = {b: tr.top_keys()
                        for b, tr in self._hot_trackers.items()}
            if jax.process_count() > 1:
                from jax.experimental import multihost_utils
                # the broadcast pytree must have IDENTICAL structure on
                # every process, so it spans ALL hot buckets (the lazy
                # _hot_trackers dict only holds observed ones, and which
                # buckets were observed can differ per process); buf[0]
                # flags whether process 0 observed the bucket — unflagged
                # buckets drop out below and keep their current residents
                padded = {}
                for b in self._hot_buckets:
                    cap = self.plan.tp_buckets[b].hot_rows
                    buf = np.full((cap + 1,), -1, np.int64)
                    if b in new_keys:
                        buf[0] = 1
                        k = np.asarray(new_keys[b],
                                       np.int64).reshape(-1)[:cap]
                        buf[1:1 + len(k)] = k
                    padded[b] = buf
                bcast = multihost_utils.broadcast_one_to_all(padded)
                new_keys = {b: np.asarray(buf)[1:]      # -1 pads filter out
                            for b, buf in bcast.items()
                            if int(np.asarray(buf)[0]) == 1}
        rep = (NamedSharding(self.mesh, P()) if self.mesh is not None
               else None)

        def _rep(x):
            return x if rep is None else jax.device_put(x, rep)

        new_params = dict(params)
        new_params["tp"] = list(params["tp"])
        new_params["hot"] = list(params["hot"])
        new_states = None
        if opt_states is not None:
            new_states = dict(opt_states)
            new_states["tp"] = list(opt_states["tp"])
            new_states["hot"] = list(opt_states.get("hot", []))
        for pos_h, b in enumerate(self._hot_buckets):
            entry = params["hot"][b]
            bucket = self.plan.tp_buckets[b]
            h_cap = bucket.hot_rows
            sent = self._hot_sentinel(b)
            scatter = self._hot_fn(b, "scatter")
            # 1. write-back: resident rows (+ state rows) -> canonical
            new_params["tp"][b] = scatter(new_params["tp"][b],
                                          entry["ids"], entry["rows"])
            if new_states is not None and pos_h < len(new_states["hot"]):
                can_st = list(new_states["tp"][b])
                hot_st = list(new_states["hot"][pos_h])
                for li, (cx, hx) in enumerate(zip(can_st, hot_st)):
                    if getattr(cx, "ndim", 0) == 3 \
                            and getattr(hx, "ndim", 0) == 2:
                        can_st[li] = scatter(cx, entry["ids"], hx)
                new_states["tp"][b] = tuple(can_st)
            # 2. optional re-admission from the synced canonical arrays
            if new_keys is not None and b in new_keys:
                keys = np.asarray(new_keys[b], np.int64).reshape(-1)
                keys = keys[(keys >= 0) & (keys < sent)]
                # over-capacity key lists truncate in CALLER order (e.g.
                # top_keys passes hottest first), never in numeric order —
                # dedup keeps each key's first occurrence
                _, first = np.unique(keys, return_index=True)
                keys = keys[np.sort(first)][:h_cap]
                pad = np.full((h_cap,), sent, np.int32)
                pad[:len(keys)] = np.sort(keys).astype(np.int32)
                # jnp.array COPIES and the block pins the transfer while
                # `pad` is still alive: a zero-copy/async staging of the
                # dying temp intermittently produced a membership array
                # holding foreign bytes (observed: the int64 key buffer
                # reinterpreted as int32 — silently wrong hits)
                kj = _rep(jnp.array(pad))
                kj.block_until_ready()
                gather = self._hot_fn(b, "gather")
                # pin the hot-shard dtype across re-admissions (a dtype
                # flip would retrace the donated step mid-run)
                new_params["hot"][b] = {
                    "ids": kj,
                    "rows": _rep(gather(new_params["tp"][b], kj)
                                 .astype(entry["rows"].dtype))}
                if new_states is not None \
                        and pos_h < len(new_states["hot"]):
                    can_st = new_states["tp"][b]
                    hot_st = list(new_states["hot"][pos_h])
                    for li, (cx, hx) in enumerate(zip(can_st, hot_st)):
                        if getattr(cx, "ndim", 0) == 3 \
                                and getattr(hx, "ndim", 0) == 2:
                            hot_st[li] = _rep(gather(cx, kj))
                        # scalar leaves (adam's count) keep the hot copy:
                        # hot and canonical counts increment in lockstep
                        # (one update each per step), and aliasing the
                        # canonical array here would donate one buffer
                        # twice in the next step
                    new_states["hot"][pos_h] = tuple(hot_st)
                # the host-side tracker mirrors the device-resident set so
                # observed hit rates describe what the step actually hits;
                # hit/miss stats re-window to this residency epoch (the
                # all-miss pre-admission stream must not dilute the rates
                # the padding report folds in)
                tr = self._hot_tracker(b)
                tr.set_resident(keys)
                tr.reset_stats()
        return new_params, new_states

    def hot_stats(self) -> dict:
        """Per-bucket admission/hit statistics of the host-side trackers
        ({} until observe_hot_ids/sync_hot_rows have run)."""
        return {b: tr.stats() for b, tr in self._hot_trackers.items()}

    # --------------------------------------------------------- weights I/O
    def _shard_host(self, arr: jax.Array, rank: int,
                    cache: Optional[dict] = None) -> np.ndarray:
        """One rank's [rows_max, w] block of a stacked param, fetched
        shard-wise (never materializing the global stack on host). Remote
        ranks' shards (multi-process runs) come from the pre-gathered
        `cache` — see get_weights, which issues the collective gathers in a
        fixed order BEFORE any per-rank reads (a conditional gather here
        would run collectives in a process-dependent order and deadlock)."""
        if cache and id(arr) in cache:
            return cache[id(arr)][rank]
        if hasattr(arr, "addressable_shards"):
            for sh in arr.addressable_shards:
                idx = sh.index[0]
                start = 0 if idx.start is None else idx.start
                stop = arr.shape[0] if idx.stop is None else idx.stop
                if start <= rank < stop:
                    return np.asarray(sh.data)[rank - start]
        return np.asarray(arr)[rank]

    # reference parity: get_weights chunks its collectives so no single
    # gather exceeds ~128M elements (reference dist_model_parallel.py
    # _split_1d + :1024-1089 bounds both the 2e9-element collective limit
    # and peak memory). Overridable for tests / small-RAM hosts.
    GATHER_CHUNK_ELEMS = int(os.environ.get("DET_GATHER_CHUNK_ELEMS",
                                            128 * 1024 * 1024))

    def _gather_global_chunked(self, arr: jax.Array) -> np.ndarray:
        """Replicate a non-fully-addressable stacked param host-side in
        row chunks: each collective moves (and each device holds) at most
        ~GATHER_CHUNK_ELEMS elements, so the peak device/temp footprint is
        O(chunk) + the unavoidable host result, never a second full bucket
        (VERDICT r4 item 5; the single-call process_allgather it replaces
        replicated the ENTIRE stacked bucket on every device first)."""
        from jax.experimental import multihost_utils
        world = max(int(arr.shape[0]), 1)
        rows = int(arr.shape[1]) if arr.ndim > 1 else 1
        tail = int(np.prod(arr.shape[2:])) if arr.ndim > 2 else 1
        chunk = max(1, self.GATHER_CHUNK_ELEMS // max(world * tail, 1))
        # offloaded (pinned-host) buckets: process_allgather's replicated
        # jit cannot consume host-placement inputs (the same partitioner
        # RET_CHECK the train-path pershard apply sidesteps). A jit SLICE of
        # the host input lands in device memory partitioned — so each chunk
        # is moved host->device per-shard first, and only device arrays ever
        # meet the collective. Chunking bounds the device temp to O(chunk).
        host_kind = getattr(arr.sharding, "memory_kind", "device") not in (
            None, "device")
        if arr.ndim < 2:
            if host_kind:
                arr = jax.device_put(
                    arr, arr.sharding.with_memory_kind("device"))
            return np.asarray(
                multihost_utils.process_allgather(arr, tiled=True))
        if chunk >= rows and not host_kind:
            return np.asarray(
                multihost_utils.process_allgather(arr, tiled=True))
        out = np.empty(arr.shape, dtype=arr.dtype)
        for r0 in range(0, rows, chunk):
            r1 = min(rows, r0 + chunk)
            # jit-sliced for BOTH memory kinds: eager indexing of a
            # non-fully-addressable device array is backend-dependent
            # (ADVICE r5), while the cached jitted slice is always legal
            piece = _slice_rows_jit(arr, r0, r1)
            out[:, r0:r1] = np.asarray(
                multihost_utils.process_allgather(piece, tiled=True))
        return out

    def get_weights(self, params, all_ranks: bool = False) -> List[np.ndarray]:
        """Reassemble global per-table weights in original table order
        (reference get_weights :1139-1162), reading device shards one at a
        time. Multi-process: every non-fully-addressable stacked param is
        first replicated host-side by a collective all-gather, in fixed
        (tp-bucket, row-table) order — so ALL processes must call
        get_weights together (the reference's get_weights is likewise
        collective, :1084-1089).
        """
        del all_ranks  # SPMD: every process sees the global jax.Array
        cache: dict = {}
        if self.mesh is not None and jax.process_count() > 1:
            scales = [s for s in params.get("tp_scale", []) if s is not None]
            for arr in list(params["tp"]) + list(params["row"]) + scales:
                if (hasattr(arr, "is_fully_addressable")
                        and not arr.is_fully_addressable):
                    cache[id(arr)] = self._gather_global_chunked(arr)
        strat = self.strategy
        n = len(strat.global_configs)
        out: List[Optional[np.ndarray]] = [None] * n

        for j, gtid in enumerate(strat.table_groups[0]):
            out[gtid] = np.asarray(params["dp"][j])

        for t_local, gtid in enumerate(strat.table_groups[1]):
            cols = []
            for pl_ in sorted((p for p in self.plan.tp_placements
                               if p.table_id == t_local),
                              key=lambda p: p.col_start):
                shard = self._shard_host(params["tp"][pl_.bucket], pl_.rank,
                                         cache)
                sd = self._bucket_store_dtype(pl_.bucket)
                if sd != "f32":
                    # quantized storage (ISSUE 15): the portable dump is
                    # ALWAYS f32 — decode payload x per-row scale here,
                    # so checkpoints/streams stay format-stable
                    sshard = self._shard_host(
                        params["tp_scale"][pl_.bucket], pl_.rank, cache)
                    shard = wire_ops.decode_rows_np(shard, sshard, sd)
                cols.append(shard[pl_.row_offset:pl_.row_offset + pl_.rows, :])
            out[gtid] = np.concatenate(cols, axis=1) if len(cols) > 1 else cols[0]

        for t_local, gtid in enumerate(strat.table_groups[2]):
            rt = self.plan.row_tables[t_local]
            parts = [self._shard_host(params["row"][t_local], r,
                                      cache)[:rt.rows_per_rank[r], :]
                     for r in range(self.world_size)]
            out[gtid] = np.concatenate(parts, axis=0)

        # hot-row overlay (ISSUE 4): while resident, the replicated hot
        # shard is authoritative for its rows (the canonical table stops
        # receiving their gradients) — merge them into the portable dump
        # so get_weights is correct even without a prior sync_hot_rows.
        # The resident set comes from `hot_resident_rows`, the SAME
        # single source the table store's versioned `read_rows` overlays
        # from (ISSUE 6): both consumers see one derivation, so they
        # cannot drift.
        for b, (keys_v, rows_v) in self.hot_resident_rows(params).items():
            rows_max = max(self.plan.tp_buckets[b].rows_max, 1)
            w_idx = keys_v // rows_max
            r_idx = keys_v % rows_max
            for pl_ in self.plan.tp_placements:
                if pl_.bucket != b:
                    continue
                m = ((w_idx == pl_.rank) & (r_idx >= pl_.row_offset)
                     & (r_idx < pl_.row_offset + pl_.rows))
                if not m.any():
                    continue
                gtid = strat.table_groups[1][pl_.table_id]
                if not out[gtid].flags.writeable:
                    out[gtid] = out[gtid].copy()
                out[gtid][r_idx[m] - pl_.row_offset,
                          pl_.col_start:pl_.col_end] = rows_v[m]
        return out

    def set_weights(self, weights: Sequence) -> dict:
        """Build a new params pytree from global per-table weights
        (numpy arrays or .npy file paths; reference set_weights :971-1022).
        Purely functional: returns new params with the same shardings.
        Each rank's shard is assembled and staged independently, so peak host
        memory is one shard — .npy paths are mmap'd and only the placed
        slices are read (reference np.load(mmap_mode='r') :911-950 and
        128M-element chunked scatter :1002-1017 serve the same purpose).
        """
        strat = self.strategy
        if len(weights) != len(strat.global_configs):
            raise ValueError(
                f"Expected {len(strat.global_configs)} weights, got {len(weights)}")
        weights = [np.load(w, mmap_mode="r") if isinstance(w, str) else np.asarray(w)
                   for w in weights]
        for w, cfg in zip(weights, strat.global_configs):
            expect = (cfg["input_dim"], cfg["output_dim"])
            if tuple(w.shape) != expect:
                raise ValueError(f"Weight shape {w.shape} != expected {expect}")

        new = {"dp": [], "tp": [], "row": []}
        for j, gtid in enumerate(strat.table_groups[0]):
            new["dp"].append(jnp.asarray(weights[gtid]))

        def tp_shard(rank: int, b: int) -> np.ndarray:
            bucket = self.plan.tp_buckets[b]
            arr = np.zeros((max(bucket.rows_max, 1), bucket.width), np.float32)
            for pl_ in self.plan.tp_placements:
                if pl_.bucket != b or pl_.rank != rank:
                    continue
                gtid = strat.table_groups[1][pl_.table_id]
                arr[pl_.row_offset:pl_.row_offset + pl_.rows, :] = (
                    weights[gtid][:, pl_.col_start:pl_.col_end])
            return arr

        def row_shard(rank: int, t_local: int, gtid: int) -> np.ndarray:
            rt = self.plan.row_tables[t_local]
            arr = np.zeros((max(rt.rows_max, 1), rt.width), np.float32)
            start = int(sum(rt.rows_per_rank[:rank]))
            rows = rt.rows_per_rank[rank]
            arr[:rows, :] = weights[gtid][start:start + rows, :]
            return arr

        qbs = self.quantized_buckets
        scales: Dict[int, jax.Array] = {}
        q_shard = self._encoded_shard_fn(tp_shard, wire_ops.encode_rows_np)
        if self.mesh is not None:
            rep = NamedSharding(self.mesh, P())
            new["dp"] = [jax.device_put(a, rep) for a in new["dp"]]
            for b in range(len(self.plan.tp_buckets)):
                mk = self._bucket_memory_kind(b)
                if b in qbs:
                    new["tp"].append(self._stack_sharded(
                        lambda rank, b=b: q_shard(rank, b, 0),
                        memory_kind=mk))
                    scales[b] = self._stack_sharded(
                        lambda rank, b=b: q_shard(rank, b, 1),
                        memory_kind=mk)
                else:
                    new["tp"].append(self._stack_sharded(
                        lambda rank, b=b: tp_shard(rank, b),
                        memory_kind=mk))
            for t_local, gtid in enumerate(strat.table_groups[2]):
                new["row"].append(self._stack_sharded(
                    lambda rank, t=t_local, g=gtid: row_shard(rank, t, g)))
        else:
            for b in range(len(self.plan.tp_buckets)):
                mk = self._bucket_memory_kind(b)
                scale = None
                if b in qbs:
                    arr = np.stack([q_shard(r, b, 0)
                                    for r in range(self.world_size)])
                    scale = jnp.asarray(np.stack(
                        [q_shard(r, b, 1) for r in range(self.world_size)]))
                    arr = jnp.asarray(arr)
                else:
                    arr = jnp.stack([jnp.asarray(tp_shard(r, b))
                                     for r in range(self.world_size)])
                if mk:
                    hsh = jax.sharding.SingleDeviceSharding(
                        jax.devices()[0], memory_kind=mk)
                    arr = jax.device_put(arr, hsh)
                    if scale is not None:
                        scale = jax.device_put(scale, hsh)
                new["tp"].append(arr)
                if scale is not None:
                    scales[b] = scale
            for t_local, gtid in enumerate(strat.table_groups[2]):
                new["row"].append(jnp.stack(
                    [jnp.asarray(row_shard(r, t_local, gtid))
                     for r in range(self.world_size)]))
        if qbs:
            new["tp_scale"] = [scales.get(b)
                               for b in range(len(self.plan.tp_buckets))]
        if self._hot_buckets:
            # global weights are the canonical tables; the hot set starts
            # empty (re-admit + sync after loading to repopulate it)
            new["hot"] = self._init_hot_params()
        return new


def broadcast_variables(params, root_rank: int = 0):
    """Reference-API shim (dist_model_parallel.py:1219-1239).

    Under SPMD there is nothing to broadcast: every process constructs the
    same global jax.Arrays (same program, same seed). For multi-process
    setups initializing from process-local data, broadcast from process 0.
    """
    if root_rank != 0:
        raise NotImplementedError(
            "broadcast_one_to_all always originates from process 0; "
            "root_rank != 0 is not supported")
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        return multihost_utils.broadcast_one_to_all(params)
    return params
