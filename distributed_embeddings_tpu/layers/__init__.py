from distributed_embeddings_tpu.layers.embedding import (
    Embedding,
    ConcatOneHotEmbedding,
    IntegerLookup,
)
