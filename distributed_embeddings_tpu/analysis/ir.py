"""Typed StableHLO IR layer: parse a lowered module ONCE into
functions/instructions/operands/results with dtype+shape+attrs and an
interprocedural call graph (ISSUE 10).

Three generations of bespoke HLO checks (the PR 2 sort gates, the PR 5
collective-byte audit, the PR 8 overlap classifier) each re-walked the
lowered StableHLO text with their own ad-hoc regexes. This module is the
one parse they now share: ``parse_module(text)`` builds a :class:`Module`
and the measurement functions (:func:`op_counts`,
:func:`collective_bytes`, :func:`collective_overlap`) are the three
legacy auditors ported onto it — behavior-identical, asserted against
the regex era's recorded outputs on checked-in fixtures
(tests/fixtures/hlo/expected_legacy.json) before the old parsers were
deleted. ``analysis/passes.py`` layers invariant checks (findings) on
top; ``tools/hlo_audit.py`` is the driver.

Parsing model (matches what jax's ``.lower(...).as_text()`` emits):

  * one :class:`Function` per ``func.func`` — public/private visibility,
    arguments with their types and raw attribute text (donation /
    aliasing markers live there), terminator operand refs;
  * one :class:`Instruction` per TOP-LEVEL operation of a function body.
    Operations inside nested regions (stablehlo.while / sort / reduce
    bodies) FOLD INTO the enclosing instruction — their op mnemonics,
    operand refs and (for collectives) operand types are recorded on the
    owner as ``region_ops`` / ``region_refs`` — the same conservative
    granularity the regex-era overlap classifier shipped with: a region
    mixing collectives and compute taints one node, and its collectives
    can never classify as overlap candidates;
  * jax lowers ``shard_map`` bodies and jnp helpers to private functions
    reached via ``call @shmap_body`` — the call graph (callees per
    instruction, acyclic) is what makes the measurements
    interprocedural.

The parser is deliberately text-tolerant: it never throws on lines it
does not understand (they land in ``Module.residual_text``), so a jax
upgrade that changes printing degrades measurements instead of crashing
audits. Every instruction keeps its source ``text`` — op-count semantics
are TEXTUAL-MENTION counts (``#stablehlo.gather<...>`` attribute
references count, exactly as the historical counter did), which is what
keeps a decade of recorded baselines comparable.
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

__all__ = [
    "Type", "Argument", "Instruction", "Function", "Module",
    "parse_module", "op_counts", "collective_bytes", "collective_overlap",
    "COLLECTIVE_OPS", "COMPUTE_OPS", "DTYPE_BYTES",
]

# ------------------------------------------------------------ constants
# payload-moving cross-device ops the byte/seam/overlap measurements
# audit (psum lowers to all_reduce — a cross-device ACCUMULATION, not an
# exchange; it is deliberately outside this set, see ops/wire.py's
# declared-uncompressed contract)
COLLECTIVE_OPS = ("ragged_all_to_all", "all_to_all", "all_gather",
                  "reduce_scatter", "collective_permute")

# dense-compute anchors of the overlap classification (the MXU work a
# prefetch collective must be dependency-free of)
COMPUTE_OPS = ("dot_general", "convolution")

DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
               "f8E4M3FN": 1, "f8E5M2": 1, "f8E4M3": 1,
               "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
               "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1}

# element types that only a declared quantized STORAGE dtype may put in
# a program (ISSUE 15) — i1 (preds) and the int id/metadata types are
# not storage payloads and are policed by the other passes
QUANTIZED_STORAGE_DTYPES = ("i8", "ui8", "f8E4M3FN", "f8E5M2", "f8E4M3")

_LINE_RE = re.compile(r'^\s*(%[\w]+)(?::(\d+))?\s*=\s*(.*)$')
_OP_RE = re.compile(r'"?(stablehlo|mhlo|chlo)\.([\w.]+)"?')
# NOTE: intentionally unanchored, like the regex era: `custom_call
# @Sharding` also "matches" as a callee — @Sharding is not a function in
# the module, so the call-graph lookup is a no-op, but the parity with
# recorded overlap numbers is exact.
_CALL_RE = re.compile(r'(?:func\.)?call\s+@([\w$.-]+)')
_FUNC_RE = re.compile(r'func\.func\s+(?:(public|private)\s+)?@([\w$.-]+)')
_REF_RE = re.compile(r'%[A-Za-z0-9_]+')
_TENSOR_RE = re.compile(r'tensor<([^>]*)>')
_SIG_RE = re.compile(r':\s*\(([^()]*)\)\s*->\s*(.*?)\s*$', re.MULTILINE)
_RET_RE = re.compile(r'^\s*(?:func\.)?return\b(.*)$')
_ARG_RE = re.compile(r'%arg\d+')


# ----------------------------------------------------------------- types
@dataclasses.dataclass(frozen=True)
class Type:
    """One ``tensor<...>`` value type: element dtype + static shape.
    Non-tensor or unparseable types keep ``dtype=None`` and measure as
    0 elements (they carry no audited payload)."""

    text: str
    dtype: Optional[str] = None
    shape: Tuple[Optional[int], ...] = ()

    @classmethod
    def parse(cls, text: str) -> "Type":
        text = text.strip()
        m = _TENSOR_RE.search(text)
        if not m:
            return cls(text=text)
        parts = m.group(1).split("x")
        dims: List[Optional[int]] = []
        for p in parts[:-1]:
            try:
                dims.append(int(p))
            except ValueError:
                dims.append(None)      # dynamic '?' dimension
        return cls(text=text, dtype=parts[-1], shape=tuple(dims))

    @property
    def elements(self) -> int:
        n = 1
        for d in self.shape:
            if d is None:
                return 0
            n *= d
        return n if self.dtype else 0

    @property
    def nbytes(self) -> int:
        """Payload bytes; unknown dtypes default to 4 (the historical
        convention the recorded byte baselines were measured under)."""
        if not self.dtype:
            return 0
        return self.elements * DTYPE_BYTES.get(self.dtype, 4)


def _parse_type_list(s: str) -> List[Type]:
    return [Type.parse("tensor<" + inner + ">")
            for inner in _TENSOR_RE.findall(s)]


@dataclasses.dataclass
class Argument:
    """One function argument: SSA name, type, raw attribute text
    (``{jax.buffer_donor = true, mhlo.sharding = ...}``)."""

    name: str
    type: Type
    attrs: str = ""

    @property
    def donated(self) -> bool:
        return "jax.buffer_donor" in self.attrs

    @property
    def aliased_output(self) -> Optional[int]:
        m = re.search(r'tf\.aliasing_output\s*=\s*(\d+)', self.attrs)
        return int(m.group(1)) if m else None


@dataclasses.dataclass
class Instruction:
    """One top-level operation of a function body, regions folded in."""

    kind: str                 # first op mnemonic ('all_to_all', 'call'…)
    dialect: Optional[str]    # 'stablehlo' | 'mhlo' | 'chlo' | None
    results: List[str]        # SSA base names produced (['%5'])
    num_results: int
    operands: List[str]       # %refs on the first line's rhs
    callees: List[str]        # call targets (first line + region lines)
    attrs: str                # raw '<{...}>' / '{...}' attribute text
    line: int                 # 1-based source line of the first line
    text: str                 # full source text (all folded lines)
    region_ops: List[Tuple[Optional[str], str]] = \
        dataclasses.field(default_factory=list)   # (dialect, kind)
    region_refs: List[str] = dataclasses.field(default_factory=list)
    region_collectives: List[Tuple[str, Type]] = \
        dataclasses.field(default_factory=list)   # (kind, first-operand)
    operand_types: List[Type] = dataclasses.field(default_factory=list)
    result_types: List[Type] = dataclasses.field(default_factory=list)

    @property
    def ops(self) -> List[Tuple[Optional[str], str]]:
        """(dialect, kind) of every operation this node owns — itself
        plus its folded region ops (assignment lines)."""
        return [(self.dialect, self.kind)] + self.region_ops

    @property
    def refs(self) -> List[str]:
        return self.operands + self.region_refs

    def is_collective(self, collectives=COLLECTIVE_OPS) -> bool:
        return any(k in collectives for _, k in self.ops)

    def collective_payloads(self, collectives=COLLECTIVE_OPS
                            ) -> List[Tuple[str, Type]]:
        """(kind, first-operand Type) per collective op on this node —
        the payload the byte audit charges (metadata operands, e.g.
        ragged_all_to_all's offset/size vectors, are bookkeeping)."""
        out = []
        if self.kind in collectives:
            t = self.operand_types[0] if self.operand_types else Type("")
            out.append((self.kind, t))
        out.extend((k, t) for k, t in self.region_collectives
                   if k in collectives)
        return out

    def _finalize(self) -> None:
        """Parse the trailing type signature out of the accumulated
        text: the LAST ``: (operand types) -> result types`` wins (for
        region-carrying generic ops that is the region-closing line);
        the pretty one-type form (``stablehlo.add %a, %b : tensor<…>``)
        falls back to that single type for operands and results."""
        sig = None
        for sig in _SIG_RE.finditer(self.text):
            pass
        if sig is not None:
            self.operand_types = _parse_type_list(sig.group(1))
            self.result_types = _parse_type_list(sig.group(2))
            return
        m = re.search(r':\s*([^:()=]*?)\s*$', self.text)
        if m:
            tl = _parse_type_list(m.group(1))
            if tl:
                self.operand_types = tl if self.operands else []
                self.result_types = tl


@dataclasses.dataclass
class Function:
    name: str
    visibility: str                  # 'public' | 'private'
    args: List[Argument]
    instructions: List[Instruction]
    returns: List[str] = dataclasses.field(default_factory=list)
    line: int = 0

    @property
    def donated_args(self) -> List[Argument]:
        return [a for a in self.args
                if a.donated or a.aliased_output is not None]

    def producers(self) -> Dict[str, int]:
        """SSA base name -> producing instruction index (top level)."""
        return {r: i for i, inst in enumerate(self.instructions)
                for r in inst.results}


@dataclasses.dataclass
class Module:
    functions: Dict[str, Function]
    source: str
    residual_text: str = ""          # lines owned by no instruction

    @property
    def entry(self) -> Optional[Function]:
        """The analyzed entry: @main when present, else the largest
        function (the regex era's convention, kept for parity)."""
        if "main" in self.functions:
            return self.functions["main"]
        if not self.functions:
            return None
        return max(self.functions.values(),
                   key=lambda f: len(f.instructions))

    def walk(self) -> Iterator[Tuple[Function, Instruction]]:
        for fn in self.functions.values():
            for inst in fn.instructions:
                yield fn, inst

    def call_graph(self) -> Dict[str, List[str]]:
        """function -> callees that exist in this module (acyclic in
        jax lowerings; cycles are tolerated by the summarizers)."""
        return {name: [c for inst in fn.instructions
                       for c in inst.callees if c in self.functions]
                for name, fn in self.functions.items()}


# ---------------------------------------------------------------- parse
def _parse_args(sig_text: str) -> List[Argument]:
    """Arguments from a ``func.func`` signature line. Attribute dicts can
    contain braces and commas INSIDE quoted strings (mhlo.sharding), so
    the split points are the ``%argN`` tokens themselves — nothing else
    in a signature can look like one."""
    body = sig_text.split("->")[0]
    starts = [m for m in _ARG_RE.finditer(body)]
    args = []
    for i, m in enumerate(starts):
        seg = body[m.end():starts[i + 1].start() if i + 1 < len(starts)
                   else len(body)]
        tm = _TENSOR_RE.search(seg)
        am = re.search(r'\{(.*)\}', seg, re.DOTALL)
        args.append(Argument(
            name=m.group(0),
            type=Type.parse("tensor<" + tm.group(1) + ">") if tm
            else Type(seg.strip(" :,()")),
            attrs=am.group(1) if am else ""))
    return args


def parse_module(text) -> Module:
    """Parse StableHLO MLIR text (or a ``jax.jit(f).lower(...)`` result)
    into a :class:`Module`. Never raises on unrecognized lines."""
    if not isinstance(text, str):
        text = text.as_text()
    functions: Dict[str, Function] = {}
    residual: List[str] = []
    cur: Optional[Function] = None
    depth = 0
    for lineno, raw in enumerate(text.splitlines(), start=1):
        fm = _FUNC_RE.search(raw)
        if fm:
            cur = Function(name=fm.group(2),
                           visibility=fm.group(1) or "public",
                           args=_parse_args(raw), instructions=[],
                           line=lineno)
            functions[cur.name] = cur
            # the signature line's opening brace is the body baseline
            depth = raw.count("{") - raw.count("}")
            continue
        if cur is None:
            residual.append(raw)
            continue
        at_top = depth <= 1
        depth += raw.count("{") - raw.count("}")
        m = _LINE_RE.match(raw)
        if at_top and m:
            lhs, nres, rhs = m.group(1), m.group(2), m.group(3)
            callee_m = _CALL_RE.search(rhs)
            op_m = _OP_RE.search(rhs)
            if op_m:
                dialect, kind = op_m.group(1), op_m.group(2)
            elif callee_m:
                dialect, kind = None, "call"
            else:
                dialect = None
                kind = rhs.split("(")[0].split()[0] if rhs.split() else ""
            am = re.search(r'<\{(.*)\}>', rhs, re.DOTALL)
            cur.instructions.append(Instruction(
                kind=kind, dialect=dialect, results=[lhs],
                num_results=int(nres) if nres else 1,
                operands=_REF_RE.findall(rhs),
                callees=[callee_m.group(1)] if callee_m else [],
                attrs=am.group(1) if am else "",
                line=lineno, text=raw))
        elif at_top:
            rm = _RET_RE.match(raw)
            if rm:
                cur.returns.extend(
                    t.split("#")[0] for t in _REF_RE.findall(rm.group(1)))
            residual.append(raw)
        else:
            # region line: folds into the enclosing instruction (or
            # opens one if the body somehow starts nested — parity with
            # the regex era's owner-or-new fallback)
            if not cur.instructions:
                cur.instructions.append(Instruction(
                    kind="", dialect=None, results=[], num_results=0,
                    operands=[], callees=[], attrs="", line=lineno,
                    text=""))
            owner = cur.instructions[-1]
            owner.text += "\n" + raw
            if m:
                rhs = m.group(3)
                callee_m = _CALL_RE.search(rhs)
                op_m = _OP_RE.search(rhs)
                if op_m:
                    d, k = op_m.group(1), op_m.group(2)
                elif callee_m:
                    d, k = None, "call"
                else:
                    d = None
                    k = (rhs.split("(")[0].split()[0]
                         if rhs.split() else "")
                owner.region_ops.append((d, k))
                owner.region_refs.extend(_REF_RE.findall(rhs))
                if callee_m:
                    owner.callees.append(callee_m.group(1))
                if k in COLLECTIVE_OPS:
                    # a collective nested in control flow still carries
                    # payload: charge its own line's first operand type
                    sig = _SIG_RE.search(raw)
                    t = (_parse_type_list(sig.group(1))
                         if sig else [])
                    owner.region_collectives.append(
                        (k, t[0] if t else Type("")))
    for fn in functions.values():
        for inst in fn.instructions:
            inst._finalize()
    return Module(functions=functions, source=text,
                  residual_text="\n".join(residual))


# ---------------------------------------------------- ported measurements
def _as_module(lowered) -> Module:
    return lowered if isinstance(lowered, Module) else parse_module(lowered)


def op_counts(lowered, ops: Sequence[str] = ("sort", "scatter", "gather",
                                             "all_to_all")) -> dict:
    """StableHLO op-mention counts — the PR 2 sort-gate measurement,
    ported. Counts are TEXTUAL mentions as whole words (``stablehlo.sort``
    counts, ``sort_key`` identifiers do not; attribute-embedded
    references like ``#stablehlo.gather<...>`` DO count, one per gather
    op in practice) — per textual instance, not per call-site execution.
    Identical by construction to the regex era (every source line lands
    in exactly one instruction's text or the residual), and asserted so
    on recorded fixtures."""
    mod = _as_module(lowered)
    pats = {op: re.compile(rf'stablehlo\.{re.escape(op)}\b')
            for op in ops}
    out = {op: len(pat.findall(mod.residual_text))
           for op, pat in pats.items()}
    for _, inst in mod.walk():
        for op, pat in pats.items():
            out[op] += len(pat.findall(inst.text))
    return out


def collective_bytes(lowered, collectives=COLLECTIVE_OPS) -> dict:
    """Collective payload (first-operand) bytes by element dtype — the
    PR 5 wire-audit measurement, ported. Shapes inside shard_map bodies
    are PER-DEVICE; ratios between two lowerings of the same program are
    what audits assert, not absolute fleet bytes
    (``analysis.programs.expected_collective_bytes`` is the exact
    model-side twin when fleet accounting is needed).

    Returns {op: {dtype: bytes}, "total": {dtype: bytes},
    "float_bytes": int, "int_bytes": int}."""
    mod = _as_module(lowered)
    out: dict = {op: {} for op in collectives}
    total: dict = {}
    for _, inst in mod.walk():
        for kind, t in inst.collective_payloads(collectives):
            if not t.dtype:
                continue
            out[kind][t.dtype] = out[kind].get(t.dtype, 0) + t.nbytes
            total[t.dtype] = total.get(t.dtype, 0) + t.nbytes
    out["total"] = total
    out["float_bytes"] = sum(v for k, v in total.items()
                             if k in ("f64", "f32", "bf16", "f16", "f8"))
    out["int_bytes"] = sum(v for k, v in total.items()
                           if k.startswith(("i", "ui")))
    return out


def collective_overlap(lowered, collectives=COLLECTIVE_OPS,
                       compute_ops=COMPUTE_OPS) -> dict:
    """Classify every collective by its dependency relation to the
    module's dense compute — the PR 8 lookahead overlap measurement,
    ported. A collective with dot/convolution ops in NEITHER its
    transitive fan-in NOR fan-out is an **overlap candidate**: no data
    dependency orders it against the dense stage, so XLA's
    latency-hiding scheduler may run it concurrently with MXU work.

    Granularity is the call SITE in the entry function: private helpers
    (shmap_body and friends) are summarized transitively, a call site
    inherits its callee's collective counts and compute content, and a
    site that itself contains compute (or a region mixing both) is never
    a candidate — conservative where imprecise. Region-folded
    instructions classify as one node (see module docstring).

    Returns {"collectives_total", "overlap_candidates",
    "serialized_collectives", "candidates_by_op", "compute_sites"}."""
    mod = _as_module(lowered)
    empty = {"collectives_total": 0, "overlap_candidates": 0,
             "serialized_collectives": 0, "candidates_by_op": {},
             "compute_sites": 0}
    entry = mod.entry
    if entry is None:
        return empty

    summaries: Dict[str, dict] = {}

    def summarize(fname: str, stack=()) -> dict:
        if fname in summaries:
            return summaries[fname]
        fn = mod.functions.get(fname)
        if fn is None or fname in stack:
            return {"coll": {}, "compute": False}
        coll: dict = {}
        compute = False
        for inst in fn.instructions:
            for _, kind in inst.ops:
                if kind in collectives:
                    coll[kind] = coll.get(kind, 0) + 1
                if kind in compute_ops:
                    compute = True
            for callee in inst.callees:
                sub = summarize(callee, stack + (fname,))
                compute = compute or sub["compute"]
                for k, v in sub["coll"].items():
                    coll[k] = coll.get(k, 0) + v
        summaries[fname] = {"coll": coll, "compute": compute}
        return summaries[fname]

    body = entry.instructions
    n = len(body)
    producer = entry.producers()
    deps = [[producer[r] for r in inst.refs if r in producer]
            for inst in body]
    node_coll: List[dict] = []
    node_compute: List[bool] = []
    for inst in body:
        c: dict = {}
        compute = False
        for _, kind in inst.ops:
            if kind in collectives:
                c[kind] = c.get(kind, 0) + 1
            if kind in compute_ops:
                compute = True
        for callee in inst.callees:
            sub = summarize(callee)
            compute = compute or sub["compute"]
            for k, v in sub["coll"].items():
                c[k] = c.get(k, 0) + v
        node_coll.append(c)
        node_compute.append(compute)

    # SSA text order is topological: one forward pass taints fan-ins,
    # one reverse pass taints fan-outs
    dot_in_fanin = [False] * n
    for i in range(n):
        dot_in_fanin[i] = any(node_compute[d] or dot_in_fanin[d]
                              for d in deps[i])
    consumers: List[List[int]] = [[] for _ in range(n)]
    for i, ds in enumerate(deps):
        for d in ds:
            consumers[d].append(i)
    dot_in_fanout = [False] * n
    for i in range(n - 1, -1, -1):
        dot_in_fanout[i] = any(node_compute[c] or dot_in_fanout[c]
                               for c in consumers[i])

    total = 0
    candidates = 0
    cand_by_op: dict = {}
    for i in range(n):
        cnt = sum(node_coll[i].values())
        if not cnt:
            continue
        total += cnt
        # a site that itself CONTAINS compute is never a candidate (the
        # collective may order against its own callee's dots)
        if (not dot_in_fanin[i] and not dot_in_fanout[i]
                and not node_compute[i]):
            candidates += cnt
            for k, v in node_coll[i].items():
                cand_by_op[k] = cand_by_op.get(k, 0) + v
    return {"collectives_total": total,
            "overlap_candidates": candidates,
            "serialized_collectives": total - candidates,
            "candidates_by_op": cand_by_op,
            "compute_sites": sum(node_compute)}
