"""The audited program matrix + mutation fixtures (ISSUE 10).

This is the jax-heavy half of the analysis package: it builds the
standard programs the static auditor proves invariants over —
monolithic train step, lookahead fused + prefetch, serve forward,
vocab-slack plan — lowered ONCE each over an 8-virtual-device mesh
(``program_matrix``: one lowering per program, shared by every pass —
the <=60s CI budget lives or dies on that cache), plus the legacy
per-arm audit entry points ``bench.py`` embeds in its records, plus
``mutation_cases()``: for every pass, a program that deliberately
violates its invariant and MUST produce exactly the expected finding.
An auditor that cannot fail is not a gate.

``expected_collective_bytes`` is the reconciled byte model (ISSUE 10
satellite): ONE formula turning ``exchange_padding_report``'s per-group
accounting into the exact per-device payload bytes the lowered
program's collectives must measure — id wire at the NARROWED dtype
(int16 buckets charge 2 bytes, matching the i16 operand the HLO
carries), activations twice in a train step (forward + gradient
transpose), weights once (weights are INPUTS, not params: no gradient
flows back through the weight exchange, so a train step moves the
weight block forward-only). tests/test_wire.py asserts HLO == model on
every wire config; the collective-bytes pass asserts it on every audit.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from . import ir
from .passes import PlanContext

__all__ = [
    "ensure_world", "build_model", "head_params",
    "expected_collective_bytes", "Program", "program_matrix",
    "mutation_cases", "MutationCase",
    "audit_tapped_step", "audit_exchange_bytes",
    "audit_lookahead_overlap", "wire_byte_arms",
    "WIRE_BYTE_MIN_REDUCTION",
]


def ensure_world(n: int = 8) -> int:
    """Request >= n virtual CPU devices (meshed lowerings emit real
    collectives only at world > 1). Must run before the backend
    initializes; returns the device count actually available."""
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={n}").strip()
    import jax
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except Exception:  # noqa: BLE001 - backend already up / older jax
        pass
    return len(jax.devices())


def build_model(vocab: int, width: int, combiner: str, hot_rows: int = 0,
                tables: int = 1, mesh=None, exchange_wire=None,
                dense_head: bool = False, vocab_slack: int = 0,
                weighted: bool = False, gpu_embedding_size=None,
                storage_dtype=None):
    """Minimal tapped model (the shape make_sparse_train_step expects)
    around a DistributedEmbedding — THE one copy of this harness, shared
    by the audit program matrix, the legacy sort/byte/overlap arms, and
    bench.py's --mode wire / --mode lookahead A/Bs, so the audit and the
    bench always lower the same program.

    ``dense_head=True`` puts a real matmul between the embedding outputs
    and the loss (params gain a ``head`` kernel, built by
    ``head_params``). The overlap passes classify collectives by
    dependency on dot ops — without a dot in the module the metric is
    vacuous — and a dense head is what the pipeline overlaps against in
    the first place. ``weighted=True`` feeds (ids, uniform-weights)
    tuples so the weight-exchange wire lowers too."""
    import jax.numpy as jnp
    from ..layers.dist_model_parallel import DistributedEmbedding
    from ..layers.embedding import Embedding

    class _Tapped:
        def __init__(self, emb):
            self.embedding = emb

        def loss_fn(self, p, numerical, cats, labels, taps=None,
                    return_residuals=False):
            ins = ([(c, jnp.ones(c.shape, jnp.float32)) for c in cats]
                   if weighted else list(cats))
            out = self.embedding(p["embedding"], ins, taps=taps,
                                 return_residuals=return_residuals)
            outs, res = out if return_residuals else (out, None)
            x = jnp.concatenate([o.reshape(o.shape[0], -1) for o in outs],
                                axis=1)
            if dense_head:
                pred = (x.astype(jnp.float32) @ p["head"])[:, 0]
            else:
                pred = jnp.sum(x, axis=1)
            loss = jnp.mean((pred - labels.reshape(-1)) ** 2)
            return (loss, res) if return_residuals else loss

    emb = DistributedEmbedding(
        [Embedding(vocab, width, combiner=combiner) for _ in range(tables)],
        mesh=mesh, hot_rows=hot_rows, exchange_wire=exchange_wire,
        vocab_slack=vocab_slack or None,
        gpu_embedding_size=gpu_embedding_size,
        storage_dtype=storage_dtype)
    return _Tapped(emb)


def head_params(tables: int, width: int, hotness: int, combiner: str):
    """The replicated dense-head kernel matching ``build_model``'s
    ``dense_head=True`` loss (one output column)."""
    import jax.numpy as jnp
    per = width * (1 if combiner else hotness)
    return jnp.zeros((tables * per, 1), jnp.float32)


# -------------------------------------------------- reconciled byte model
def expected_collective_bytes(emb, hotness, batch: int,
                              weighted: bool = False,
                              train: bool = True) -> Dict[str, int]:
    """Exact per-device collective payload bytes by StableHLO dtype for
    one lowered PADDED-path program over this layer's plan — the
    model-side twin of ``ir.collective_bytes`` (see module docstring for
    the fwd/bwd accounting). Returns {} at world 1 (no collectives).
    Only the padded exchange is modeled: the ragged emulation moves
    world x the payload through its all_gathers by construction, which
    is a path choice, not a wire property."""
    world = emb.world_size
    if world <= 1:
        return {}
    rep = emb.exchange_padding_report(hotness=hotness)
    out: Dict[str, int] = {}

    def add(dtype: str, n: int):
        if n:
            out[dtype] = out.get(dtype, 0) + n

    from ..ops import wire as wire_ops
    for g in rep["groups"]:
        # formats -> payload element types through the seam hooks, so
        # 'bf16-sr' models as the bf16 it actually puts on the wire
        id_dtype = wire_ops.seam_id_dtypes(g["id_wire_dtype"])[0]
        f_dtype = wire_ops.seam_float_dtypes(g["wire_dtype"])[0]
        id_b = wire_ops.id_wire_itemsize(g["id_wire_dtype"])
        wire_b = wire_ops.wire_itemsize(g["wire_dtype"])
        # report fields are per GLOBAL sample over the fleet; one
        # device's operand is the fleet volume x batch / world
        add(id_dtype, batch * g["exchanged_ids"] * id_b // world)
        acts = batch * g["act_bytes"] // world
        add(f_dtype, acts * (2 if train else 1))
        if weighted:
            add(f_dtype, batch * g["weight_bytes_if_weighted"] // world)
    return out


# --------------------------------------------------------- program matrix
@dataclasses.dataclass
class Program:
    """One lowered program + the plan context its invariants are checked
    against. Lowered AND parsed exactly once — ``module`` is the shared
    parse every pass (and the matrix's own cross-program bounds) runs
    on; ``text`` is kept for fixtures/debugging."""

    name: str
    text: str
    ctx: PlanContext
    module: "ir.Module" = None
    # driver hint: passes to SKIP for this program (e.g. overlap on a
    # program with no dense compute, where the metric is vacuous)
    skip_passes: tuple = ()

    def __post_init__(self):
        if self.module is None:
            self.module = ir.parse_module(self.text)


def _lower_step(model, optimizer: str, donate: bool, batch: int,
                hotness: int, tables: int, strategy: str = "auto"):
    import jax
    import jax.numpy as jnp
    from ..training import make_sparse_train_step
    emb = model.embedding
    init_fn, step_fn = make_sparse_train_step(
        model, optimizer, lr=0.01, donate=donate, strategy=strategy)
    params = {"embedding": emb.init(jax.random.PRNGKey(0))}
    if hasattr(model, "_head_width"):
        params["head"] = model._head_width
    state = init_fn(params)
    num = jnp.zeros((batch, 1), jnp.float32)
    cats = [jnp.zeros((batch, hotness), jnp.int32) for _ in range(tables)]
    lab = jnp.zeros((batch,), jnp.float32)
    kw = {"donate_argnums": (0, 1)} if donate else {}
    return jax.jit(step_fn, **kw).lower(
        params, state, num, cats, lab), params, cats


def _plan_wires(emb):
    """(float wire formats, id wire formats, folded sort bound, groups)
    of a layer's plan — the PlanContext ingredients."""
    key = tuple((2, False) for _ in range(len(
        emb.strategy.input_groups[1])))
    groups, _ = emb._exchange_groups_for_key(key)
    wires = tuple(sorted({b.wire_dtype for b in emb.plan.tp_buckets}))
    id_wires = tuple(sorted({b.id_wire_dtype
                             for b in emb.plan.tp_buckets}))
    return wires or ("f32",), id_wires or ("int32",), len(groups)


def program_matrix(vocab: int = 4096, width: int = 16, tables: int = 4,
                   batch: int = 32, hotness: int = 2,
                   optimizer: str = "adagrad",
                   world: int = 8) -> List[Program]:
    """Lower the standard program matrix over a `world`-device mesh —
    ONE lowering per program, every pass runs on the shared parse.

    Programs: monolithic train step (f32 + bf16 wire), lookahead
    fused + prefetch, serve forward, vocab-slack plan (int32 id wire —
    the big-vocab end of the id-narrowing gate)."""
    import jax
    import jax.numpy as jnp
    from ..parallel.mesh import create_mesh
    from ..schedule import LookaheadEngine
    from ..training import default_donate

    devs = jax.devices()
    if len(devs) < world:
        raise RuntimeError(
            f"program matrix needs {world} devices, have {len(devs)} — "
            "call ensure_world() before the backend initializes")
    mesh = create_mesh(devs[:world])
    donate = default_donate()
    programs: List[Program] = []

    def steps(name, wire, vocab_, slack=0, weighted=False,
              strategy="auto", sort_bound=None):
        model = build_model(vocab_, width, "sum", tables=tables,
                            mesh=mesh, exchange_wire=wire,
                            dense_head=True, vocab_slack=slack,
                            weighted=weighted)
        emb = model.embedding
        model._head_width = head_params(tables, width, hotness, "sum")
        lowered, _, _ = _lower_step(model, optimizer, donate, batch,
                                    hotness, tables, strategy=strategy)
        wires, id_wires, n_groups = _plan_wires(emb)
        ctx = PlanContext(
            program=name, wire_dtypes=wires, id_wire_dtypes=id_wires,
            sort_bound=(n_groups if sort_bound is None else sort_bound),
            donate_expected=donate,
            overlap={"max_candidates": 0},
            expected_bytes=expected_collective_bytes(
                emb, [hotness] * tables, batch, weighted=weighted,
                train=True))
        programs.append(Program(name=name, text=lowered.as_text(),
                                ctx=ctx))
        return model, emb

    # 1+2: the monolithic step at both float wires (the bf16 arm is the
    # compiled form of the 2.0x wire claim; exact bytes, not a ratio)
    model, emb = steps("monolithic_f32", "f32", vocab)
    steps("monolithic_bf16", "bf16", vocab, weighted=True)

    # 3: vocab-slack plan (ISSUE 7's growth rows; big vocab -> int32 id
    # wire, so both narrowing verdicts are represented in the matrix)
    steps("vocab_slack_step", "f32", 40_000, slack=256)

    # 3b+3c (ISSUE 12): the monolithic model under the tiled and the
    # fused pallas scatter strategies. The tiled arm is the baseline the
    # fused arm is measured against: the pallas arm's sort bound is the
    # tiled lowering's MEASURED sort count (zero extra sorts — its dedup
    # must consume the folded forward sort, never add one), and both
    # arms carry the exact padding-report byte model (zero collective
    # deltas — the update strategy must not change what moves on the
    # wire; the collective-bytes pass asserts compiled == model exactly
    # on each). tools/hlo_audit.py's mutation fixture
    # 'pallas-arm-extra-sort' proves this arm can fail.
    steps("monolithic_tiled", "f32", vocab, strategy="tiled")
    tiled_sorts = ir.op_counts(programs[-1].module, ops=("sort",))["sort"]
    steps("pallas_strategy_step", "f32", vocab, strategy="pallas",
          sort_bound=tiled_sorts)

    # 4+5: lookahead fused + prefetch from the SAME model as the
    # monolithic arm — the fused step's prefetch collectives must all be
    # overlap candidates, the monolithic arm pinned zero above, and the
    # fused lowering must add no sorts over the monolithic bound
    params = {"embedding": emb.init(jax.random.PRNGKey(0)),
              "head": head_params(tables, width, hotness, "sum")}
    engine = LookaheadEngine(model, optimizer, lr=0.01, donate=False)
    state = engine.init(params)
    num = jnp.zeros((batch, 1), jnp.float32)
    cats = [jnp.zeros((batch, hotness), jnp.int32) for _ in range(tables)]
    lab = jnp.zeros((batch,), jnp.float32)
    b0 = (num, cats, lab)
    pre_text = engine.lower_prefetch(params, cats).as_text()
    fused_text = engine.lower_fused(params, state, b0, b0).as_text()
    wires, id_wires, n_groups = _plan_wires(emb)
    # cross-program bounds come from the already-parsed modules — no
    # program is parsed twice anywhere in an audit run
    pre_module = ir.parse_module(pre_text)
    pre_total = ir.collective_overlap(pre_module)["collectives_total"]
    mono_sorts = ir.op_counts(programs[0].module, ops=("sort",))["sort"]
    programs.append(Program(
        name="lookahead_prefetch", text=pre_text, module=pre_module,
        ctx=PlanContext(
            program="lookahead_prefetch", wire_dtypes=wires,
            id_wire_dtypes=id_wires, sort_bound=n_groups,
            overlap={"all_candidates": True},
            expected_bytes=expected_collective_bytes(
                emb, [hotness] * tables, batch, train=False))))
    programs.append(Program(
        name="lookahead_fused", text=fused_text,
        ctx=PlanContext(
            program="lookahead_fused", wire_dtypes=wires,
            id_wire_dtypes=id_wires,
            # PR 2 gate carried over: the staged restructure must add
            # ZERO sorts vs the monolithic lowering of the same model
            sort_bound=mono_sorts,
            overlap={"min_candidates": pre_total})))

    # 6: serve forward — the apply-only program InferenceEngine jits;
    # forward-only bytes, no dense compute (overlap is vacuous -> skip)
    import jax as _jax
    sp = {"embedding": emb.init(_jax.random.PRNGKey(0))}
    serve_text = _jax.jit(
        lambda p, i: emb.apply(p["embedding"], list(i))).lower(
        sp, cats).as_text()
    programs.append(Program(
        name="serve_forward", text=serve_text,
        ctx=PlanContext(
            program="serve_forward", wire_dtypes=wires,
            id_wire_dtypes=id_wires, sort_bound=n_groups,
            donate_expected=False,
            expected_bytes=expected_collective_bytes(
                emb, [hotness] * tables, batch, train=False)),
        skip_passes=("collective-overlap",)))

    # 7: quantized-storage serve forward (ISSUE 15) — an offloaded
    # bucket at storage_dtype='int8': the lowered program must carry i8
    # row buffers, every one attributable to the declared dtype (the
    # storage-dtype pass is vacuous on programs 1-6, which declare
    # ('f32',) and must lower ZERO quantized buffers). Byte model
    # skipped: the offloaded activation return is a GSPMD resharding,
    # not a seam collective, so expected_collective_bytes does not
    # model this program; the wire-seam pass still polices every
    # collective payload it does emit.
    # per-RANK element budget (offload flags on post-slicing per-rank
    # configs): under it every table offloads into one quantized bucket
    q_model = build_model(vocab, width, "sum", tables=tables, mesh=mesh,
                          gpu_embedding_size=(vocab * width) // world,
                          storage_dtype="int8")
    q_emb = q_model.embedding
    assert q_emb.quantized_buckets, \
        "quantized_store_serve: budget failed to offload any bucket"
    q_sp = {"embedding": q_emb.init(_jax.random.PRNGKey(0))}
    q_text = _jax.jit(
        lambda p, i: q_emb.apply(p["embedding"], list(i))).lower(
        q_sp, cats).as_text()
    q_wires, q_id_wires, q_groups = _plan_wires(q_emb)
    programs.append(Program(
        name="quantized_store_serve", text=q_text,
        ctx=PlanContext(
            program="quantized_store_serve", wire_dtypes=q_wires,
            id_wire_dtypes=q_id_wires, sort_bound=q_groups,
            donate_expected=False,
            storage_dtypes=tuple(sorted(
                {b.storage_dtype for b in q_emb.plan.tp_buckets}))),
        skip_passes=("collective-overlap",)))

    # 8: HBM-resident quantized serve forward (ISSUE 17) — the same
    # int8 declaration with NO offload budget, so every bucket stays
    # device-resident and quantizes under the lifted planner gate. The
    # i8 payload tables and their f32 per-row scales enter the jitted
    # program as params and decode at gather time, so the lowering must
    # carry i8 buffers attributable to the declaration — and the
    # declared-but-f32 direction of the storage-dtype pass proves the
    # declaration actually reached the compiled program (a plan that
    # says 'int8' over an all-f32 lowering now flags instead of
    # silently shipping 4x the HBM).
    h_model = build_model(vocab, width, "sum", tables=tables, mesh=mesh,
                          storage_dtype="int8")
    h_emb = h_model.embedding
    assert h_emb.quantized_buckets and not any(
        b.offload for b in h_emb.plan.tp_buckets), \
        "quantized_hbm_serve: expected device-resident quantized buckets"
    h_sp = {"embedding": h_emb.init(_jax.random.PRNGKey(0))}
    h_text = _jax.jit(
        lambda p, i: h_emb.apply(p["embedding"], list(i))).lower(
        h_sp, cats).as_text()
    h_wires, h_id_wires, h_groups = _plan_wires(h_emb)
    programs.append(Program(
        name="quantized_hbm_serve", text=h_text,
        ctx=PlanContext(
            program="quantized_hbm_serve", wire_dtypes=h_wires,
            id_wire_dtypes=h_id_wires, sort_bound=h_groups,
            donate_expected=False,
            storage_dtypes=tuple(sorted(
                {b.storage_dtype for b in h_emb.plan.tp_buckets}))),
        skip_passes=("collective-overlap",)))
    return programs


# ------------------------------------------------------ mutation fixtures
@dataclasses.dataclass
class MutationCase:
    """A program that deliberately violates ONE invariant. The driver
    runs only ``pass_name`` over it and must get exactly
    ``expect_fids`` — proof the gate can fail."""

    name: str
    pass_name: str
    text: str
    ctx: PlanContext
    expect_fids: tuple


_MUT_TWO_SORTS = """
module @m {
  func.func public @main(%arg0: tensor<8xf32>) -> tensor<8xf32> {
    %0 = "stablehlo.sort"(%arg0) <{dimension = 0 : i64, is_stable = true}> ({
    ^bb0(%a0: tensor<f32>, %b0: tensor<f32>):
      %c0 = stablehlo.compare LT, %a0, %b0 : (tensor<f32>, tensor<f32>) -> tensor<i1>
      stablehlo.return %c0 : tensor<i1>
    }) : (tensor<8xf32>) -> tensor<8xf32>
    %1 = "stablehlo.sort"(%0) <{dimension = 0 : i64, is_stable = true}> ({
    ^bb0(%a1: tensor<f32>, %b1: tensor<f32>):
      %c1 = stablehlo.compare LT, %a1, %b1 : (tensor<f32>, tensor<f32>) -> tensor<i1>
      stablehlo.return %c1 : tensor<i1>
    }) : (tensor<8xf32>) -> tensor<8xf32>
    return %1 : tensor<8xf32>
  }
}
"""

_MUT_BF16_ON_F32_WIRE = """
module @m {
  func.func public @main(%arg0: tensor<8x4xf32>) -> tensor<8x4xf32> {
    %0 = stablehlo.convert %arg0 : (tensor<8x4xf32>) -> tensor<8x4xbf16>
    %1 = "stablehlo.all_to_all"(%0) <{concat_dimension = 0 : i64, split_count = 8 : i64, split_dimension = 0 : i64}> : (tensor<8x4xbf16>) -> tensor<8x4xbf16>
    %2 = stablehlo.convert %1 : (tensor<8x4xbf16>) -> tensor<8x4xf32>
    return %2 : tensor<8x4xf32>
  }
}
"""

_MUT_FREE_COLLECTIVE = """
module @m {
  func.func public @main(%arg0: tensor<8xf32>, %arg1: tensor<8x8xf32>) -> tensor<8xf32> {
    %0 = "stablehlo.all_to_all"(%arg0) <{concat_dimension = 0 : i64, split_count = 8 : i64, split_dimension = 0 : i64}> : (tensor<8xf32>) -> tensor<8xf32>
    %1 = stablehlo.dot_general %arg1, %arg1, contracting_dims = [1] x [0] : (tensor<8x8xf32>, tensor<8x8xf32>) -> tensor<8x8xf32>
    return %0 : tensor<8xf32>
  }
}
"""

_MUT_SERIAL_COLLECTIVE = """
module @m {
  func.func public @main(%arg0: tensor<8x8xf32>) -> tensor<8x8xf32> {
    %0 = "stablehlo.all_to_all"(%arg0) <{concat_dimension = 0 : i64, split_count = 8 : i64, split_dimension = 0 : i64}> : (tensor<8x8xf32>) -> tensor<8x8xf32>
    %1 = stablehlo.dot_general %0, %arg0, contracting_dims = [1] x [0] : (tensor<8x8xf32>, tensor<8x8xf32>) -> tensor<8x8xf32>
    return %1 : tensor<8x8xf32>
  }
}
"""

_MUT_F64 = """
module @m {
  func.func public @main(%arg0: tensor<8xf32>) -> tensor<8xf32> {
    %0 = stablehlo.convert %arg0 : (tensor<8xf32>) -> tensor<8xf64>
    %1 = stablehlo.add %0, %0 : tensor<8xf64>
    %2 = stablehlo.convert %1 : (tensor<8xf64>) -> tensor<8xf32>
    return %2 : tensor<8xf32>
  }
}
"""

_MUT_DUP_COLLECTIVE = """
module @m {
  func.func public @main(%arg0: tensor<8xf32>) -> tensor<64xf32> {
    %0 = "stablehlo.all_gather"(%arg0) <{all_gather_dim = 0 : i64}> : (tensor<8xf32>) -> tensor<64xf32>
    %1 = "stablehlo.all_gather"(%arg0) <{all_gather_dim = 0 : i64}> : (tensor<8xf32>) -> tensor<64xf32>
    %2 = stablehlo.add %0, %1 : tensor<64xf32>
    return %2 : tensor<64xf32>
  }
}
"""

_MUT_QUANT_BUFFER = """
module @m {
  func.func public @main(%arg0: tensor<8x4xf32>) -> tensor<8x4xf32> {
    %0 = stablehlo.convert %arg0 : (tensor<8x4xf32>) -> tensor<8x4xi8>
    %1 = stablehlo.convert %0 : (tensor<8x4xi8>) -> tensor<8x4xf32>
    return %1 : tensor<8x4xf32>
  }
}
"""

_MUT_F32_UNDER_INT8_DECL = """
module @m {
  func.func public @main(%arg0: tensor<8x4xf32>, %arg1: tensor<2xi32>) -> tensor<2x4xf32> {
    %0 = "stablehlo.gather"(%arg0, %arg1) {dimension_numbers = #stablehlo.gather<offset_dims = [1], collapsed_slice_dims = [0], start_index_map = [0], index_vector_dim = 1>, slice_sizes = array<i64: 1, 4>} : (tensor<8x4xf32>, tensor<2xi32>) -> tensor<2x4xf32>
    return %0 : tensor<2x4xf32>
  }
}
"""

_MUT_DEAD_COLLECTIVE = """
module @m {
  func.func public @main(%arg0: tensor<8xf32>) -> tensor<8xf32> {
    %0 = "stablehlo.all_gather"(%arg0) <{all_gather_dim = 0 : i64}> : (tensor<8xf32>) -> tensor<64xf32>
    %1 = stablehlo.add %arg0, %arg0 : tensor<8xf32>
    return %1 : tensor<8xf32>
  }
}
"""


def _lower_naked_collective() -> str:
    """A REAL jax lowering of a naked `lax.all_to_all` around the seam —
    an f32 payload in a program whose plan declares a bf16 wire, the
    exact seam escape the wire-seam pass exists to catch (and the
    Python-side twin of tools/lint_invariants.py's 'naked-collective'
    AST rule, which would flag this source before it ever lowered)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P
    from .. import compat
    from ..parallel.mesh import create_mesh

    mesh = create_mesh(jax.devices()[:8])
    f = compat.shard_map(
        # the seeded violation itself — lint: allow(naked-collective)
        lambda x: lax.all_to_all(x, "mp", split_axis=0, concat_axis=0),
        mesh=mesh, in_specs=P("mp"), out_specs=P("mp"))
    return jax.jit(f).lower(jnp.zeros((64, 4), jnp.float32)).as_text()


def _lower_donated() -> str:
    """A REAL donated lowering (jax.buffer_donor arg attrs) for the
    donation-policy mutation."""
    import jax
    import jax.numpy as jnp
    return jax.jit(lambda x: x + 1.0, donate_argnums=0).lower(
        jnp.zeros((16, 16), jnp.float32)).as_text()


def mutation_cases() -> List[MutationCase]:
    """One seeded violation per pass (two for overlap/dead-dup: both
    failure directions). Each must produce EXACTLY its expected finding
    ids when its pass runs — asserted in CI by `hlo_audit.py --assert`
    (mutations run by default; `--skip-mutations` opts out) and by
    tests/test_analysis.py."""
    bf16_ctx = PlanContext(program="mutation", wire_dtypes=("bf16",),
                           id_wire_dtypes=("int16",))
    return [
        MutationCase(
            name="two-sorts-over-bound", pass_name="op-counts",
            text=_MUT_TWO_SORTS,
            ctx=PlanContext(program="mutation", sort_bound=1),
            expect_fids=("op-counts/sort-over-bound",)),
        MutationCase(
            name="pallas-arm-extra-sort", pass_name="op-counts",
            text=_MUT_TWO_SORTS,
            # the ISSUE 12 pallas-strategy arm's gate, seeded violated: a
            # fused step that re-sorts past the tiled baseline's measured
            # count must flag (blind-gate discipline — the arm can fail)
            ctx=PlanContext(program="pallas_strategy_step", sort_bound=1),
            expect_fids=("op-counts/sort-over-bound",)),
        MutationCase(
            name="bf16-bytes-on-f32-wire", pass_name="collective-bytes",
            text=_MUT_BF16_ON_F32_WIRE,
            ctx=PlanContext(program="mutation", wire_dtypes=("f32",)),
            expect_fids=("collective-bytes/bf16-in-f32-program",)),
        MutationCase(
            name="free-collective-in-sequential-contract",
            pass_name="collective-overlap", text=_MUT_FREE_COLLECTIVE,
            ctx=PlanContext(program="mutation",
                            overlap={"max_candidates": 0}),
            expect_fids=("collective-overlap/unexpected-candidates",)),
        MutationCase(
            name="serialized-prefetch", pass_name="collective-overlap",
            text=_MUT_SERIAL_COLLECTIVE,
            ctx=PlanContext(program="mutation",
                            overlap={"min_candidates": 1}),
            expect_fids=("collective-overlap/candidates-under-bound",)),
        MutationCase(
            name="naked-lax-all-to-all", pass_name="wire-seam",
            text=_lower_naked_collective(), ctx=bf16_ctx,
            expect_fids=("wire-seam/escape.all_to_all.f32",)),
        MutationCase(
            name="donated-under-donation-off-policy",
            pass_name="donation", text=_lower_donated(),
            ctx=PlanContext(program="mutation", donate_expected=False),
            expect_fids=("donation/unexpected-donation",)),
        MutationCase(
            name="forced-f64-upcast", pass_name="dtype-promotion",
            text=_MUT_F64, ctx=PlanContext(program="mutation"),
            expect_fids=("dtype-promotion/f64",)),
        MutationCase(
            name="f32-leak-on-bf16-wire", pass_name="dtype-promotion",
            text=_MUT_FREE_COLLECTIVE, ctx=bf16_ctx,
            expect_fids=("dtype-promotion/f32-wire-leak.all_to_all",)),
        MutationCase(
            # ISSUE 15: an int8 buffer in a program whose plan declares
            # only f32 storage — a row table quantized outside the
            # ops/wire.py storage seam (the blind-gate fixture of the
            # storage-dtype pass)
            name="quantized-buffer-under-f32-storage",
            pass_name="storage-dtype", text=_MUT_QUANT_BUFFER,
            ctx=PlanContext(program="mutation",
                            storage_dtypes=("f32",)),
            expect_fids=("storage-dtype/undeclared.i8",)),
        MutationCase(
            # ISSUE 17 (inverse direction): the plan declares int8
            # storage but every buffer in the lowered program is f32 —
            # an HBM-resident table whose quantization was silently
            # dropped (the declared ~4x HBM saving never compiled in)
            name="declared-int8-but-f32-buffers",
            pass_name="storage-dtype", text=_MUT_F32_UNDER_INT8_DECL,
            ctx=PlanContext(program="mutation",
                            storage_dtypes=("f32", "int8")),
            expect_fids=("storage-dtype/declared-but-f32.i8",)),
        MutationCase(
            name="self-duplicated-collective",
            pass_name="dead-dup-collective", text=_MUT_DUP_COLLECTIVE,
            ctx=PlanContext(program="mutation"),
            expect_fids=("dead-dup-collective/duplicate.all_gather",)),
        MutationCase(
            name="dead-fanout-collective",
            pass_name="dead-dup-collective", text=_MUT_DEAD_COLLECTIVE,
            ctx=PlanContext(program="mutation"),
            expect_fids=("dead-dup-collective/dead.all_gather",)),
    ]


# ------------------------------------------------------------ legacy arms
# Per-arm audit entry points predating the pass matrix, kept because
# bench.py embeds them in every hardware record (`hlo_sort_audit`,
# `wire_hlo`) and their bounds are shape-parameterized in ways the fixed
# matrix is not (30M-row vocabs, tiled lookup, hot shards). They run on
# the same IR measurements as the passes.

def audit_tapped_step(vocab: int = 30_000_000, width: int = 8,
                      batch: int = 8, hotness: int = 4,
                      optimizer: str = "adagrad", strategy: str = "sort",
                      lookup_path: Optional[str] = None, fold: bool = True,
                      combiner: str = "sum", hot_rows: int = 0) -> dict:
    """Lower one tapped sparse train step (abstract avals — no giant
    table is materialized) and count its StableHLO ops. Returns the
    counts plus the exchange-group count the sort bound is measured
    against (one canonical sort per group, +1 per group for the tiled
    forward's inverse-permute; hot_rows adds ZERO — the PR 4 gate)."""
    import jax
    import jax.numpy as jnp
    from ..training import make_sparse_train_step

    prev = os.environ.get("DET_LOOKUP_PATH")
    try:
        if lookup_path is None:
            os.environ.pop("DET_LOOKUP_PATH", None)
        else:
            os.environ["DET_LOOKUP_PATH"] = lookup_path
        model = build_model(vocab, width, combiner, hot_rows=hot_rows)
        emb = model.embedding
        init_fn, step_fn = make_sparse_train_step(
            model, optimizer, lr=0.01, strategy=strategy, fold_sort=fold)
        params = jax.eval_shape(
            lambda: {"embedding": emb.init(jax.random.PRNGKey(0))})
        state = jax.eval_shape(init_fn, params)
        num = jax.ShapeDtypeStruct((batch, 1), jnp.float32)
        cats = [jax.ShapeDtypeStruct((batch, hotness), jnp.int32)]
        lab = jax.ShapeDtypeStruct((batch,), jnp.float32)
        lowered = jax.jit(step_fn).lower(params, state, num, cats, lab)
        counts = ir.op_counts(lowered.as_text())
        key = ((hotness, False),)
        groups, _ = emb._exchange_groups_for_key(key)
        n_groups = len(groups)
    finally:
        if prev is None:
            os.environ.pop("DET_LOOKUP_PATH", None)
        else:
            os.environ["DET_LOOKUP_PATH"] = prev
    # the bound the fold ships under: one canonical sort per exchange
    # group, plus the tiled/fused forward gather's inverse-permute sort
    # (the one residual sort — scatter-free inversion needs a second
    # sort op; the fused gather->combine consumes the same artifact)
    bound = n_groups * (2 if lookup_path in ("tiled", "fused") else 1)
    return {
        "optimizer": optimizer, "strategy": strategy,
        "lookup_path": lookup_path or "default", "fold": fold,
        "hot_rows": hot_rows,
        "n_exchange_groups": n_groups, "sort_bound": bound,
        **{f"hlo_{k}": v for k, v in counts.items()},
    }


def audit_exchange_bytes(wire: str = "f32", vocab: int = 4096,
                         width: int = 32, tables: int = 8, batch: int = 16,
                         hotness: int = 2, optimizer: str = "adagrad",
                         world: int = 8) -> dict:
    """Lower the tapped sparse train step over a `world`-device mesh at
    one exchange-wire format and return its collective-byte accounting
    (plus the per-group padding-report byte fields, so the static claim
    and the compiled HLO can be cross-checked in one record)."""
    import jax
    import jax.numpy as jnp
    from ..parallel.mesh import create_mesh
    from ..training import make_sparse_train_step

    devs = jax.devices()
    if len(devs) < world:
        return {"wire": wire, "skipped":
                f"need {world} devices for the meshed lowering, "
                f"have {len(devs)}"}
    mesh = create_mesh(devs[:world])
    model = build_model(vocab, width, "sum", tables=tables, mesh=mesh,
                        exchange_wire=wire)
    emb = model.embedding
    init_fn, step_fn = make_sparse_train_step(model, optimizer, lr=0.01)
    params = {"embedding": emb.init(jax.random.PRNGKey(0))}
    state = init_fn(params)
    num = jnp.zeros((batch, 1), jnp.float32)
    cats = [jnp.zeros((batch, hotness), jnp.int32) for _ in range(tables)]
    lab = jnp.zeros((batch,), jnp.float32)
    text = jax.jit(step_fn).lower(params, state, num, cats,
                                  lab).as_text()
    mod = ir.parse_module(text)
    bytes_ = ir.collective_bytes(mod)
    rep = emb.exchange_padding_report(hotness=[hotness] * tables)
    return {
        "wire": wire, "optimizer": optimizer, "world": world,
        "vocab": vocab, "width": width, "tables": tables, "batch": batch,
        "hotness": hotness,
        "collective_float_bytes": bytes_["float_bytes"],
        "collective_int_bytes": bytes_["int_bytes"],
        "collective_bytes_by_dtype": bytes_["total"],
        "expected_bytes_by_dtype": expected_collective_bytes(
            emb, [hotness] * tables, batch),
        "report_act_bytes": rep["act_bytes"],
        "report_act_bytes_f32": rep["act_bytes_f32"],
        "report_act_wire_reduction": round(rep["act_wire_reduction"], 3),
        "report_exchanged_bytes": rep["exchanged_bytes"],
        "report_true_bytes": rep["true_bytes"],
        "id_narrowed_groups": rep["id_narrowed_groups"],
        **{f"hlo_{k}": v for k, v in ir.op_counts(mod).items()},
    }


def audit_lookahead_overlap(vocab: int = 4096, width: int = 32,
                            tables: int = 4, batch: int = 64,
                            hotness: int = 2, optimizer: str = "adagrad",
                            world: int = 8, stale_ok: bool = False) -> dict:
    """Lower the lookahead engine's FUSED staged step over a
    `world`-device mesh and prove, on the dependency graph of the
    StableHLO, that batch N+1's exchange collectives carry NO data
    dependency on batch N's dense compute (ISSUE 9) — the static twin of
    an ICI/MXU overlap measurement, checkable without hardware.
    Three lowerings, one record: the fused step, the standalone prefetch
    (defines the collective count the candidates must cover), and the
    monolithic baseline (must audit to ZERO candidates and pins the
    zero-extra-sorts bound)."""
    import jax
    import jax.numpy as jnp
    from ..parallel.mesh import create_mesh
    from ..schedule import LookaheadEngine
    from ..training import make_sparse_train_step

    devs = jax.devices()
    if len(devs) < world:
        return {"arm": "lookahead_overlap", "skipped":
                f"need {world} devices for the meshed lowering, "
                f"have {len(devs)}"}
    mesh = create_mesh(devs[:world])
    model = build_model(vocab, width, "sum", tables=tables, mesh=mesh,
                        dense_head=True)
    emb = model.embedding
    params = {"embedding": emb.init(jax.random.PRNGKey(0)),
              "head": head_params(tables, width, hotness, "sum")}
    engine = LookaheadEngine(model, optimizer, lr=0.01,
                             stale_ok=stale_ok, donate=False)
    state = engine.init(params)
    num = jnp.zeros((batch, 1), jnp.float32)
    cats = [jnp.zeros((batch, hotness), jnp.int32) for _ in range(tables)]
    lab = jnp.zeros((batch,), jnp.float32)
    b0 = (num, cats, lab)

    fused_txt = engine.lower_fused(params, state, b0, b0).as_text()
    pre_txt = engine.lower_prefetch(params, cats).as_text()
    init2, step2 = make_sparse_train_step(model, optimizer, lr=0.01,
                                          donate=False)
    base_txt = jax.jit(step2).lower(params, init2(params), num, cats,
                                    lab).as_text()

    fused_ov = ir.collective_overlap(fused_txt)
    pre_ov = ir.collective_overlap(pre_txt)
    base_ov = ir.collective_overlap(base_txt)
    fused_sorts = ir.op_counts(fused_txt)["sort"]
    base_sorts = ir.op_counts(base_txt)["sort"]
    rec = {
        "arm": "lookahead_overlap", "optimizer": optimizer,
        "world": world, "vocab": vocab, "width": width, "tables": tables,
        "batch": batch, "hotness": hotness, "stale_ok": stale_ok,
        "fused_collectives": fused_ov["collectives_total"],
        "fused_overlap_candidates": fused_ov["overlap_candidates"],
        "fused_candidates_by_op": fused_ov["candidates_by_op"],
        "prefetch_collectives": pre_ov["collectives_total"],
        "baseline_collectives": base_ov["collectives_total"],
        "baseline_overlap_candidates": base_ov["overlap_candidates"],
        "fused_sorts": fused_sorts, "baseline_sorts": base_sorts,
        "extra_sorts": fused_sorts - base_sorts,
    }
    rec["over_bound"] = bool(
        rec["prefetch_collectives"] == 0
        or rec["fused_overlap_candidates"] < rec["prefetch_collectives"]
        or rec["baseline_overlap_candidates"] != 0
        or rec["extra_sorts"] > 0)
    return rec


# minimum float-collective-byte shrink the bf16 wire must show vs f32 on
# the same lowered step — the wire moves half the bits, so the compiled
# ratio is 2.0 minus whatever small float traffic is not behind the seam
WIRE_BYTE_MIN_REDUCTION = 1.9


def wire_byte_arms(**kw) -> list:
    """The f32-vs-bf16 collective-byte A/B records (+ derived reduction
    stamped on the bf16 record)."""
    base = audit_exchange_bytes(wire="f32", **kw)
    comp = audit_exchange_bytes(wire="bf16", **kw)
    if "skipped" not in comp and "skipped" not in base:
        fb = base["collective_float_bytes"]
        cb = comp["collective_float_bytes"]
        comp["float_bytes_reduction_vs_f32"] = (
            round(fb / cb, 3) if cb else None)
        comp["min_reduction_required"] = WIRE_BYTE_MIN_REDUCTION
        base["bf16_collective_bytes"] = (
            base["collective_bytes_by_dtype"].get("bf16", 0))
    return [base, comp]
