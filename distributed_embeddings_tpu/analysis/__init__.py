"""Static program analysis over lowered StableHLO (ISSUE 10).

One parse, many auditors: ``ir`` is the typed IR layer (functions /
instructions / operands / results with dtype+shape+attrs and the
interprocedural call graph through jax's private ``shmap_body``
structure), ``passes`` is the invariant-check framework
(``(Module, PlanContext) -> list[Finding]``), and ``programs`` builds
the standard audited program matrix plus the mutation fixtures that
prove every pass can fail. ``tools/hlo_audit.py`` is the CLI driver;
docs/analysis.md is the catalog.
"""

from . import ir, passes  # noqa: F401  (programs imports jax-heavy deps lazily)
from .ir import (Module, parse_module, op_counts, collective_bytes,  # noqa: F401
                 collective_overlap)
from .passes import (Finding, PlanContext, run_passes,  # noqa: F401
                     list_passes, PASS_REGISTRY)

__all__ = ["ir", "passes", "Module", "parse_module", "op_counts",
           "collective_bytes", "collective_overlap", "Finding",
           "PlanContext", "run_passes", "list_passes", "PASS_REGISTRY"]
