"""Static-analysis pass framework over the StableHLO IR (ISSUE 10).

A pass is ``(Module, PlanContext) -> list[Finding]``: it proves one
repo invariant about a LOWERED program and reports violations as typed
findings with a severity, an op location, and a STABLE finding id
(content-derived — op kind + dtype + rule, never a line number — so the
checked-in allowlist ``tools/audit_baseline.json`` diffs like a
snapshot across recompiles).

The catalog (docs/analysis.md has the long form, and every pass carries
a mutation fixture that CI proves it flags — an auditor that cannot
fail is not a gate):

  op-counts            sort mentions <= the plan's folded bound (PR 2)
  collective-bytes     measured payload bytes == the padding-report
                       model, per dtype; zero bf16 bytes in an f32-wire
                       program (PR 5)
  collective-overlap   dependency classification of every collective vs
                       the dense compute matches the program's schedule
                       contract (PR 8)
  wire-seam            every exchange collective's payload dtype is
                       attributable to a plan group's declared
                       wire_dtype/id_wire_dtype — an unattributed
                       collective is a seam escape (new)
  donation             input-output aliasing vs the default_donate()
                       policy — the PR 5 XLA:CPU donation+cache
                       miscompile class, statically detectable (new)
  dtype-promotion      no f64 anywhere; no f32 payload feeding a seam
                       collective in an all-bf16-wire program (new)
  dead-dup-collective  no two collectives with identical operand SSA
                       sources + attrs; no collective whose result has
                       empty transitive fan-out (new)
  storage-dtype        every quantized (i8/f8*) buffer in the program is
                       attributable to a plan bucket's declared
                       storage_dtype — an i8 tensor in an all-f32-storage
                       program is a storage-seam escape (ISSUE 15)
"""

from __future__ import annotations

import dataclasses
import re
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import ir

__all__ = ["Finding", "PlanContext", "register_pass", "run_passes",
           "list_passes", "PASS_REGISTRY"]


@dataclasses.dataclass
class Finding:
    """One invariant violation in one lowered program."""

    pass_name: str
    fid: str                      # stable id, allowlist key
    severity: str                 # 'error' | 'warning'
    message: str
    func: str = ""                # function the finding anchors to
    line: int = 0                 # source line (display only, NOT in fid)
    op: str = ""                  # op mnemonic involved

    def to_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class PlanContext:
    """What the PLAN says the lowered program must look like — the
    second input of every pass. Built by the driver
    (``analysis.programs`` / ``tools/hlo_audit.py``) from the model's
    plan plus the program's build parameters; ``None`` fields disable
    the corresponding check (a context-free pass run is a no-op, not a
    failure)."""

    program: str = "program"
    platform: str = "cpu"
    # declared float/id wire formats over the plan's exchange groups
    # (ops/wire.py seam hooks translate them to StableHLO dtypes)
    wire_dtypes: Tuple[str, ...] = ("f32",)
    id_wire_dtypes: Tuple[str, ...] = ("int32",)
    # the ragged CPU emulation moves its i32 split metadata through
    # all_gathers (ops/wire.py ragged_exchange); padded-path programs
    # leave this False so a stray i32 collective cannot hide behind it
    ragged_emulation: bool = False
    # declared at-rest storage dtypes over the plan's tp buckets
    # (ISSUE 15); ('f32',) declares NO quantized buffer anywhere — the
    # storage-dtype pass flags every i8/f8 tensor it then finds
    storage_dtypes: Tuple[str, ...] = ("f32",)
    sort_bound: Optional[int] = None
    donate_expected: Optional[bool] = None
    # {"max_candidates": n} | {"min_candidates": n} |
    # {"all_candidates": True} — see collective-overlap
    overlap: Optional[dict] = None
    # exact per-device payload bytes by dtype, usually from
    # analysis.programs.expected_collective_bytes
    expected_bytes: Optional[Dict[str, int]] = None


PASS_REGISTRY: "Dict[str, Tuple[Callable, str]]" = {}


def register_pass(name: str, doc: str):
    def deco(fn):
        PASS_REGISTRY[name] = (fn, doc)
        return fn
    return deco


def list_passes() -> List[Tuple[str, str]]:
    return [(name, doc) for name, (_, doc) in PASS_REGISTRY.items()]


def run_passes(module, ctx: PlanContext,
               passes: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the selected passes (default: all, registration order) over
    one parsed module. Accepts raw StableHLO text or a lowered object;
    parse once, reuse the Module across passes."""
    mod = module if isinstance(module, ir.Module) else \
        ir.parse_module(module)
    names = list(passes) if passes is not None else list(PASS_REGISTRY)
    findings: List[Finding] = []
    for name in names:
        fn, _ = PASS_REGISTRY[name]
        findings.extend(fn(mod, ctx))
    return findings


# ------------------------------------------------------------ the passes
@register_pass("op-counts",
               "sort mentions <= the plan's folded sort bound (PR 2)")
def op_counts_pass(mod: ir.Module, ctx: PlanContext) -> List[Finding]:
    if ctx.sort_bound is None:
        return []
    n = ir.op_counts(mod, ops=("sort",))["sort"]
    if n <= ctx.sort_bound:
        return []
    return [Finding(
        pass_name="op-counts", fid="op-counts/sort-over-bound",
        severity="error", op="sort",
        message=(f"{n} stablehlo.sort mentions, plan bound is "
                 f"{ctx.sort_bound} (one canonical sort per exchange "
                 f"group; docs/perf_model.md 'Sort folding')"))]


@register_pass("collective-bytes",
               "collective payload bytes == the padding-report model, "
               "per dtype; zero bf16 bytes on the f32 wire (PR 5)")
def collective_bytes_pass(mod: ir.Module,
                          ctx: PlanContext) -> List[Finding]:
    measured = ir.collective_bytes(mod)
    out: List[Finding] = []
    # declared wire FORMATS ('f32'/'bf16'/'bf16-sr') map to payload
    # element types through the seam hooks — 'bf16-sr' puts bf16 on the
    # wire, so the zero-compressed-bytes contract only binds plans whose
    # formats all decode to f32
    floats, _ = _allowed_payload_dtypes(ctx)
    if "bf16" not in floats and measured["total"].get("bf16", 0):
        out.append(Finding(
            pass_name="collective-bytes",
            fid="collective-bytes/bf16-in-f32-program",
            severity="error", op="*",
            message=(f"{measured['total']['bf16']} bf16 collective "
                     "payload bytes in a program whose plan declares no "
                     "bf16 wire — the f32 default's bit-exactness "
                     "contract moves ZERO compressed bytes")))
    if ctx.expected_bytes is not None:
        for dtype in sorted(set(ctx.expected_bytes)
                            | set(measured["total"])):
            want = ctx.expected_bytes.get(dtype, 0)
            got = measured["total"].get(dtype, 0)
            if want != got:
                out.append(Finding(
                    pass_name="collective-bytes",
                    fid=f"collective-bytes/model-mismatch.{dtype}",
                    severity="error", op="*",
                    message=(f"{dtype} collective payload: HLO measures "
                             f"{got} bytes/device, the "
                             f"exchange_padding_report model says {want} "
                             "— the static claim and the compiled "
                             "program disagree")))
    return out


@register_pass("collective-overlap",
               "dependency classification of collectives vs dense "
               "compute matches the schedule contract (PR 8)")
def collective_overlap_pass(mod: ir.Module,
                            ctx: PlanContext) -> List[Finding]:
    if not ctx.overlap:
        return []
    ov = ir.collective_overlap(mod)
    out: List[Finding] = []
    cand, total = ov["overlap_candidates"], ov["collectives_total"]
    if "max_candidates" in ctx.overlap and \
            cand > ctx.overlap["max_candidates"]:
        out.append(Finding(
            pass_name="collective-overlap",
            fid="collective-overlap/unexpected-candidates",
            severity="error", op="*",
            message=(f"{cand} overlap candidates, contract allows "
                     f"<= {ctx.overlap['max_candidates']} (a sequential "
                     "program's collectives must all sit on the dense "
                     "critical path — the metric's honesty anchor)")))
    want_min = ctx.overlap.get("min_candidates")
    if ctx.overlap.get("all_candidates"):
        want_min = total
    if want_min is not None and cand < want_min:
        out.append(Finding(
            pass_name="collective-overlap",
            fid="collective-overlap/candidates-under-bound",
            severity="error", op="*",
            message=(f"{cand}/{total} collectives are overlap "
                     f"candidates, schedule contract requires >= "
                     f"{want_min} (a prefetch collective acquired a "
                     "data dependency on the dense compute)")))
    return out


def _allowed_payload_dtypes(ctx: PlanContext) -> Tuple[set, set]:
    """(float dtypes, int dtypes) the plan's seam may legally put on an
    exchange collective — read from ops/wire.py so the pass and the
    seam cannot drift."""
    from ..ops import wire as wire_ops
    floats = {d for w in ctx.wire_dtypes
              for d in wire_ops.seam_float_dtypes(w)}
    ints = {d for w in ctx.id_wire_dtypes
            for d in wire_ops.seam_id_dtypes(w)}
    if ctx.ragged_emulation:
        ints |= set(wire_ops.RAGGED_METADATA_DTYPES)
    return floats, ints


@register_pass("wire-seam",
               "every exchange collective's payload dtype is "
               "attributable to a declared wire format (new)")
def wire_seam_pass(mod: ir.Module, ctx: PlanContext) -> List[Finding]:
    floats, ints = _allowed_payload_dtypes(ctx)
    escapes: Dict[Tuple[str, str], List[ir.Instruction]] = {}
    for _, inst in mod.walk():
        for kind, t in inst.collective_payloads():
            if not t.dtype:
                continue
            ok = t.dtype in floats if t.dtype.startswith(("f", "bf")) \
                else t.dtype in ints
            if not ok:
                escapes.setdefault((kind, t.dtype), []).append(inst)
    out = []
    for (kind, dtype), insts in sorted(escapes.items()):
        out.append(Finding(
            pass_name="wire-seam", fid=f"wire-seam/escape.{kind}.{dtype}",
            severity="error", op=kind, line=insts[0].line,
            message=(f"{len(insts)} {kind} collective(s) move a {dtype} "
                     f"payload no plan group declares (float wires "
                     f"{sorted(floats)}, id wires {sorted(ints)}) — an "
                     "exchange outside the ops/wire.py seam")))
    return out


@register_pass("donation",
               "input-output aliasing table vs the default_donate() "
               "policy — the PR 5 CPU miscompile class (new)")
def donation_pass(mod: ir.Module, ctx: PlanContext) -> List[Finding]:
    if ctx.donate_expected is None:
        return []
    entry = mod.entry
    if entry is None:
        return []
    donated = entry.donated_args
    if donated and not ctx.donate_expected:
        names = [a.name for a in donated]
        return [Finding(
            pass_name="donation", fid="donation/unexpected-donation",
            severity="error", func=entry.name, line=entry.line,
            message=(f"{len(donated)} donated/aliased arg(s) "
                     f"{names[:4]} but the donation policy for this "
                     f"build is OFF (platform={ctx.platform}; on "
                     "XLA:CPU a donated module loaded from the "
                     "persistent compilation cache can mis-execute — "
                     "compat.install_cpu_donation_cache_guard)")) ]
    if ctx.donate_expected and not donated:
        return [Finding(
            pass_name="donation", fid="donation/missing-donation",
            severity="warning", func=entry.name, line=entry.line,
            message=("donation policy is ON but no argument carries "
                     "jax.buffer_donor/tf.aliasing_output — the step "
                     "updates out of place (double table HBM)"))]
    return []


@register_pass("dtype-promotion",
               "no f64 anywhere; no f32 payload on a seam collective "
               "in an all-bf16-wire program (new)")
def dtype_promotion_pass(mod: ir.Module,
                         ctx: PlanContext) -> List[Finding]:
    out: List[Finding] = []
    f64_sites: List[Tuple[str, ir.Instruction]] = []
    for fn, inst in mod.walk():
        if any(t.dtype == "f64"
               for t in inst.operand_types + inst.result_types):
            f64_sites.append((fn.name, inst))
    if f64_sites:
        fn0, i0 = f64_sites[0]
        out.append(Finding(
            pass_name="dtype-promotion", fid="dtype-promotion/f64",
            severity="error", func=fn0, line=i0.line, op=i0.kind,
            message=(f"{len(f64_sites)} op(s) carry f64 values (first: "
                     f"{i0.kind} in @{fn0}) — nothing in this system "
                     "computes at f64; an accidental weak_type/np "
                     "promotion doubles HBM and halves MXU throughput")))
    # the f32-feeding-a-collective check only has meaning when the plan
    # is UNIFORMLY compressed: a mixed plan legitimately moves f32 on
    # its f32-wire groups (the wire-seam pass attributes those).
    # Formats map through the seam hooks so 'bf16-sr' counts as
    # compressed — comparing format STRINGS would fail open on it
    floats, _ = _allowed_payload_dtypes(ctx)
    if floats == {"bf16"}:
        hits: Dict[str, int] = {}
        for _, inst in mod.walk():
            for kind, t in inst.collective_payloads():
                if t.dtype == "f32":
                    hits[kind] = hits.get(kind, 0) + 1
        for kind in sorted(hits):
            out.append(Finding(
                pass_name="dtype-promotion",
                fid=f"dtype-promotion/f32-wire-leak.{kind}",
                severity="error", op=kind,
                message=(f"{hits[kind]} {kind} collective(s) move f32 "
                         "payloads in an all-bf16-wire program — an "
                         "encode was dropped, the declared uncompressed "
                         "set (hot/loss psum, combiner-None) never "
                         "lowers to this op")))
    return out


@register_pass("storage-dtype",
               "every quantized (i8/f8*) buffer is attributable to a "
               "declared bucket storage_dtype (ISSUE 15)")
def storage_dtype_pass(mod: ir.Module, ctx: PlanContext) -> List[Finding]:
    """The wire-seam discipline applied to MEMORY: quantized element
    types may appear in a lowered program only where a plan bucket
    declared that storage dtype (`ops/wire.seam_storage_dtypes` maps
    the declarations, so pass and codec cannot drift). In the default
    all-f32-storage program the allowed set is EMPTY — any i8/f8
    tensor is a buffer quantized outside the seam (or a stray integer
    narrowing masquerading as one), exactly the class of silent
    numerics change this gate exists to catch."""
    from ..ops import wire as wire_ops
    allowed = {d for s in ctx.storage_dtypes
               for d in wire_ops.seam_storage_dtypes(s)}
    hits: Dict[Tuple[str, str], List[ir.Instruction]] = {}
    present: set = set()
    for _, inst in mod.walk():
        for t in inst.operand_types + inst.result_types:
            if t.dtype in ir.QUANTIZED_STORAGE_DTYPES:
                present.add(t.dtype)
                if t.dtype not in allowed:
                    hits.setdefault((t.dtype, inst.kind), []).append(inst)
    out: List[Finding] = []
    # ---- inverse direction (ISSUE 17, HBM-resident buffers): a plan
    # that DECLARES a quantized storage dtype whose seam element type
    # appears in NO buffer of the lowered program. The declaration was
    # dropped on the floor — the table lowered as plain f32, so the
    # promised ~4x HBM saving silently never materialized (the mirror
    # failure of the undeclared case; both directions are blind-gated
    # by tools/hlo_audit.py mutation fixtures).
    for dtype in sorted(allowed - present):
        out.append(Finding(
            pass_name="storage-dtype",
            fid=f"storage-dtype/declared-but-f32.{dtype}",
            severity="error", op="module",
            message=(f"plan declares a storage dtype lowering to {dtype} "
                     f"(declared: {sorted(ctx.storage_dtypes)}) but no op "
                     f"in the program carries {dtype} values — the bucket "
                     "lowered as f32, the declared quantized residency "
                     "never reached the compiled program")))
    by_dtype: Dict[str, int] = {}
    first: Dict[str, ir.Instruction] = {}
    for (dtype, _), insts in sorted(hits.items()):
        by_dtype[dtype] = by_dtype.get(dtype, 0) + len(insts)
        first.setdefault(dtype, insts[0])
    for dtype in sorted(by_dtype):
        i0 = first[dtype]
        out.append(Finding(
            pass_name="storage-dtype",
            fid=f"storage-dtype/undeclared.{dtype}",
            severity="error", op=i0.kind, line=i0.line,
            message=(f"{by_dtype[dtype]} op(s) carry {dtype} values but "
                     f"no plan bucket declares a storage dtype lowering "
                     f"to {dtype} (declared: "
                     f"{sorted(ctx.storage_dtypes)}) — a buffer "
                     "quantized outside the ops/wire.py storage seam")))
    return out


@register_pass("dead-dup-collective",
               "no duplicate collectives over identical operands; no "
               "collective with empty transitive fan-out (new)")
def dead_dup_pass(mod: ir.Module, ctx: PlanContext) -> List[Finding]:
    out: List[Finding] = []
    dup_counts: Dict[str, int] = {}
    dead_counts: Dict[str, int] = {}
    for fn in mod.functions.values():
        producers = fn.producers()
        # ---- duplicates: same op, same operand SSA sources, same attrs.
        # jax stamps every collective with a UNIQUE channel_handle, so
        # the handle must be stripped from the key — comparing raw attrs
        # would make two byte-identical exchanges always look distinct
        # and the check could never fire on a real lowering
        seen: Dict[Tuple, str] = {}
        for inst in fn.instructions:
            if inst.kind not in ir.COLLECTIVE_OPS:
                continue
            attrs = re.sub(
                r'channel_handle\s*=\s*#stablehlo\.channel_handle<[^>]*>,?',
                "", inst.attrs)
            key = (inst.kind, tuple(inst.operands),
                   re.sub(r'\s+', " ", attrs))
            if key in seen:
                dup_counts[inst.kind] = dup_counts.get(inst.kind, 0) + 1
            else:
                seen[key] = inst.results[0] if inst.results else ""
        # ---- dead: liveness from the function's terminator operands
        live = set()
        stack = [producers[r] for r in fn.returns if r in producers]
        while stack:
            i = stack.pop()
            if i in live:
                continue
            live.add(i)
            stack.extend(producers[r] for r in fn.instructions[i].refs
                         if r in producers)
        for i, inst in enumerate(fn.instructions):
            if inst.is_collective() and fn.returns and i not in live:
                dead_counts[inst.kind] = dead_counts.get(inst.kind, 0) + 1
    for kind in sorted(dup_counts):
        out.append(Finding(
            pass_name="dead-dup-collective",
            fid=f"dead-dup-collective/duplicate.{kind}",
            severity="error", op=kind,
            message=(f"{dup_counts[kind]} {kind} collective(s) repeat "
                     "an identical (operands, attrs) exchange already "
                     "performed in the same function — CSE the result "
                     "instead of paying the wire twice")))
    for kind in sorted(dead_counts):
        out.append(Finding(
            pass_name="dead-dup-collective",
            fid=f"dead-dup-collective/dead.{kind}",
            severity="error", op=kind,
            message=(f"{dead_counts[kind]} {kind} collective(s) have "
                     "empty transitive fan-out (nothing on the path to "
                     "the function's results consumes them) — dead wire "
                     "traffic left behind by a restructure")))
    return out
