"""Datasets for the DLRM / Criteo examples.

Mirror of the reference's data path (reference: examples/dlrm/utils.py:116-307):
  * RawBinaryDataset — the split-binary Criteo-1TB format (label.bin bool,
    numerical.bin float16, cat_{i}.bin with the smallest int dtype that fits
    each table). Reads are positional (pread) and prefetched ahead of the
    training step by the native C++ thread pool (native/io.cpp) instead of the
    reference's single-thread Python executor.
  * DummyDataset — constant tensors for benchmarking.
"""

import math
import os
from typing import Optional, Sequence

import numpy as np


def get_categorical_feature_type(size: int):
    """Smallest signed int dtype that holds `size` (reference utils.py:116-123)."""
    for np_type in (np.int8, np.int16, np.int32):
        if size < np.iinfo(np_type).max:
            return np_type
    raise RuntimeError(f"Categorical feature of size {size} is too big")


class DummyDataset:
    """Constant batches for benchmarking (reference utils.py:126-154)."""

    def __init__(self, batch_size: int, num_numerical_features: int,
                 table_sizes: Sequence[int], num_batches: int = 100,
                 hotness: Optional[Sequence[int]] = None):
        self.numerical = np.zeros((batch_size, num_numerical_features),
                                  np.float32)
        if hotness is None:
            self.categorical = [np.zeros((batch_size,), np.int32)
                                for _ in table_sizes]
        else:
            self.categorical = [np.zeros((batch_size, h), np.int32)
                                for h in hotness]
        self.labels = np.ones((batch_size, 1), np.float32)
        self.num_batches = num_batches

    def __len__(self):
        return self.num_batches

    def __getitem__(self, idx):
        if idx >= self.num_batches:
            raise IndexError
        return self.numerical, self.categorical, self.labels


class RawBinaryDataset:
    """Split-binary Criteo dataset with native prefetch.

    The read and decode halves are separately exposed (`read_raw` /
    `preprocess`) so `utils.pipeline.IngestPipeline` can run them in
    dedicated worker threads; `ds[idx]` composes them inline.

    Args:
      data_path: directory containing train/ or test/ with label.bin,
        numerical.bin, cat_{i}.bin.
      batch_size: samples per batch (global batch).
      numerical_features: how many dense features to load (0 = none).
      categorical_features: which table ids this process loads (model-parallel
        input loads only locally-owned tables — reference utils.py:260-266).
      categorical_feature_sizes: vocab size per table (for dtype selection).
      prefetch_depth: batches to read ahead.
      offset / local_batch_size: slice [offset:offset+lbs] out of each global
        batch for data-parallel inputs.
    """

    def __init__(self,
                 data_path: str,
                 batch_size: int = 1,
                 numerical_features: int = 0,
                 categorical_features: Optional[Sequence[int]] = None,
                 categorical_feature_sizes: Optional[Sequence[int]] = None,
                 prefetch_depth: int = 10,
                 drop_last_batch: bool = False,
                 valid: bool = False,
                 offset: int = -1,
                 local_batch_size: int = -1,
                 dp_input: bool = False,
                 use_native_prefetch: bool = True):
        split = "test" if valid else "train"
        base = os.path.join(data_path, split)
        self.batch_size = batch_size
        self.numerical_features = numerical_features
        self.categorical_features = list(categorical_features or [])
        sizes = list(categorical_feature_sizes or [])
        self.cat_types = [get_categorical_feature_type(s) for s in sizes]
        self.offset = offset
        self.local_batch_size = local_batch_size
        self.valid = valid
        self.dp_input = dp_input

        self._label_bytes = np.dtype(np.bool_).itemsize * batch_size
        self._num_bytes = numerical_features * np.dtype(np.float16).itemsize * batch_size
        self._cat_bytes = [np.dtype(t).itemsize * batch_size for t in self.cat_types]

        self.paths = [os.path.join(base, "label.bin")]
        if numerical_features > 0:
            self.paths.append(os.path.join(base, "numerical.bin"))
        self._num_file_idx = 1 if numerical_features > 0 else None
        self._cat_file_idx = {}
        for cat_id in self.categorical_features:
            self._cat_file_idx[cat_id] = len(self.paths)
            self.paths.append(os.path.join(base, f"cat_{cat_id}.bin"))

        label_size = os.path.getsize(self.paths[0])
        rounder = math.floor if drop_last_batch else math.ceil
        self._num_entries = int(rounder(label_size / self._label_bytes))
        for path, nbytes in [(self.paths[0], self._label_bytes)] + (
                [(os.path.join(base, "numerical.bin"), self._num_bytes)]
                if numerical_features > 0 else []):
            n = int(rounder(os.path.getsize(path) / nbytes))
            if n != self._num_entries:
                raise ValueError(
                    f"Size mismatch in {path}: expected {self._num_entries}, got {n}")

        self._prefetcher = None
        self._fds = None
        if use_native_prefetch:
            try:
                from distributed_embeddings_tpu.native import loader
                import ctypes
                lib = loader.load()
                arr = (ctypes.c_char_p * len(self.paths))(
                    *[p.encode() for p in self.paths])
                self._prefetcher_lib = lib
                self._prefetcher = lib.pf_create(arr, len(self.paths), 4)
            except Exception:  # noqa: BLE001 - fall back to os.pread
                self._prefetcher = None
        if self._prefetcher is None:
            self._fds = [os.open(p, os.O_RDONLY) for p in self.paths]

        self._pending = {}
        self.prefetch_depth = min(prefetch_depth, self._num_entries)

    def __len__(self):
        return self._num_entries

    def _read(self, file_idx: int, offset: int, size: int) -> np.ndarray:
        buf = np.empty((size,), np.uint8)
        if self._prefetcher is not None:
            self._prefetcher_lib.pf_read(
                self._prefetcher, file_idx, offset, size, buf.ctypes.data)
            return buf
        data = os.pread(self._fds[file_idx], size, offset)
        return np.frombuffer(data, np.uint8)

    def _submit(self, file_idx: int, offset: int, size: int):
        """Start an async read; returns (request, buffer)."""
        buf = np.empty((size,), np.uint8)
        req = self._prefetcher_lib.pf_submit(
            self._prefetcher, file_idx, offset, size, buf.ctypes.data)
        return req, buf

    def _start_batch(self, idx: int):
        reads = [(0, idx * self._label_bytes, self._label_bytes)]
        if self._num_file_idx is not None:
            reads.append((self._num_file_idx, idx * self._num_bytes,
                          self._num_bytes))
        for cat_id in self.categorical_features:
            nbytes = self._cat_bytes[cat_id]
            reads.append((self._cat_file_idx[cat_id], idx * nbytes, nbytes))
        self._pending[idx] = [self._submit(*r) for r in reads]

    def _finish_batch(self, idx: int):
        bufs = []
        for req, buf in self._pending.pop(idx):
            self._prefetcher_lib.pf_wait(self._prefetcher, req)
            bufs.append(buf)
        return bufs

    def preprocess(self, bufs):
        """Decode raw byte buffers (from `read_raw`) into a batch.

        THE preprocess hook of the ingestion pipeline: dtype views, the
        min-int -> int32 cast, the f16 -> f32 numerical cast, the label
        reshape and the dp/mp slicing all happen here — in whatever thread
        the caller runs it in (`utils.pipeline.IngestPipeline` gives it a
        dedicated worker so it overlaps the device step). Subclass or wrap
        it to fuse extra host transforms (e.g. an IntegerLookup raw-key
        translation) into the same single pass over the batch.
        """
        return self._decode(bufs)

    def _decode(self, bufs):
        it = iter(bufs)
        labels = next(it).view(np.bool_).astype(np.float32)[:, None]
        numerical = None
        if self._num_file_idx is not None:
            numerical = next(it).view(np.float16).astype(np.float32).reshape(
                -1, self.numerical_features)
        cats = []
        for cat_id in self.categorical_features:
            cats.append(next(it).view(self.cat_types[cat_id]).astype(np.int32))
        if self.offset >= 0:
            sl = slice(self.offset, self.offset + self.local_batch_size)
            if not self.valid:
                labels = labels[sl]
            if numerical is not None:
                numerical = numerical[sl]
            if self.dp_input:
                cats = [c[sl] for c in cats]
        return numerical, cats, labels

    def read_raw(self, idx: int):
        """Raw per-file byte buffers for batch `idx` — the read stage.

        Pure I/O: pread (native async prefetch window when available) with
        no decoding, so an ingestion pipeline can run it in a reader thread
        while `preprocess` and device staging proceed on earlier batches.
        `__getitem__` remains `preprocess(read_raw(idx))`.
        """
        if idx >= self._num_entries:
            raise IndexError
        if self._prefetcher is None or self.prefetch_depth <= 1:
            bufs = [self._read(0, idx * self._label_bytes, self._label_bytes)]
            if self._num_file_idx is not None:
                bufs.append(self._read(self._num_file_idx,
                                       idx * self._num_bytes, self._num_bytes))
            for cat_id in self.categorical_features:
                nbytes = self._cat_bytes[cat_id]
                bufs.append(self._read(self._cat_file_idx[cat_id],
                                       idx * nbytes, nbytes))
            return bufs
        # async: keep prefetch_depth batches in flight
        if idx == 0:
            self._pending.clear()
            for i in range(self.prefetch_depth):
                self._start_batch(i)
        nxt = idx + self.prefetch_depth
        if nxt < self._num_entries and nxt not in self._pending:
            self._start_batch(nxt)
        return self._finish_batch(idx)

    def raw_batches(self, steps: Optional[int] = None):
        """Generator over raw (undecoded) batches, wrapping indices — the
        natural `IngestPipeline` source: pair with
        ``stages=[("preprocess", ds.preprocess), ("stage", ...)]``."""
        n = steps if steps is not None else self._num_entries
        for i in range(n):
            yield self.read_raw(i % self._num_entries)

    def __getitem__(self, idx: int):
        return self.preprocess(self.read_raw(idx))

    def __del__(self):
        try:
            if self._prefetcher is not None:
                self._prefetcher_lib.pf_destroy(self._prefetcher)
                self._prefetcher = None
            if self._fds:
                for fd in self._fds:
                    os.close(fd)
        except Exception:  # noqa: BLE001 - interpreter teardown
            pass
