"""DLRM (Deep Learning Recommendation Model) on TPU.

Functional re-design of the reference DLRM example
(reference: examples/dlrm/main.py:77-140, examples/dlrm/utils.py:27-113):
bottom MLP over dense features -> 26 embedding lookups via
DistributedEmbedding -> pairwise dot-interaction -> top MLP -> logit.

TPU-first details:
  * MLPs run in bfloat16-friendly sizes and map onto the MXU; the whole train
    step is one jit-compiled SPMD program (dense part data-parallel via batch
    sharding, embeddings hybrid-parallel via DistributedEmbedding).
  * dot_interact extracts the strictly-lower-triangular pairwise dots with a
    static boolean mask — a gather with a trace-time-constant index vector,
    not tf.boolean_mask's dynamic shapes.
"""

import math
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)


def dlrm_initializer():
    """Uniform(+-1/sqrt(rows)) embedding init (reference utils.py:27-41)."""
    def init(key, shape, dtype=jnp.float32):
        maxval = 1.0 / math.sqrt(shape[0])
        return jax.random.uniform(key, shape, dtype, -maxval, maxval)
    return init


def dot_interact(emb_outs: Sequence[jax.Array],
                 bottom_mlp_out: jax.Array) -> jax.Array:
    """Pairwise-dot feature interaction (reference utils.py:92-113).

    Stacks [bottom_mlp_out] + emb_outs into [B, F+1, d], computes the Gram
    matrix on the MXU, gathers the strictly-lower-triangular entries with a
    static index, and re-concats the bottom MLP output.
    """
    feats = jnp.stack([bottom_mlp_out] + list(emb_outs), axis=1)  # [B, F+1, d]
    gram = jnp.einsum("bfd,bgd->bfg", feats, feats,
                      preferred_element_type=jnp.float32)
    n = feats.shape[1]
    rows, cols = np.tril_indices(n, k=-1)
    flat = gram.reshape(gram.shape[0], n * n)
    pairwise = flat[:, rows * n + cols]                            # [B, n(n-1)/2]
    return jnp.concatenate([pairwise, bottom_mlp_out], axis=1)


def _mlp_init(key, dims: List[int], in_dim: int):
    params = []
    for i, out_dim in enumerate(dims):
        kw, kb, key = jax.random.split(key, 3)
        # glorot-normal kernel, bias ~ N(0, 1/out) (reference main.py:127-139)
        std = math.sqrt(2.0 / (in_dim + out_dim))
        params.append({
            "w": jax.random.normal(kw, (in_dim, out_dim)) * std,
            "b": jax.random.normal(kb, (out_dim,)) * math.sqrt(1.0 / out_dim),
        })
        in_dim = out_dim
    return params


def _mlp_apply(params, x, final_activation=False):
    for i, layer in enumerate(params):
        x = x @ layer["w"] + layer["b"]
        if i < len(params) - 1 or final_activation:
            x = jax.nn.relu(x)
    return x


class DLRM:
    """DLRM with hybrid-parallel embeddings.

    Args:
      table_sizes: vocab size per categorical feature (26 for Criteo).
      embedding_dim: embedding width (128 for the MLPerf config).
      bottom_mlp_dims / top_mlp_dims: layer sizes; top ends at 1 logit.
      num_numerical_features: dense feature count (13 for Criteo).
      mesh: device mesh (None = single device).
      dist_strategy / column_slice_threshold / row_slice_threshold /
      data_parallel_threshold: forwarded to DistributedEmbedding.
      compute_dtype: activations dtype (bfloat16 recommended on TPU).
    """

    def __init__(self,
                 table_sizes: Sequence[int],
                 embedding_dim: int = 128,
                 bottom_mlp_dims: Sequence[int] = (512, 256, 128),
                 top_mlp_dims: Sequence[int] = (1024, 1024, 512, 256, 1),
                 num_numerical_features: int = 13,
                 mesh=None,
                 dist_strategy: str = "memory_balanced",
                 column_slice_threshold: Optional[int] = None,
                 row_slice_threshold: Optional[int] = None,
                 data_parallel_threshold: Optional[int] = None,
                 dp_input: bool = True,
                 compute_dtype=jnp.float32):
        self.table_sizes = list(table_sizes)
        self.embedding_dim = embedding_dim
        self.bottom_mlp_dims = list(bottom_mlp_dims)
        self.top_mlp_dims = list(top_mlp_dims)
        self.num_numerical_features = num_numerical_features
        self.compute_dtype = compute_dtype

        embeddings = [
            Embedding(v, embedding_dim, embeddings_initializer=dlrm_initializer())
            for v in self.table_sizes
        ]
        self.embedding = DistributedEmbedding(
            embeddings,
            strategy=dist_strategy,
            column_slice_threshold=column_slice_threshold,
            row_slice_threshold=row_slice_threshold,
            data_parallel_threshold=data_parallel_threshold,
            dp_input=dp_input,
            mesh=mesh,
            # bf16 inside the embedding halves the mp->dp all_to_all bytes
            compute_dtype=(compute_dtype
                           if compute_dtype != jnp.float32 else None))
        self.mesh = mesh

    def init(self, key) -> dict:
        ke, kb, kt = jax.random.split(key, 3)
        n_feats = len(self.table_sizes) + 1
        interact_dim = n_feats * (n_feats - 1) // 2 + self.bottom_mlp_dims[-1]
        return {
            "embedding": self.embedding.init(ke),
            "bottom_mlp": _mlp_init(kb, self.bottom_mlp_dims,
                                    self.num_numerical_features),
            "top_mlp": _mlp_init(kt, self.top_mlp_dims, interact_dim),
        }

    def apply(self, params: dict, numerical: jax.Array,
              categorical: Sequence[jax.Array], taps=None,
              return_residuals: bool = False):
        """Forward: [B, num_numerical] + categorical ids -> [B, 1] logit.

        With dp_input=True `categorical` is one global-batch id array per
        feature; with dp_input=False it is the nested per-rank form expected
        by DistributedEmbedding.apply_mp (reference dp_input semantics,
        dist_model_parallel.py:729-731). taps/return_residuals: sparse
        training hooks (see DistributedEmbedding.apply).
        """
        x = numerical.astype(self.compute_dtype)
        bottom = _mlp_apply(params["bottom_mlp"], x, final_activation=True)
        res = None
        if taps is not None or return_residuals:
            emb_outs, res = self.embedding(
                params["embedding"], list(categorical), taps=taps,
                return_residuals=True)
        else:
            emb_outs = self.embedding(params["embedding"], list(categorical))
        emb_outs = [e.astype(self.compute_dtype) for e in emb_outs]
        interact = dot_interact(emb_outs, bottom).astype(self.compute_dtype)
        out = _mlp_apply(params["top_mlp"], interact)
        return (out, res) if return_residuals else out

    def loss_fn(self, params, numerical, categorical, labels, taps=None,
                return_residuals: bool = False):
        out = self.apply(params, numerical, categorical, taps=taps,
                         return_residuals=return_residuals)
        logits, res = out if return_residuals else (out, None)
        logits = logits[:, 0]
        labels = labels.reshape(-1).astype(jnp.float32)
        logits = logits.astype(jnp.float32)
        # sigmoid binary cross-entropy, mean over the global batch
        loss = jnp.mean(
            jnp.maximum(logits, 0) - logits * labels
            + jnp.log1p(jnp.exp(-jnp.abs(logits))))
        return (loss, res) if return_residuals else loss

    def make_train_step(self, optimizer):
        """Build a jittable train step: (opt_state, params, batch) -> updated."""
        def step(params, opt_state, numerical, categorical, labels):
            loss, grads = jax.value_and_grad(self.loss_fn)(
                params, numerical, categorical, labels)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = jax.tree.map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss
        return step


def make_lr_schedule(base_lr: float, warmup_steps: int, decay_start_step: int,
                     decay_steps: int, poly_power: int = 2):
    """Warmup -> constant -> polynomial decay LR schedule
    (reference utils.py:45-88), as a pure optax-style schedule function."""
    def schedule(step):
        step = jnp.asarray(step, jnp.float32)
        warmup = 1.0 - (warmup_steps - step) / warmup_steps
        decay_end = decay_start_step + decay_steps
        decay = jnp.clip((decay_end - step) / decay_steps, 0.0, 1.0) ** poly_power
        factor = jnp.where(step < warmup_steps, warmup,
                           jnp.where(step < decay_start_step, 1.0, decay))
        return base_lr * factor
    return schedule
