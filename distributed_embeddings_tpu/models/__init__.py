from distributed_embeddings_tpu.models.dlrm import (
    DLRM, dot_interact, dlrm_initializer, make_lr_schedule)
from distributed_embeddings_tpu.models.synthetic import (
    EmbeddingConfig, ModelConfig, SyntheticModel, SYNTHETIC_MODELS)
