"""Synthetic benchmark model zoo.

Mirror of the reference's synthetic suite
(reference: examples/benchmarks/synthetic_models/{config_v3,synthetic_models}.py):
7 model scales (tiny 4.2 GiB ... colossal 22.3 TiB of embeddings), each a
DLRM-shaped net: many embedding tables ('sum' combiner, some shared multi-hot)
-> feature interaction (concat, or strided average pooling for the big models)
-> MLP -> logit.

The table/size/hotness configurations are benchmark-defining data and are kept
numerically identical to the reference's config_v3.py so step-time numbers are
comparable (BASELINE.md).
"""

import math
from typing import List, NamedTuple, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.layers.embedding import Embedding
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.models.dlrm import _mlp_apply, _mlp_init


class EmbeddingConfig(NamedTuple):
    num_tables: int
    nnz: List[int]       # hotness per input; len>1 => shared table, many inputs
    num_rows: int
    width: int
    shared: bool


class ModelConfig(NamedTuple):
    name: str
    embedding_configs: List[EmbeddingConfig]
    mlp_sizes: List[int]
    num_numerical_features: int
    interact_stride: Optional[int]


# Benchmark-defining constants (values match reference config_v3.py:30-142).
SYNTHETIC_MODELS = {
    "criteo": ModelConfig(
        "Criteo-dlrm-like",
        [EmbeddingConfig(26, [1], 100000, 128, False)],
        [512, 256, 128], 13, None),
    "tiny": ModelConfig(
        "Tiny V3",
        [EmbeddingConfig(1, [1, 10], 10000, 8, True),
         EmbeddingConfig(1, [1, 10], 1000000, 16, True),
         EmbeddingConfig(1, [1, 10], 25000000, 16, True),
         EmbeddingConfig(1, [1], 25000000, 16, False),
         EmbeddingConfig(16, [1], 10, 8, False),
         EmbeddingConfig(10, [1], 1000, 8, False),
         EmbeddingConfig(4, [1], 10000, 8, False),
         EmbeddingConfig(2, [1], 100000, 16, False),
         EmbeddingConfig(19, [1], 1000000, 16, False)],
        [256, 128], 10, None),
    "small": ModelConfig(
        "Small V3",
        [EmbeddingConfig(5, [1, 30], 10000, 16, True),
         EmbeddingConfig(3, [1, 30], 4000000, 32, True),
         EmbeddingConfig(1, [1, 30], 50000000, 32, True),
         EmbeddingConfig(1, [1], 50000000, 32, False),
         EmbeddingConfig(30, [1], 10, 16, False),
         EmbeddingConfig(30, [1], 1000, 16, False),
         EmbeddingConfig(5, [1], 10000, 16, False),
         EmbeddingConfig(5, [1], 100000, 32, False),
         EmbeddingConfig(27, [1], 4000000, 32, False)],
        [512, 256, 128], 10, None),
    "medium": ModelConfig(
        "Medium v3",
        [EmbeddingConfig(20, [1, 50], 100000, 64, True),
         EmbeddingConfig(5, [1, 50], 10000000, 64, True),
         EmbeddingConfig(1, [1, 50], 100000000, 128, True),
         EmbeddingConfig(1, [1], 100000000, 128, False),
         EmbeddingConfig(80, [1], 10, 32, False),
         EmbeddingConfig(60, [1], 1000, 32, False),
         EmbeddingConfig(80, [1], 100000, 64, False),
         EmbeddingConfig(24, [1], 200000, 64, False),
         EmbeddingConfig(40, [1], 10000000, 64, False)],
        [1024, 512, 256, 128], 25, 7),
    "large": ModelConfig(
        "Large v3",
        [EmbeddingConfig(40, [1, 100], 100000, 64, True),
         EmbeddingConfig(16, [1, 100], 15000000, 64, True),
         EmbeddingConfig(1, [1, 100], 200000000, 128, True),
         EmbeddingConfig(1, [1], 200000000, 128, False),
         EmbeddingConfig(100, [1], 10, 32, False),
         EmbeddingConfig(100, [1], 10000, 32, False),
         EmbeddingConfig(160, [1], 100000, 64, False),
         EmbeddingConfig(50, [1], 500000, 64, False),
         EmbeddingConfig(144, [1], 15000000, 64, False)],
        [2048, 1024, 512, 256], 100, 8),
    "jumbo": ModelConfig(
        "Jumbo v3",
        [EmbeddingConfig(50, [1, 200], 100000, 128, True),
         EmbeddingConfig(24, [1, 200], 20000000, 128, True),
         EmbeddingConfig(1, [1, 200], 400000000, 256, True),
         EmbeddingConfig(1, [1], 400000000, 256, False),
         EmbeddingConfig(100, [1], 10, 32, False),
         EmbeddingConfig(200, [1], 10000, 64, False),
         EmbeddingConfig(350, [1], 100000, 128, False),
         EmbeddingConfig(80, [1], 1000000, 128, False),
         EmbeddingConfig(216, [1], 20000000, 128, False)],
        [2048, 1024, 512, 256], 200, 20),
    "colossal": ModelConfig(
        "Colossal v3",
        [EmbeddingConfig(100, [1, 300], 100000, 128, True),
         EmbeddingConfig(50, [1, 300], 40000000, 256, True),
         EmbeddingConfig(1, [1, 300], 2000000000, 256, True),
         EmbeddingConfig(1, [1], 1000000000, 256, False),
         EmbeddingConfig(100, [1], 10, 32, False),
         EmbeddingConfig(400, [1], 10000, 128, False),
         EmbeddingConfig(100, [1], 100000, 128, False),
         EmbeddingConfig(800, [1], 1000000, 128, False),
         EmbeddingConfig(450, [1], 40000000, 256, False)],
        [4096, 2048, 1024, 512, 256], 500, 30),
}


def expand_embedding_configs(model_config: ModelConfig):
    """Flatten EmbeddingConfigs into (table specs, input_table_map, hotness).

    A config with len(nnz) > 1 and shared=True creates num_tables tables each
    fed by len(nnz) inputs (reference synthetic_models.py:134-143).
    """
    tables, table_map, hotness = [], [], []
    for cfg in model_config.embedding_configs:
        if len(cfg.nnz) > 1 and not cfg.shared:
            raise NotImplementedError(
                "Non-shared multi-hot embedding is not implemented")
        for _ in range(cfg.num_tables):
            tables.append((cfg.num_rows, cfg.width))
            for h in cfg.nnz:
                table_map.append(len(tables) - 1)
                hotness.append(h)
    return tables, table_map, hotness


def power_law(k_min, k_max, alpha, r):
    """Map U(0,1) samples to a power-law distribution
    (reference synthetic_models.py:31-35)."""
    gamma = 1 - alpha
    return ((r * (k_max ** gamma - k_min ** gamma) + k_min ** gamma)
            ** (1.0 / gamma)).astype(np.int64)


def gen_power_law_data(batch_size, hotness, num_rows, alpha, rng=None):
    rng = rng or np.random
    y = power_law(1, num_rows + 1, alpha, rng.rand(batch_size * hotness)) - 1
    return y.reshape(batch_size, hotness)


class InputGenerator:
    """Synthetic input generator (reference synthetic_models.py:51-113).

    Produces (numerical [B, n], categorical list of [B, hotness], labels).
    alpha=0 -> uniform ids; alpha>0 -> power-law ids.
    """

    def __init__(self, model_config: ModelConfig, global_batch_size: int,
                 alpha: float = 0.0, num_batches: int = 10, seed: int = 0):
        rng = np.random.RandomState(seed)
        _, table_map, hotness = expand_embedding_configs(model_config)
        tables, _, _ = expand_embedding_configs(model_config)
        self.batches = []
        for _ in range(num_batches):
            cats = []
            for inp, t in enumerate(table_map):
                rows = tables[t][0]
                h = hotness[inp]
                if alpha == 0.0:
                    ids = rng.randint(0, rows, size=(global_batch_size, h))
                else:
                    ids = gen_power_law_data(global_batch_size, h, rows, alpha,
                                             rng)
                cats.append(jnp.asarray(ids.astype(np.int32)))
            numerical = jnp.asarray(
                rng.rand(global_batch_size,
                         model_config.num_numerical_features).astype(np.float32)
                * 100.0)
            labels = jnp.asarray(
                rng.randint(0, 2, size=(global_batch_size, 1)).astype(np.float32))
            self.batches.append((numerical, cats, labels))

    def __len__(self):
        return len(self.batches)

    def __getitem__(self, idx):
        return self.batches[idx]


class ClickGenerator:
    """Learnable synthetic CTR stream (convergence evidence, VERDICT r2
    item 5).

    The reference validates training end-to-end by AUC on Criteo-1TB
    (reference examples/dlrm/README.md:7: 0.8025); that dataset is not
    available here, so this generator produces a stream with planted
    structure a DLRM can actually learn: each table t has a hidden
    per-row score s_t ~ N(0,1), the numerical features a hidden weight
    vector, and

        logit* = scale * (sum_t s_t[id_t] + w . x) / sqrt(T + 1)
        label  ~ Bernoulli(sigmoid(logit*))

    With the default scale the Bayes AUC is ~0.85, so a model reaching
    the 0.70 test threshold has demonstrably learned embedding structure
    (random embeddings give 0.5). Ids are power-law distributed like the
    reference's synthetic zoo.

    Deterministic per (seed, step): `batch(step)` regenerates the same
    batch, usable as both a fit() data callable and an eval stream
    (use disjoint step ranges for train/eval).
    """

    def __init__(self, table_sizes, num_numerical: int, batch_size: int,
                 alpha: float = 1.05, scale: float = 3.0, seed: int = 0):
        rng = np.random.RandomState(seed)
        self.table_sizes = list(table_sizes)
        self.num_numerical = num_numerical
        self.batch_size = batch_size
        self.alpha = alpha
        self.scale = scale
        self.seed = seed
        self.scores = [rng.randn(v).astype(np.float32)
                       for v in self.table_sizes]
        self.w_num = rng.randn(num_numerical).astype(np.float32)

    def batch(self, step: int):
        rng = np.random.RandomState(
            (self.seed * 1_000_003 + step) % (2 ** 31))
        cats, total = [], 0.0
        for t, rows in enumerate(self.table_sizes):
            if self.alpha > 0:
                ids = gen_power_law_data(self.batch_size, 1, rows,
                                         self.alpha, rng)[:, 0]
            else:
                ids = rng.randint(0, rows, size=self.batch_size)
            cats.append(ids.astype(np.int32))
            total = total + self.scores[t][ids]
        x = rng.rand(self.batch_size, self.num_numerical).astype(np.float32)
        total = total + x @ self.w_num
        logit = self.scale * total / np.sqrt(len(self.table_sizes) + 1)
        labels = (rng.rand(self.batch_size)
                  < 1.0 / (1.0 + np.exp(-logit))).astype(np.float32)
        return x, cats, labels

    def __call__(self, step: int):
        return self.batch(step)


def _avg_pool_1d(x: jax.Array, stride: int) -> jax.Array:
    """Strided 'same' average pooling along the feature axis — the
    bandwidth-limited interaction emulation (reference synthetic_models.py:152-156).
    Padding positions are excluded from each window's average."""
    b, c = x.shape
    pad = (-c) % stride
    xp = jnp.pad(x, ((0, 0), (0, pad)))
    win = xp.reshape(b, -1, stride)
    counts = jnp.pad(jnp.ones((c,), x.dtype), (0, pad)).reshape(-1, stride)
    return jnp.sum(win, axis=-1) / jnp.sum(counts, axis=-1)[None, :]


class SyntheticModel:
    """Synthetic recommender: embeddings -> interact -> MLP -> logit.

    distributed=True uses DistributedEmbedding with strategy='auto'
    (comm_balanced for these multi-hot configs — hotness hints are always
    passed; the reference benchmark's memory_balanced remains selectable);
    False uses plain per-table lookups — the 'native' comparison model
    (reference synthetic_models.py:179-234).
    """

    def __init__(self, model_config: ModelConfig, mesh=None,
                 column_slice_threshold=None, distributed: bool = True,
                 strategy: str = "auto", dp_input: bool = True,
                 compute_dtype=jnp.float32, **dist_kwargs):
        self.config = model_config
        self.compute_dtype = compute_dtype
        tables, table_map, self.hotness = expand_embedding_configs(model_config)
        self.table_map = table_map
        self.distributed = distributed
        self.embedding_layers = [
            Embedding(rows, width, combiner="sum") for rows, width in tables
        ]
        if distributed:
            # hotness hints serve the comm_balanced strategy AND allow
            # ragged inputs; harmless otherwise
            dist_kwargs.setdefault("input_max_hotness", list(self.hotness))
            self.embedding = DistributedEmbedding(
                self.embedding_layers, strategy=strategy,
                input_table_map=table_map,
                column_slice_threshold=column_slice_threshold,
                dp_input=dp_input, mesh=mesh,
                compute_dtype=(compute_dtype
                               if compute_dtype != jnp.float32 else None),
                **dist_kwargs)
        self.mesh = mesh
        self.interact_stride = model_config.interact_stride

        emb_out_width = sum(self.embedding_layers[t].output_dim
                            for t in table_map)
        if self.interact_stride is not None:
            emb_out_width = -(-emb_out_width // self.interact_stride)
        self.mlp_in = emb_out_width + model_config.num_numerical_features
        self.mlp_sizes = list(model_config.mlp_sizes) + [1]

    def init(self, key) -> dict:
        ke, km = jax.random.split(key)
        if self.distributed:
            emb = self.embedding.init(ke)
        else:
            keys = jax.random.split(ke, len(self.embedding_layers))
            emb = [l.init(k) for l, k in zip(self.embedding_layers, keys)]
        return {"embedding": emb, "mlp": _mlp_init(km, self.mlp_sizes, self.mlp_in)}

    def apply(self, params, numerical, cat_features, taps=None,
              return_residuals: bool = False):
        res = None
        if self.distributed:
            # __call__ dispatches on dp_input: flat per-feature inputs for
            # the dp path, nested per-rank lists for the mp path
            if taps is not None or return_residuals:
                embs, res = self.embedding(
                    params["embedding"], list(cat_features), taps=taps,
                    return_residuals=True)
            else:
                embs = self.embedding(params["embedding"], list(cat_features))
        else:
            embs = [self.embedding_layers[t](params["embedding"][t], ids)
                    for t, ids in zip(self.table_map, cat_features)]
        embs = [e.astype(self.compute_dtype) for e in embs]
        x = jnp.concatenate(embs, axis=1)
        if self.interact_stride is not None:
            x = _avg_pool_1d(x, self.interact_stride)
        x = jnp.concatenate([x, numerical.astype(self.compute_dtype)], axis=1)
        out = _mlp_apply(params["mlp"], x)
        return (out, res) if return_residuals else out

    def loss_fn(self, params, numerical, cat_features, labels, taps=None,
                return_residuals: bool = False):
        out = self.apply(params, numerical, cat_features, taps=taps,
                         return_residuals=return_residuals)
        logits, res = out if return_residuals else (out, None)
        logits = logits[:, 0]
        labels = labels.reshape(-1).astype(jnp.float32)
        logits = logits.astype(jnp.float32)
        loss = jnp.mean(jnp.maximum(logits, 0) - logits * labels
                        + jnp.log1p(jnp.exp(-jnp.abs(logits))))
        return (loss, res) if return_residuals else loss
