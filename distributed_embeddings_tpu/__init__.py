"""distributed_embeddings_tpu: TPU-native hybrid-parallel embedding framework.

A from-scratch JAX/XLA re-design of the capabilities of
NVIDIA-Merlin/distributed-embeddings (reference: distributed_embeddings/__init__.py:17-27):
model-parallel embedding tables sharded over a `jax.sharding.Mesh`, with the
Horovod all-to-all exchange replaced by XLA collectives inside `shard_map`,
and the CUDA lookup kernels replaced by XLA-native gather/segment-sum plus
optional Pallas kernels.
"""

from distributed_embeddings_tpu.version import __version__

from distributed_embeddings_tpu.ops.embedding_ops import (
    embedding_lookup,
    RaggedIds,
    SparseIds,
)
from distributed_embeddings_tpu.layers.embedding import (
    Embedding,
    ConcatOneHotEmbedding,
    IntegerLookup,
)
from distributed_embeddings_tpu.layers import dist_model_parallel
from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistEmbeddingStrategy,
    DistributedEmbedding,
    broadcast_variables,
)
from distributed_embeddings_tpu.training import (
    BroadcastGlobalVariablesCallback,
    DistributedGradientTape,
    DistributedOptimizer,
)
from distributed_embeddings_tpu import serving
from distributed_embeddings_tpu.serving import (
    HotRowCache,
    InferenceEngine,
    MicroBatcher,
)
from distributed_embeddings_tpu import store
from distributed_embeddings_tpu.store import (
    DeltaConsumer,
    TableStore,
)
from distributed_embeddings_tpu import vocab
from distributed_embeddings_tpu.vocab import VocabManager

__all__ = [
    "__version__",
    "embedding_lookup",
    "RaggedIds",
    "SparseIds",
    "Embedding",
    "ConcatOneHotEmbedding",
    "IntegerLookup",
    "dist_model_parallel",
    "DistEmbeddingStrategy",
    "DistributedEmbedding",
    "broadcast_variables",
    "DistributedGradientTape",
    "DistributedOptimizer",
    "BroadcastGlobalVariablesCallback",
    "serving",
    "InferenceEngine",
    "HotRowCache",
    "MicroBatcher",
    "store",
    "TableStore",
    "DeltaConsumer",
    "vocab",
    "VocabManager",
]
