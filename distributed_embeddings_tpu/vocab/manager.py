"""Dynamic vocabulary manager: streaming admission, cold-row eviction,
and recompile-free table growth (ISSUE 7).

The reference's third pillar is on-the-fly vocabulary building — an
`IntegerLookup` over a device-side cuCollections hash map. We reproduce
the hash-lookup half host-side (`native/hashmap.cpp`); this module turns
it into a full runtime-capacity system for production key spaces that
are unbounded and DRIFT:

  * **Frequency-gated admission.** Raw (untranslated, arbitrary int64)
    keys flow through a per-managed-table `ManagedVocab`. Unknown keys
    translate to the table's FALLBACK row (row 0 — the classic shared
    OOV bucket, exactly `IntegerLookup`'s index-0 contract) or, in
    ``on_miss='drop'`` mode, to zero-weight lanes. A decayed
    `HotnessTracker` counts the raw stream; a key whose recent
    frequency crosses `admit_threshold` is bound to a free physical row
    at the next `maintain()` — from then on it owns private capacity.
  * **Eviction.** When a table's occupancy crosses `high_watermark`,
    the coldest resident keys (by the same decayed counters) are
    demoted back to fallback: their embedding rows are stashed
    host-side, their bindings erased (`IntegerLookup.erase` — the slot
    returns to the free list). A re-admitted key restores its stashed
    row, so a key that oscillates around the threshold does not lose
    its training each cycle.
  * **Recompile-free growth.** The planner pre-reserves
    ``vocab_slack`` rows per managed table
    (`DistributedEmbedding(vocab_slack=)` / ``DET_VOCAB_SLACK``), so
    every admission fills pre-allocated ``[world, rows_max, width]``
    capacity: no array shape ever changes, the jitted train step and
    the serving forward compile exactly once per (plan, batch shape).
    Device writes (admitted-row init/restore, optimizer-row reset) go
    through the same pow2-padded cached row scatter the table store
    uses. At `replan_watermark` occupancy the manager LOGS a re-plan
    recommendation (more slack / bigger plan) — the one thing that
    genuinely needs a recompile is deliberately left to the operator.

Division of labor (one owner per piece of state):

  * binding (key -> physical row) + free slots: the erasable
    `IntegerLookup` — `state_dict` round-trips its key table and free
    list through checkpoints and the publish stream;
  * recent-frequency counters + admission candidates: the shared
    `HotnessTracker` (decay= mode), the same class training hot rows
    and the serving cache admit through;
  * the rows themselves: the layer's stacked params — the manager only
    ever touches them through gather/scatter at maintain time, so
    train/serve steps see ordinary arrays.

Translation is pure host-side numpy on the raw id stream (the same
place `IntegerLookup` already runs) and never enters jit.
"""

import os
import re
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax

from distributed_embeddings_tpu.layers.embedding import IntegerLookup
from distributed_embeddings_tpu.ops import wire as wire_ops
from distributed_embeddings_tpu.ops.embedding_ops import RaggedIds, SparseIds
# one implementation of the pow2-padded cached row scatter/gather
# (out-of-range world index drops) — shared with the table store so the
# per-shape retrace count AND the padded-index convention stay in one
# place across both subsystems
from distributed_embeddings_tpu.store.table_store import (
    padded_gather_rows, padded_scatter_rows)
from distributed_embeddings_tpu.utils.checkpoint import (load_row_delta,
                                                         save_row_delta)
from distributed_embeddings_tpu.utils.hotness import HotnessTracker

__all__ = ["ManagedVocab", "VocabManager", "default_admit_threshold",
           "latest_vocab_state", "vocab_state_path"]

_HOLE = np.iinfo(np.int64).min
# index-rebuild placeholder keys (load_state): astronomically outside any
# plausible raw-key space; erased immediately after replay
_DUMMY_BASE = -(2 ** 62)

_VOCAB_FILE_RE = re.compile(r"^vocab_v(\d{8})\.npz$")


def default_admit_threshold() -> int:
    """`DET_VOCAB_ADMIT` environment default for the admission threshold
    (recent decayed count at which an unknown key earns a private row).
    Default 2: one sighting is noise, a repeat is a signal — the same
    default the serving cache promotes at."""
    from distributed_embeddings_tpu.tune import resolve as _tune_resolve
    try:
        return max(1, int(_tune_resolve.knob_value("DET_VOCAB_ADMIT", "2")))
    except ValueError:
        return 2


def vocab_state_path(directory: str, version: int) -> str:
    """Binding-state sidecar path for one published store version."""
    return os.path.join(directory, f"vocab_v{version:08d}.npz")


def latest_vocab_state(directory: str,
                       upto: Optional[int] = None) -> Optional[str]:
    """Newest ``vocab_v{V}.npz`` sidecar in a publish directory with
    V <= `upto` (None = any) — the binding a consumer loads to match the
    row payloads it just applied."""
    if not os.path.isdir(directory):
        return None
    best = None
    for name in os.listdir(directory):
        m = _VOCAB_FILE_RE.match(name)
        if not m:
            continue
        v = int(m.group(1))
        if upto is not None and v > upto:
            continue
        if best is None or v > best[0]:
            best = (v, os.path.join(directory, name))
    return best[1] if best else None


class ManagedVocab:
    """Binding + admission state of ONE managed table.

    Rows: ``capacity`` physical rows (configured input_dim, which the
    planner already inflated by vocab_slack). Row 0 is the shared
    fallback/OOV row and is never bound; rows 1..capacity-1 are the
    bindable pool. The binding is an erasable `IntegerLookup` whose
    index space IS the row space.
    """

    def __init__(self, table_id: int, capacity: int, base_rows: int,
                 slack: int, admit_threshold: int, decay: float,
                 use_native: Optional[bool] = None,
                 stash_max: Optional[int] = None,
                 stash_dtype: Optional[str] = None,
                 stash_max_bytes: Optional[int] = None):
        if capacity < 2:
            raise ValueError(
                f"managed table {table_id}: capacity {capacity} leaves no "
                "bindable row beyond the fallback")
        self.table_id = int(table_id)
        self.capacity = int(capacity)
        self.base_rows = int(base_rows)
        self.slack = int(slack)
        self.binding = IntegerLookup(max_tokens=capacity - 1,
                                     use_native=use_native)
        if self.binding.native and not getattr(
                self.binding._backend, "supports_erase", True):
            # stale prebuilt .so from before the erasable map (no g++ to
            # rebuild): erase would raise at the FIRST eviction, hours
            # into a run — fall back to the numpy binding now instead
            import warnings
            warnings.warn(
                "native _det_native.so predates il_erase and could not "
                "be rebuilt; vocab binding falls back to the numpy "
                "backend (slower translation, identical semantics)",
                RuntimeWarning, stacklevel=3)
            self.binding = IntegerLookup(max_tokens=capacity - 1,
                                         use_native=False)
        self.tracker = HotnessTracker(
            capacity=capacity - 1, promote_threshold=admit_threshold,
            decay=decay)
        # host-side demotion storage: evicted keys' embedding rows
        # ([table_width] f32), restored verbatim on re-admission.
        # BOUNDED: under a genuinely drifting key universe most evicted
        # keys never return, so an uncapped stash (and therefore every
        # published sidecar, which carries it) would grow for the life
        # of the run. Insertion-ordered dict, oldest demotion dropped
        # first past `stash_max` (default: one table's worth of rows —
        # a key evicted longer ago than capacity-many later evictions
        # restarts from zero, the pre-stash semantics).
        self.stash: Dict[int, np.ndarray] = {}
        self.stash_max = (capacity - 1 if stash_max is None
                          else max(0, int(stash_max)))
        # quantized stash storage (ISSUE 15): evicted rows park at
        # `stash_dtype` (int8/fp8 payload + one f32 scale per row —
        # ~4x more evicted tenants resident per stash byte; re-admission
        # decodes, so the restore differs from the demoted row by at
        # most one quantization step). None defers to DET_STORE_DTYPE;
        # 'f32' keeps the exact pre-seam stash. `stash_max_bytes`
        # optionally bounds the stash in BYTES (oldest demotion drops
        # first, like the row cap) — the budget under which a quantized
        # stash holds ~4x more tenants.
        self.stash_dtype = wire_ops.resolve_store_dtype(
            wire_ops.default_store_dtype() if stash_dtype is None
            else stash_dtype)
        self.stash_max_bytes = (None if stash_max_bytes is None
                                else max(0, int(stash_max_bytes)))
        self._stash_bytes = 0
        # lifetime stats
        self.admissions = 0
        self.evictions = 0
        self.fallback_hits = 0
        self.translated = 0

    # ------------------------------------------------------------ queries
    @property
    def bound(self) -> int:
        """Live bound keys (excludes the fallback row)."""
        return self.binding.size - 1

    @property
    def occupancy(self) -> float:
        """bound / bindable — the watermark the eviction policy runs on."""
        return self.bound / max(self.capacity - 1, 1)

    def resident_keys(self) -> np.ndarray:
        """Bound raw keys ([n] int64, binding-index order)."""
        vocab = self.binding.get_vocabulary()[1:]
        return np.asarray([k for k in vocab if k is not None], np.int64)

    # ---------------------------------------------------------- translate
    def translate(self, keys: np.ndarray) -> np.ndarray:
        """Raw keys -> physical rows; unbound keys -> 0 (fallback row).
        Query-only: never binds, never counts."""
        rows = self.binding.lookup(keys)
        self.translated += int(np.asarray(keys).size)
        self.fallback_hits += int((np.asarray(rows) == 0).sum())
        return rows

    def observe(self, keys: np.ndarray,
                valid: Optional[np.ndarray] = None) -> None:
        """Feed the admission tracker (decayed recent-frequency counts)."""
        self.tracker.observe(keys, valid=valid)

    # ---------------------------------------------------- admission policy
    def pending_fresh(self) -> np.ndarray:
        """Unbound keys whose recent count crossed the admission
        threshold, hottest first ([n] int64) — the admission DEMAND the
        manager sizes eviction against. Stale pendings (keys that got
        bound since crossing) are dropped as a side effect."""
        cands = self.tracker.pending_candidates()
        if not cands:
            return np.empty((0,), np.int64)
        keys = np.asarray([k for _, k in cands], np.int64)
        bound_rows = np.asarray(self.binding.lookup(keys))
        self.tracker.drop_pending(keys[bound_rows != 0])
        return keys[bound_rows == 0]

    def bind(self, keys: Sequence[int]) -> np.ndarray:
        """Bind keys to rows (free-list reuse first). Returns the rows."""
        if not len(keys):
            return np.empty((0,), np.int64)
        arr = np.asarray(keys, np.int64)
        rows = np.asarray(self.binding(arr))
        ok = rows != 0
        self.tracker.drop_pending(arr[ok])
        self.admissions += int(ok.sum())
        return rows

    def plan_evictions(self, low_watermark: float) -> np.ndarray:
        """Coldest resident keys to demote so occupancy lands at
        `low_watermark` ([n] int64; empty when nothing to do)."""
        bindable = self.capacity - 1
        target = int(low_watermark * bindable)
        n_evict = self.bound - target
        if n_evict <= 0:
            return np.empty((0,), np.int64)
        keys = self.resident_keys()
        scores = self.tracker.counts_for(keys)
        order = np.argsort(scores, kind="stable")      # coldest first
        return keys[order[:n_evict]]

    # --------------------------------------------------- stash internals
    @staticmethod
    def _entry_bytes(entry) -> int:
        """Resident bytes of one stash entry: the 8-byte key + payload
        (+ the per-row scale for quantized entries)."""
        if isinstance(entry, tuple):
            return 8 + entry[0].nbytes + 4
        return 8 + entry.nbytes

    def _stash_put(self, key: int, row_f32: np.ndarray) -> None:
        """Insert one demoted row (f32 in, stored at `stash_dtype`) and
        keep both stash bounds: the row cap and the optional byte
        budget, oldest demotion first."""
        old = self.stash.pop(key, None)        # re-stash refreshes age
        if old is not None:
            self._stash_bytes -= self._entry_bytes(old)
        if self.stash_dtype == "f32":
            entry = np.asarray(row_f32, np.float32)
        else:
            p, s = wire_ops.encode_rows_np(
                np.asarray(row_f32, np.float32)[None], self.stash_dtype)
            entry = (p[0], np.float32(s[0, 0]))
        self.stash[key] = entry
        self._stash_bytes += self._entry_bytes(entry)
        while self.stash and (
                len(self.stash) > self.stash_max
                or (self.stash_max_bytes is not None
                    and self._stash_bytes > self.stash_max_bytes)):
            dropped = self.stash.pop(next(iter(self.stash)))
            self._stash_bytes -= self._entry_bytes(dropped)

    def stash_take(self, key: int) -> Optional[np.ndarray]:
        """Pop + decode one stashed row (f32), or None."""
        entry = self.stash.pop(int(key), None)
        if entry is None:
            return None
        self._stash_bytes -= self._entry_bytes(entry)
        if isinstance(entry, tuple):
            return wire_ops.decode_rows_np(
                entry[0], np.asarray(entry[1]).reshape(1),
                self.stash_dtype)
        return entry

    def stash_bytes(self) -> int:
        """Resident stash bytes (keys + payloads + scales) — the
        ``vocab/stash_bytes`` gauge's per-table term."""
        return self._stash_bytes

    def unbind(self, keys: np.ndarray,
               rows_payload: Optional[np.ndarray] = None) -> np.ndarray:
        """Erase bindings (eviction). `rows_payload` ([n, width]) is the
        keys' current embedding rows — stashed (at `stash_dtype`) for
        re-admission. Returns the freed row indices."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        if rows_payload is not None:
            for i, k in enumerate(keys.tolist()):
                self._stash_put(k, rows_payload[i])
        freed = self.binding.erase(keys)
        self.evictions += int((np.asarray(freed) != 0).sum())
        return freed

    # -------------------------------------------------------------- state
    def state_dict(self, full: bool = True) -> Dict[str, np.ndarray]:
        """`full=False` keeps only the serving-critical binding (key
        table + free list): the tracker counters and the demotion stash
        are trainer-resume state and can be a table-sized payload — a
        consumer that only translates must not re-download them on
        every publish."""
        vocab = self.binding.get_vocabulary()[1:]   # index order, None holes
        keys = np.asarray([_HOLE if k is None else k for k in vocab],
                          np.int64)
        out = {"keys": keys,
               "free": np.asarray(self.binding.free_slots(), np.int64)}
        if full:
            ck, cv = self._tracker_items()
            stash_keys = np.asarray(sorted(self.stash), np.int64)
            if self.stash_dtype == "f32":
                stash_rows = (np.stack([self.stash[int(k)]
                                        for k in stash_keys])
                              if len(stash_keys)
                              else np.zeros((0, 0), np.float32))
                out.update({"count_keys": ck, "count_vals": cv,
                            "stash_keys": stash_keys,
                            "stash_rows": stash_rows})
            else:
                # quantized stash (ISSUE 15): checkpoint the payloads at
                # rest — a table-sized stash must not inflate 4x through
                # every save — with the per-row scales as a sibling
                entries = [self.stash[int(k)] for k in stash_keys]
                stash_rows = (np.stack([e[0] for e in entries])
                              if entries else np.zeros((0, 0), np.int8))
                stash_scale = np.asarray([e[1] for e in entries],
                                         np.float32)
                out.update({"count_keys": ck, "count_vals": cv,
                            "stash_keys": stash_keys,
                            "stash_rows": stash_rows,
                            "stash_scale": stash_scale})
        return out

    def _tracker_items(self) -> Tuple[np.ndarray, np.ndarray]:
        # stored counts are in lazily-decayed INFLATED units; persist
        # true units so a restore (fresh tracker, scale 1) is exact
        inv = 1.0 / self.tracker._scale
        items = sorted(self.tracker._counts.items())
        ck = np.asarray([k for k, _ in items], np.int64)
        cv = np.asarray([float(v) * inv for _, v in items], np.float64)
        return ck, cv

    def load_state(self, state: Dict[str, np.ndarray],
                   stash_dtype: str = "f32") -> None:
        """Rebuild binding/free-list/counters exactly from `state_dict`
        output. The index table is replayed in index order with
        placeholder keys in the holes; erasing the placeholders in the
        SAVED free-list order reproduces both the hole pattern and the
        LIFO reuse order bit-exactly."""
        keys = np.asarray(state["keys"], np.int64)
        free = np.asarray(state["free"], np.int64)
        fresh = IntegerLookup(max_tokens=self.capacity - 1,
                              use_native=self.binding.native)
        replay = keys.copy()
        holes = replay == _HOLE
        if holes.any():
            replay[holes] = _DUMMY_BASE - np.arange(len(replay))[holes]
        if len(replay):
            got = np.asarray(fresh(replay))
            expect = np.arange(1, len(replay) + 1)
            if not np.array_equal(got, expect):
                raise ValueError(
                    "vocab state replay produced non-sequential indices "
                    "(corrupt state file or raw keys colliding with the "
                    "reserved placeholder range)")
        if len(free):
            # each erase APPENDS its index to the free list, so erasing
            # the hole placeholders in saved order rebuilds the exact
            # list (and therefore the exact LIFO reuse order)
            dummies = _DUMMY_BASE - (free - 1)
            fresh.erase(dummies)
            rebuilt = np.asarray(fresh.free_slots())
            if not np.array_equal(rebuilt, free):
                raise ValueError("vocab free-list replay mismatch")
        self.binding = fresh
        self.tracker = HotnessTracker(
            capacity=self.capacity - 1,
            promote_threshold=self.tracker.promote_threshold,
            decay=self.tracker.decay)
        ck = np.asarray(state.get("count_keys", []), np.int64)
        cv = np.asarray(state.get("count_vals", []), np.float64)
        self.tracker._counts = {int(k): float(v) for k, v in zip(ck, cv)}
        if len(ck):
            # one vectorized probe for the whole counter set — a per-key
            # loop here would stall every consumer poll that loads a
            # sidecar at production counter counts
            unbound = np.asarray(fresh.lookup(ck)) == 0
            hot = cv >= self.tracker.promote_threshold
            self.tracker._pending = {int(k) for k in ck[unbound & hot]}
        self.stash = {}
        self._stash_bytes = 0
        sk = np.asarray(state.get("stash_keys", []), np.int64)
        sr = np.asarray(state.get("stash_rows", np.zeros((0, 0))))
        # saved entries decode at the SAVED stash dtype, then re-park at
        # this manager's configured dtype (legacy f32 files carry none)
        if wire_ops.resolve_store_dtype(stash_dtype) != "f32":
            sr = wire_ops.decode_rows_np(
                sr, np.asarray(state["stash_scale"],
                               np.float32)[:, None], stash_dtype)
        sr = np.asarray(sr, np.float32)
        for i, k in enumerate(sk.tolist()):
            self._stash_put(k, sr[i])

    def stats(self) -> dict:
        return {"capacity": self.capacity, "base_rows": self.base_rows,
                "slack_rows": self.slack, "bound": self.bound,
                "occupancy": round(self.occupancy, 4),
                "admissions": self.admissions, "evictions": self.evictions,
                "fallback_hits": self.fallback_hits,
                "translated": self.translated,
                "fallback_hit_rate": round(
                    self.fallback_hits / self.translated, 4)
                if self.translated else 0.0,
                "stashed": len(self.stash),
                "stash_bytes": self.stash_bytes(),
                "stash_dtype": self.stash_dtype}


class VocabManager:
    """Runtime vocabulary control for a `DistributedEmbedding`.

    Args:
      emb: the layer (dp-input mode). Managed tables are its
        table-parallel (group 1) tables whose placements are all
        device-resident; dp/row-sliced/offloaded tables pass through
        untranslated (their key spaces stay caller-managed).
      tables: optional explicit global-table-id subset to manage.
      admit_threshold: recent decayed count at which an unknown key is
        bound (None -> `DET_VOCAB_ADMIT`, default 2).
      decay: tracker aging factor per observed batch (default 0.99 —
        a key unseen for ~500 batches ages to noise); 1.0 = all-time
        counts (no drift tracking).
      high_watermark / low_watermark: occupancy that triggers eviction /
        the occupancy eviction drains down to.
      replan_watermark: occupancy at which `maintain` logs the re-plan
        recommendation (the capacity, not the policy, is the problem).
      on_miss: 'fallback' (default) routes unknown keys to row 0;
        'drop' zero-weights their lanes instead (translated inputs
        become (ids, weights) tuples — reducing-combiner inputs only).
      max_admit_per_cycle: bound on bindings per maintain() call
        (None = fill all free slots).
      use_native: force the native/numpy binding backend (tests).
      stash_max: per-table bound on the host-side demotion stash
        (None = one table's worth of rows); the oldest stashed demotion
        drops first, and a dropped key re-admits from zeros.
      stash_dtype: at-rest storage of stashed rows (ISSUE 15): 'f32'
        (exact, default via ``DET_STORE_DTYPE``) or 'int8'/'fp8'
        (per-row-scaled quantized payloads — ~4x more evicted tenants
        resident per stash byte; a re-admitted row restores within one
        quantization step of its demoted value).
      stash_max_bytes: optional per-table BYTE budget on the stash
        (keys + payloads + scales; oldest drops first) — the budget a
        quantized stash holds ~4x more tenants under.
      registry: optional `obs.MetricRegistry` (ISSUE 11) the manager's
        vocabulary metrics land in — ``vocab/admissions`` /
        ``vocab/evictions`` counters and the ``vocab/occupancy`` /
        ``vocab/high_watermark`` / ``vocab/low_watermark`` /
        ``vocab/fallback_hit_rate`` / ``vocab/bound_rows`` gauges
        (updated after every observing translate and every maintain
        cycle). Default: a private registry; `training.fit` rebinds via
        `use_registry`.

    Workflow::

        mgr = VocabManager(emb)
        cats = mgr.translate(raw_cats, observe=True)   # every step
        params, opt = mgr.maintain(params, opt)        # every N steps

    or hand both jobs to ``training.fit(vocab=mgr, vocab_every=N)``.
    """

    def __init__(self, emb, tables: Optional[Sequence[int]] = None,
                 admit_threshold: Optional[int] = None, decay: float = 0.99,
                 high_watermark: float = 0.9, low_watermark: float = 0.75,
                 replan_watermark: float = 0.98, on_miss: str = "fallback",
                 max_admit_per_cycle: Optional[int] = None,
                 use_native: Optional[bool] = None,
                 stash_max: Optional[int] = None,
                 stash_dtype: Optional[str] = None,
                 stash_max_bytes: Optional[int] = None, log_fn=None,
                 registry=None):
        if not emb.dp_input:
            raise ValueError(
                "VocabManager translates data-parallel input batches; this "
                "layer was built with dp_input=False")
        if jax.process_count() > 1:
            # per-process trackers/bindings would silently diverge the
            # SPMD programs' id streams (the TableStore producer's
            # failure mode, and worse: different ROWS per process) —
            # refuse loudly; translate on one controller (or broadcast
            # the binding) is the supported multi-process shape for now
            raise NotImplementedError(
                "VocabManager is single-controller: per-process bindings "
                "would diverge the SPMD id streams. Run admission on one "
                "controller and distribute translated rows (or the saved "
                "binding state) instead.")
        if on_miss not in ("fallback", "drop"):
            raise ValueError(f"on_miss must be 'fallback'|'drop', "
                             f"got {on_miss!r}")
        if not 0.0 < low_watermark <= high_watermark <= 1.0:
            raise ValueError(
                f"need 0 < low_watermark <= high_watermark <= 1, got "
                f"{low_watermark}/{high_watermark}")
        self.emb = emb
        self.on_miss = on_miss
        self.high_watermark = float(high_watermark)
        self.low_watermark = float(low_watermark)
        self.replan_watermark = float(replan_watermark)
        self.max_admit_per_cycle = max_admit_per_cycle
        self.admit_threshold = (default_admit_threshold()
                                if admit_threshold is None
                                else max(1, int(admit_threshold)))
        self._log = log_fn or (lambda msg: None)
        strat = emb.strategy
        eligible = self._eligible_tables()
        if tables is None:
            managed = eligible
        else:
            managed = [int(t) for t in tables]
            bad = [t for t in managed if t not in eligible]
            if bad:
                raise ValueError(
                    f"tables {bad} are not manageable (must be "
                    "table-parallel, non-offloaded, and not in a "
                    "hot-row-replicated bucket — hot write-back and "
                    "vocab rebind would fight over physical rows)")
        if not managed:
            raise ValueError(
                "no manageable tables in this plan (table-parallel, "
                "non-offloaded, hot-rows-free) — a VocabManager here "
                "would silently pass every input through untranslated")
        self.vocabs: Dict[int, ManagedVocab] = {}
        for gtid in managed:
            cfg = strat.global_configs[gtid]
            cap = int(cfg["input_dim"])
            self.vocabs[gtid] = ManagedVocab(
                gtid, capacity=cap,
                base_rows=int(cfg.get("vocab_base_rows", cap)),
                slack=int(cfg.get("vocab_slack", 0)),
                admit_threshold=self.admit_threshold,
                decay=decay, use_native=use_native, stash_max=stash_max,
                stash_dtype=stash_dtype, stash_max_bytes=stash_max_bytes)
        if on_miss == "drop":
            for gtid in self.vocabs:
                if strat.global_configs[gtid].get("combiner") is None:
                    raise ValueError(
                        f"on_miss='drop' zero-weights missed lanes, which "
                        f"needs a reducing combiner; managed table {gtid} "
                        "has combiner=None")
        # per-table placement geometry, precomputed for maintain()
        self._placements = {gtid: self._table_placements(gtid)
                            for gtid in self.vocabs}
        # admitted-slot flat keys per bucket since the last drain — the
        # rows maintain() rewrote, i.e. exactly what a weight-streaming
        # delta must republish (evictions rewrite nothing). Kept as
        # dedup'd sorted arrays merged at write time, so a
        # never-drained manager (no publisher attached) is bounded by
        # bucket capacity, not by run length.
        self._touched: Dict[Tuple[str, int], np.ndarray] = {}
        self.maintain_cycles = 0
        # observing translate() calls — one per training step in the fit
        # wiring, the honest "per step" denominator for eviction rates
        self.observe_steps = 0
        self._replan_warned: set = set()
        from distributed_embeddings_tpu.obs.registry import MetricRegistry
        self._metrics = registry if registry is not None \
            else MetricRegistry()
        # last cumulative totals already exported as counter increments
        self._exported = {"admissions": 0, "evictions": 0}

    def use_registry(self, registry) -> None:
        """Rebind metrics onto `registry` (ISSUE 11; the
        `TableStore.use_registry` idiom — `training.fit` unifies the
        run's namespace through this). Counter baselines carry over, so
        only admissions/evictions that happen AFTER the rebind land in
        the new registry."""
        self._metrics = registry

    def _export_metrics(self) -> None:
        """Refresh the registry view of the manager (cheap: O(tables)
        attribute sums — called per observing translate and per
        maintain cycle). Admissions/evictions export as counter DELTAS
        against the cumulative per-table totals; occupancy/fallback
        rate as gauges."""
        adm = sum(mv.admissions for mv in self.vocabs.values())
        ev = sum(mv.evictions for mv in self.vocabs.values())
        m = self._metrics
        m.counter("vocab/admissions").inc(adm - self._exported["admissions"])
        m.counter("vocab/evictions").inc(ev - self._exported["evictions"])
        self._exported = {"admissions": adm, "evictions": ev}
        cap = sum(mv.capacity - 1 for mv in self.vocabs.values())
        bound = sum(mv.bound for mv in self.vocabs.values())
        tr = sum(mv.translated for mv in self.vocabs.values())
        fb = sum(mv.fallback_hits for mv in self.vocabs.values())
        m.gauge("vocab/occupancy").set(bound / cap if cap else 0.0)
        m.gauge("vocab/bound_rows").set(bound)
        m.gauge("vocab/high_watermark").set(self.high_watermark)
        m.gauge("vocab/low_watermark").set(self.low_watermark)
        m.gauge("vocab/fallback_hit_rate").set(fb / tr if tr else 0.0)
        m.gauge("vocab/maintain_cycles").set(self.maintain_cycles)
        m.gauge("vocab/stash_bytes").set(
            sum(mv.stash_bytes() for mv in self.vocabs.values()))
        for gtid, mv in self.vocabs.items():
            m.gauge("vocab/occupancy", table=gtid).set(mv.occupancy)

    # ---------------------------------------------------------- geometry
    def _eligible_tables(self) -> List[int]:
        """Manageable = table-parallel, non-offloaded, and NOT in a
        hot-row-replicated bucket. The hot-bucket exclusion is a
        correctness gate, not a convenience: while a row is
        hot-resident the replicated hot shard is authoritative and the
        canonical row is stale — eviction would stash the stale copy,
        and a rebind of the freed physical row would be overwritten by
        the OLD tenant's hot row at the next `sync_hot_rows` write-back
        (hot membership is keyed by flat physical row). Until the two
        policies coordinate, a table is managed by at most one of
        them."""
        strat = self.emb.strategy
        out = []
        for t_local, gtid in enumerate(strat.table_groups[1]):
            pls = [pl for pl in self.emb.plan.tp_placements
                   if pl.table_id == t_local]
            if pls and not any(
                    self.emb.plan.tp_buckets[pl.bucket].offload
                    or self.emb.plan.tp_buckets[pl.bucket].hot_rows > 0
                    for pl in pls):
                out.append(gtid)
        return out

    def _table_placements(self, gtid: int):
        t_local = self.emb.strategy.table_groups[1].index(gtid)
        return sorted((pl for pl in self.emb.plan.tp_placements
                       if pl.table_id == t_local),
                      key=lambda pl: pl.col_start)

    # --------------------------------------------------------- translate
    def _managed_for_input(self, i: int) -> Optional[ManagedVocab]:
        return self.vocabs.get(self.emb.strategy.input_table_map[i])

    @staticmethod
    def _host_ids(x) -> np.ndarray:
        return np.asarray(jax.device_get(x)).astype(np.int64)

    def _translate_one(self, mv: ManagedVocab, x, raws_out=None):
        """One input through its table's binding, preserving form.
        `raws_out`: optional list collecting the raw flat keys (the
        caller observes them per TABLE, not per input — see translate)."""
        if isinstance(x, RaggedIds):
            vals = self._host_ids(x.values)
            if raws_out is not None:
                raws_out.append(vals.reshape(-1))
            rows = mv.translate(vals)
            if self.on_miss == "drop":
                raise ValueError(
                    "on_miss='drop' cannot synthesize weights for "
                    "RaggedIds inputs; use dense [B, k] (+weights) forms")
            return RaggedIds(rows.astype(np.int32), x.row_splits)
        if isinstance(x, SparseIds):
            vals = self._host_ids(x.values)
            if raws_out is not None:
                raws_out.append(vals.reshape(-1))
            rows = mv.translate(vals)
            if self.on_miss == "drop":
                raise ValueError(
                    "on_miss='drop' cannot zero-weight SparseIds values; "
                    "use dense [B, k] (+weights) forms")
            return SparseIds(x.indices, rows.astype(np.int32),
                             x.dense_shape)
        weights = None
        if isinstance(x, tuple) and len(x) == 2:
            x, weights = x
        ids = self._host_ids(x)
        orig_dtype = np.asarray(x).dtype
        if not np.issubdtype(orig_dtype, np.integer):
            orig_dtype = np.int32
        if raws_out is not None:
            raws_out.append(ids.reshape(-1))
        rows = mv.translate(ids).astype(orig_dtype)
        if self.on_miss == "drop":
            miss = rows == 0
            w = (np.ones(ids.shape, np.float32) if weights is None
                 else np.asarray(jax.device_get(weights),
                                 np.float32).copy())
            w[miss] = 0.0
            return (rows, w)
        return (rows, weights) if weights is not None else rows

    def translate(self, inputs: Sequence, observe: bool = False) -> List:
        """Translate one batch's raw keys to physical rows (host-side).
        Unmanaged inputs pass through untouched. `observe=True`
        additionally feeds the admission tracker — the training side's
        form; serving translates query-only. Observation is aggregated
        PER TABLE: a table shared by k inputs (input_table_map) gets one
        decay tick per batch over the union stream, not k ticks — the
        aging window is a property of the table, not of how many inputs
        feed it."""
        if len(inputs) != self.emb._n_inputs:
            raise ValueError(
                f"expected {self.emb._n_inputs} inputs, got {len(inputs)}")
        if observe:
            self.observe_steps += 1
        per_table_raws: Dict[int, List[np.ndarray]] = {}
        out = []
        for i, x in enumerate(inputs):
            mv = self._managed_for_input(i)
            if mv is None:
                out.append(x)
                continue
            raws = (per_table_raws.setdefault(mv.table_id, [])
                    if observe else None)
            out.append(self._translate_one(mv, x, raws_out=raws))
        for gtid, chunks in per_table_raws.items():
            self.vocabs[gtid].observe(np.concatenate(chunks))
        if observe:
            # training-side translate = one step: refresh the registry
            # view (fallback-hit rate moves per batch, not per cycle)
            self._export_metrics()
        return out

    # ---------------------------------------------------------- maintain
    def _flat_keys(self, gtid: int, rows: np.ndarray):
        """Physical rows of table `gtid` -> per-bucket (flat keys, col
        ranges): one entry per placement (column slices live on
        different ranks; every slice stores the row)."""
        out = []
        for pl in self._placements[gtid]:
            rows_max = max(self.emb.plan.tp_buckets[pl.bucket].rows_max, 1)
            flat = pl.rank * rows_max + pl.row_offset + rows
            out.append((pl.bucket, flat, pl.col_start, pl.col_end))
        return out

    def _gather_table_rows(self, params: dict, gtid: int,
                           rows: np.ndarray) -> np.ndarray:
        """Current [n, table_width] rows assembled across placements."""
        width = sum(pl.col_end - pl.col_start
                    for pl in self._placements[gtid])
        out = np.zeros((len(rows), width), np.float32)
        for bucket, flat, c0, c1 in self._flat_keys(gtid, rows):
            arr = params["tp"][bucket]
            rows_max = max(self.emb.plan.tp_buckets[bucket].rows_max, 1)
            out[:, c0:c1] = padded_gather_rows(arr, flat // rows_max,
                                               flat % rows_max)
        return out

    def _scatter_bucket(self, arr, flat: np.ndarray, rows_max: int,
                        payload: np.ndarray):
        """Row scatter into one stacked leaf via the store's shared
        pow2-padded kernel (pad lanes drop)."""
        return padded_scatter_rows(arr, flat // rows_max,
                                   flat % rows_max, payload)

    def _write_admitted(self, params: dict, opt_states: Optional[dict],
                        gtid: int, keys: np.ndarray, rows: np.ndarray):
        """Write admitted keys' rows: stashed payload (re-admission) or
        zeros (fresh key), and ZERO the optimizer-state rows of the slot
        — a reused slot must not leak its previous tenant's momentum or
        accumulator."""
        mv = self.vocabs[gtid]
        width = sum(pl.col_end - pl.col_start
                    for pl in self._placements[gtid])
        payload = np.zeros((len(keys), width), np.float32)
        for i, k in enumerate(keys.tolist()):
            stashed = mv.stash_take(k)     # decoded f32 (ISSUE 15)
            if stashed is not None:
                payload[i] = stashed
        new_tp = list(params["tp"])
        new_opt = (None if opt_states is None
                   else {**opt_states, "tp": list(opt_states["tp"])})
        for bucket, flat, c0, c1 in self._flat_keys(gtid, rows):
            rows_max = max(self.emb.plan.tp_buckets[bucket].rows_max, 1)
            new_tp[bucket] = self._scatter_bucket(
                new_tp[bucket], flat, rows_max, payload[:, c0:c1])
            cur = self._touched.get(("tp", bucket))
            self._touched[("tp", bucket)] = (
                np.union1d(cur, flat) if cur is not None
                else np.unique(flat))
            if new_opt is not None:
                shape = tuple(new_tp[bucket].shape[:2])

                def reset_rows(leaf, flat=flat, rows_max=rows_max,
                               shape=shape):
                    if (getattr(leaf, "ndim", 0) >= 2
                            and tuple(leaf.shape[:2]) == shape):
                        zeros = np.zeros(
                            (len(flat),) + tuple(leaf.shape[2:]), np.float32)
                        return self._scatter_bucket(leaf, flat, rows_max,
                                                    zeros)
                    return leaf

                new_opt["tp"][bucket] = jax.tree.map(
                    reset_rows, new_opt["tp"][bucket])
        params = {**params, "tp": new_tp}
        return params, (opt_states if new_opt is None else new_opt)

    def maintain(self, params: dict, opt_states: Optional[dict] = None):
        """Run one admission/eviction cycle against the owned tables.

        Policy (per table): admissions stop at the HIGH watermark, so
        steady-state occupancy never exceeds it; when admission DEMAND
        (pending threshold-crossers) does not fit under that line, the
        coldest residents drain to the LOW watermark first — pressure,
        not occupancy alone, drives eviction, so a stable key universe
        never churns and a drifting one turns over exactly the cold
        tail. When even a full drain cannot absorb the demand, the
        manager logs the re-plan recommendation (more `vocab_slack`):
        capacity, not policy, is the bottleneck.

        Order is load-bearing within a table: evicted rows are gathered
        into the stash BEFORE new keys bind (a freed slot may be
        rebound in the same cycle — the old tenant's row must be
        captured before the new tenant's write). Returns
        (params, opt_states) with touched leaves replaced — same
        shapes/shardings, nothing recompiles.
        """
        self.maintain_cycles += 1
        for gtid, mv in self.vocabs.items():
            bindable = mv.capacity - 1
            cap_rows = int(self.high_watermark * bindable)
            fresh = mv.pending_fresh()
            if len(fresh) > cap_rows - mv.bound:
                # admission pressure beyond the watermark: drain the
                # cold tail first
                evict_keys = mv.plan_evictions(self.low_watermark)
                if len(evict_keys):
                    rows = np.asarray(mv.binding.lookup(evict_keys))
                    payload = self._gather_table_rows(params, gtid, rows)
                    mv.unbind(evict_keys, payload)
            free = cap_rows - mv.bound
            if len(fresh) > max(free, 0) and gtid not in \
                    self._replan_warned:
                self._replan_warned.add(gtid)
                msg = (f"vocab: table {gtid} admission demand "
                       f"({len(fresh)} keys) exceeds post-eviction "
                       f"capacity ({max(free, 0)} free rows under the "
                       f"{self.high_watermark} watermark): re-plan with "
                       "a larger vocab_slack (DET_VOCAB_SLACK) at the "
                       "next restart")
                self._log(msg)
                import warnings
                warnings.warn(msg, RuntimeWarning, stacklevel=2)
            if self.max_admit_per_cycle is not None:
                free = min(free, self.max_admit_per_cycle)
            if free <= 0 or not len(fresh):
                continue
            keys = fresh[:free]
            rows = mv.bind(keys)
            ok = rows != 0
            if ok.any():
                params, opt_states = self._write_admitted(
                    params, opt_states, gtid, keys[ok], rows[ok])
        self._export_metrics()
        return params, opt_states

    @property
    def pending_publication(self) -> bool:
        """True when maintain() rewrote rows that no publication has
        carried yet (fit uses this to force a tail publish — a consumer
        must never miss a rebind's row init)."""
        return any(len(v) for v in self._touched.values())

    def drain_touched(self) -> Dict[Tuple[str, int], np.ndarray]:
        """Flat row keys maintain() rewrote since the last drain, per tp
        bucket — merge into `TableStore.commit(touched=...)` so the next
        published delta republishes rebound rows."""
        out = {k: v for k, v in self._touched.items() if len(v)}
        self._touched = {}
        return out

    # -------------------------------------------------------------- state
    def state_dict(self, full: bool = True
                   ) -> Tuple[dict, Dict[str, np.ndarray]]:
        meta = {"kind": "vocab_state",
                "tables": sorted(self.vocabs),
                "admit_threshold": self.admit_threshold,
                "decay": (self.vocabs[min(self.vocabs)].tracker.decay
                          if self.vocabs else None),
                # stash payload encoding of THIS save (ISSUE 15) — a
                # loader decodes with it, then re-parks at its own
                # configured dtype; legacy files carry none (= f32)
                "stash_dtype": (self.vocabs[min(self.vocabs)].stash_dtype
                                if self.vocabs else "f32"),
                "capacity": {str(t): mv.capacity
                             for t, mv in self.vocabs.items()}}
        arrays = {}
        for gtid, mv in self.vocabs.items():
            for name, arr in mv.state_dict(full=full).items():
                arrays[f"t{gtid}_{name}"] = arr
        return meta, arrays

    def save_state(self, path: str, full: bool = True) -> str:
        """Write the binding state as one npz. `full=True` (checkpoint
        form) carries everything a trainer resume needs: key table,
        free list, decayed counters, demotion stash. `full=False`
        (the publish sidecar form `fit` writes) carries only what a
        translating consumer needs — key table + free list + policy
        header — so per-publish sidecar bytes scale with the BINDING,
        not with a table-sized stash.

        The write is crash-durable like `TableStore.publish` (ISSUE 13):
        fsync file + directory around the atomic rename, and the
        ``vocab.save_state`` fault point can corrupt the payload or
        crash before the rename (consumers verify the container
        checksums on load and keep serving the previous binding)."""
        from distributed_embeddings_tpu import faults
        from distributed_embeddings_tpu.utils.checkpoint import (
            publish_atomic)
        meta, arrays = self.state_dict(full=full)
        final = path if path.endswith(".npz") else path + ".npz"
        spec = faults.check("vocab.save_state", path=final)
        tmp = save_row_delta(path + ".tmp", meta, arrays)
        if spec is not None and spec.kind in faults.CORRUPTING_KINDS:
            faults.corrupt_file(tmp, spec)
        if spec is not None and spec.kind == "crash_before_rename":
            raise faults.InjectedCrash(
                f"save_state {final}: injected crash before rename "
                f"(orphaned {os.path.basename(tmp)})")
        return publish_atomic(tmp, final)

    def load_state(self, path: str) -> None:
        """Restore the full saved state — including the ADMISSION POLICY
        (threshold + decay): a restored manager must resume the saved
        run's behavior, not whatever this instance was constructed with
        (a policy mismatch would silently change which keys admit and
        how fast counters age after every checkpoint restore)."""
        meta, arrays = load_row_delta(path)
        if meta.get("kind") != "vocab_state":
            raise ValueError(f"{path}: not a vocab state file")
        if "admit_threshold" in meta:
            self.admit_threshold = int(meta["admit_threshold"])
        saved_decay = meta.get("decay")
        for gtid, mv in self.vocabs.items():
            # mv.load_state rebuilds the tracker from these fields
            mv.tracker.promote_threshold = self.admit_threshold
            if "decay" in meta:
                mv.tracker.decay = (None if saved_decay is None
                                    else float(saved_decay))
            cap = int(meta.get("capacity", {}).get(str(gtid), mv.capacity))
            if cap != mv.capacity:
                raise ValueError(
                    f"{path}: table {gtid} capacity {cap} != plan "
                    f"capacity {mv.capacity} (different vocab_slack?)")
            prefix = f"t{gtid}_"
            state = {name[len(prefix):]: arr
                     for name, arr in arrays.items()
                     if name.startswith(prefix)}
            if state:
                mv.load_state(state,
                              stash_dtype=meta.get("stash_dtype", "f32"))

    # -------------------------------------------------------------- stats
    def occupancy(self) -> Dict[int, float]:
        return {t: mv.occupancy for t, mv in self.vocabs.items()}

    def stats(self) -> dict:
        per = {t: mv.stats() for t, mv in self.vocabs.items()}
        tot_cap = sum(mv.capacity - 1 for mv in self.vocabs.values())
        tot_bound = sum(mv.bound for mv in self.vocabs.values())
        tot_tr = sum(mv.translated for mv in self.vocabs.values())
        tot_fb = sum(mv.fallback_hits for mv in self.vocabs.values())
        return {
            "tables": per,
            "occupancy": round(tot_bound / tot_cap, 4) if tot_cap else 0.0,
            "bound": tot_bound,
            "admissions": sum(mv.admissions for mv in self.vocabs.values()),
            "evictions": sum(mv.evictions for mv in self.vocabs.values()),
            "fallback_hit_rate": round(tot_fb / tot_tr, 4) if tot_tr
            else 0.0,
            "maintain_cycles": self.maintain_cycles,
        }
