"""Dynamic vocabulary management (ISSUE 7): streaming admission of new
raw keys, cold-row eviction, and recompile-free table growth over
pre-reserved slack rows. See `vocab.manager` for the design notes."""

from distributed_embeddings_tpu.vocab.manager import (  # noqa: F401
    ManagedVocab, VocabManager, default_admit_threshold,
    latest_vocab_state, vocab_state_path)

__all__ = ["ManagedVocab", "VocabManager", "default_admit_threshold",
           "latest_vocab_state", "vocab_state_path"]
