"""TableStore: one versioned owner for a DistributedEmbedding's tables.

Before this subsystem, training (`layers/dist_model_parallel.py` + the
hot-row shard) and serving (`serving/engine.py` / `serving/cache.py`)
each held their own copy of table, optimizer and hot-row state,
reconciled only by whole-table `refresh()` / `sync_hot_rows()` steps —
there was no way to push an updated table into a running
`InferenceEngine` short of a restart or a full-table copy. `TableStore`
is the parameter-server-style answer:

  * **One source of truth.** The store owns the layer's params pytree
    (per-bucket fused tables, row-sliced tables, dp tables, hot
    membership) and optimizer state behind one interface. Its
    `read_rows` is THE versioned read — canonical table rows with the
    AUTHORITATIVE hot-resident rows overlaid, via the same
    `DistributedEmbedding.hot_resident_rows` helper `get_weights` uses,
    so a stale overlay (the old two-path failure, where serving and
    checkpointing re-derived resident rows independently) is
    structurally impossible.
  * **Monotonic versions.** Every `commit`/`replace`/`sync_hot_rows`
    bumps the store version; per-original-table versions record the
    last commit that touched each table (`table_versions`).
  * **Row-delta publication.** The training side accumulates the
    sparse update's touched-row sets host-side (`observe`, mirroring
    `DistributedEmbedding.touched_row_keys` — the same dedup'd
    post-sentinel-mask id stream PR 2's `canonical_id_sort`/`dedup_sum`
    consume on device) and `publish`es them as row-delta files: dedup'd
    touched keys + MERGED row payloads + a version header
    (`utils/checkpoint.save_row_delta`). The first publish — and every
    `snapshot_every`-th after — is a full-snapshot compaction so a
    fresh replica (or one that fell off the delta chain) can resync.
  * **In-place consumption.** A consumer-side store applies deltas
    without recompiling or copying full tables: HBM buckets via a
    cached jitted row scatter, host-offloaded buckets via the existing
    `host_apply_rows_inplace` seam (`kind="set"`), dp tables by
    replicated replacement (they train dense — every row may move, and
    they are small by construction, so each delta carries them whole).
    `DeltaConsumer` drives a directory poll loop with
    staleness-vs-publish accounting (version lag + seconds).

Payload semantics (load-bearing): delta rows are the MERGED view
(`read_rows`), so a consumer's canonical tables reproduce the
publisher's `get_weights` output bit-exactly at every consumed version
— whether or not the publisher had hot-resident rows at the time. A
consumer with a NON-EMPTY hot set of its own would shadow those writes,
so delta application refuses it (serving replicas are hot-less by
construction; call `sync_hot_rows` + re-admit after a snapshot if you
must consume into a training layer).

Multi-process note: the producer side (`observe`/`publish`/`read_rows`)
is SINGLE-CONTROLLER for now and raises under multi-process meshes —
touched-row observation and row reads see only this process's
addressable shards, so a multi-process publish would silently drop rows
touched or stored on other processes (the one failure mode the delta
contract cannot tolerate). Gather to one controller first (e.g. publish
from a `get_weights` snapshot), or run the publisher single-process;
consumer-side `apply_published` must be called collectively (every
process, same file) like any other SPMD param update.
"""

import os
import re
import time
import warnings
from typing import Dict, List, Optional, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu import faults
from distributed_embeddings_tpu.obs.trace import default_recorder
from distributed_embeddings_tpu.ops import sparse_update as sparse_update_ops
from distributed_embeddings_tpu.ops import wire as wire_ops
from distributed_embeddings_tpu.utils import checkpoint as ckpt_lib

__all__ = ["DeltaChainError", "DeltaConsumer", "TableStore",
           "padded_gather_rows", "padded_scatter_rows",
           "restore_from_published", "scan_published"]


# ------------------------------------------------- failure classification
# (ISSUE 13) Two failure classes a consumer must tell apart:
#   * TRANSIENT — the read may succeed if retried (filesystem flake,
#     injected `InjectedIOError`): capped-exponential-backoff retry, give
#     the file up for THIS poll if retries exhaust (the next poll tries
#     again — serving latency must not absorb unbounded sleeps).
#   * CORRUPT — the file's bytes are wrong and, streams being immutable
#     once renamed into place, will stay wrong forever: quarantine (skip
#     permanently + `store/corrupt_files_total` + one loud warning) and
#     let the chain re-anchor on the next snapshot. The load layer
#     (`checkpoint.load_row_delta*`) funnels every parse-level failure
#     — bad zip, member CRC, torn payload, unparseable header — into
#     `StreamIntegrityError`, so corruption is exactly ONE type here.
# Anything else is a programming/config error and propagates (a
# shape-signature mismatch or a hot-resident guard must fail loudly,
# not quarantine a healthy stream; the serving engine's `poll_updates`
# still converts it to degraded mode).
def _is_transient_error(e: BaseException) -> bool:
    return isinstance(e, OSError)


def _is_corrupt_error(e: BaseException) -> bool:
    return isinstance(e, ckpt_lib.StreamIntegrityError)


class DeltaChainError(RuntimeError):
    """A delta's base_version does not match the consumer's version —
    the consumer fell off the publish chain (missed/compacted file) and
    must resync from a snapshot."""


# cached jitted row scatter/gather over stacked [world, rows, w] params:
# out-of-range w_idx (the pad sentinel == world) drops, so delta batches
# pad to power-of-2 sizes and the per-shape retrace count stays bounded.
@jax.jit
def _scatter_rows(stack, w_idx, r_idx, rows):
    return stack.at[w_idx, r_idx].set(rows.astype(stack.dtype), mode="drop")


@jax.jit
def _gather_rows(stack, w_idx, r_idx):
    return stack[w_idx, r_idx]


def _next_pow2(n: int) -> int:
    return 1 << max(int(max(n, 1) - 1).bit_length(), 0)


def padded_gather_rows(arr, w_idx: np.ndarray,
                       r_idx: np.ndarray) -> np.ndarray:
    """Rows of a stacked [world, rows, w] param at (w_idx, r_idx), via
    the cached jitted gather over pow2-padded (clipped) indices — the
    ONE padded-index preparation both the store and the vocab manager
    batch row reads through (the per-shape retrace count stays bounded
    across both subsystems)."""
    n = len(w_idx)
    m = _next_pow2(n)
    wp = np.zeros((m,), np.int64)
    rp = np.zeros((m,), np.int64)
    wp[:n] = np.clip(w_idx, 0, arr.shape[0] - 1)
    rp[:n] = np.clip(r_idx, 0, arr.shape[1] - 1)
    return np.asarray(_gather_rows(arr, jnp.asarray(wp),
                                   jnp.asarray(rp)))[:n]


def padded_scatter_rows(arr, w_idx: np.ndarray, r_idx: np.ndarray,
                        rows: np.ndarray):
    """Set rows of a stacked param at (w_idx, r_idx) via the cached
    jitted scatter; pow2-pad lanes carry an out-of-range world index
    and drop. Shared by delta apply and vocab admission writes."""
    n = len(w_idx)
    m = _next_pow2(n)
    wp = np.full((m,), arr.shape[0], np.int64)     # OOB pad -> dropped
    rp = np.zeros((m,), np.int64)
    vp = np.zeros((m,) + tuple(rows.shape[1:]), np.float32)
    wp[:n], rp[:n], vp[:n] = w_idx, r_idx, rows
    return _scatter_rows(arr, jnp.asarray(wp), jnp.asarray(rp),
                         jnp.asarray(vp))


def _np_rows_from_shards(arr, w_idx: np.ndarray,
                         r_idx: np.ndarray) -> np.ndarray:
    """Row gather from a (host-resident) stacked array via its
    addressable shards — no XLA program touches the host placement.
    Output is f32 VALUES regardless of the stored dtype (int8/fp8
    payloads cast losslessly; the caller multiplies in the per-row
    scale for quantized buckets)."""
    out = np.zeros((len(w_idx), arr.shape[-1]), np.float32)
    for sh in arr.addressable_shards:
        start = sh.index[0].start or 0
        data = np.asarray(sh.data)
        for j in range(data.shape[0]):
            m = w_idx == start + j
            if m.any():
                out[m] = data[j][r_idx[m]]
    return out


def _host_set_rows(table_h, w_idx: np.ndarray, r_idx: np.ndarray,
                   rows: np.ndarray):
    """Set rows of a pinned-host stacked bucket in place, shard by shard,
    through the `host_apply_rows_inplace` seam (kind='set') — the same
    XLA-free path the offloaded sparse apply uses, so only the delta rows
    ever cross a memory boundary."""
    new_shards = []
    for sh in table_h.addressable_shards:
        start = sh.index[0].start or 0
        stop = start + sh.data.shape[0]
        hit = (w_idx >= start) & (w_idx < stop)
        if not hit.any():
            # untouched shard: pass the existing buffer through — the
            # rows-only-traffic contract (no full-shard copy/restage for
            # world slices the delta never reaches)
            new_shards.append(sh.data)
            continue
        t_np = np.array(sh.data)               # host->host copy, mutable
        for j in range(t_np.shape[0]):
            m = w_idx == start + j
            if m.any():
                n = int(m.sum())
                if t_np.dtype == np.float32:
                    sparse_update_ops.host_apply_rows_inplace(
                        "set", t_np[j], (),
                        np.ascontiguousarray(r_idx[m], np.int32),
                        np.ascontiguousarray(rows[m], np.float32),
                        np.ones((n,), np.float32), 0.0)
                else:
                    # quantized payload/scale leaves (ISSUE 15): the
                    # C++ row kernels are f32-only; a plain fancy-index
                    # set is the same rows-only write at these dtypes
                    t_np[j][r_idx[m]] = np.asarray(rows[m], t_np.dtype)
        new_shards.append(jax.device_put(t_np, sh.data.sharding))
    return jax.make_array_from_single_device_arrays(
        table_h.shape, table_h.sharding, new_shards)


_FILE_RE = re.compile(r"^stream_v(\d{8})_(delta|snapshot)\.npz$")


def _publish_path(directory: str, version: int, kind: str) -> str:
    return os.path.join(directory, f"stream_v{version:08d}_{kind}.npz")


def scan_published(directory: str) -> List[Tuple[int, str, str]]:
    """Sorted [(version, kind, path)] of the publish stream in a
    directory (the delta log a consumer polls). The ``store.scan``
    fault point filters the result (delayed-visibility injection: a
    lagging directory view hides fresh files for N scans)."""
    if not os.path.isdir(directory):
        return []
    out = []
    for name in os.listdir(directory):
        m = _FILE_RE.match(name)
        if m:
            out.append((int(m.group(1)), m.group(2),
                        os.path.join(directory, name)))
    return faults.filter_scan("store.scan", sorted(out))


class TableStore:
    """Versioned owner of one `DistributedEmbedding`'s parameter state.

    Args:
      emb: the `DistributedEmbedding` whose plan keys everything.
      params: the layer params pytree ({'dp', 'tp', 'row'[, 'hot']}).
      opt_states: optional sparse-optimizer state pytree (training side).
      snapshot_every: every N-th publish is a full-snapshot compaction
        (0/None = only the mandatory first publish; env default
        `DET_STORE_SNAPSHOT_EVERY`).
      delta_dtype: payload dtype of published stream files (ISSUE 15):
        'f32' (default — byte-identical files to the pre-seam
        container), 'int8' or 'fp8' (per-row-scaled quantized row
        payloads, ~4x smaller; the container header carries the dtype
        and consumers decode on apply). None defers to
        ``DET_DELTA_DTYPE``. Keys stay int64 and dp tables stay f32
        (dense-trained and small by construction). Applies to what THIS
        store publishes; consuming is driven by each file's header.
      registry: optional `obs.MetricRegistry` (ISSUE 11) the store's
        streaming metrics land in — producer counters
        (``store/publishes``, ``store/publish_bytes``,
        ``store/publish_rows``), consumer counters (``store/applies``,
        ``store/apply_bytes``, ``store/apply_rows``) and the
        ``store/version{role=publisher|consumer}`` gauges;
        `DeltaConsumer` adds the staleness
        family (``store/version_lag``,
        ``store/publish_to_apply_seconds``). Default: a private
        registry; `training.fit` rebinds its publisher store onto the
        run registry via `use_registry`.
    """

    def __init__(self, emb, params: dict, opt_states: Optional[dict] = None,
                 snapshot_every: Optional[int] = None, registry=None,
                 delta_dtype: Optional[str] = None):
        from distributed_embeddings_tpu.obs.registry import MetricRegistry
        self._metrics = registry if registry is not None \
            else MetricRegistry()
        self.emb = emb
        self._params = params
        self._opt = opt_states
        if snapshot_every is None:
            from distributed_embeddings_tpu.tune import resolve \
                as _tune_resolve
            snapshot_every = int(_tune_resolve.knob_value(
                "DET_STORE_SNAPSHOT_EVERY", "0"))
        self.snapshot_every = int(snapshot_every)
        self.delta_dtype = (wire_ops.default_delta_dtype()
                            if delta_dtype is None
                            else wire_ops.resolve_store_dtype(delta_dtype))
        # cumulative published bytes per payload dtype -> the
        # ``store/bytes{dtype=}`` gauge (docs/observability.md)
        self._published_bytes_by_dtype: Dict[str, int] = {}
        self.version = 0
        strat = emb.strategy
        self._n_tables = len(strat.global_configs)
        self.table_versions = [0] * self._n_tables
        # plan signature: consumers refuse a stream published for a
        # different model (shape mismatch would otherwise scatter-drop
        # or corrupt silently)
        self._sig = [(int(c["input_dim"]), int(c["output_dim"]))
                     for c in strat.global_configs]
        # kind/index -> original table ids (version bookkeeping)
        self._bucket_tables: Dict[int, List[int]] = {}
        for pl in emb.plan.tp_placements:
            gtid = strat.table_groups[1][pl.table_id]
            self._bucket_tables.setdefault(pl.bucket, [])
            if gtid not in self._bucket_tables[pl.bucket]:
                self._bucket_tables[pl.bucket].append(gtid)
        self._row_tables = list(strat.table_groups[2])
        self._dp_tables = list(strat.table_groups[0])
        # producer-side accumulation: touched flat keys since last
        # publish, and the kinds touched since the last commit (drives
        # per-table version bumps)
        self._pending: Dict[Tuple[str, int], np.ndarray] = {}
        self._since_commit: set = set()
        self._publishes = 0
        # version of the last publish (None = never published: the next
        # publish is forced to a snapshot so consumers have an anchor)
        self._published_version: Optional[int] = None
        # consumer-side chain marker: True after an out-of-band swap
        # (`replace`/`set_weights`) until the next SNAPSHOT apply. The
        # version counter alone cannot carry this — a local bump lands
        # in the same integer namespace as the publisher's versions, so
        # one publish later a delta's base_version could alias the
        # replaced state and chain onto unrelated tables silently.
        self._chain_broken = False
        # directories whose orphaned tmp files this publisher already
        # swept (once per directory per store — publisher startup)
        self._swept_dirs: set = set()

    # ------------------------------------------------------------- state
    def use_registry(self, registry) -> None:
        """Rebind the store's metrics onto `registry` (ISSUE 11) —
        `training.fit` calls this so a run's publisher reports into the
        ONE run registry. Counts accumulated in the previous registry
        stay there (instruments are resolved per event, not cached)."""
        self._metrics = registry

    @property
    def params(self) -> dict:
        return self._params

    @property
    def opt_states(self) -> Optional[dict]:
        return self._opt

    def full_table_bytes(self) -> int:
        """Bytes of one full portable copy of every table (f32) — the
        denominator of the delta-vs-full-copy accounting."""
        return sum(v * w * 4 for v, w in self._sig)

    @staticmethod
    def _require_single_controller(what: str) -> None:
        """The producer-side reads are process-local (addressable shards
        only): under multi-process they would silently DROP rows touched
        or stored on other processes — the one failure a SET-payload
        delta cannot tolerate — so they refuse loudly instead."""
        if jax.process_count() > 1:
            raise NotImplementedError(
                f"TableStore.{what} is single-controller: it reads only "
                "this process's batch/table shards, so a multi-process "
                "publish would silently drop other processes' rows. "
                "Publish from one controller over gathered state, or run "
                "the training publisher single-process.")

    # ------------------------------------------------- producer: touched
    def observe(self, inputs) -> None:
        """Accumulate the touched-row sets of one training batch
        (host-side numpy; the same per-bucket flat keys the sparse
        update writes — see `DistributedEmbedding.touched_row_keys`).
        Call once per step on the SAME inputs `apply` sees; the union
        since the last publish becomes the next delta's key set."""
        self._require_single_controller("observe")
        touched = self.emb.touched_row_keys(inputs)
        self._merge_touched(touched)

    def _merge_touched(self, touched: Dict[Tuple[str, int], np.ndarray]):
        for key, keys in touched.items():
            keys = np.asarray(keys, np.int64).reshape(-1)
            if not len(keys):
                continue
            cur = self._pending.get(key)
            self._pending[key] = (np.union1d(cur, keys)
                                  if cur is not None else np.unique(keys))
            self._since_commit.add(key)

    def commit(self, params: dict, opt_states: Optional[dict] = None,
               touched: Optional[Dict[Tuple[str, int], np.ndarray]] = None
               ) -> int:
        """Swap in the post-step pytrees and bump the store version.
        `touched` optionally merges extra touched keys (same shape as
        `touched_row_keys` output) for callers that track them
        elsewhere. Returns the new version."""
        if touched:
            self._merge_touched(touched)
        self._params = params
        if opt_states is not None:
            self._opt = opt_states
        self.version += 1
        # dp tables train dense: every commit may move every dp row
        for gtid in self._dp_tables:
            self.table_versions[gtid] = self.version
        for kind, idx in self._since_commit:
            gtids = (self._bucket_tables.get(idx, []) if kind == "tp"
                     else [self._row_tables[idx]])
            for gtid in gtids:
                self.table_versions[gtid] = self.version
        self._since_commit = set()
        # lineage (ISSUE 14): a commit OPENS version V's async track in
        # the flight recorder — publish/scan/apply/serve land on it
        default_recorder().lineage(self.version, "commit")
        return self.version

    def replace(self, params: dict, opt_states: Optional[dict] = None) -> int:
        """Full out-of-band swap (e.g. `InferenceEngine.set_params`):
        bumps the version and BREAKS the delta chain — the next publish
        is forced to a snapshot, and a consumer store that replaced its
        params mid-stream resyncs at the next snapshot."""
        self._params = params
        if opt_states is not None:
            self._opt = opt_states
        self.version += 1
        for gtid in range(self._n_tables):
            self.table_versions[gtid] = self.version
        self._pending = {}
        self._since_commit = set()
        self._published_version = None
        self._chain_broken = True
        return self.version

    # -------------------------------------------------- versioned reads
    def table(self, kind: str, idx: int):
        """The current param leaf for ('tp'|'row'|'dp', index) — use this
        (never a cached array reference) wherever code needs the table a
        serving path reads, so the read is at the store's version by
        construction."""
        return self._params[kind][idx]

    def read_rows(self, b: int, keys) -> np.ndarray:
        """THE versioned read of tp bucket `b`: rows for flat keys
        (`rank * rows_max + row`, the layout `HotRowCache` and the hot
        shard share), canonical table values with the authoritative
        hot-resident rows overlaid — byte-identical to what
        `get_weights` would report for those rows at this version."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        bucket = self.emb.plan.tp_buckets[b]
        rows_max = max(bucket.rows_max, 1)
        arr = self._params["tp"][b]
        w_idx = keys // rows_max
        r_idx = keys % rows_max
        sd = self.emb._bucket_store_dtype(b)
        if self.emb._bucket_memory_kind(b):
            out = _np_rows_from_shards(arr, w_idx, r_idx)
            if sd != "f32":
                # quantized at-rest storage (ISSUE 15): the versioned
                # read is ALWAYS decoded f32 — payload values (cast
                # losslessly above) x the per-row scale leaf
                out = out * _np_rows_from_shards(
                    self._params["tp_scale"][b], w_idx, r_idx)
        else:
            out = padded_gather_rows(arr, w_idx, r_idx)
            if sd != "f32":
                # HBM-resident quantized buckets (ISSUE 17): payload
                # codes gather losslessly through the f32 transit, so
                # decode is the same multiply by the scale rows
                out = out * padded_gather_rows(
                    self._params["tp_scale"][b], w_idx, r_idx)
        overlay = self.emb.hot_resident_rows(self._params).get(b)
        if overlay is not None:
            okeys, orows = overlay                 # sorted by construction
            pos = np.searchsorted(okeys, keys)
            pos_c = np.minimum(pos, len(okeys) - 1)
            hit = (pos < len(okeys)) & (okeys[pos_c] == keys)
            if hit.any():
                out = np.array(out)
                out[hit] = orows[pos_c[hit]]
        return out.astype(np.float32)

    def read_row_table_rows(self, t: int, keys) -> np.ndarray:
        """Versioned read of row-sliced table `t` by GLOBAL row ids."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        rt = self.emb.plan.row_tables[t]
        base = np.asarray(rt.row_base, np.int64)
        w_idx = np.searchsorted(base, keys, side="right") - 1
        r_idx = keys - base[w_idx]
        arr = self._params["row"][t]
        return padded_gather_rows(arr, w_idx, r_idx)

    def get_weights(self) -> List[np.ndarray]:
        """Portable merged per-table weights at the current version
        (delegates to `DistributedEmbedding.get_weights`, whose hot
        overlay reads the same `hot_resident_rows` source as
        `read_rows`)."""
        return self.emb.get_weights(self._params)

    def set_weights(self, weights) -> int:
        """Rebuild params from portable per-table weights (empty hot
        set, like `DistributedEmbedding.set_weights`) and bump the
        version. Chain-breaking like `replace`."""
        params = self.emb.set_weights(weights)
        return self.replace(params, self._opt)

    def sync_hot_rows(self, new_keys=None, admit: bool = False) -> int:
        """Route the hot shard's consistency step through the store:
        write-back + optional re-admission on the OWNED pytrees, then a
        version bump. The merged view (`read_rows`/`get_weights`) is
        invariant under this step — only the canonical/hot split moves."""
        p, s = self.emb.sync_hot_rows(self._params, self._opt,
                                      new_keys=new_keys, admit=admit)
        self._params = p
        if s is not None:
            self._opt = s
        self.version += 1
        for b in self.emb._hot_buckets:
            for gtid in self._bucket_tables.get(b, []):
                self.table_versions[gtid] = self.version
        return self.version

    # --------------------------------------------------------- publishing
    def publish(self, directory: str, force_snapshot: bool = False) -> dict:
        """Write the next stream file into `directory`.

        The first publish (and every `snapshot_every`-th, and any forced
        one) is a full snapshot: one merged per-table array each, the
        compaction consumers resync from. Otherwise a row-delta: per
        touched tp bucket / row table the dedup'd keys + merged row
        payloads accumulated by `observe`/`commit` since the last
        publish, plus the dp tables whole. Requires a commit since the
        last publish (versions must be distinct per file).

        Robustness (ISSUE 13): the first publish into a directory sweeps
        orphaned ``*.tmp*`` files a crashed predecessor left; the stream
        file is fsync'd before — and its directory after — the atomic
        rename (rename is atomic against concurrent readers but not
        against power loss); and all publisher state (`_publishes`,
        `_published_version`, the pending touched keys) moves ONLY after
        the rename lands, so an injected `InjectedCrash` (or a real
        exception) between write and rename leaves the publisher able to
        retry the same content under a later version. The
        ``store.publish`` fault point wraps the write: ``pause`` skips
        the publish (returns ``{"kind": "paused", ...}``, state kept),
        ``truncate``/``bit_flip`` corrupt the renamed-in file (the
        consumer's quarantine path owns those), ``crash_before_rename``
        raises after writing the tmp file.

        Returns {"kind", "version", "base_version", "path", "bytes",
        "rows"}."""
        self._require_single_controller("publish")
        if self.version == self._published_version:
            raise ValueError(
                "publish: nothing committed since the last publish "
                "(stream files are keyed by version)")
        os.makedirs(directory, exist_ok=True)
        m = self._metrics
        if directory not in self._swept_dirs:
            self._swept_dirs.add(directory)
            removed = ckpt_lib.sweep_orphan_tmp(directory)
            if removed:
                m.counter("store/orphan_tmp_swept_total").inc(len(removed))
                warnings.warn(
                    f"publish: swept {len(removed)} orphaned tmp file(s) "
                    f"from {directory} (crashed publisher leftovers): "
                    f"{[os.path.basename(p) for p in removed]}",
                    RuntimeWarning, stacklevel=2)
        publishes = self._publishes + 1
        snap = (force_snapshot or self._published_version is None
                or (self.snapshot_every
                    and publishes % self.snapshot_every == 0))
        dd = self.delta_dtype
        meta = {"version": self.version,
                "base_version": self._published_version,
                "published_at": time.time(),
                "dtype": dd,
                "sig": self._sig}

        def enc(arrays, name, rows):
            # quantized stream payload (ISSUE 15): rows encode at the
            # store's delta_dtype with the per-row scale as a sibling
            # array; f32 writes the rows verbatim (byte-identical file)
            p, s = wire_ops.encode_rows_np(rows, dd)
            arrays[name] = p
            if s is not None:
                arrays[f"{name}_scale"] = s

        # model payload bytes through the ONE shared formula
        # (ops/wire.delta_row_bytes / snapshot_row_bytes) — the bench's
        # measured-vs-model reconciliation and `exchange_padding_report`
        # charge the same arithmetic
        model_bytes = 0
        if snap:
            meta["kind"] = "snapshot"
            weights = self.get_weights()
            arrays = {}
            for i, w in enumerate(weights):
                enc(arrays, f"table{i}", np.asarray(w, np.float32))
                model_bytes += w.shape[0] * wire_ops.snapshot_row_bytes(
                    w.shape[1], dd)
            n_rows = sum(w.shape[0] for w in weights)
        else:
            meta["kind"] = "delta"
            arrays = {}
            n_rows = 0
            for (kind, idx), keys in sorted(self._pending.items()):
                rows = (self.read_rows(idx, keys) if kind == "tp"
                        else self.read_row_table_rows(idx, keys))
                arrays[f"{kind}{idx}_keys"] = keys
                enc(arrays, f"{kind}{idx}_rows", rows)
                model_bytes += len(keys) * wire_ops.delta_row_bytes(
                    rows.shape[1], dd)
                n_rows += len(keys)
            for j in range(len(self._params["dp"])):
                # dp tables stay f32: dense-trained (every row moves
                # every delta) and small by construction
                dp = np.asarray(self._params["dp"][j], np.float32)
                arrays[f"dp{j}_full"] = dp
                model_bytes += dp.nbytes
                n_rows += dp.shape[0]
        path = _publish_path(directory, self.version, meta["kind"])
        spec = faults.check("store.publish", path=path,
                            stream_kind=meta["kind"])
        if spec is not None:
            m.counter("store/publish_faults_total", kind=spec.kind).inc()
        if spec is not None and spec.kind == "pause":
            # publisher pause: nothing written, nothing advanced — the
            # pending touched keys ride into the next (resumed) publish
            return {"kind": "paused", "version": self.version,
                    "base_version": meta["base_version"], "path": None,
                    "bytes": 0, "rows": 0}
        # atomic publication: a concurrent consumer's directory scan must
        # never see a half-written file (the tmp name does not match the
        # stream pattern, and os.replace is atomic on one filesystem);
        # fsync file-then-rename-then-directory makes it crash-durable
        tmp = ckpt_lib.save_row_delta(path + ".tmp", meta, arrays)
        if spec is not None and spec.kind in faults.CORRUPTING_KINDS:
            faults.corrupt_file(tmp, spec)
        if spec is not None and spec.kind == "crash_before_rename":
            raise faults.InjectedCrash(
                f"publish {path}: injected crash before rename "
                f"(orphaned {os.path.basename(tmp)})")
        ckpt_lib.publish_atomic(tmp, path)
        self._publishes = publishes
        self._published_version = self.version
        self._pending = {}
        info = {"kind": meta["kind"], "version": self.version,
                "base_version": meta["base_version"], "path": path,
                "bytes": os.path.getsize(path), "rows": n_rows,
                "dtype": dd,
                # measured sum of in-file array bytes vs the shared byte
                # model (wire.delta_row_bytes/snapshot_row_bytes) — equal
                # by construction; the bench and tier-1 assert it stays so
                "payload_bytes": int(sum(a.nbytes
                                         for a in arrays.values())),
                "model_payload_bytes": int(model_bytes)}
        m.counter("store/publishes").inc()
        m.counter("store/publish_bytes").inc(info["bytes"])
        m.counter("store/publish_rows").inc(n_rows)
        self._published_bytes_by_dtype[dd] = (
            self._published_bytes_by_dtype.get(dd, 0) + info["bytes"])
        m.gauge("store/bytes", dtype=dd).set(
            self._published_bytes_by_dtype[dd])
        # role-labeled: a publisher and a consumer store on ONE shared
        # run registry (the bench serve mode shape) must not flap a
        # single version gauge between the two meanings
        m.gauge("store/version", role="publisher").set(self.version)
        default_recorder().lineage(self.version, "publish",
                                   kind=meta["kind"], bytes=info["bytes"],
                                   rows=n_rows)
        return info

    # --------------------------------------------------------- consuming
    def _check_sig(self, meta: dict, path: str) -> None:
        sig = [tuple(int(x) for x in pair) for pair in meta.get("sig", [])]
        if sig != self._sig:
            raise ValueError(
                f"{path}: published for a different model "
                f"(table shapes {sig} != {self._sig})")

    def _hot_resident_guard(self) -> None:
        if self.emb.hot_resident_rows(self._params):
            raise ValueError(
                "delta consumption requires an EMPTY hot set on the "
                "consumer: resident hot rows would shadow the canonical "
                "writes (serving replicas are hot-less; training "
                "consumers must sync_hot_rows + drop residency first)")

    def _apply_tp_rows(self, b: int, keys: np.ndarray, rows: np.ndarray):
        """Set decoded f32 `rows` into bucket b. Returns (table, scale):
        scale is None for f32-stored buckets; quantized buckets (ISSUE
        15) re-encode the incoming rows at the bucket's storage dtype
        (deterministic RNE — stream application must be reproducible)
        and write payload + per-row scale leaves in one pass."""
        bucket = self.emb.plan.tp_buckets[b]
        rows_max = max(bucket.rows_max, 1)
        arr = self._params["tp"][b]
        w_idx = keys // rows_max
        r_idx = keys % rows_max
        sd = self.emb._bucket_store_dtype(b)
        if sd != "f32":
            payload, scale = wire_ops.encode_rows_np(rows, sd)
            if self.emb._bucket_memory_kind(b):
                return (_host_set_rows(arr, w_idx, r_idx, payload),
                        _host_set_rows(self._params["tp_scale"][b],
                                       w_idx, r_idx, scale))
            # HBM-resident quantized bucket (ISSUE 17): payload codes
            # transit the f32 scatter lanes exactly (ints on the int8
            # grid / exact e4m3 values), `_scatter_rows` casts back to
            # the stored dtype on write
            return (padded_scatter_rows(arr, w_idx, r_idx, payload),
                    padded_scatter_rows(self._params["tp_scale"][b],
                                        w_idx, r_idx, scale))
        if self.emb._bucket_memory_kind(b):
            return _host_set_rows(arr, w_idx, r_idx,
                                  np.asarray(rows, np.float32)), None
        return padded_scatter_rows(arr, w_idx, r_idx, rows), None

    def _apply_row_rows(self, t: int, keys: np.ndarray, rows: np.ndarray):
        rt = self.emb.plan.row_tables[t]
        base = np.asarray(rt.row_base, np.int64)
        arr = self._params["row"][t]
        w_idx = np.searchsorted(base, keys, side="right") - 1
        r_idx = keys - base[w_idx]
        return padded_scatter_rows(arr, w_idx, r_idx, rows)

    def apply_published(self, path: str) -> dict:
        """Apply one stream file (delta or snapshot) in place.

        Deltas require `meta['base_version'] == self.version`
        (DeltaChainError otherwise — resync from a snapshot); snapshots
        apply from any version. Returns {"kind", "version", "rows",
        "bytes", "published_at", "payload"} — payload maps
        ("tp", b) -> (keys, rows) for delta files so callers (the
        serving engine) can update HBM caches straight off the wire."""
        meta, arrays = ckpt_lib.load_row_delta(path)
        if "crc" not in meta:
            # checksum-less legacy (container v1) file: applied, but
            # counted — the rolling-upgrade signal (ISSUE 13)
            self._metrics.counter("store/legacy_files_total").inc()
        self._check_sig(meta, path)
        # payload dtype (ISSUE 15): legacy headers carry none and load
        # as the f32 they are; quantized payloads decode against their
        # `_scale` siblings here, so every downstream consumer (row
        # scatter, HBM caches, the returned payload map) sees f32 rows.
        # load_row_delta already refused dtypes this build cannot decode.
        stream_dtype = meta.get("dtype", "f32")

        def dec(name):
            if stream_dtype == "f32":
                return np.asarray(arrays[name], np.float32)
            scale = arrays.get(f"{name}_scale")
            if scale is None:
                raise ValueError(
                    f"{path}: array {name} is {stream_dtype}-encoded but "
                    "carries no _scale sibling — publisher bug, not "
                    "stream damage")
            return wire_ops.decode_rows_np(arrays[name], scale,
                                           stream_dtype)

        payload: Dict[Tuple[str, int], Tuple[np.ndarray, np.ndarray]] = {}
        if meta["kind"] == "snapshot":
            tables = [dec(f"table{i}") for i in range(self._n_tables)]
            self._params = self.emb.set_weights(tables)
            n_rows = sum(t.shape[0] for t in tables)
            self._chain_broken = False       # snapshots re-anchor the chain
        else:
            if self._chain_broken:
                raise DeltaChainError(
                    f"{path}: this store's params were replaced out of "
                    "band (set_params/replace) after its last snapshot — "
                    "a version match alone cannot prove the delta chains "
                    "from the current tables; resync from a snapshot")
            if meta["base_version"] != self.version:
                raise DeltaChainError(
                    f"{path}: delta base_version {meta['base_version']} "
                    f"!= consumer version {self.version}; resync from a "
                    "snapshot")
            self._hot_resident_guard()
            new_params = dict(self._params)
            new_params["tp"] = list(self._params["tp"])
            new_params["row"] = list(self._params["row"])
            new_params["dp"] = list(self._params["dp"])
            if "tp_scale" in self._params:
                new_params["tp_scale"] = list(self._params["tp_scale"])
            n_rows = 0
            for name in sorted(arrays):
                m = re.match(r"^(tp|row)(\d+)_keys$", name)
                if not m:
                    continue
                kind, idx = m.group(1), int(m.group(2))
                keys = np.asarray(arrays[name], np.int64)
                rows = dec(f"{kind}{idx}_rows")
                n_rows += len(keys)
                if kind == "tp":
                    new_params["tp"][idx], scale_leaf = self._apply_tp_rows(
                        idx, keys, rows)
                    if scale_leaf is not None:
                        new_params["tp_scale"][idx] = scale_leaf
                    payload[("tp", idx)] = (keys, rows)
                else:
                    new_params["row"][idx] = self._apply_row_rows(
                        idx, keys, rows)
            for j in range(len(new_params["dp"])):
                name = f"dp{j}_full"
                if name in arrays:
                    dp = jnp.asarray(arrays[name])
                    if self.emb.mesh is not None:
                        from jax.sharding import (NamedSharding,
                                                  PartitionSpec as P)
                        dp = jax.device_put(
                            dp, NamedSharding(self.emb.mesh, P()))
                    new_params["dp"][j] = dp
                    n_rows += arrays[name].shape[0]
            self._params = new_params
        self.version = int(meta["version"])
        self._published_version = None     # consumers never publish onward
        info = {"kind": meta["kind"], "version": self.version,
                "rows": n_rows, "bytes": os.path.getsize(path),
                "published_at": meta.get("published_at"),
                "payload": payload}
        m = self._metrics
        m.counter("store/applies").inc()
        m.counter("store/apply_bytes").inc(info["bytes"])
        m.counter("store/apply_rows").inc(n_rows)
        m.gauge("store/version", role="consumer").set(self.version)
        default_recorder().lineage(self.version, "apply",
                                   kind=meta["kind"], rows=n_rows)
        return info


class DeltaConsumer:
    """Poll loop + staleness accounting over one store and one publish
    directory: apply every new stream file in chain order, falling back
    to the newest snapshot when the chain breaks (missed or compacted
    deltas).

    Hardened (ISSUE 13): a corrupt file (failed checksum, bad zip, torn
    payload) is QUARANTINED — skipped permanently, counted in
    ``store/corrupt_files_total``, one loud warning — and the chain
    re-anchors on the publisher's next snapshot through the existing
    snapshot-fallback path; a transient read error (`OSError`) retries
    with capped exponential backoff (``store/poll_retries_total``) and,
    if it persists, gives the file up for THIS poll only. `poll` leaves
    the store in a consistent last-good state on every path — the
    serving engine's `poll_updates` wraps it so nothing escapes to the
    request loop. The metadata cache is bounded by the LIVE stream:
    entries whose files left the directory (compaction, operator
    cleanup) evict at the end of each poll.

    Args:
      store: consumer-side `TableStore`.
      directory: publish directory to poll.
      max_transient_retries: in-poll retry budget per file for transient
        read errors (backoff 2^k * `retry_backoff_s`, capped at
        `retry_backoff_cap_s` — bounded so a poll can never stall the
        serving loop for more than ~0.1 s).
    """

    def __init__(self, store: TableStore, directory: str,
                 max_transient_retries: int = 3,
                 retry_backoff_s: float = 0.005,
                 retry_backoff_cap_s: float = 0.05):
        self.store = store
        self.directory = directory
        self.max_transient_retries = int(max_transient_retries)
        self.retry_backoff_s = float(retry_backoff_s)
        self.retry_backoff_cap_s = float(retry_backoff_cap_s)
        self._meta_cache: Dict[str, dict] = {}
        self.applied: List[dict] = []
        self._lag_versions: List[int] = []
        self._lag_seconds: List[float] = []
        self._apply_seconds = 0.0
        self._rows_applied = 0
        # path -> reason string; quarantined files are invisible to the
        # chooser forever (stream files are immutable once renamed, so
        # corruption is permanent)
        self.quarantined: Dict[str, str] = {}
        self._retries_total = 0
        self._degraded: set = set()
        self._last_scan: List[Tuple[int, str, str]] = []
        # versions whose first directory sighting was already recorded
        # on the lineage track (one "scan" per version per consumer)
        self._lineage_scanned: set = set()

    # ------------------------------------------------------------ internals
    def _visible(self, upto: Optional[int] = None
                 ) -> List[Tuple[int, str, str]]:
        self._last_scan = scan_published(self.directory)
        files = [f for f in self._last_scan if f[2] not in self.quarantined]
        if upto is not None:
            # version ceiling (fleet canary pinning): files beyond the
            # ceiling stay out of the view — NOT out of `_last_scan`,
            # whose bookkeeping (meta-cache eviction, quarantine GC)
            # must keep tracking the whole live stream
            files = [f for f in files if f[0] <= upto]
        return files

    def _quarantine(self, path: str, err: BaseException) -> None:
        reason = f"{type(err).__name__}: {err}"
        self.quarantined[path] = reason[:300]
        self._degraded.add("corrupt_stream")
        self.store._metrics.counter("store/corrupt_files_total").inc()
        warnings.warn(
            f"stream file quarantined (corrupt, will re-anchor on the "
            f"next snapshot): {path}: {reason[:200]}",
            RuntimeWarning, stacklevel=3)

    def _backoff(self, attempt: int) -> None:
        self._retries_total += 1
        self.store._metrics.counter("store/poll_retries_total").inc()
        time.sleep(min(self.retry_backoff_s * (2 ** attempt),
                       self.retry_backoff_cap_s))

    def _meta(self, path: str) -> Optional[dict]:
        """Cached metadata-header read (stream files are immutable once
        renamed into place, so a path's header never changes). Returns
        None when the header cannot be read this poll — corrupt headers
        quarantine the file, transient errors leave it for the next
        poll."""
        meta = self._meta_cache.get(path)
        if meta is not None:
            return meta
        for attempt in range(self.max_transient_retries + 1):
            try:
                meta = ckpt_lib.load_row_delta_meta(path)
                self._meta_cache[path] = meta
                return meta
            except Exception as e:  # noqa: BLE001 - classified below
                if _is_transient_error(e):
                    if attempt >= self.max_transient_retries:
                        self._degraded.add("io_transient")
                        return None
                    self._backoff(attempt)
                    continue
                if _is_corrupt_error(e):
                    self._quarantine(path, e)
                    return None
                raise

    def _choose(self, files: List[Tuple[int, str, str]]) -> Optional[str]:
        """The next applicable stream file, or None (caught up / waiting
        on the publisher's next compaction)."""
        if self.store._chain_broken:
            # out-of-band replace: the local version bump is
            # meaningless against the publisher's namespace, so no
            # version filter and no delta qualifies — re-anchor on
            # the NEWEST snapshot (even one consumed before the
            # replace: re-applying re-syncs, then deltas replay)
            snaps = [f for f in files if f[1] == "snapshot"]
            return snaps[-1][2] if snaps else None
        cand = [f for f in files if f[0] > self.store.version]
        # prefer the delta that chains from the current version (the
        # cheap path); otherwise the oldest newer snapshot — the chain
        # replays from there on later iterations. Neither found = chain
        # gap with no snapshot yet: wait for the next compaction.
        nxt = None
        for version, kind, path in cand:
            if kind == "delta":
                meta = self._meta(path)
                if meta is not None \
                        and meta["base_version"] == self.store.version:
                    return path
            elif nxt is None:
                nxt = path                   # snapshot: applies from any v
        return nxt

    def _apply_one(self, path: str) -> Tuple[Optional[dict], str]:
        """Apply one file with transient retry; returns (info, status)
        with status in {"applied", "transient", "quarantined"}."""
        for attempt in range(self.max_transient_retries + 1):
            t0 = time.perf_counter()
            try:
                info = self.store.apply_published(path)
            except DeltaChainError:
                raise            # chooser contract violation: loud
            except Exception as e:  # noqa: BLE001 - classified below
                if _is_transient_error(e):
                    if attempt >= self.max_transient_retries:
                        self._degraded.add("io_transient")
                        return None, "transient"
                    self._backoff(attempt)
                    continue
                if _is_corrupt_error(e):
                    self._quarantine(path, e)
                    return None, "quarantined"
                raise
            self._apply_seconds += time.perf_counter() - t0
            return info, "applied"
        return None, "transient"             # unreachable; keeps mypy honest

    def _evict_meta_cache(self) -> None:
        """Bound the metadata cache by the LIVE stream (ISSUE 13
        satellite): a long-running consumer's cache otherwise grows with
        run length as compaction deletes superseded deltas. Uses the
        poll's own final scan — no extra directory walk."""
        live = {path for _, _, path in self._last_scan}
        if any(p not in live for p in self._meta_cache):
            self._meta_cache = {p: m for p, m in self._meta_cache.items()
                                if p in live}
        for p in [p for p in self.quarantined if p not in live]:
            del self.quarantined[p]          # counted already; file gone
        # the scan-lineage dedup set stays bounded by IN-FLIGHT versions:
        # applied versions can never re-emit (the emission requires
        # version > store.version), so their entries are dead weight
        self._lineage_scanned = {v for v in self._lineage_scanned
                                 if v > self.store.version}

    def degraded_reasons(self) -> frozenset:
        """The consumer's current degradation set (empty = healthy):
        ``corrupt_stream`` while quarantined damage keeps it behind the
        publisher, ``io_transient`` while reads flake. Cleared when a
        poll ends fully caught up."""
        return frozenset(self._degraded)

    def poll(self, upto: Optional[int] = None) -> List[dict]:
        """Apply every applicable published file. Returns the applied
        infos (possibly empty). Never raises on corrupt or transiently
        unreadable stream files (see class docstring); the
        ``consumer.poll`` fault point can inject a transient error at
        entry (exercising the engine-level degradation path).

        `upto` caps consumption at a version ceiling: files above it are
        invisible to this poll, and staleness/health accounting is
        measured against the ceiling, not the stream head — a replica
        pinned at a rollout's last-promoted version is CAUGHT UP, not
        degraded, while newer unvetted versions accumulate."""
        faults.check_raise("consumer.poll", directory=self.directory)
        files = self._visible(upto)
        # lineage (ISSUE 14): the first time this consumer's directory
        # scan SEES a not-yet-applied version, stamp it on the
        # version's async track — the scan->apply gap is the consumer
        # half of staleness
        for version, _, _ in files:
            if (version > self.store.version
                    and version not in self._lineage_scanned):
                self._lineage_scanned.add(version)
                default_recorder().lineage(version, "scan")
        newer = [f for f in files if f[0] > self.store.version]
        if not newer and not self.store._chain_broken:
            self._evict_meta_cache()
            # healthy only if nothing newer exists even among the
            # quarantined files (a quarantined NEWER file means serving
            # is genuinely behind the publisher: stay degraded until
            # the re-anchoring snapshot arrives); under a ceiling,
            # "newer" means newer WITHIN the ceiling
            if not any(f[0] > self.store.version for f in self._last_scan
                       if upto is None or f[0] <= upto):
                self._degraded.clear()
            return []
        if newer:
            # staleness just before this poll: how many published
            # versions serving had not yet consumed
            self._lag_versions.append(newer[-1][0] - self.store.version)
            self.store._metrics.gauge("store/version_lag").set(
                self._lag_versions[-1])
        out = []
        latest_seen = self.store.version
        while True:
            files = self._visible(upto)
            capped = [f for f in self._last_scan
                      if upto is None or f[0] <= upto]
            if capped:
                latest_seen = max(latest_seen, capped[-1][0])
            nxt = self._choose(files)
            if nxt is None:
                break
            info, status = self._apply_one(nxt)
            if status == "quarantined":
                continue                     # rescan: snapshot fallback
            if status != "applied":
                break                        # transient: next poll retries
            self._rows_applied += info["rows"]
            if info.get("published_at"):
                self._lag_seconds.append(
                    max(time.time() - info["published_at"], 0.0))
                self.store._metrics.histogram(
                    "store/publish_to_apply_seconds").record(
                        self._lag_seconds[-1])
            self.applied.append(info)
            out.append(info)
        # post-poll residual lag (0 when fully caught up; >0 when the
        # chain still waits on the publisher's next compaction) — from
        # the apply loop's own final scan, no extra directory walk on
        # the serving hot path
        residual = max(0, latest_seen - self.store.version)
        if out or residual:
            self.store._metrics.gauge("store/version_lag").set(residual)
        if residual == 0 and not self.store._chain_broken:
            self._degraded.clear()           # caught up: healed
        self._evict_meta_cache()
        return out

    def stats(self) -> dict:
        d_bytes = [i["bytes"] for i in self.applied if i["kind"] == "delta"]
        s_bytes = [i["bytes"] for i in self.applied
                   if i["kind"] == "snapshot"]
        versions = [i["version"] for i in self.applied]
        return {
            "applied": len(self.applied),
            "applied_deltas": len(d_bytes),
            "applied_snapshots": len(s_bytes),
            "rows_applied": self._rows_applied,
            "delta_bytes_total": int(sum(d_bytes)),
            "delta_bytes_mean": (int(np.mean(d_bytes)) if d_bytes else 0),
            "snapshot_bytes": (int(s_bytes[-1]) if s_bytes else 0),
            "apply_seconds": round(self._apply_seconds, 6),
            "apply_rows_per_sec": (
                round(self._rows_applied / self._apply_seconds)
                if self._apply_seconds > 0 else 0),
            "staleness_versions_max": (max(self._lag_versions)
                                       if self._lag_versions else 0),
            "staleness_versions_mean": (
                round(float(np.mean(self._lag_versions)), 3)
                if self._lag_versions else 0.0),
            "staleness_s_max": (round(max(self._lag_seconds), 6)
                                if self._lag_seconds else 0.0),
            "staleness_s_mean": (
                round(float(np.mean(self._lag_seconds)), 6)
                if self._lag_seconds else 0.0),
            "version_monotonic": versions == sorted(versions)
            and len(set(versions)) == len(versions),
            "version": self.store.version,
            "quarantined_files": len(self.quarantined),
            "poll_retries": self._retries_total,
            "degraded_reasons": sorted(self._degraded),
        }


def restore_from_published(emb, directory: str,
                           upto: Optional[int] = None) -> TableStore:
    """Rebuild a store's params from a publish stream: the newest
    snapshot (<= `upto` when given) plus every chained delta after it —
    the (snapshot + deltas) checkpoint-restore path. Returns a consumer
    `TableStore` positioned at the reconstructed version."""
    files = scan_published(directory)
    if upto is not None:
        files = [f for f in files if f[0] <= upto]
    snaps = [f for f in files if f[1] == "snapshot"]
    if not snaps:
        raise FileNotFoundError(
            f"no snapshot in {directory}: a delta chain needs its anchor")
    _, _, snap_path = snaps[-1]
    meta, arrays = ckpt_lib.load_row_delta(snap_path)
    n = len(meta["sig"])
    sd = meta.get("dtype", "f32")

    def table(i):
        if sd == "f32":
            return arrays[f"table{i}"]
        scale = arrays.get(f"table{i}_scale")
        if scale is None:
            # same publisher-bug guard as apply_published's dec()
            raise ValueError(
                f"{snap_path}: array table{i} is {sd}-encoded but "
                "carries no _scale sibling — publisher bug, not "
                "stream damage")
        return wire_ops.decode_rows_np(arrays[f"table{i}"], scale, sd)

    store = TableStore(emb, emb.set_weights([table(i) for i in range(n)]))
    store._check_sig(meta, snap_path)
    store.version = int(meta["version"])
    for version, kind, path in files:
        if version <= store.version or kind != "delta":
            continue
        store.apply_published(path)
    return store
