"""Versioned table store: train-to-serve weight streaming (ISSUE 6).

One parameter store powering both subsystems (ROADMAP item 3): a
training job owns its tables through a `TableStore`, publishes row-delta
snapshots (dedup'd touched-row ids + row payloads + a monotonic version
header) every N steps, and any number of serving replicas consume them
in-place — no restart, no full-table copy. See docs/serving.md
"Weight streaming" for the contract and the on-disk format.
"""

from distributed_embeddings_tpu.store.table_store import (DeltaChainError,
                                                          DeltaConsumer,
                                                          TableStore,
                                                          restore_from_published,
                                                          scan_published)

__all__ = [
    "DeltaChainError",
    "DeltaConsumer",
    "TableStore",
    "restore_from_published",
    "scan_published",
]
