"""Apply-only inference engine over a DistributedEmbedding (+ dense model).

Training forwards in this library drag machinery a serving path never
needs: tap perturbations and their residual exports, optimizer state
threading, and a host round trip for every offloaded-bucket lookup.
`InferenceEngine` is the serving half of the ROADMAP's north star — an
apply-only wrapper that:

  * holds ONLY parameters (anything shaped like a checkpoint's
    ``{"params": ..., "opt_state": ...}`` is stripped to its params on the
    way in);
  * freezes the exchange plan: exchange groups are resolved once per input
    signature and the whole forward (dense model + embedding exchange +
    lookups) is one jit-compiled program per padded batch shape, with
    ``warmup()`` compile-ahead for the shapes the batcher will use;
  * serves offloaded buckets through the HBM hot-row cache
    (`serving/cache.py`) plugged into the layer's
    ``offload_lookup_scope`` seam — hot rows gather at HBM bandwidth, only
    the cold tail pays the host round trip;
  * pads every request batch to the nearest prepared shape (a static-shape
    requirement on TPU) and slices the true rows back out.

Consistency: the engine's embedding tables are OWNED by a versioned
`TableStore` (ISSUE 6) — `predict` reads the store's current params, so
every table mutation routes through one interface. Three update paths:
``set_params(new)`` swaps whole pytrees (cached hot rows are STALE until
``refresh()``, which re-reads residents through the store's versioned
read); ``apply_delta(path)`` / ``poll_updates(dir)`` consume row-delta
publications from a live training job in place — no restart, no
full-table copy, HBM cache slots patched straight off the wire (see
docs/serving.md "Weight streaming" for the contract).
"""

import math
import os
from typing import Dict, List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.layers.dist_model_parallel import (
    DistributedEmbedding)
from distributed_embeddings_tpu.obs import trace as obs_trace
from distributed_embeddings_tpu.obs.spans import span as obs_span
from distributed_embeddings_tpu.serving.cache import (HotRowCache,
                                                      cached_group_lookup)
from distributed_embeddings_tpu.store import DeltaConsumer, TableStore

__all__ = ["InferenceEngine"]


class _NpInput:
    """Host-side normalized input: ids [B, k] int64 (+ weights or None),
    plus the original array to feed the traced forward."""

    __slots__ = ("ids", "weights", "k", "orig")

    def __init__(self, ids, weights, k, orig):
        self.ids = ids
        self.weights = weights
        self.k = k
        self.orig = orig


class InferenceEngine:
    """Serve ``predict(batch)`` from a trained model at inference cost.

    Args:
      model: either a `DistributedEmbedding` (embedding-only serving —
        `predict` takes the per-feature id batch and returns the per-input
        embedding outputs) or an object exposing ``.embedding`` (a
        `DistributedEmbedding`) and ``.apply(params, numerical, cats)``
        (e.g. `models.dlrm.DLRM`) — `predict` then takes
        ``(numerical, cats)`` and returns the model output.
      params: the parameter pytree — the embedding params pytree in
        embedding-only mode, the full model params otherwise. A
        ``{"params": ..., "opt_state": ...}`` checkpoint dict is accepted
        and stripped to its params.
      cache_capacity: rows of HBM cache per offloaded bucket (0 = no
        caching; lookups keep the stock host path). A dict
        ``{bucket_index: capacity}`` caches selected buckets only.
      promote_threshold: access count before a row is promotion-eligible.
      donate_batch: donate the staged request buffers to the compiled
        forward (saves an HBM copy per request; leave False where the
        caller reuses its input arrays).
      vocab_manager: optional `vocab.VocabManager` over the same plan
        (ISSUE 7): `predict` then takes RAW keys for managed tables and
        translates them to physical rows host-side, query-only (unknown
        keys serve the fallback row — serving never admits).
        `poll_updates` keeps the binding current by loading the
        publisher's ``vocab_v{version}.npz`` sidecars alongside the row
        deltas, so rebinds arrive through the same publication path as
        the row payloads they describe.
      registry: optional `obs.MetricRegistry` (ISSUE 11) the engine's
        serving counters (``serve/predicts``, ``serve/rows_served``,
        ``serve/rows_padded``) land in — and which the owned
        `TableStore` (and its `DeltaConsumer`s) report through
        (``store/applies``, ``store/version_lag``,
        ``store/publish_to_apply_seconds``...). Default: a private
        registry per engine.
      replica: optional replica name (fleet tier, ISSUE 16). When set,
        every ``serve/*`` metric family this engine reports carries a
        ``replica=`` label, so ONE shared `MetricRegistry` can host a
        whole fleet without key collisions (``store/*`` families stay
        unlabeled — counters aggregate across the fleet, which is the
        fleet-wide reading the soak gates want).
    """

    def __init__(self, model, params, *, cache_capacity=0,
                 promote_threshold: int = 2, donate_batch: bool = False,
                 vocab_manager=None, registry=None,
                 replica: Optional[str] = None):
        if isinstance(model, DistributedEmbedding):
            self._model = None
            self.embedding = model
        else:
            self._model = model
            self.embedding = model.embedding
        if not self.embedding.dp_input:
            raise ValueError(
                "InferenceEngine serves data-parallel input batches; this "
                "layer was built with dp_input=False")
        if isinstance(params, dict) and "params" in params \
                and "opt_state" in params:
            params = params["params"]      # checkpoint dict: strip opt state
        self.params = params
        from distributed_embeddings_tpu.obs.registry import MetricRegistry
        self._metrics = registry if registry is not None \
            else MetricRegistry()
        self.replica = replica
        self._labels = {} if replica is None else {"replica": str(replica)}
        # versioned ownership (ISSUE 6): the embedding tables live behind
        # a TableStore — `refresh()` and delta consumption read/write
        # through it, so serving can never hold a second derivation of
        # the row state
        self.store = TableStore(self.embedding, self._emb_params(params),
                                registry=self._metrics)
        self._consumers: Dict[str, DeltaConsumer] = {}
        if vocab_manager is not None and vocab_manager.emb is not \
                self.embedding:
            raise ValueError(
                "vocab_manager was built over a different layer; the "
                "binding's physical rows are plan-specific")
        self.vocab = vocab_manager
        self._vocab_loaded_path = None
        # degradation accounting (ISSUE 13): reasons currently active —
        # mirrored into the `serve/degraded{reason=}` gauge family (1
        # while active, reset to 0 when the reason clears)
        self._degraded_active: frozenset = frozenset()
        self.last_poll_error: Optional[str] = None
        # postmortem artifacts written on degraded ENTRY (ISSUE 14;
        # paths, newest last) — one per reason activation while
        # DET_OBS_POSTMORTEM_DIR is set
        self.postmortems: List[str] = []
        # store version of the newest predict served (drives the
        # lineage "serve" close: first predict at >= V ends V's track)
        self._lineage_served_version = 0

        emb = self.embedding
        self.caches: Dict[int, HotRowCache] = {}
        if emb._offload_enabled:
            off = [b for b, bk in enumerate(emb.plan.tp_buckets)
                   if bk.offload]
            if isinstance(cache_capacity, dict):
                caps = {b: cache_capacity.get(b, 0) for b in off}
            else:
                caps = {b: int(cache_capacity) for b in off}
            for b, cap in caps.items():
                if cap > 0:
                    self.caches[b] = HotRowCache(
                        emb, b, cap, promote_threshold=promote_threshold)
        # quantized buckets cache too now — the decode seam (ISSUE 17)
        # stores decoded f32 rows in the slots. The gauge stays (its
        # absence would read as "not measured" on dashboards that
        # tracked the PR 16 bypass): constant 0 is the signal that
        # every configured bucket is actually cached
        self._metrics.gauge("serve/cache_bypassed_buckets",
                            **self._labels).set(0)
        self._warmed: List[int] = []
        self._jit_fwd = jax.jit(
            self._fwd, donate_argnums=(1,) if donate_batch else ())
        self.n_predicts = 0
        self.rows_served = 0
        self.rows_padded = 0

    # ------------------------------------------------------------ internals
    def _emb_params(self, params):
        return params if self._model is None else params["embedding"]

    def _normalize(self, cats: Sequence) -> List[_NpInput]:
        emb = self.embedding
        if len(cats) != emb._n_inputs:
            raise ValueError(
                f"expected {emb._n_inputs} categorical inputs, "
                f"got {len(cats)}")
        out = []
        for i, x in enumerate(cats):
            weights = None
            if isinstance(x, tuple) and len(x) == 2:
                x, weights = x
                weights = np.asarray(weights, np.float32)
            ids = np.asarray(x)
            if not np.issubdtype(ids.dtype, np.integer):
                raise TypeError(
                    f"input {i}: serving takes integer id arrays "
                    f"(or (ids, weights) tuples), got dtype {ids.dtype}")
            ids2 = ids[:, None] if ids.ndim == 1 else ids
            if ids2.ndim != 2:
                raise ValueError(
                    f"input {i}: expected [B] or [B, k] ids, "
                    f"got shape {ids.shape}")
            orig = (ids, weights) if weights is not None else ids
            out.append(_NpInput(ids2.astype(np.int64), weights,
                                ids2.shape[1], orig))
        return out

    def _pad_rows(self, arr: np.ndarray, target: int) -> np.ndarray:
        b = arr.shape[0]
        if b == target:
            return arr
        pad = np.zeros((target - b,) + arr.shape[1:], arr.dtype)
        return np.concatenate([arr, pad], axis=0)

    def _target_batch(self, b: int) -> int:
        for size in self._warmed:
            if size >= b:
                return size
        world = max(self.embedding.world_size, 1)
        return int(math.ceil(b / world) * world)

    def _tp_key(self, prepped: List[_NpInput]):
        emb = self.embedding
        tp = [prepped[i] for i in emb.strategy.input_groups[1]]
        return tuple((p.k, p.weights is not None) for p in tp), tp

    def _off_groups(self, key):
        """(g, grp) for offloaded exchange groups with a cache attached."""
        emb = self.embedding
        groups, _ = emb._exchange_groups_for_key(key)
        return [(g, grp) for g, grp in enumerate(groups)
                if emb.plan.tp_buckets[grp.bucket].offload
                and emb._offload_enabled and grp.bucket in self.caches]

    def _group_keys(self, grp, tp_prepped, batch, true_rows):
        """Host mirror of the on-device dp->mp id exchange for one group:
        the global row keys [world, B*f*k] each destination shard will look
        up, plus the validity mask (False on exchange-padding lanes and on
        batch-padding rows — those never reach a consumed output slot)."""
        emb = self.embedding
        world = emb.world_size
        rows_max = max(emb.plan.tp_buckets[grp.bucket].rows_max, 1)
        ids = np.zeros((world, batch, grp.f_max, grp.k), np.int64)
        valid = np.zeros((world, batch, grp.f_max, grp.k), bool)
        for r in range(world):
            for j in range(len(grp.rank_slots[r])):
                i = grp.class_inputs[int(grp.sel[r, j])]
                member = tp_prepped[i].ids          # [b, k], b <= batch
                ids[r, :member.shape[0], j, :] = (member
                                                  + int(grp.offs[r, j]))
                valid[r, :true_rows, j, :] = True
        np.clip(ids, 0, rows_max - 1, out=ids)
        keys = ids + (np.arange(world, dtype=np.int64)[:, None, None, None]
                      * rows_max)
        return keys.reshape(world, -1), valid.reshape(world, -1)

    def _fwd(self, params, batch, slot_map, slots_map):
        numerical, cats = batch
        emb = self.embedding

        def hook(g, grp, table, ids_g, w_g):
            slot_g = slot_map.get(g)
            if slot_g is None:
                return None
            # quantized buckets (ISSUE 17): fetch the scale leaf from
            # the SAME traced params the payload came from, so the
            # decode seam can never pair a payload with a stale scale
            scale = (emb._bucket_scale(self._emb_params(params),
                                       grp.bucket)
                     if emb._bucket_store_dtype(grp.bucket) != "f32"
                     else None)
            return cached_group_lookup(emb, grp, table,
                                       slots_map[grp.bucket], ids_g,
                                       slot_g, w_g, scale_h=scale)

        with emb.offload_lookup_scope(hook):
            if self._model is None:
                return emb.apply(params, cats)
            return self._model.apply(params, numerical, cats)

    def _predict_padded(self, numerical, prepped, target, true_rows,
                        observe=True):
        emb = self.embedding
        key, tp_prepped = self._tp_key(prepped)
        emb_params = self._emb_params(self.params)
        slot_map, slots_map = {}, {}
        for g, grp in self._off_groups(key):
            cache = self.caches[grp.bucket]
            if observe:
                # admit on the counters accumulated so far, so this batch
                # already hits rows that just crossed the threshold
                # (quantized buckets decode through the scale leaf)
                cache.admit(emb_params["tp"][grp.bucket],
                            scale=(emb._bucket_scale(emb_params,
                                                     grp.bucket)
                                   if emb._bucket_store_dtype(grp.bucket)
                                   != "f32" else None))
            keys, valid = self._group_keys(grp, tp_prepped, target, true_rows)
            slot_map[g] = jnp.asarray(
                cache.lookup_slots(keys, valid, observe=observe))
            slots_map[grp.bucket] = cache.slots
        cats = [jnp.asarray(self._pad_rows(np.asarray(p.orig[0]), target))
                if isinstance(p.orig, tuple)
                else jnp.asarray(self._pad_rows(p.orig, target))
                for p in prepped]
        for i, p in enumerate(prepped):
            if isinstance(p.orig, tuple):
                cats[i] = (cats[i],
                           jnp.asarray(self._pad_rows(p.orig[1], target)))
        num = (None if numerical is None
               else jnp.asarray(self._pad_rows(np.asarray(numerical),
                                               target)))
        return self._jit_fwd(self.params, (num, cats), slot_map, slots_map)

    # --------------------------------------------------------------- API
    def predict(self, batch):
        """Serve one request batch.

        Args:
          batch: embedding-only mode — the list of per-feature id arrays
            ([B] / [B, k] ints, or (ids, weights) tuples); model mode — a
            ``(numerical, cats)`` tuple.

        Returns the forward output(s) sliced to the request's true batch
        size (model output array, or one array per embedding input).

        The request runs inside a ``serve/predict`` span (ISSUE 14) so
        serving device time attributes next to the trainer's
        ``train/step`` phases in a profiler capture, and the request
        edge lands on the flight recorder's timeline.
        """
        if self._model is None:
            numerical, cats = None, list(batch)
        else:
            numerical, cats = batch
            cats = list(cats)
        with obs_span("serve/predict", self._metrics):
            if self.vocab is not None:
                # raw keys -> physical rows, query-only (misses serve
                # the fallback row; serving traffic never admits or
                # counts)
                cats = self.vocab.translate(cats)
            prepped = self._normalize(cats)
            b = prepped[0].ids.shape[0]
            target = self._target_batch(b)
            out = self._predict_padded(numerical, prepped, target, b)
        self.n_predicts += 1
        self.rows_served += b
        self.rows_padded += target - b
        self._metrics.counter("serve/predicts", **self._labels).inc()
        self._metrics.counter("serve/rows_served", **self._labels).inc(b)
        self._metrics.counter("serve/rows_padded",
                              **self._labels).inc(target - b)
        if self.store.version > self._lineage_served_version:
            # lineage (ISSUE 14): the FIRST predict answered at >= V
            # closes version V's async track — commit -> publish ->
            # scan -> apply -> served, end to end. A predict at V is
            # also the first at >= every still-open version below it
            # (versions applied in one burst), so all of them close.
            v = self.store.version
            self._lineage_served_version = v
            rec = obs_trace.default_recorder()
            for ov in rec.lineage_open_versions():
                if ov <= v:
                    rec.lineage(ov, "serve", served_at_version=v)
        return jax.tree.map(lambda a: a[:b], out)

    def warmup(self, batch_sizes: Sequence[int], example=None) -> List[int]:
        """Compile-ahead for a fixed set of padded batch shapes.

        Args:
          batch_sizes: the shapes `predict` will pad to (each is rounded up
            to a multiple of the mesh size). Kept sorted; `predict` pads to
            the smallest warmed shape that fits.
          example: an example `predict` batch whose per-input structure
            (hotness, weights, dtypes) matches real traffic; required when
            the layer has no `input_max_hotness` hints and inputs are
            multi-hot. Default: hotness-1 int32 ids (1-D), zeros.

        Returns the warmed sizes. Warmup forwards do NOT touch cache
        counters or stats.
        """
        emb = self.embedding
        world = max(emb.world_size, 1)
        sizes = sorted({int(math.ceil(b / world) * world)
                        for b in batch_sizes})
        for size in sizes:
            if example is not None:
                if self._model is None:
                    numerical, cats = None, list(example)
                else:
                    numerical, cats = example
                # an example larger than this warm size is cut down to it
                # (only its per-input STRUCTURE matters here); smaller ones
                # pad up inside _predict_padded as usual
                cut = lambda a: np.asarray(a)[:size]
                cats = [(cut(x[0]), cut(x[1])) if isinstance(x, tuple)
                        else cut(x) for x in cats]
                prepped = self._normalize(list(cats))
                num = None if numerical is None else cut(numerical)
            else:
                mh = emb.input_max_hotness or [None] * emb._n_inputs
                cats = [np.zeros((size,), np.int32) if (h or 1) == 1
                        else np.zeros((size, h), np.int32) for h in mh]
                prepped = self._normalize(cats)
                num = (None if self._model is None
                       else np.zeros((size, getattr(
                           self._model, "num_numerical_features", 1)),
                           np.float32))
            self._predict_padded(num, prepped, size, size, observe=False)
        # merge with earlier warmups: shapes already compiled must stay
        # padding targets, or a later warmup([small]) would silently send
        # big requests to an unwarmed (compile-on-request) shape
        self._warmed = sorted(set(self._warmed) | set(sizes))
        return self._warmed

    def set_params(self, params, refresh: bool = False) -> None:
        """Swap in new parameters (e.g. after training steps). The swap
        routes through the table store (`TableStore.replace` — version
        bump, delta chain broken: the next consumed stream file must be
        a snapshot). Cached hot rows still hold the OLD table values
        until `refresh()` — pass refresh=True (or call it explicitly)
        whenever bit-exact serving matters more than the swap latency."""
        if isinstance(params, dict) and "params" in params \
                and "opt_state" in params:
            params = params["params"]
        self.params = params
        self.store.replace(self._emb_params(params))
        if refresh:
            self.refresh()

    def _sync_store_params(self) -> None:
        """Reflect the store's current (post-apply) param pytree into the
        pytree `predict` feeds the compiled forward."""
        if self._model is None:
            self.params = self.store.params
        else:
            self.params = {**self.params, "embedding": self.store.params}

    def refresh(self) -> int:
        """Re-copy every cached row from the current tables through the
        store's versioned read (the explicit cache-consistency step
        after table mutation — a stale table reference cannot reach the
        cache from here by construction). Returns total rows refreshed
        across buckets."""
        return sum(cache.refresh_from(self.store)
                   for cache in self.caches.values())

    def reanchor_published(self, publish_dir: str,
                           upto: Optional[int] = None) -> int:
        """Rebuild the tables from the publish stream — the newest
        snapshot at or below `upto` plus every chained delta after it —
        and swap them in with a full cache refresh. The fleet tier's
        rollback / re-anchor primitive (ISSUE 16): a canary that applied
        a bad version returns to the pinned one; a late joiner
        materializes the fleet's serving state in one shot. Unlike a
        bare `set_params`, the store re-joins the PUBLISHER's version
        number space afterwards (chain intact): the next poll chains
        deltas from the restored version instead of waiting for a fresh
        snapshot. Returns the restored version. Raises when the stream
        holds no snapshot at or below `upto` — callers on a never-raise
        path guard it (`FleetRouter` falls back to an in-memory pin)."""
        from distributed_embeddings_tpu.store import restore_from_published
        restored = restore_from_published(self.embedding, publish_dir,
                                          upto=upto)
        if self._model is None:
            self.params = restored.params
        else:
            self.params = {**self.params, "embedding": restored.params}
        self.store.replace(self._emb_params(self.params))
        # replace() bumped into a local version space and broke the
        # chain; the restored state IS publisher version
        # `restored.version`, so adopt its numbering wholesale
        self.store.version = restored.version
        self.store.table_versions = list(restored.table_versions)
        self.store._chain_broken = False
        self.refresh()
        return restored.version

    def apply_delta(self, path: str) -> dict:
        """Consume one published stream file (row delta or snapshot) in
        place: the store applies it to the tables (HBM scatter / host
        row set — no recompile, no full-table copy except for
        snapshots), and resident HBM cache slots are patched straight
        off the delta payload so cached serving stays bit-exact at the
        new version. Returns the store's apply info."""
        info = self.store.apply_published(path)
        self._absorb_apply(info)
        return info

    def poll_updates(self, publish_dir: str,
                     upto: Optional[int] = None) -> List[dict]:
        """Apply every new stream file a training job has published into
        `publish_dir` (chain order; snapshot fallback), patching caches
        per file. Returns the applied infos; `update_stats(publish_dir)`
        exposes the consumer's staleness accounting. `upto` caps the
        poll at a version ceiling (fleet canary pinning, ISSUE 16):
        newer files stay invisible and a replica held at the ceiling
        reads as caught up, not stale.

        NEVER raises on consumer-side faults (ISSUE 13): corrupt files
        quarantine inside `DeltaConsumer.poll`; anything that still
        escapes (injected poll errors, sidecar damage, cache-patch
        failures) is caught here — the engine keeps serving the
        last-good version, the failure lands in
        ``serve/poll_errors_total`` + `last_poll_error`, and the active
        degradation reasons are mirrored into the
        ``serve/degraded{reason=}`` gauges (set to 1 while active, reset
        to 0 when the reason clears) while staleness accounting keeps
        running. Reasons: ``poll_error`` (the poll itself failed),
        ``corrupt_stream`` / ``io_transient`` (from the consumer),
        ``vocab_sidecar`` (binding sidecar unreadable), ``cache_patch``
        (HBM cache patch failed; the cache was refreshed from the store
        instead)."""
        consumer = self._consumers.get(publish_dir)
        if consumer is None:
            consumer = DeltaConsumer(self.store, publish_dir)
            self._consumers[publish_dir] = consumer
        reasons = set()
        infos: List[dict] = []
        try:
            infos = consumer.poll(upto=upto)
            for info in infos:
                if "cache_patch" in reasons:
                    break            # full refresh below covers the rest
                try:
                    self._absorb_apply(info)
                except Exception as e:  # noqa: BLE001 - degrade, never crash
                    self._note_poll_error(e)
                    reasons.add("cache_patch")
            if "cache_patch" in reasons:
                # tables already moved (consumer.poll applied every
                # file) but a cache patch failed: re-read every
                # resident row through the store ONCE, after the loop,
                # so cached serving cannot hold pre-apply bytes —
                # per-file refreshes would be N full refreshes for one
                # correct end state
                self._sync_store_params()
                for cache in self.caches.values():
                    cache.refresh_from(self.store)
        except Exception as e:  # noqa: BLE001 - serve last-good instead
            self._note_poll_error(e)
            reasons.add("poll_error")
        if self.vocab is not None:
            # rebinds ride the same publication: load the newest binding
            # sidecar at-or-below the consumed version. NOT gated on new
            # row files — the publisher writes the sidecar before the
            # stream file, but a consumer that raced an earlier publish
            # (or was started against a partially-synced directory) must
            # still pick the matching binding up on its NEXT poll, not
            # only when more rows happen to arrive.
            from distributed_embeddings_tpu.vocab import latest_vocab_state
            try:
                path = latest_vocab_state(publish_dir,
                                          upto=self.store.version)
                if path is not None and path != self._vocab_loaded_path:
                    self.vocab.load_state(path)
                    self._vocab_loaded_path = path
            except Exception as e:  # noqa: BLE001 - keep previous binding
                # a corrupt/unreadable sidecar must not take serving
                # down: the previous binding keeps translating —
                # documented staleness (keys rebound at the damaged
                # version translate per the older binding) until the
                # next publish's sidecar supersedes it
                self._note_poll_error(e)
                reasons.add("vocab_sidecar")
        reasons |= consumer.degraded_reasons()
        for r in reasons:
            self._metrics.gauge("serve/degraded", reason=r,
                                **self._labels).set(1)
        for r in self._degraded_active - reasons:
            self._metrics.gauge("serve/degraded", reason=r,
                                **self._labels).set(0)
        entered = frozenset(reasons) - self._degraded_active
        self._degraded_active = frozenset(reasons)
        if entered:
            # degraded ENTRY is the incident moment (ISSUE 14): mark it
            # on the flight recorder, and — when an operator pointed
            # DET_OBS_POSTMORTEM_DIR somewhere — dump the ring +
            # registry snapshot as the postmortem artifact, once per
            # newly-activated reason. Dump failures degrade silently
            # into last_poll_error: the artifact must never take
            # serving down with it.
            rec = obs_trace.default_recorder()
            for r in sorted(entered):
                rec.instant("serve/degraded_entry", reason=r,
                            error=self.last_poll_error)
            pm_dir = os.environ.get("DET_OBS_POSTMORTEM_DIR")
            if pm_dir:
                for r in sorted(entered):
                    try:
                        self.postmortems.append(obs_trace.dump_postmortem(
                            pm_dir, f"degraded:{r}",
                            registry=self._metrics,
                            extra={"publish_dir": publish_dir,
                                   "store_version": self.store.version,
                                   "last_poll_error":
                                       self.last_poll_error,
                                   "active_reasons": sorted(reasons)}))
                    except Exception as e:  # noqa: BLE001 - never crash
                        self._note_poll_error(e)
        return infos

    def _note_poll_error(self, e: BaseException) -> None:
        self.last_poll_error = f"{type(e).__name__}: {e}"[:300]
        self._metrics.counter("serve/poll_errors_total",
                              **self._labels).inc()

    def degraded_reasons(self) -> frozenset:
        """The reasons currently holding this engine in degraded mode
        (empty = healthy; mirrors the ``serve/degraded{reason=}``
        gauges)."""
        return self._degraded_active

    def update_stats(self, publish_dir: str) -> dict:
        consumer = self._consumers.get(publish_dir)
        return consumer.stats() if consumer is not None else {}

    def _absorb_apply(self, info: dict) -> None:
        self._sync_store_params()
        if info["kind"] == "snapshot":
            # whole tables were rebuilt: every resident row re-reads
            for cache in self.caches.values():
                cache.refresh_from(self.store)
            return
        for b, cache in self.caches.items():
            hit = info["payload"].get(("tp", b))
            if hit is not None:
                cache.apply_rows(*hit)
                cache.refreshed_version = self.store.version

    def cache_stats(self) -> dict:
        """Aggregate + per-bucket cache statistics."""
        per = {b: c.stats() for b, c in self.caches.items()}
        hits = sum(c.hits for c in self.caches.values())
        misses = sum(c.misses for c in self.caches.values())
        return {"hit_rate": round(hits / (hits + misses), 4)
                if hits + misses else 0.0,
                "hits": hits, "misses": misses,
                "n_predicts": self.n_predicts,
                "rows_served": self.rows_served,
                "rows_padded": self.rows_padded,
                "store_version": self.store.version,
                "buckets": per}
