"""HBM hot-row cache for host-offloaded embedding buckets.

Production recommender traffic is power-law distributed: a few thousand hot
rows absorb most lookups (the DLRM/Criteo zipfian regime). For buckets past
the device-memory budget the library keeps tables in host memory
(`pinned_host`) and serves every lookup through a host round trip — correct,
but the hot head of the distribution pays the host-memory latency on every
request. `HotRowCache` is the serving-side fix: a fixed-capacity,
device-resident (HBM) tensor of cached rows plus a host-maintained id→slot
index, so the hot rows are gathered at HBM bandwidth and only the cold tail
touches host memory.

Design:

  * **Device side** — `slots`: a `[capacity, width]` f32 tensor of cached
    rows, replicated over the mesh (it is small by construction). The
    forward uses a masked two-source gather
    (`ops.embedding_ops.masked_two_source_gather`): lanes whose slot index
    is >= 0 read their row from `slots` in HBM; miss lanes read from the
    host-resident table inside a `compute_on("device_host")` region with
    their hit lanes' ids clamped to row 0, so a cache hit never generates
    table traffic in host memory.
  * **Host side** — the id→slot index, per-row access counters, and the
    admission policy, all provided by `utils.hotness.HotnessTracker` (the
    SAME module the training hot-row shard admits through, so serving and
    training admission cannot drift). Admission is counter-based: a row is
    promoted into a free slot once its access count crosses
    `promote_threshold`; when the cache is full, a candidate evicts the
    coldest resident row only if the candidate's count is strictly
    higher. All host structures are plain numpy/dicts — the cache never
    syncs device state to make a decision.
  * **Consistency** — cached rows are bit-exact copies of table rows taken
    at promotion/refresh time. The cache does NOT observe table updates:
    after a training step mutates an offloaded table, serving reads are
    stale until `refresh(table)` re-copies every resident row (see
    docs/serving.md for the full contract).

Rows are keyed by ``world_slice * rows_max + local_row`` — the stacked
bucket layout `[world, rows_max, width]` gives every world slice its own row
space, so the flat key is the unique global row identity.
"""

from typing import Dict, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_embeddings_tpu.ops import sparse_update as sparse_update_ops
from distributed_embeddings_tpu.ops import wire as wire_ops
from distributed_embeddings_tpu.ops.embedding_ops import (
    masked_two_source_gather, miss_only_ids)
from distributed_embeddings_tpu.utils.hotness import HotnessTracker

__all__ = ["HotRowCache", "cached_group_lookup"]


def _ceil_pow2(n: int) -> int:
    return 1 << max(int(n - 1).bit_length(), 0)


class HotRowCache:
    """Software-managed HBM cache over one offloaded bucket's rows.

    Args:
      emb: the `DistributedEmbedding` owning the bucket.
      bucket: index into ``emb.plan.tp_buckets`` (must be offloaded).
      capacity: number of rows the HBM tensor holds (static).
      promote_threshold: access count at which a row becomes
        promotion-eligible (>= 1; 1 promotes on first touch).
    """

    def __init__(self, emb, bucket: int, capacity: int,
                 promote_threshold: int = 2,
                 max_tracked: Optional[int] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        if promote_threshold < 1:
            raise ValueError("promote_threshold must be >= 1")
        bk = emb.plan.tp_buckets[bucket]
        if not (bk.offload and emb._offload_enabled):
            raise ValueError(
                f"bucket {bucket} is not host-offloaded; a hot-row cache "
                "only makes sense over a host-resident table")
        self.emb = emb
        self.bucket = bucket
        self.capacity = int(capacity)
        self.promote_threshold = int(promote_threshold)
        self.width = bk.width
        self.rows_max = max(bk.rows_max, 1)
        # quantized at-rest storage (ISSUE 17): the cache is the DECODE
        # seam — slots always hold decoded f32 rows; quantized buckets
        # decode at read time (`_read_rows`), so every resident row is
        # served at full HBM bandwidth with no per-request codec work,
        # and a quantized bucket's ~4x row density carries over to
        # cache capacity per HBM byte
        self.store_dtype = bk.storage_dtype

        # host-side index / counters / admission policy: the shared
        # tracker (utils/hotness.py) — long-lived servers see unbounded
        # unique ids, so counters prune back to the hottest max_tracked/2
        # (plus residents), and promotion scans only the threshold-crossed
        # pending set, never the full dict
        self._tracker = HotnessTracker(capacity,
                                       promote_threshold=promote_threshold,
                                       max_tracked=max_tracked)
        self.max_tracked = self._tracker.max_tracked
        self._slots_np = np.zeros((capacity, self.width), np.float32)
        self._slots = self._put_slots()
        self._reader_cache: dict = {}
        self.refreshes = 0
        # store version the residents were last refreshed/synced at
        # (None until a store-routed refresh has run)
        self.refreshed_version = None

    # tracker views — the host-side state lives on the shared tracker;
    # these names are the cache's public/test surface
    @property
    def _index(self) -> Dict[int, int]:
        return self._tracker._index

    @property
    def _counts(self) -> Dict[int, int]:
        return self._tracker._counts

    @property
    def _pending(self) -> set:
        return self._tracker._pending

    @property
    def _slot_keys(self) -> np.ndarray:
        return self._tracker.slot_keys

    @property
    def hits(self) -> int:
        return self._tracker.hits

    @property
    def misses(self) -> int:
        return self._tracker.misses

    @property
    def promotions(self) -> int:
        return self._tracker.promotions

    @property
    def evictions(self) -> int:
        return self._tracker.evictions

    # ------------------------------------------------------------ device IO
    def _put_slots(self):
        """Stage the numpy slot mirror as the replicated device tensor."""
        if self.emb.mesh is not None:
            sh = NamedSharding(self.emb.mesh, P())
        else:
            sh = jax.sharding.SingleDeviceSharding(jax.devices()[0])
        return jax.device_put(self._slots_np, sh)

    def _update_slots(self, slot_idx, rows) -> None:
        """Write `rows` into slots `slot_idx` on host mirror AND device —
        only the changed rows cross the host->device link (a full
        [capacity, width] re-upload per promotion would put a
        capacity-sized transfer on the serving latency path)."""
        self._slots_np[slot_idx] = rows
        self._slots = self._slots.at[jnp.asarray(slot_idx)].set(
            jnp.asarray(rows, jnp.float32))

    @property
    def slots(self) -> jax.Array:
        """The device-resident `[capacity, width]` row tensor (pass into the
        jitted forward next to the slot indices)."""
        return self._slots

    def _read_rows(self, table: jax.Array, keys: np.ndarray,
                   scale: Optional[jax.Array] = None) -> np.ndarray:
        """Fetch table rows for `keys` ([M] int64) host-side, via a cached
        jitted gather in the table's host memory space (rows-only traffic —
        the bucket itself never moves). Quantized buckets pass the per-row
        `scale` leaf and get DECODED f32 rows back (the cache's decode
        seam): payload + scale rows gather together, the codec runs on
        the fetched rows only."""
        world = self.emb.world_size
        m_pad = _ceil_pow2(max(len(keys), 1))
        ids = np.zeros((world, m_pad), np.int32)
        w_idx = (keys // self.rows_max).astype(np.int64)
        rows = (keys % self.rows_max).astype(np.int32)
        pos = np.arange(len(keys))
        ids[w_idx, pos] = rows
        fn = self._reader_cache.get((m_pad, scale is not None))
        if fn is None:
            emb = self.emb
            if emb.mesh is not None:
                host_sh = NamedSharding(emb.mesh, P(emb.axis),
                                        memory_kind=emb._host_kind)
            else:
                host_sh = jax.sharding.SingleDeviceSharding(
                    jax.devices()[0], memory_kind=emb._host_kind)

            def run(table_h, ids, *scale_h):
                ids_h = jax.device_put(ids, host_sh)
                from jax.experimental import compute_on
                with compute_on.compute_on("device_host"):
                    out = jax.vmap(sparse_update_ops.take_rows)(
                        table_h, ids_h)
                    if scale_h:
                        sc = jax.vmap(sparse_update_ops.take_rows)(
                            scale_h[0], ids_h)
                        return out, sc
                    return out

            fn = jax.jit(run)
            self._reader_cache[(m_pad, scale is not None)] = fn
        if scale is not None:
            pay, sc = fn(table, ids, scale)
            pay = np.asarray(jax.device_get(pay))          # [world, Mp, w]
            sc = np.asarray(jax.device_get(sc))            # [world, Mp, 1]
            return wire_ops.decode_rows_np(pay[w_idx, pos],
                                           sc[w_idx, pos],
                                           self.store_dtype)
        out = np.asarray(jax.device_get(fn(table, ids)))   # [world, Mp, w]
        return out[w_idx, pos]

    # ------------------------------------------------------- host-side index
    def lookup_slots(self, keys: np.ndarray,
                     valid: Optional[np.ndarray] = None,
                     observe: bool = True) -> np.ndarray:
        """Map global row keys to cache slots: >= 0 on hit, -1 on miss.

        Args:
          keys: int64 array (any shape) of ``world*rows_max + row`` keys.
          valid: optional same-shape bool mask; invalid lanes (exchange
            padding) always map to -1 and never touch counters or stats.
          observe: update access counters + hit/miss stats (warmup passes
            set False so compile-ahead does not skew admission).

        Returns an int32 array of `keys`' shape.
        """
        return self._tracker.lookup_slots(keys, valid=valid, observe=observe)

    def admit(self, table: jax.Array,
              scale: Optional[jax.Array] = None) -> int:
        """Run the admission policy against the current counters, copying
        newly-promoted rows out of `table` (decoded through `scale` for
        quantized buckets). Returns rows promoted."""
        plan = self._tracker.plan_admissions()
        if not plan:
            return 0
        keys = np.asarray([k for _, k in plan], np.int64)
        rows = self._read_rows(table, keys, scale=scale)
        self._update_slots(np.asarray([s for s, _ in plan]), rows)
        return self._tracker.commit_admissions(plan)

    def refresh(self, table: jax.Array,
                scale: Optional[jax.Array] = None) -> int:
        """Re-copy every resident row from `table` into the HBM slots —
        REQUIRED after anything mutates the offloaded table (see the
        consistency contract in docs/serving.md). Returns rows refreshed.

        Prefer `refresh_from(store)` where a `TableStore` owns the
        tables: passing an array here re-derives the row source by hand,
        which is exactly the two-path staleness seam the store closes."""
        resident = np.flatnonzero(self._slot_keys >= 0)
        if len(resident):
            rows = self._read_rows(table, self._slot_keys[resident],
                                   scale=scale)
            self._update_slots(resident, rows)
        self.refreshes += 1
        return int(len(resident))

    def refresh_from(self, store) -> int:
        """Re-copy every resident row through the table store's
        versioned `read_rows` (ISSUE 6): the row source is the store's
        CURRENT merged view by construction — a caller cannot hand this
        path a stale table reference. Records the store version the
        residents now reflect (`refreshed_version`)."""
        resident = np.flatnonzero(self._slot_keys >= 0)
        if len(resident):
            rows = store.read_rows(self.bucket, self._slot_keys[resident])
            self._update_slots(resident, rows)
        self.refreshes += 1
        self.refreshed_version = store.version
        return int(len(resident))

    def apply_rows(self, keys: np.ndarray, rows: np.ndarray) -> int:
        """Delta-consumption fast path (ISSUE 6): update any RESIDENT
        slots among `keys` with the given row payload — the values come
        straight off the published wire (bit-exact copies of the
        publisher's merged view), so no table read happens at all.
        Counters and stats are untouched. Returns slots updated."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        slot = self._tracker.lookup_slots(keys, observe=False)
        m = slot >= 0
        if m.any():
            self._update_slots(slot[m], np.asarray(rows)[m])
        return int(m.sum())

    def invalidate(self) -> None:
        """Drop every resident row (hits resume only after re-admission)."""
        self._tracker.invalidate()

    # ---------------------------------------------------------------- stats
    @property
    def hit_rate(self) -> float:
        return self._tracker.hit_rate

    def stats(self) -> dict:
        return {"bucket": self.bucket, "capacity": self.capacity,
                "resident": int((self._slot_keys >= 0).sum()),
                "hits": self.hits, "misses": self.misses,
                "hit_rate": round(self.hit_rate, 4),
                "promotions": self.promotions, "evictions": self.evictions,
                "refreshes": self.refreshes}


def cached_group_lookup(emb, grp, table_h, slots, ids_g, slot_g, w_g,
                        scale_h=None):
    """One offloaded exchange group's output through the hot-row cache.

    The numerics mirror ``DistributedEmbedding._host_group_exchange``
    step for step (same reshapes, same weighted-sum expression, same
    cast-then-scale order) so cached and uncached serving outputs bit-match;
    the only difference is the row source: hit lanes gather from the HBM
    `slots` tensor, miss lanes from the host table with hit ids clamped to
    row 0 (`miss_only_ids`) so hits generate no host-memory table traffic.

    Quantized buckets (ISSUE 17) pass `scale_h` (the per-row scale
    leaf): miss lanes decode inside the SAME host region their payload
    rows gather in — identical codec expression to the stock offloaded
    lookup's decode-at-gather, so the bit-match contract holds there
    too. Hit lanes read already-decoded f32 slots and never touch the
    codec.

    Transfer trade-off (deliberate): the stock host path combines on host
    and streams `[world, B, f, wf]` COMBINED rows device-ward; here the
    combine must run on device (hit rows already live there), so the
    miss-side stream is `[world, B*f*k, wf]` RAW rows — k× more
    host->device bytes for multi-hot combiner buckets. Splitting the
    combine (host-partial for misses + device-partial for hits) would undo
    that but changes float summation order and breaks the bit-exactness
    contract above, which is the stronger requirement. What the cache
    buys is host *table* bandwidth on the hot head — the resource the
    offloaded regime is actually starved of; one-hot buckets (k=1, the
    DLRM/Criteo shape) see no stream inflation at all.

    Args (all traced):
      table_h: [world, rows_max, width] host-resident bucket.
      slots: [capacity, width] device-resident cached rows.
      ids_g: [world, B, f, k] exchanged absolute rows (per world slice).
      slot_g: [world, B*f*k] int32 slot indices (-1 = miss).
      w_g: [world, B, f, k] effective weights or None.

    Returns [world, B, f, w_out] matching `_tp_bucket_exchange` layout.
    """
    from jax.experimental import compute_on

    bucket = emb.plan.tp_buckets[grp.bucket]
    if scale_h is None and bucket.storage_dtype != "f32":
        raise ValueError(
            f"bucket {grp.bucket} stores {bucket.storage_dtype} rows: "
            "cached_group_lookup needs the params['tp_scale'] leaf as "
            "scale_h — gathering raw payload codes would serve them as "
            "embedding values")
    world = emb.world_size
    k, wf = grp.k, bucket.width
    rows_max = max(bucket.rows_max, 1)
    combiner = bucket.combiner
    if w_g is None:
        from distributed_embeddings_tpu.layers.dist_model_parallel import (
            _effective_weights)
        _, scale = _effective_weights(None, k, combiner)
    else:
        scale = 1.0
    if emb.mesh is not None:
        host_sh = NamedSharding(emb.mesh, P(emb.axis),
                                memory_kind=emb._host_kind)
        dev_sh = NamedSharding(emb.mesh, P(emb.axis))
    else:
        dev0 = jax.devices()[0]
        host_sh = jax.sharding.SingleDeviceSharding(
            dev0, memory_kind=emb._host_kind)
        dev_sh = jax.sharding.SingleDeviceSharding(dev0)

    B, f = ids_g.shape[1], ids_g.shape[2]
    ids = jnp.clip(ids_g, 0, rows_max - 1).reshape(world, -1)
    hit = slot_g >= 0
    # miss lanes keep their id; hit lanes read host row 0 only (never the
    # hit row) — the host table sees no traffic proportional to hits
    ids_h = jax.device_put(miss_only_ids(ids, slot_g), host_sh)
    with compute_on.compute_on("device_host"):
        miss_rows_h = jax.vmap(sparse_update_ops.take_rows)(table_h, ids_h)
        if scale_h is not None:
            miss_sc_h = jax.vmap(sparse_update_ops.take_rows)(scale_h,
                                                              ids_h)
            miss_rows_h = wire_ops.decode_rows(miss_rows_h, miss_sc_h,
                                               bucket.storage_dtype)
    miss_rows = jax.device_put(miss_rows_h, dev_sh)        # [world, N, wf]
    rows = masked_two_source_gather(slots, slot_g, miss_rows)
    if combiner is None:
        out = rows.reshape(world, B, f, k * wf)
    else:
        rows = rows.reshape(world, B * f, k, wf)
        out = (rows if w_g is None
               else rows * w_g.reshape(world, B * f, k)[..., None]).sum(axis=2)
        out = out.reshape(world, B, f, wf)
    out = emb._cast(out)
    if scale != 1.0:
        out = out * jnp.asarray(scale, out.dtype)
    if emb.mesh is not None and world > 1:
        out = lax.with_sharding_constraint(
            out, NamedSharding(emb.mesh, P(None, emb.axis)))
    return out
