"""Micro-batching request queue for the inference engine.

Real serving traffic arrives as many small, variable-size requests; TPU
forwards want few large, fixed-shape batches. `MicroBatcher` bridges the
two: requests enqueue with `submit()`, `flush()` coalesces everything queued
into the engine's warmed padded shapes (splitting across several forwards
when the queue exceeds the largest shape), runs the engine, and hands each
request its own slice of the results.

The batcher is deliberately synchronous and single-threaded: the caller —
an RPC handler loop, the serve benchmark, a test — decides when to flush
(every request for latency, every N for throughput). That keeps the
component deterministic and testable; an async wrapper is a thin layer on
top, not the other way around.

Observability (the serving metrics the ROADMAP's "heavy traffic" goal
needs): per-request queueing+compute latency lands in a
``serve/request_seconds`` registry histogram (p50/p95/p99), and every
flush records queue depth, batch occupancy (true rows / padded rows)
and the engine's cache hit rate as registry counters/gauges (ISSUE 11 —
pass ``registry=`` to land them in a shared run registry). `summary()`
bundles the same numbers as one dict.
"""

import time
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np
import jax

from distributed_embeddings_tpu.obs.registry import MetricRegistry

__all__ = ["MicroBatcher"]


class MicroBatcher:
    """Coalesce variable-size requests into padded engine batches.

    Args:
      engine: an `InferenceEngine` (already `warmup()`-ed for the shapes
        this batcher should fill; an un-warmed engine still works but every
        new padded size compiles on first use).
      max_batch: cap on true rows per forward (default: the engine's
        largest warmed shape, else 1024).
      clock: injectable time source (seconds) for latency accounting.
      registry: optional `obs.MetricRegistry` for the serving metrics
        (``serve/request_seconds``, ``serve/requests``,
        ``serve/batches``, ``serve/batch_occupancy``,
        ``serve/cache_hit_rate``). Default: a private registry —
        per-batcher accounting, the historical behavior.
      replica: optional replica name (fleet tier, ISSUE 16) — every
        metric family above then carries a ``replica=`` label so one
        shared registry hosts a whole fleet's batchers without
        collisions, and per-replica p50/p99 stay addressable. Default:
        the engine's own ``replica`` name, so an engine built with one
        labels its batcher consistently for free.
    """

    def __init__(self, engine, max_batch: Optional[int] = None,
                 clock: Callable[[], float] = time.perf_counter,
                 registry: Optional[MetricRegistry] = None,
                 replica: Optional[str] = None):
        self.engine = engine
        warmed = getattr(engine, "_warmed", [])
        self.max_batch = int(max_batch or (max(warmed) if warmed else 1024))
        self.clock = clock
        self._queue: List[Tuple[int, Any, List, int, float]] = []
        self._next_handle = 0
        self._metrics = registry if registry is not None \
            else MetricRegistry()
        if replica is None:
            replica = getattr(engine, "replica", None)
        self.replica = replica
        self._labels = {} if replica is None else {"replica": str(replica)}
        self.latency = self._metrics.histogram("serve/request_seconds",
                                               **self._labels)
        self.requests = 0
        self.batches = 0
        self.queue_depth_max = 0
        self._occupancy_rows = 0       # true rows over padded rows
        self._padded_rows = 0

    def submit(self, batch) -> int:
        """Enqueue one request (same `batch` structure as
        `engine.predict`). Returns a handle resolved by the next `flush`."""
        if self.engine._model is None:
            numerical, cats = None, list(batch)
        else:
            numerical, cats = batch
            cats = list(cats)
        rows = int(np.asarray(cats[0][0] if isinstance(cats[0], tuple)
                              else cats[0]).shape[0])
        if rows > self.max_batch:
            raise ValueError(
                f"request of {rows} rows exceeds max_batch={self.max_batch};"
                " split it upstream")
        handle = self._next_handle
        self._next_handle += 1
        self._queue.append((handle, numerical, cats, rows, self.clock()))
        self.requests += 1
        self._metrics.counter("serve/requests", **self._labels).inc()
        self.queue_depth_max = max(self.queue_depth_max, len(self._queue))
        return handle

    @property
    def queue_depth(self) -> int:
        return len(self._queue)

    @property
    def queued_rows(self) -> int:
        """True rows currently queued (the row-level occupancy signal
        fleet admission control sheds on, next to `queue_depth`)."""
        return sum(req[3] for req in self._queue)

    def _concat(self, parts: List):
        if isinstance(parts[0], tuple):
            return (np.concatenate([np.asarray(p[0]) for p in parts]),
                    np.concatenate([np.asarray(p[1]) for p in parts]))
        return np.concatenate([np.asarray(p) for p in parts])

    def flush(self) -> Dict[int, Any]:
        """Run everything queued; returns {handle: outputs} with each
        request's rows sliced back out of the coalesced forwards."""
        results: Dict[int, Any] = {}
        while self._queue:
            group, rows = [], 0
            while self._queue and rows + self._queue[0][3] <= self.max_batch:
                req = self._queue.pop(0)
                group.append(req)
                rows += req[3]
            if not group:        # single over-size request cannot happen
                raise AssertionError("max_batch smaller than queued request")
            cats = [self._concat([req[2][i] for req in group])
                    for i in range(len(group[0][2]))]
            if group[0][1] is None:
                batch = cats
            else:
                batch = (np.concatenate([np.asarray(req[1])
                                         for req in group]), cats)
            out = self.engine.predict(batch)
            # latency must cover device compute, not just async dispatch:
            # wait for the coalesced forward before stamping completion
            jax.block_until_ready(out)
            done = self.clock()
            padded = self.engine._target_batch(rows)
            self.batches += 1
            self._metrics.counter("serve/batches", **self._labels).inc()
            self._occupancy_rows += rows
            self._padded_rows += padded
            start = 0
            for handle, _, _, n, t_in in group:
                sl = slice(start, start + n)
                results[handle] = jax.tree.map(lambda a, s=sl: a[s], out)
                start += n
                self.latency.record(done - t_in)
        m = self._metrics
        m.gauge("serve/batch_occupancy", **self._labels).set(
            self._occupancy_rows / self._padded_rows
            if self._padded_rows else 0.0)
        # cheap attribute sums, not cache_stats() (which builds
        # per-bucket dicts) — this runs per flush
        caches = getattr(self.engine, "caches", {}) or {}
        hits = sum(c.hits for c in caches.values())
        misses = sum(c.misses for c in caches.values())
        m.gauge("serve/cache_hit_rate", **self._labels).set(
            hits / (hits + misses) if hits + misses else 0.0)
        return results

    def summary(self) -> dict:
        """Serving metrics: latency percentiles, batch occupancy, queue
        depth, and the engine's cache hit rate."""
        occ = (self._occupancy_rows / self._padded_rows
               if self._padded_rows else 0.0)
        cache = self.engine.cache_stats()
        return {
            "requests": self.requests,
            "batches": self.batches,
            "queue_depth": self.queue_depth,
            "queue_depth_max": self.queue_depth_max,
            "batch_occupancy": round(occ, 4),
            "hit_rate": cache["hit_rate"],
            **self.latency.summary(),
        }
