"""Serving subsystem: apply-only inference over trained embedding models.

Three pieces (see docs/serving.md for the lifecycle and consistency
contract):

  * `InferenceEngine` (engine.py) — apply-only, compile-ahead jitted
    forward over a `DistributedEmbedding` (+ optional dense model);
    strips optimizer state and tap machinery from the serving path.
  * `HotRowCache` (cache.py) — software-managed HBM cache of the hot rows
    of host-offloaded buckets, with counter-based admission and an
    explicit `refresh()` consistency step.
  * `MicroBatcher` (batcher.py) — coalesces variable-size requests into
    the engine's padded shapes and records serving metrics (latency
    percentiles, occupancy, hit rate).
"""

from distributed_embeddings_tpu.serving.batcher import MicroBatcher
from distributed_embeddings_tpu.serving.cache import HotRowCache
from distributed_embeddings_tpu.serving.engine import InferenceEngine

__all__ = ["InferenceEngine", "HotRowCache", "MicroBatcher"]
