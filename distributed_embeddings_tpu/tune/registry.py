"""The declarative knob-space registry — single source of truth.

Before this module every performance knob lived as a scattered
``os.environ.get``/``measured_default`` call site, and the list of what
is tunable existed only in humans (and a duplicated copy inside
tools/window_rehearsal.py). The registry makes the space declarative:

  * ``bench.py --mode tune`` enumerates its search space from here,
  * ``docs/perf_model.md``'s knob table is GENERATED from here
    (``knob_table_markdown``; drift-gated by tests/test_tune.py),
  * ``tools/lint_invariants.py``'s scenario-knob rule validates soak /
    fleet scenario ``"knobs"`` overrides against it,
  * ``tune.runtime.RuntimeTuner`` refuses to auto-flip any knob whose
    safety class is not ``runtime``,
  * ``tune.resolve`` rejects tuned-config entries naming unknown knobs
    or illegal values (loudly — warning + counter, never a crash).

Safety classes:
  offline  changes the lowered program / plan (wire dtypes, kernel
           dispatch, lookahead depth...): legal only between runs,
           decided by the offline search harness.
  runtime  host-side policy read per use (publish cadence, admission
           limits...): safe for the RuntimeTuner to flip on a live
           system.

Parity classes (what adopting a non-default value does to numerics):
  exact    bit-exact vs the fallback by construction or by a standing
           parity gate (tiled/pallas scatter, int16 id wire, lookahead
           patching, pipeline depth, cadences). The offline tuner may
           adopt these into a config-of-record's ``winner``.
  bounded  parity-gated to a documented tolerance (bf16 wire, int8/fp8
           storage, hot-row float reorder). The tuner never silently
           adopts these: they ride as ``staged_tpu_arms`` for a human +
           tunnel-window decision.
  numerics user-visible numerics trade (cumsum dedup's ~sqrt(N)*eps +
           weakened rep promise). Never auto-flipped, mirroring
           bench._maybe_write_measured_defaults's standing refusal.
"""

import dataclasses
from typing import Dict, Optional, Sequence, Tuple

OFFLINE = "offline"
RUNTIME = "runtime"

PARITY_EXACT = "exact"
PARITY_BOUNDED = "bounded"
PARITY_NUMERICS = "numerics"


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable knob. ``values`` is the closed legal set for enum
    knobs; ``None`` means an integer domain bounded by
    [``int_min``, ``int_max``] (``None`` bound = open). ``fallback`` is
    the hand-picked default the resolution chain bottoms out at —
    always legal by construction (validated at import)."""
    name: str                       # short slug, e.g. "scatter_impl"
    env: str                        # e.g. "DET_SCATTER_IMPL"
    values: Optional[Tuple[str, ...]]
    fallback: str
    safety: str                     # OFFLINE | RUNTIME
    parity: str                     # exact | bounded | numerics
    cost_model: Optional[str]       # cost hook the search prunes with
    doc: str
    int_min: Optional[int] = None
    int_max: Optional[int] = None

    def is_legal(self, value: str) -> bool:
        if self.values is not None:
            return value in self.values
        try:
            v = int(value)
        except (TypeError, ValueError):
            # the empty string means "unset" for open-domain knobs whose
            # fallback is unset (fleet queue-rows cap)
            return value == "" and self.fallback == ""
        if self.int_min is not None and v < self.int_min:
            return False
        if self.int_max is not None and v > self.int_max:
            return False
        return True

    def domain_str(self) -> str:
        if self.values is not None:
            return "/".join(self.values)
        lo = "-inf" if self.int_min is None else str(self.int_min)
        hi = "inf" if self.int_max is None else str(self.int_max)
        return f"int [{lo}, {hi}]"


# Cost-model hook names (what `bench.py --mode tune` prunes/ranks with):
#   collective_bytes  analysis.programs.expected_collective_bytes over
#                     the arm's plan — exact per-device payload bytes
#   padding_report    layer.exchange_padding_report structural fields
#   sort_audit        analysis op-count gates (stablehlo.sort bounds)
#   overlap_audit     collective-overlap classification (lookahead)
#   payload_bytes     wire.delta_row_bytes at-rest/stream accounting
#   step_time         no static model — measured arm only
KNOBS: Tuple[Knob, ...] = (
    Knob("scatter_impl", "DET_SCATTER_IMPL",
         ("xla", "tiled", "pallas", "pallas-dma"), "xla",
         OFFLINE, PARITY_EXACT, "sort_audit",
         "sparse-update scatter kernel family (TPU dispatch; "
         "compile-probe gated, bit-exact vs xla)"),
    Knob("lookup_path", "DET_LOOKUP_PATH",
         ("auto", "xla", "tiled", "fused", "pallas"), "auto",
         OFFLINE, PARITY_EXACT, "sort_audit",
         "forward gather/combine path (fused = Pallas "
         "gather->combine, parity-gated)"),
    Knob("dedup_impl", "DET_DEDUP_IMPL", ("sort", "cumsum"), "sort",
         OFFLINE, PARITY_NUMERICS, "step_time",
         "id-dedup aggregation; cumsum trades ~sqrt(N)*eps precision — "
         "never auto-flipped"),
    Knob("exchange_wire", "DET_EXCHANGE_WIRE",
         ("f32", "bf16", "bf16-sr"), "f32",
         OFFLINE, PARITY_BOUNDED, "collective_bytes",
         "float payload dtype on every exchange collective (bf16 "
         "halves the dominant wire)"),
    Knob("id_wire", "DET_ID_WIRE", ("auto", "int32"), "auto",
         OFFLINE, PARITY_EXACT, "collective_bytes",
         "id-exchange dtype; auto narrows to int16 where the planner "
         "proves the key space fits (lossless)"),
    Knob("store_dtype", "DET_STORE_DTYPE", ("f32", "int8", "fp8"), "f32",
         OFFLINE, PARITY_BOUNDED, "payload_bytes",
         "at-rest row storage dtype for eligible (cold/offloaded) "
         "buckets"),
    Knob("delta_dtype", "DET_DELTA_DTYPE", ("f32", "int8", "fp8"), "f32",
         OFFLINE, PARITY_BOUNDED, "payload_bytes",
         "published delta/snapshot stream payload dtype (independent "
         "of table residency)"),
    Knob("hot_rows", "DET_HOT_ROWS", None, "0",
         OFFLINE, PARITY_BOUNDED, "padding_report",
         "replicated hot-shard rows per MP bucket (0 = off; <=1e-5 "
         "multi-hot float reorder)", int_min=0),
    Knob("lookahead", "DET_LOOKAHEAD", ("0", "1"), "0",
         OFFLINE, PARITY_EXACT, "overlap_audit",
         "prefetch pipeline depth: overlap batch N+1's exchanges with "
         "batch N's dense compute (bit-exact with patching)"),
    Knob("pipeline_depth", "DET_PIPELINE_DEPTH", None, "2",
         OFFLINE, PARITY_EXACT, "step_time",
         "ingest pipeline inter-stage queue bound (backpressure)",
         int_min=1),
    Knob("publish_every", "DET_PUBLISH_EVERY", None, "0",
         RUNTIME, PARITY_EXACT, "payload_bytes",
         "training-side delta publish cadence in steps (0 = off; "
         "serving freshness vs publish cost)", int_min=0),
    Knob("snapshot_every", "DET_STORE_SNAPSHOT_EVERY", None, "0",
         RUNTIME, PARITY_EXACT, "payload_bytes",
         "full-snapshot compaction cadence in publishes (0 = only the "
         "mandatory first; re-anchor cost vs replay length)", int_min=0),
    Knob("vocab_admit", "DET_VOCAB_ADMIT", None, "2",
         RUNTIME, PARITY_BOUNDED, "step_time",
         "vocab/hot-row admission threshold: observed hits before a "
         "key is admitted", int_min=1),
    Knob("fleet_queue_depth", "DET_FLEET_MAX_QUEUE_DEPTH", None, "64",
         RUNTIME, PARITY_EXACT, "step_time",
         "admission control: shed when a replica's batcher holds this "
         "many queued requests", int_min=1),
    Knob("fleet_queue_rows", "DET_FLEET_MAX_QUEUE_ROWS", None, "",
         RUNTIME, PARITY_EXACT, "step_time",
         "admission control: shed when queued ROWS exceed this bound "
         "(empty = unlimited)", int_min=1),
)

_BY_NAME: Dict[str, Knob] = {k.name: k for k in KNOBS}
_BY_ENV: Dict[str, Knob] = {k.env: k for k in KNOBS}

# registry invariants, enforced at import: a duplicated env var or an
# illegal fallback would silently corrupt every consumer above
assert len(_BY_NAME) == len(KNOBS), "duplicate knob name in registry"
assert len(_BY_ENV) == len(KNOBS), "duplicate knob env var in registry"
for _k in KNOBS:
    assert _k.safety in (OFFLINE, RUNTIME), _k
    assert _k.parity in (PARITY_EXACT, PARITY_BOUNDED,
                         PARITY_NUMERICS), _k
    assert _k.is_legal(_k.fallback), \
        f"knob {_k.name}: fallback {_k.fallback!r} outside its own domain"


def all_knobs() -> Tuple[Knob, ...]:
    return KNOBS


def get_knob(name_or_env: str) -> Knob:
    """Look a knob up by slug or env var; KeyError on unknown."""
    k = _BY_NAME.get(name_or_env) or _BY_ENV.get(name_or_env)
    if k is None:
        raise KeyError(f"unknown knob {name_or_env!r}; registry has "
                       f"{sorted(_BY_NAME)}")
    return k


def maybe_get(name_or_env: str) -> Optional[Knob]:
    return _BY_NAME.get(name_or_env) or _BY_ENV.get(name_or_env)


def validate_override(env: str, value) -> Optional[str]:
    """One scenario/tuned-config override checked against the registry.
    Returns an error string (for the scenario lint / tuned-file
    validator) or None when (env, value) is a known knob with a legal
    value."""
    k = _BY_ENV.get(env)
    if k is None:
        return (f"unknown knob {env!r}: not in the tune registry "
                f"(known: {sorted(_BY_ENV)})")
    if not isinstance(value, str):
        return (f"{env}: override values are env-var STRINGS, got "
                f"{type(value).__name__} {value!r}")
    if not k.is_legal(value):
        return (f"{env}={value!r}: illegal value, domain is "
                f"{k.domain_str()}")
    return None


def runtime_knobs() -> Tuple[Knob, ...]:
    return tuple(k for k in KNOBS if k.safety == RUNTIME)


def offline_knobs() -> Tuple[Knob, ...]:
    return tuple(k for k in KNOBS if k.safety == OFFLINE)


def knob_table_markdown() -> str:
    """The generated knob table docs/perf_model.md embeds between its
    knob-table markers — regenerate with
    ``python -m distributed_embeddings_tpu.tune.registry`` (drift-gated
    by tests/test_tune.py)."""
    lines = [
        "| knob | env var | legal values | default | safety | parity "
        "| cost model |",
        "|---|---|---|---|---|---|---|",
    ]
    for k in KNOBS:
        lines.append(
            f"| {k.name} | `{k.env}` | {k.domain_str()} "
            f"| `{k.fallback or '(unset)'}` | {k.safety} | {k.parity} "
            f"| {k.cost_model or '—'} |")
    return "\n".join(lines)


if __name__ == "__main__":
    print(knob_table_markdown())
