"""Online adaptation for the runtime-flippable knob class (stretch).

The SLO evaluator (obs/slo.py) already turns degraded operation into
typed findings and postmortems; this module lets it also trigger a
MITIGATION — but only over knobs the registry marks ``safety ==
"runtime"`` (publish cadence, snapshot cadence, admission limits,
admission thresholds). Offline knobs (wire dtypes, kernel dispatch,
lookahead...) change the lowered program and are refused at
construction: an auto-flip there would be a silent re-plan.

Every flip is bounded (multiplicative step clamped to the rule's
[min, max]), rate-limited (one flip per knob per ``react`` call plus a
cooldown of ``cooldown_reacts`` calls), and leaves a
``tune/autoflip`` flight-recorder instant + a
``tune/autoflips_total{knob=}`` counter — the same audit discipline as
every tuned-value adoption. The tuner NEVER writes env vars or config
files: it calls the applier the owner registered (e.g. a closure over
``AdmissionController.max_queue_depth``), so the flip is visible,
typed, and revertible by the owning subsystem.
"""

from typing import Callable, Dict, List, Optional, Sequence

from . import registry as _registry

# Default reaction rules, matched by substring against finding ids
# (obs/slo.py emits `slo:<rule-name>` / degraded reasons). Shipped
# conservative: shed harder under queue pressure, publish less under
# stream distress — both runtime-class, both instantly revertible.
DEFAULT_RULES = (
    {"match": "queue", "knob": "DET_FLEET_MAX_QUEUE_DEPTH",
     "action": "scale", "factor": 0.5, "min": 4, "max": 4096},
    {"match": "publish", "knob": "DET_PUBLISH_EVERY",
     "action": "scale", "factor": 2.0, "min": 1, "max": 256},
)


class RuntimeTuner:
    """Map SLO/degraded findings to bounded runtime-knob adjustments.

    Args:
      appliers: ``{env: callable(int_value)}`` — the owner-side setter
        for each knob this tuner may touch. Every env must name a
        registry knob with ``safety == "runtime"`` (ValueError
        otherwise — the registry is the safety authority, not the
        caller).
      initial: ``{env: int}`` current values; a knob without one starts
        from its registry fallback (empty fallback = knob unusable
        until a value is provided).
      rules: reaction rules (see DEFAULT_RULES); each must name an env
        present in ``appliers``.
      cooldown_reacts: after a flip, the knob sits out this many
        subsequent ``react`` calls — mitigation, not oscillation.
    """

    def __init__(self, appliers: Dict[str, Callable],
                 initial: Optional[Dict[str, int]] = None,
                 rules: Sequence[dict] = DEFAULT_RULES,
                 cooldown_reacts: int = 2,
                 recorder=None, registry=None):
        self._appliers = dict(appliers)
        for env in self._appliers:
            k = _registry.get_knob(env)       # KeyError on unknown
            if k.safety != _registry.RUNTIME:
                raise ValueError(
                    f"knob {env} is {k.safety}-only: a runtime flip "
                    "would silently change the lowered program — "
                    "offline knobs are the search harness's, not the "
                    "RuntimeTuner's")
        self._rules = [dict(r) for r in rules
                       if r.get("knob") in self._appliers]
        for r in self._rules:
            if r.get("action") != "scale":
                raise ValueError(f"unknown rule action {r.get('action')!r}")
        self._values: Dict[str, int] = {}
        for env in self._appliers:
            fb = _registry.get_knob(env).fallback
            if (initial or {}).get(env) is not None:
                self._values[env] = int(initial[env])
            elif fb != "":
                self._values[env] = int(fb)
        self._cooldown = int(cooldown_reacts)
        self._sitting_out: Dict[str, int] = {}   # env -> reacts left
        self._recorder = recorder
        self._registry = registry
        self.flips: List[dict] = []              # full history, appended

    def _record_flip(self, flip: dict) -> None:
        self.flips.append(flip)
        try:
            rec = self._recorder
            if rec is None:
                from ..obs.trace import default_recorder
                rec = default_recorder()
            rec.instant("tune/autoflip", **flip)
        except Exception:  # noqa: BLE001 - audit must not break serving
            pass
        try:
            reg = self._registry
            if reg is None:
                from ..obs.registry import default_registry
                reg = default_registry()
            reg.counter("tune/autoflips_total", knob=flip["knob"]).inc()
        except Exception:  # noqa: BLE001
            pass

    def react(self, findings) -> List[dict]:
        """One mitigation pass over SLO findings (obs.slo Finding objects
        or dicts with an ``id``/``fid``). Returns the flips applied this
        call (each ``{knob, from, to, finding}``); knobs in cooldown or
        already at their rule bound flip nothing."""
        ids = []
        for f in findings or ():
            fid = getattr(f, "fid", None) or getattr(f, "id", None)
            if fid is None and isinstance(f, dict):
                fid = f.get("fid") or f.get("id")
            if fid:
                ids.append(str(fid))
        applied: List[dict] = []
        flipped_now = set()
        # age existing cooldowns AFTER the skip check below uses them:
        # a knob flipped on react N sits out reacts N+1..N+cooldown
        cooled = {env: left - 1 for env, left in self._sitting_out.items()
                  if left > 1}
        skip_now = set(self._sitting_out)
        self._sitting_out = cooled
        for rule in self._rules:
            env = rule["knob"]
            if env in skip_now or env in flipped_now:
                continue
            hit = next((i for i in ids if rule["match"] in i), None)
            if hit is None or env not in self._values:
                continue
            cur = self._values[env]
            new = int(round(cur * float(rule["factor"])))
            if new == cur:
                new = cur + (1 if rule["factor"] > 1 else -1)
            new = max(int(rule.get("min", 1)),
                      min(int(rule.get("max", new)), new))
            if new == cur:
                continue                       # already at the bound
            self._appliers[env](new)
            self._values[env] = new
            if self._cooldown > 0:
                self._sitting_out[env] = self._cooldown
            flipped_now.add(env)
            flip = {"knob": env, "from": cur, "to": new, "finding": hit}
            self._record_flip(flip)
            applied.append(flip)
        return applied

    def value(self, env: str) -> Optional[int]:
        return self._values.get(env)
