"""Offline knob-space search machinery for ``bench.py --mode tune``.

Bench-independent so it is unit-testable without a mesh: arm
enumeration over the registry, cost-model pruning with a full audit
trail (EVERY pruned arm is logged with its predicted costs and a
rationale — a tuner that silently capped its search space would read as
"covered everything" when it didn't), and the ``tuned-config-v1``
config-of-record schema + validator shared by the writer (bench) and
the reader (``tune.resolve``).

The config-of-record is evidence-first: the winning values ride next to
the per-arm metric snapshots, the prune log, the device-attribution
block and the audit-findings stamp that justify them, so a future
tunnel window (or reviewer) can re-litigate the decision from the file
alone.
"""

import dataclasses
import itertools
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from . import registry as _registry

TUNED_SCHEMA = "tuned-config-v1"


@dataclasses.dataclass
class Arm:
    """One point in the knob space: env-var overrides + a stable key."""
    overrides: Dict[str, str]
    key: str = ""

    def __post_init__(self):
        if not self.key:
            self.key = arm_key(self.overrides)


def arm_key(overrides: Dict[str, str]) -> str:
    """Stable, human-greppable arm label: short knob slugs, sorted."""
    if not overrides:
        return "defaults"
    parts = []
    for env in sorted(overrides):
        k = _registry.maybe_get(env)
        parts.append(f"{k.name if k else env}={overrides[env]}")
    return ",".join(parts)


def enumerate_arms(space: Dict[str, Sequence[str]],
                   include_defaults: bool = True) -> List[Arm]:
    """Cross-product over ``{env: [values...]}``. Every env must name a
    registry knob and every value must be legal — an illegal search
    space refuses at enumeration, not mid-measurement. The all-fallback
    baseline arm rides first (the hand-picked config the winner must
    match or beat)."""
    for env, values in space.items():
        k = _registry.get_knob(env)          # KeyError on unknown knob
        for v in values:
            err = _registry.validate_override(k.env, v)
            if err is not None:
                raise ValueError(f"search space: {err}")
    envs = sorted(space)
    arms: List[Arm] = []
    seen = set()
    if include_defaults:
        base = {e: _registry.get_knob(e).fallback for e in envs}
        arms.append(Arm(base, key="defaults"))
        seen.add(tuple(sorted(base.items())))
    for combo in itertools.product(*(space[e] for e in envs)):
        ov = dict(zip(envs, combo))
        sig = tuple(sorted(ov.items()))
        if sig in seen:
            continue
        seen.add(sig)
        arms.append(Arm(ov))
    return arms


def prune_by_cost(arms: Sequence[Arm],
                  cost_fn: Callable[[Arm], Dict[str, float]],
                  keep: int,
                  order: Sequence[str],
                  always_keep: Sequence[str] = ("defaults",),
                  ) -> Tuple[List[Arm], List[dict], bool]:
    """Rank arms by the cost models and keep the ``keep`` cheapest.

    ``cost_fn(arm)`` returns the arm's predicted structural costs;
    ``order`` names the cost keys in ranking priority (lexicographic —
    e.g. ``("collective_bytes", "padding_ratio")``). Arms named in
    ``always_keep`` survive unconditionally (the baseline must always
    be measured — a tuner that never re-measures the incumbent cannot
    claim "or better").

    Returns ``(survivors, pruned_log, audit_ok)``: every pruned arm is
    logged with its predicted costs, its rank and the rationale; and
    ``audit_ok`` asserts the cost-model ORDERING was respected — no
    pruned arm predicted cheaper than a kept arm (the CI tune smoke
    gates on this; a False here means the pruning logic itself is
    buggy, which must fail loudly, not ship a record)."""
    costed = []
    for arm in arms:
        costs = dict(cost_fn(arm))
        rank = tuple(float(costs.get(k, 0.0)) for k in order)
        costed.append((rank, arm, costs))
    costed.sort(key=lambda t: (t[0], t[1].key))
    keep = max(int(keep), 1)
    survivors: List[Arm] = []
    pruned_log: List[dict] = []
    kept_ranks, pruned_ranks = [], []
    for i, (rank, arm, costs) in enumerate(costed):
        forced = arm.key in always_keep
        if len(survivors) < keep or forced:
            survivors.append(arm)
            kept_ranks.append(rank)
        else:
            best = costed[0]
            pruned_log.append({
                "arm": arm.key, "overrides": arm.overrides,
                "predicted": costs, "rank": i,
                "rationale": (
                    f"predicted {order[0]}={costs.get(order[0])} ranks "
                    f"#{i + 1}/{len(costed)} (best arm "
                    f"{best[1].key!r}: {order[0]}="
                    f"{best[2].get(order[0])}); outside keep={keep}"),
            })
            pruned_ranks.append(rank)
    # ordering audit: every non-forced survivor must predict <= every
    # pruned arm on the ranking tuple
    free_kept = [r for r, a in zip(kept_ranks, survivors)
                 if a.key not in always_keep]
    audit_ok = (not pruned_ranks or not free_kept
                or max(free_kept) <= min(pruned_ranks))
    return survivors, pruned_log, audit_ok


def split_adoptable(overrides: Dict[str, str]) -> Tuple[Dict[str, str],
                                                        Dict[str, str]]:
    """(adoptable, staged): non-default override values whose knob
    parity class is ``exact`` may enter a config-of-record ``winner``;
    ``bounded``/``numerics`` overrides must ride as staged TPU-decision
    arms instead (the f32/default-path bit-exactness acceptance:
    the tuner only ADOPTS among bit-exact-gated strategies)."""
    adoptable, staged = {}, {}
    for env, value in overrides.items():
        k = _registry.get_knob(env)
        if value == k.fallback:
            adoptable[env] = value
        elif k.parity == _registry.PARITY_EXACT:
            adoptable[env] = value
        else:
            staged[env] = value
    return adoptable, staged


def build_record(workload: str, winner: Dict[str, str],
                 arms: Sequence[dict], pruned: Sequence[dict],
                 prune_order: Sequence[str], prune_audit_ok: bool,
                 beats_default: Dict[str, bool],
                 staged_tpu_arms: Sequence[dict],
                 git_sha: str, backend: str, created_at: str,
                 attribution: Optional[dict] = None,
                 extra: Optional[dict] = None) -> dict:
    """Assemble a schema-valid tuned-config-v1 doc (validated before
    return — the writer can never emit a record the reader rejects)."""
    doc = {
        "schema": TUNED_SCHEMA,
        "workload": workload,
        "created_at": created_at,
        "git_sha": git_sha,
        "backend": backend,
        "winner": dict(winner),
        "arms": list(arms),
        "pruned": list(pruned),
        "prune_order": list(prune_order),
        "prune_audit_ok": bool(prune_audit_ok),
        "beats_default": dict(beats_default),
        "staged_tpu_arms": list(staged_tpu_arms),
    }
    if attribution is not None:
        doc["device_attribution"] = attribution
    if extra:
        doc.update(extra)
    errors = validate_tuned_record(doc)
    if errors:
        raise ValueError(f"refusing to emit an invalid tuned record: "
                         f"{errors}")
    return doc


def validate_tuned_record(doc) -> List[str]:
    """Schema check for a tuned-config-v1 doc; [] = valid. Shared by
    the bench writer (refuse to emit garbage) and ``tune.resolve`` (a
    stale/malformed file must fall through loudly, never crash)."""
    errors: List[str] = []
    if not isinstance(doc, dict):
        return [f"not a JSON object ({type(doc).__name__})"]
    if doc.get("schema") != TUNED_SCHEMA:
        errors.append(f"schema {doc.get('schema')!r} != {TUNED_SCHEMA!r} "
                      "(stale or foreign file)")
    if not isinstance(doc.get("workload"), str) or not doc.get("workload"):
        errors.append("missing/invalid 'workload'")
    if not isinstance(doc.get("winner"), dict):
        errors.append("missing/invalid 'winner' (env -> value dict)")
    for field in ("created_at", "git_sha"):
        if not isinstance(doc.get(field), str) or not doc.get(field):
            errors.append(f"missing/invalid {field!r} (provenance is "
                          "not optional)")
    arms = doc.get("arms")
    if not isinstance(arms, list) or not arms:
        errors.append("missing/empty 'arms' (a record with no measured "
                      "evidence is not a config-of-record)")
    else:
        for i, arm in enumerate(arms):
            if not isinstance(arm, dict) or "overrides" not in arm \
                    or "key" not in arm:
                errors.append(f"arms[{i}]: needs 'key' + 'overrides'")
    pruned = doc.get("pruned")
    if not isinstance(pruned, list):
        errors.append("missing 'pruned' (the prune log is part of the "
                      "evidence trail; use [] when nothing was pruned)")
    else:
        for i, p in enumerate(pruned):
            if not isinstance(p, dict) or "rationale" not in p:
                errors.append(f"pruned[{i}]: every pruned arm carries "
                              "a 'rationale'")
    if "prune_audit_ok" in doc and doc["prune_audit_ok"] is not True:
        errors.append("prune_audit_ok is not True: the cost-model "
                      "ordering audit failed at write time")
    if not isinstance(doc.get("staged_tpu_arms", []), list):
        errors.append("'staged_tpu_arms' must be a list")
    return errors
