"""The knob consumption seam: env > tuned config > measured defaults.

``knob_value(env, fallback)`` is the one resolution order every DET_*
performance knob reads through (``ops.sparse_update.measured_default``
delegates here, as do the wire/storage/training/fleet env-default
helpers):

  1. the env var itself — an operator's explicit word always wins;
  2. the workload's config-of-record ``tools/tuned/<workload>.json``
     written by ``bench.py --mode tune`` — consulted ONLY when
     explicitly selected via ``DET_TUNED_WORKLOAD=<name>`` (resolved
     against the repo's tools/tuned/) or ``DET_TUNED_PATH=<file>``.
     Explicit opt-in keeps CPU test equivalence: no env, no silent
     behavior change because a tuner ran on the same checkout;
  3. ``tools/measured_defaults.json`` (the PR-2 seed of this machinery,
     now subsumed): consulted only on the TPU backend, or anywhere
     under ``DET_MEASURED_DEFAULTS_CONSULT=1`` (the rehearsal knob);
  4. the hand-picked ``fallback``.

Every adoption from layer 2 or 3 lands a flight-recorder instant
(``tune/adopt``) and bumps ``tune/adoptions_total{source=}`` — a
postmortem can always answer "which config was this process actually
running?". A malformed/stale tuned file falls through LOUDLY: one
RuntimeWarning + ``tune/tuned_config_invalid_total``, never a crash,
and entries naming unknown knobs or illegal values are dropped
individually (``tune/tuned_knob_rejected_total``) while the legal rest
still applies.
"""

import json
import os
import threading
import warnings
from typing import Dict, Optional, Tuple

from . import registry as _registry

_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_lock = threading.Lock()
_TUNED: Optional[Dict[str, str]] = None       # env -> value, or None=unread
_TUNED_INFO: Dict[str, object] = {}           # path/workload/errors diag
_MEASURED: Optional[Dict[str, str]] = None
_ADOPTED: set = set()                         # (env, value, source) emitted
_WARNED: set = set()


def reset_cache() -> None:
    """Drop every per-process cache (tests, bench arm isolation)."""
    global _TUNED, _MEASURED
    with _lock:
        _TUNED = None
        _MEASURED = None
        _TUNED_INFO.clear()
        _ADOPTED.clear()
        _WARNED.clear()


def tuned_source() -> Tuple[Optional[str], Optional[str]]:
    """(path, workload) the tuned layer would consult, or (None, None)
    when neither DET_TUNED_PATH nor DET_TUNED_WORKLOAD is set."""
    path = os.environ.get("DET_TUNED_PATH")
    if path:
        return path, os.environ.get("DET_TUNED_WORKLOAD")
    workload = os.environ.get("DET_TUNED_WORKLOAD")
    if workload:
        return (os.path.join(_ROOT, "tools", "tuned",
                             f"{workload}.json"), workload)
    return None, None


def _warn_once(key: str, msg: str) -> None:
    if key in _WARNED:
        return
    _WARNED.add(key)
    warnings.warn(msg, RuntimeWarning, stacklevel=3)


def _count(name: str, **labels) -> None:
    try:
        from ..obs.registry import default_registry
        default_registry().counter(name, **labels).inc()
    except Exception:  # noqa: BLE001 - accounting must not break dispatch
        pass


def _load_tuned_locked() -> Dict[str, str]:
    """Read + validate the selected tuned config once per process."""
    path, workload = tuned_source()
    info = {"path": path, "workload": workload, "errors": []}
    if path is None:
        _TUNED_INFO.update(info)
        return {}
    try:
        with open(path) as f:
            doc = json.load(f)
        from .search import validate_tuned_record
        errors = validate_tuned_record(doc)
    except Exception as e:  # noqa: BLE001 - absent/corrupt file = loud miss
        doc, errors = None, [f"unreadable: {e}"]
    if doc is None or errors:
        info["errors"] = errors
        _TUNED_INFO.update(info)
        _count("tune/tuned_config_invalid_total")
        _warn_once(f"invalid:{path}",
                   f"tuned config {path} is malformed/stale and was "
                   f"IGNORED (resolution falls through): {errors[:3]}")
        return {}
    if workload and doc.get("workload") != workload:
        # DET_TUNED_WORKLOAD=serve pointed (via DET_TUNED_PATH) at a
        # record tuned for a different workload: refuse, loudly
        info["errors"] = [f"workload mismatch: file is for "
                          f"{doc.get('workload')!r}, requested "
                          f"{workload!r}"]
        _TUNED_INFO.update(info)
        _count("tune/tuned_config_invalid_total")
        _warn_once(f"workload:{path}", f"tuned config {path}: "
                                       f"{info['errors'][0]}")
        return {}
    out: Dict[str, str] = {}
    for env, value in dict(doc.get("winner", {})).items():
        err = _registry.validate_override(env, value)
        if err is not None:
            info["errors"].append(err)
            _count("tune/tuned_knob_rejected_total")
            _warn_once(f"knob:{path}:{env}",
                       f"tuned config {path}: entry rejected — {err}")
            continue
        out[env] = value
    _TUNED_INFO.update(info)
    return out


def _load_measured_locked() -> Dict[str, str]:
    """tools/measured_defaults.json in its historical shape: flat
    {env: value-or-{value, provenance...}}; absent/invalid = {}."""
    path = os.environ.get(
        "DET_MEASURED_DEFAULTS_PATH",
        os.path.join(_ROOT, "tools", "measured_defaults.json"))
    try:
        with open(path) as f:
            raw = json.load(f)
        return {k: (v.get("value") if isinstance(v, dict) else v)
                for k, v in raw.items()}
    except Exception:  # noqa: BLE001 - absent/invalid file = no flips
        return {}


def _emit_adopt(env: str, value: str, source: str) -> None:
    key = (env, value, source)
    if key in _ADOPTED:
        return
    _ADOPTED.add(key)
    _count("tune/adoptions_total", source=source.split(":")[0])
    try:
        from ..obs.trace import default_recorder
        default_recorder().instant("tune/adopt", knob=env, value=value,
                                   source=source)
    except Exception:  # noqa: BLE001 - tracing must not break dispatch
        pass


def tuned_info() -> Dict[str, object]:
    """Diagnostics of the last tuned-config load (path, workload,
    per-entry errors) — empty until something resolved."""
    with _lock:
        return dict(_TUNED_INFO)


def knob_value(env_name: str, fallback: str) -> str:
    """Resolve one knob through the documented precedence (module
    docstring). Signature-compatible with the historical
    ``sparse_update.measured_default(knob, fallback)``."""
    global _TUNED, _MEASURED
    env = os.environ.get(env_name)
    if env is not None:
        return env
    with _lock:
        if _TUNED is None:
            _TUNED = _load_tuned_locked()
        tuned = _TUNED
    if env_name in tuned:
        path = _TUNED_INFO.get("path")
        workload = _TUNED_INFO.get("workload")
        _emit_adopt(env_name, tuned[env_name],
                    f"tuned:{workload or path}")
        return tuned[env_name]
    import jax
    if (jax.default_backend() != "tpu"
            and os.environ.get("DET_MEASURED_DEFAULTS_CONSULT") != "1"):
        # CPU test equivalence must not silently change because a TPU
        # bench wrote measured defaults on the same checkout (PR 2 rule)
        return fallback
    with _lock:
        if _MEASURED is None:
            _MEASURED = _load_measured_locked()
        measured = _MEASURED
    if env_name in measured:
        _emit_adopt(env_name, str(measured[env_name]),
                    "measured_defaults")
        return measured[env_name]
    return fallback
