"""Attribution-driven auto-tuning of the performance-knob space.

The system exposes ~15 orthogonal performance knobs (scatter impl,
lookup path, exchange wire, id wire, storage dtypes, hot rows,
lookahead, pipeline depth, publish cadence, admission limits, ...),
per-span device-second attribution (obs/attribution.py) and static cost
models (analysis.programs.expected_collective_bytes,
exchange_padding_report, docs/perf_model.md projections). This package
closes the measure->decide loop (ROADMAP item 5):

  registry  the declarative knob-space registry — each knob's env var,
            legal values, safety class (offline vs runtime-flippable),
            parity class and cost-model hook. THE single source of
            truth the docs table, the scenario lint and the search
            harness all read.
  resolve   the consumption seam: `knob_value(env, fallback)` resolves
            env var > tools/tuned/<workload>.json (explicit opt-in via
            DET_TUNED_WORKLOAD / DET_TUNED_PATH) >
            tools/measured_defaults.json (TPU-backend only) > fallback,
            every tuned/measured adoption leaving a flight-recorder
            event. `ops.sparse_update.measured_default` delegates here.
  search    bench-independent search machinery for `bench.py --mode
            tune`: arm enumeration over the registry, cost-model
            pruning (every pruned arm logged with its rationale — no
            silent caps), and the `tuned-config-v1` config-of-record
            schema + validator.
  runtime   the online half (stretch): `RuntimeTuner` maps SLO
            evaluator findings to bounded adjustments of
            runtime-flippable knobs only, every auto-flip leaving a
            flight-recorder event.
"""

from .registry import (Knob, all_knobs, get_knob, knob_table_markdown,
                       validate_override)
from .resolve import knob_value, reset_cache, tuned_source
from .search import (TUNED_SCHEMA, Arm, enumerate_arms, prune_by_cost,
                     validate_tuned_record)
from .runtime import RuntimeTuner

__all__ = [
    "Knob", "all_knobs", "get_knob", "knob_table_markdown",
    "validate_override", "knob_value", "reset_cache", "tuned_source",
    "TUNED_SCHEMA", "Arm", "enumerate_arms", "prune_by_cost",
    "validate_tuned_record", "RuntimeTuner",
]
