"""Canaried version rollout for the serving fleet (ISSUE 16).

A freshly published version serves fleet-wide only after K canary
replicas vouch for it:

  1. **candidate** — the newest published version above the pinned one
     (skipping versions already marked bad);
  2. **canary** — the first K serving replicas poll up to the candidate
     (`upto=` version ceiling) while the rest of the fleet stays pinned;
  3. **verdict** — a canary passes when it reached the candidate with no
     active degradation AND (when a reference is wired) its
     `TableStore.get_weights()` matches the publisher's bit-exactly
     (``parity_atol=0.0`` f32 by default);
  4. **promote** — all canaries pass: the pin advances, every other
     serving replica polls up to it, and a ``fleet/canary_promote``
     instant lands on the flight recorder next to the version's lineage
     track;
  5. **rollback** — any canary fails: the version is marked bad (never
     retried, never served fleet-wide), the canaries re-anchor on the
     pinned version via `InferenceEngine.reanchor_published`, and a
     ``fleet/canary_rollback`` instant records the incident.

A canary that merely CANNOT REACH the candidate yet (delta chain waiting
on the publisher's next compaction — e.g. after a paused publish) is
*pending*, not bad: the rollout retries on the next tick. Only a canary
that landed degraded or off-parity condemns a version.

The ``fleet.canary_apply`` fault point fires here: a ``bit_flip`` spec
perturbs one element of the canary's freshly-applied tables in memory —
the apply-went-wrong failure class the parity check must catch. The
stream files on disk stay healthy, so the SAME bytes that failed the
canary may later serve fine when a newer version promotes through them.
"""

from typing import Callable, List, Optional, Sequence

import numpy as np

from distributed_embeddings_tpu import faults
from distributed_embeddings_tpu.obs import trace as obs_trace
from distributed_embeddings_tpu.store import scan_published

__all__ = ["CanaryController"]


class CanaryController:
    """Owns the pin, the bad-version set, and the promote/rollback
    ledger. Driven by `FleetRouter.step()`; usable standalone in tests.

    Args:
      publish_dir: the publisher's stream directory.
      canaries: how many serving replicas vouch per version (capped at
        the serving count; default ``DET_FLEET_CANARIES`` env, else 1).
      reference_weights: optional ``f(version) -> list[np.ndarray] |
        None`` returning the publisher's `get_weights()` at `version`
        (None = skip parity for that version). Without it the verdict is
        health-only.
      parity_atol: max |canary - reference| tolerated (default 0.0 —
        bit-exact f32, the acceptance bar).
      registry: optional `obs.MetricRegistry` for the rollout counters
        (``fleet/promotes_total``, ``fleet/rollbacks_total``) and gauges
        (``fleet/pinned_version``, ``fleet/bad_versions``).
    """

    def __init__(self, publish_dir: str, *, canaries: Optional[int] = None,
                 reference_weights: Optional[Callable] = None,
                 parity_atol: float = 0.0, registry=None):
        import os
        if canaries is None:
            canaries = int(os.environ.get("DET_FLEET_CANARIES", 1))
        self.publish_dir = publish_dir
        self.canaries = max(int(canaries), 1)
        self.reference_weights = reference_weights
        self.parity_atol = float(parity_atol)
        from distributed_embeddings_tpu.obs.registry import MetricRegistry
        self._metrics = registry if registry is not None \
            else MetricRegistry()
        self.pinned_version = 0
        self.bad_versions: set = set()
        self.events: List[dict] = []
        self._metrics.gauge("fleet/pinned_version").set(0)

    # ------------------------------------------------------------ internals
    def candidate(self) -> Optional[int]:
        """Newest published version above the pin that is not
        condemned (None = nothing to roll out)."""
        cand = [v for v, _, _ in scan_published(self.publish_dir)
                if v > self.pinned_version and v not in self.bad_versions]
        return max(cand) if cand else None

    def _parity_dev(self, engine, version: int) -> Optional[float]:
        if self.reference_weights is None:
            return None
        ref = self.reference_weights(version)
        if ref is None:
            return None
        dev = 0.0
        for a, b in zip(ref, engine.store.get_weights()):
            a = np.asarray(a, np.float32)
            b = np.asarray(b, np.float32)
            if a.size:
                dev = max(dev, float(np.max(np.abs(a - b))))
        return dev

    def _rollback_one(self, member, backup) -> None:
        """Return one canary to the pinned state. Stream re-anchor when
        a pinned snapshot exists; in-memory backup otherwise (pin 0 =
        nothing ever promoted, so there is nothing published to anchor
        on)."""
        eng = member.engine
        try:
            if self.pinned_version > 0:
                eng.reanchor_published(self.publish_dir,
                                       upto=self.pinned_version)
                return
        except Exception:  # noqa: BLE001 - fall through to the backup
            pass
        params = eng.embedding.set_weights(backup)
        if eng._model is not None:
            params = {**eng.params, "embedding": params}
        eng.set_params(params, refresh=True)

    # ---------------------------------------------------------------- API
    def advance(self, serving: Sequence) -> Optional[dict]:
        """One rollout tick over the serving members (objects exposing
        ``.name`` and ``.engine``, rotation order). Returns None when
        idle, else a dict with ``event`` in {"pending", "promote",
        "rollback"}. Promote/rollback land in `events` and on the
        flight recorder; pending is transient and only returned."""
        members = list(serving)
        if not members:
            return None
        target = self.candidate()
        if target is None:
            return None
        canaries = members[:min(self.canaries, len(members))]
        rest = members[len(canaries):]
        rec = obs_trace.default_recorder()

        results, reached_all = [], True
        for m in canaries:
            backup = [np.asarray(w, np.float32).copy()
                      for w in m.engine.store.get_weights()]
            m.engine.poll_updates(self.publish_dir, upto=target)
            reached = int(m.engine.store.version) >= target
            dev = None
            if reached:
                # the canary-apply fault seam: deterministic in-memory
                # perturbation of the freshly-applied tables (see
                # module docstring) — occurrence counted per canary
                # evaluation that actually reached the candidate
                spec = faults.check("fleet.canary_apply", replica=m.name,
                                    version=target)
                if spec is not None and spec.kind == "bit_flip":
                    w = [np.asarray(t, np.float32).copy()
                         for t in m.engine.store.get_weights()]
                    w[0].flat[0] += 1.0
                    params = m.engine.embedding.set_weights(w)
                    if m.engine._model is not None:
                        params = {**m.engine.params, "embedding": params}
                    m.engine.set_params(params, refresh=True)
                dev = self._parity_dev(m.engine, target)
            degraded = sorted(m.engine.degraded_reasons())
            ok = (reached and not degraded
                  and (dev is None or dev <= self.parity_atol))
            reached_all = reached_all and reached
            results.append({"replica": m.name, "reached": reached,
                            "degraded": degraded, "parity_dev": dev,
                            "ok": ok, "backup": backup})

        if all(r["ok"] for r in results):
            self.pinned_version = target
            for m in rest:
                m.engine.poll_updates(self.publish_dir, upto=target)
            event = {"event": "promote", "version": target,
                     "canaries": [r["replica"] for r in results],
                     "parity_devs": [r["parity_dev"] for r in results]}
            rec.instant("fleet/canary_promote", version=target,
                        canaries=",".join(r["replica"] for r in results))
            self._metrics.counter("fleet/promotes_total").inc()
            self._metrics.gauge("fleet/pinned_version").set(target)
        elif reached_all or any(not r["ok"] and r["reached"]
                                for r in results):
            # at least one canary REACHED the candidate and failed it:
            # condemn the version and pull every canary back to the pin
            self.bad_versions.add(target)
            for m, r in zip(canaries, results):
                self._rollback_one(m, r["backup"])
            event = {"event": "rollback", "version": target,
                     "pinned": self.pinned_version,
                     "canaries": [r["replica"] for r in results],
                     "failed": [r["replica"] for r in results
                                if not r["ok"]],
                     "parity_devs": [r["parity_dev"] for r in results],
                     "degraded": sorted({d for r in results
                                         for d in r["degraded"]})}
            rec.instant("fleet/canary_rollback", version=target,
                        pinned=self.pinned_version,
                        failed=",".join(event["failed"]))
            self._metrics.counter("fleet/rollbacks_total").inc()
            self._metrics.gauge("fleet/bad_versions").set(
                len(self.bad_versions))
        else:
            # no canary reached the candidate (chain waiting on the next
            # compaction): retry next tick, condemn nothing
            return {"event": "pending", "version": target,
                    "reached": [r["replica"] for r in results
                                if r["reached"]]}
        for r in results:
            r.pop("backup", None)
        event["results"] = results
        self.events.append(event)
        return event
