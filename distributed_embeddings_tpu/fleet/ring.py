"""Consistent-hash ring for the serving fleet (ISSUE 16).

Routing must satisfy two properties the `HotRowCache` tier depends on:

  * **stability** — the same request key always lands on the same
    replica (while membership holds), so each replica's cache sees a
    stable key subset and warms for exactly that slice of traffic;
  * **bounded movement** — when a replica joins or leaves, ONLY the keys
    in the affected hash range move (≈ 1/N of traffic for an N-node
    fleet), so one membership change does not cold-start every cache in
    the fleet. A modulo router fails this catastrophically: resizing
    N→N+1 remaps ~N/(N+1) of all keys.

The classic construction: each node is hashed onto a 64-bit ring at
`vnodes` pseudo-random positions (virtual nodes smooth the load split),
and a key routes to the first node position at or clockwise-after its
own hash. Hashing is `blake2b`-based and **process-independent** —
Python's builtin ``hash()`` is salted per process (PYTHONHASHSEED), and
a ring whose assignment changed across restarts would silently void the
cache-affinity story.
"""

import hashlib
from typing import Dict, List, Optional

import numpy as np

__all__ = ["HashRing", "stable_hash64"]


def stable_hash64(key) -> int:
    """Deterministic 64-bit hash, identical across processes and runs:
    ints hash their 8-byte little-endian encoding, everything else its
    UTF-8 ``str()``."""
    if isinstance(key, (bool, float)):
        data = str(key).encode("utf-8")
    elif isinstance(key, (int, np.integer)):
        data = int(key).to_bytes(8, "little", signed=True)
    elif isinstance(key, bytes):
        data = key
    else:
        data = str(key).encode("utf-8")
    return int.from_bytes(
        hashlib.blake2b(data, digest_size=8).digest(), "little")


class HashRing:
    """Vnode consistent-hash ring: ``add``/``remove`` nodes, ``route``
    keys. Pure data structure — no IO, no metrics; the `FleetRouter`
    owns the policy around it."""

    def __init__(self, vnodes: int = 64):
        self.vnodes = max(int(vnodes), 1)
        self._points: Dict[int, str] = {}      # ring position -> node
        self._keys = np.empty(0, np.uint64)    # sorted positions
        self._owners: List[str] = []           # owner per position

    def _rebuild(self) -> None:
        items = sorted(self._points.items())
        self._keys = np.array([h for h, _ in items], np.uint64)
        self._owners = [n for _, n in items]

    def add(self, name: str) -> None:
        """Place `name` at its `vnodes` ring positions (idempotent)."""
        if name in self._owners:
            return
        for i in range(self.vnodes):
            h = stable_hash64(f"{name}#{i}")
            while h in self._points and self._points[h] != name:
                h = (h + 1) % (1 << 64)        # vanishing-odds collision
            self._points[h] = name
        self._rebuild()

    def remove(self, name: str) -> None:
        """Drop every position owned by `name` (idempotent). Keys in its
        ranges fall through to the next clockwise owner — nothing else
        moves (the bounded-movement property)."""
        if name not in self._owners:
            return
        self._points = {h: n for h, n in self._points.items() if n != name}
        self._rebuild()

    def nodes(self) -> List[str]:
        return sorted(set(self._owners))

    def __len__(self) -> int:
        return len(set(self._owners))

    def __contains__(self, name: str) -> bool:
        return name in self._owners

    def route(self, key) -> Optional[str]:
        """The node owning `key`'s ring position (None on an empty
        ring). First position at or after the key hash, wrapping."""
        if not self._owners:
            return None
        h = stable_hash64(key)
        idx = int(np.searchsorted(self._keys, np.uint64(h), side="left"))
        return self._owners[idx % len(self._owners)]

    def assignments(self, keys) -> Dict[object, Optional[str]]:
        """Route a batch of keys at once — the membership-change
        movement audit tests (and capacity sweeps) use this to compare
        whole assignment maps before/after add/remove."""
        return {k: self.route(k) for k in keys}
