"""Admission control for the serving fleet (ISSUE 16).

The serve path NEVER raises for load reasons: `FleetRouter.submit`
returns a typed `RouteResult`, and a shed is a value the caller can
count, retry elsewhere, or degrade on — not an exception unwinding an
RPC handler mid-traffic. The policy sheds *before* p99 explodes: the
signals are the per-replica `MicroBatcher` instruments that already
exist (`queue_depth`, `queued_rows`), read at submit time, so a replica
drowning in queued work stops accepting more instead of serving every
request late.

Shedding (not spilling to a sibling) is deliberate: a spilled request
would land on a replica whose cache never sees that key range — it
would be served, slowly, while polluting the sibling's cache. Capacity
comes from adding replicas (elastic membership), not from breaking key
affinity under pressure.
"""

import os
from typing import Optional

__all__ = ["RouteResult", "AdmissionController"]


class RouteResult:
    """Typed outcome of one `FleetRouter.submit`.

    ``accepted=True``: `replica` took the request, `handle` resolves in
    the next `FleetRouter.flush()`. ``accepted=False``: the request was
    shed — `shed_reason` says why (``queue_depth`` / ``queue_rows`` /
    ``no_replicas`` / ``oversize`` / ``router_error``) and `replica`
    names the overloaded target when one was resolved."""

    __slots__ = ("accepted", "replica", "handle", "shed_reason", "key")

    def __init__(self, accepted: bool, replica: Optional[str] = None,
                 handle: Optional[int] = None,
                 shed_reason: Optional[str] = None, key=None):
        self.accepted = bool(accepted)
        self.replica = replica
        self.handle = handle
        self.shed_reason = shed_reason
        self.key = key

    def __bool__(self) -> bool:
        return self.accepted

    def __repr__(self) -> str:
        if self.accepted:
            return (f"RouteResult(accepted, replica={self.replica!r}, "
                    f"handle={self.handle})")
        return (f"RouteResult(shed, reason={self.shed_reason!r}, "
                f"replica={self.replica!r})")


class AdmissionController:
    """Shed decision over one replica's batcher instruments.

    Args:
      max_queue_depth: shed when the target batcher already holds this
        many queued requests (default: ``DET_FLEET_MAX_QUEUE_DEPTH``
        env, else 64).
      max_queue_rows: optional row-level cap — shed when accepting the
        request would push the batcher's queued true rows past it
        (default: ``DET_FLEET_MAX_QUEUE_ROWS`` env, else unlimited).
    """

    def __init__(self, max_queue_depth: Optional[int] = None,
                 max_queue_rows: Optional[int] = None):
        from distributed_embeddings_tpu.tune import resolve as _tune_resolve
        if max_queue_depth is None:
            max_queue_depth = int(_tune_resolve.knob_value(
                "DET_FLEET_MAX_QUEUE_DEPTH", "64"))
        if max_queue_rows is None:
            raw = _tune_resolve.knob_value("DET_FLEET_MAX_QUEUE_ROWS", "")
            max_queue_rows = int(raw) if raw else None
        self.max_queue_depth = int(max_queue_depth)
        self.max_queue_rows = (None if max_queue_rows is None
                               else int(max_queue_rows))

    def shed_reason(self, batcher, rows: int) -> Optional[str]:
        """None = admit; otherwise the typed shed reason. Reads only
        host-side queue state — never touches the device."""
        if batcher.queue_depth >= self.max_queue_depth:
            return "queue_depth"
        if self.max_queue_rows is not None \
                and batcher.queued_rows + int(rows) > self.max_queue_rows:
            return "queue_rows"
        return None
