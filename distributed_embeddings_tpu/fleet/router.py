"""`FleetRouter`: the serving fleet's traffic front-end (ISSUE 16).

One router owns N replicas (each an `InferenceEngine` + `MicroBatcher`
pair) and composes the tier's four behaviors:

  * **routing** — `submit(batch, key=)` consistent-hashes the request
    key over the serving members (`fleet/ring.py`), so each replica's
    `HotRowCache` sees a stable key subset and hit rate becomes a
    function of fleet size;
  * **admission** — before enqueueing, the target's queue instruments
    are checked (`fleet/admission.py`); overload returns a typed shed
    `RouteResult`, never an exception, and shed/admit counters land on
    the shared registry;
  * **elastic membership** — `add_replica` starts a member in the
    ``joining`` state: it re-anchors on the published stream up to the
    pinned version and enters rotation (the hash ring) only once caught
    up; `remove_replica` drains the member's queue and drops its ring
    positions — bounded key movement by the ring's construction;
  * **canaried rollout** — `step()` drives the `CanaryController`: new
    published versions promote fleet-wide only after the canaries report
    parity, and a degraded canary rolls back to the pinned version.

Thread model: synchronous and single-threaded like `MicroBatcher` — the
caller decides when to `flush()` (latency vs throughput) and when to
`step()` (the control-plane tick). `submit`/`flush`/`step` never raise:
serve-path failures become typed sheds / dropped handles / counted
control errors, because a routing bug must degrade traffic, not unwind
the caller's serving loop.
"""

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from distributed_embeddings_tpu.fleet.admission import (AdmissionController,
                                                        RouteResult)
from distributed_embeddings_tpu.fleet.ring import HashRing
from distributed_embeddings_tpu.fleet.rollout import CanaryController
from distributed_embeddings_tpu.obs import trace as obs_trace
from distributed_embeddings_tpu.serving.batcher import MicroBatcher

__all__ = ["FleetRouter"]


class _Member:
    __slots__ = ("name", "engine", "batcher", "state", "routed")

    def __init__(self, name, engine, batcher):
        self.name = name
        self.engine = engine
        self.batcher = batcher
        self.state = "joining"         # joining -> serving (-> left)
        self.routed = 0


class FleetRouter:
    """Route request batches across an elastic replica fleet.

    Args:
      publish_dir: the training job's publish stream — joiners re-anchor
        from it, the rollout promotes versions out of it.
      registry: shared `obs.MetricRegistry`; every member's engine
        should be built with ``registry=`` this one and a unique
        ``replica=`` name so the per-replica serve families coexist.
      vnodes: ring positions per member (``DET_FLEET_VNODES`` env,
        else 64).
      admission: `AdmissionController` (default: env-tuned defaults).
      canaries / reference_weights / parity_atol: forwarded to
        `CanaryController`.
      max_batch: per-member `MicroBatcher` cap (default: batcher's own).
      key_fn: optional ``f(batch) -> hashable`` extracting the routing
        key; default uses the first id of the first categorical feature.
        Callers with a real session/user key should pass ``key=`` to
        `submit` explicitly — the fallback keeps untyped traffic
        routable, not affine.
    """

    def __init__(self, publish_dir: str, *, registry=None,
                 vnodes: Optional[int] = None, admission=None,
                 canaries: Optional[int] = None, reference_weights=None,
                 parity_atol: float = 0.0,
                 max_batch: Optional[int] = None, key_fn=None):
        import os
        if vnodes is None:
            vnodes = int(os.environ.get("DET_FLEET_VNODES", 64))
        from distributed_embeddings_tpu.obs.registry import MetricRegistry
        self._metrics = registry if registry is not None \
            else MetricRegistry()
        self.publish_dir = publish_dir
        self.ring = HashRing(vnodes)
        self.admission = admission if admission is not None \
            else AdmissionController()
        self.rollout = CanaryController(
            publish_dir, canaries=canaries,
            reference_weights=reference_weights, parity_atol=parity_atol,
            registry=self._metrics)
        self._max_batch = max_batch
        self._key_fn = key_fn
        self._members: Dict[str, _Member] = {}     # insertion-ordered
        self._pending: Dict[int, Tuple[str, int]] = {}  # global -> (m, local)
        self._next_handle = 0
        self.submitted = 0
        self.shed = 0
        self.errors: List[str] = []

    # ------------------------------------------------------------ internals
    def _serving(self) -> List[_Member]:
        return [m for m in self._members.values() if m.state == "serving"]

    def _default_key(self, batch):
        if self._key_fn is not None:
            return self._key_fn(batch)
        first = next(iter(self._members.values()))
        cats = batch if first.engine._model is None else batch[1]
        x = cats[0]
        ids = np.asarray(x[0] if isinstance(x, tuple) else x).reshape(-1)
        return int(ids[0]) if ids.size else 0

    def _request_rows(self, member: _Member, batch) -> int:
        cats = batch if member.engine._model is None else batch[1]
        x = cats[0]
        return int(np.asarray(x[0] if isinstance(x, tuple)
                              else x).shape[0])

    def _shed_result(self, reason: str, replica: Optional[str],
                     key) -> RouteResult:
        self.shed += 1
        self._metrics.counter("fleet/shed_total", reason=reason).inc()
        self._metrics.gauge("fleet/shed_rate").set(
            self.shed / max(self.submitted, 1))
        return RouteResult(False, replica=replica, shed_reason=reason,
                           key=key)

    def _note_error(self, where: str, e: BaseException) -> None:
        self.errors.append(f"{where}: {type(e).__name__}: {e}"[:200])
        self._metrics.counter("fleet/router_errors_total").inc()

    def _try_enter(self, m: _Member) -> bool:
        """joining -> serving once caught up to the pinned version. With
        nothing promoted yet there is nothing to catch up on: the member
        enters with its constructed state."""
        pinned = self.rollout.pinned_version
        if pinned > 0:
            m.engine.poll_updates(self.publish_dir, upto=pinned)
            if int(m.engine.store.version) < pinned \
                    or m.engine.degraded_reasons():
                return False
        m.state = "serving"
        self.ring.add(m.name)
        obs_trace.default_recorder().instant(
            "fleet/replica_enter", replica=m.name,
            version=int(m.engine.store.version), pinned=pinned)
        self._metrics.gauge("fleet/replicas").set(len(self._serving()))
        return True

    # ------------------------------------------------------- membership API
    def add_replica(self, name: str, engine, *,
                    max_batch: Optional[int] = None) -> None:
        """Register a member in the ``joining`` state (control-plane
        call: duplicate names raise). It enters rotation on this call if
        already caught up, else on a later `step()` once its re-anchor
        poll reaches the pinned version."""
        if name in self._members:
            raise ValueError(f"replica {name!r} already in the fleet")
        batcher = MicroBatcher(engine, max_batch or self._max_batch,
                               registry=self._metrics, replica=name)
        m = _Member(name, engine, batcher)
        self._members[name] = m
        obs_trace.default_recorder().instant(
            "fleet/replica_join", replica=name,
            pinned=self.rollout.pinned_version)
        self._try_enter(m)

    def remove_replica(self, name: str) -> Dict[int, Any]:
        """Take a member out of rotation and drain its queue. Returns
        the drained ``{global_handle: outputs}`` (empty when the final
        flush failed — counted, never raised). Its hash ranges fall to
        the clockwise neighbors; every other key keeps its replica."""
        m = self._members.pop(name, None)
        if m is None:
            return {}
        self.ring.remove(name)
        m.state = "left"
        obs_trace.default_recorder().instant("fleet/replica_leave",
                                             replica=name)
        self._metrics.gauge("fleet/replicas").set(len(self._serving()))
        drained: Dict[int, Any] = {}
        try:
            local_results = m.batcher.flush() if m.batcher.queue_depth \
                else {}
        except Exception as e:  # noqa: BLE001 - drain must not unwind
            self._note_error(f"drain:{name}", e)
            local_results = {}
        lmap = {local: g for g, (n, local) in self._pending.items()
                if n == name}
        for local, val in local_results.items():
            g = lmap.get(local)
            if g is not None:
                drained[g] = val
        for g in lmap.values():
            self._pending.pop(g, None)
        return drained

    # ------------------------------------------------------------ serve API
    def submit(self, batch, key=None) -> RouteResult:
        """Route one request batch. Never raises: overload, an empty
        rotation, oversize requests, and router bugs all return typed
        shed results."""
        self.submitted += 1
        self._metrics.counter("fleet/submitted_total").inc()
        try:
            serving = self._serving()
            if not serving:
                return self._shed_result("no_replicas", None, key)
            if key is None:
                key = self._default_key(batch)
            name = self.ring.route(key)
            m = self._members[name]
            rows = self._request_rows(m, batch)
            if rows > m.batcher.max_batch:
                return self._shed_result("oversize", name, key)
            reason = self.admission.shed_reason(m.batcher, rows)
            if reason is not None:
                return self._shed_result(reason, name, key)
            local = m.batcher.submit(batch)
        except Exception as e:  # noqa: BLE001 - typed shed, never raise
            self._note_error("submit", e)
            return self._shed_result("router_error", None, key)
        g = self._next_handle
        self._next_handle += 1
        self._pending[g] = (name, local)
        m.routed += 1
        self._metrics.counter("fleet/admitted_total", replica=name).inc()
        self._metrics.gauge("fleet/shed_rate").set(
            self.shed / max(self.submitted, 1))
        return RouteResult(True, replica=name, handle=g, key=key)

    def flush(self) -> Dict[int, Any]:
        """Flush every member's queue; returns ``{global_handle:
        outputs}``. A member whose flush fails drops its in-flight
        handles (counted in ``fleet/flush_errors_total`` and `errors`)
        — the other members' results still return."""
        out: Dict[int, Any] = {}
        by_member: Dict[str, Dict[int, int]] = {}
        for g, (name, local) in self._pending.items():
            by_member.setdefault(name, {})[local] = g
        for name, m in list(self._members.items()):
            if m.batcher.queue_depth == 0:
                continue
            try:
                local_results = m.batcher.flush()
            except Exception as e:  # noqa: BLE001 - degrade, never raise
                self._note_error(f"flush:{name}", e)
                self._metrics.counter("fleet/flush_errors_total",
                                      replica=name).inc()
                for g in by_member.get(name, {}).values():
                    self._pending.pop(g, None)
                continue
            lmap = by_member.get(name, {})
            for local, val in local_results.items():
                g = lmap.get(local)
                if g is not None:
                    out[g] = val
                    self._pending.pop(g, None)
        return out

    # ---------------------------------------------------- control-plane API
    def step(self) -> dict:
        """One control-plane tick: joiners attempt rotation entry, the
        canary rollout advances, and the bad-version containment check
        runs. Never raises — control-plane failures land in `errors` /
        ``fleet/control_errors_total`` and serving continues pinned."""
        info: dict = {"entered": [], "event": None}
        try:
            for m in list(self._members.values()):
                if m.state == "joining" and self._try_enter(m):
                    info["entered"].append(m.name)
            serving = self._serving()
            info["event"] = self.rollout.advance(serving)
            # containment audit: no member OUTSIDE the canary set may
            # ever sit at a condemned version (the canaries themselves
            # transit through one by design, then roll back)
            k = min(self.rollout.canaries, len(serving))
            for m in serving[k:]:
                if int(m.engine.store.version) in self.rollout.bad_versions:
                    self._metrics.counter(
                        "fleet/bad_version_served_total").inc()
        except Exception as e:  # noqa: BLE001 - control plane degrades
            self._note_error("step", e)
            self._metrics.counter("fleet/control_errors_total").inc()
            info["error"] = self.errors[-1]
        return info

    # ------------------------------------------------------------ stats API
    @property
    def pinned_version(self) -> int:
        return self.rollout.pinned_version

    def stats(self) -> dict:
        """Fleet-level accounting + per-member state (host-side reads
        only)."""
        members = {}
        for name, m in self._members.items():
            members[name] = {
                "state": m.state, "routed": m.routed,
                "queue_depth": m.batcher.queue_depth,
                "version": int(m.engine.store.version),
                "degraded": sorted(m.engine.degraded_reasons()),
            }
        return {
            "submitted": self.submitted, "shed": self.shed,
            "shed_rate": round(self.shed / max(self.submitted, 1), 4),
            "pinned_version": self.rollout.pinned_version,
            "bad_versions": sorted(self.rollout.bad_versions),
            "promotes": sum(1 for e in self.rollout.events
                            if e["event"] == "promote"),
            "rollbacks": sum(1 for e in self.rollout.events
                             if e["event"] == "rollback"),
            "router_errors": len(self.errors),
            "members": members,
        }
