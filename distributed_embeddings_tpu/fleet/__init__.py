"""Serving fleet tier (ISSUE 16): consistent-hash routing, admission
control, elastic membership, and canaried version rollout over the
existing one-replica serving stack (`InferenceEngine` + `MicroBatcher`
+ the publish stream).

The soak's replica "fleet" was N independent engines polled from a
callback; this package is the traffic tier that composes the parts the
ROADMAP's "millions of users" claims need: a `FleetRouter` front-end
(stable key -> replica affinity so HBM caches warm per key subset),
typed load shedding driven by the batcher's queue instruments, replicas
that join/leave at runtime with bounded key movement, and published
versions that serve fleet-wide only after canaries report bit-exact
parity against the publisher — with automatic rollback to the pinned
version when one lands degraded. Driven end-to-end by
``bench.py --mode fleet``; semantics in docs/serving.md "Fleet tier".
"""

from distributed_embeddings_tpu.fleet.admission import (AdmissionController,
                                                        RouteResult)
from distributed_embeddings_tpu.fleet.ring import HashRing, stable_hash64
from distributed_embeddings_tpu.fleet.rollout import CanaryController
from distributed_embeddings_tpu.fleet.router import FleetRouter

__all__ = [
    "AdmissionController",
    "CanaryController",
    "FleetRouter",
    "HashRing",
    "RouteResult",
    "stable_hash64",
]
