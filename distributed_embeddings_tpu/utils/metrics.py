"""Evaluation + serving metrics.

The reference evaluates DLRM with tf.keras.metrics.AUC over allgathered
predictions (reference: examples/dlrm/main.py:223-243). The TPU-native
equivalent is a thresholded streaming AUC whose accumulation is a fixed-size
histogram update — jit-friendly (static shapes, no host sync per batch), with
the final trapezoidal integration on host at epoch end.

`LatencyHistogram` is the serving-side counterpart: a host-side,
geometrically-bucketed latency histogram the micro-batcher uses for
p50/p95/p99 request latency (serving/batcher.py) — O(1) per record, fixed
memory, no per-request list growth on long-lived servers.
"""

from typing import NamedTuple, Tuple

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["StreamingAUC", "auc_exact", "LatencyHistogram"]


class AUCState(NamedTuple):
    tp: jax.Array  # [bins] true positives per score bin
    fp: jax.Array  # [bins] false positives per score bin


class StreamingAUC:
    """Histogram-based ROC AUC (the tf.keras.metrics.AUC approach: bucket
    scores into `bins` thresholds, integrate the ROC curve).

    Usage:
      metric = StreamingAUC(bins=8192)
      state = metric.init()
      state = metric.update(state, labels, scores)   # inside jit if desired
      value = metric.result(state)                    # host-side float
    """

    def __init__(self, bins: int = 8192, from_logits: bool = True):
        self.bins = bins
        self.from_logits = from_logits

    def init(self) -> AUCState:
        z = jnp.zeros((self.bins,), jnp.float32)
        return AUCState(tp=z, fp=z)

    def update(self, state: AUCState, labels: jax.Array,
               scores: jax.Array) -> AUCState:
        labels = labels.reshape(-1).astype(jnp.float32)
        scores = scores.reshape(-1).astype(jnp.float32)
        if self.from_logits:
            scores = jax.nn.sigmoid(scores)
        idx = jnp.clip((scores * self.bins).astype(jnp.int32), 0,
                       self.bins - 1)
        tp = state.tp.at[idx].add(labels)
        fp = state.fp.at[idx].add(1.0 - labels)
        return AUCState(tp=tp, fp=fp)

    def result(self, state: AUCState) -> float:
        tp = np.asarray(state.tp)[::-1]   # descending threshold
        fp = np.asarray(state.fp)[::-1]
        ctp = np.cumsum(tp)
        cfp = np.cumsum(fp)
        pos, neg = ctp[-1], cfp[-1]
        if pos == 0 or neg == 0:
            return 0.0
        tpr = ctp / pos
        fpr = cfp / neg
        tpr = np.concatenate([[0.0], tpr])
        fpr = np.concatenate([[0.0], fpr])
        trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2
        return float(trapezoid(tpr, fpr))


class LatencyHistogram:
    """Geometric-bucket latency histogram with percentile estimates.

    O(1) `record`, fixed memory (`~bins_per_decade * decades` int64 slots),
    so a long-lived server can keep one per metric without unbounded
    per-request lists. Percentiles interpolate within the winning bucket —
    with the default 32 buckets/decade the edge-quantization error is
    < 7.5%, far below the run-to-run variance of real serving latencies.

    Usage:
      h = LatencyHistogram()
      h.record(0.0123)                  # seconds
      h.percentile(99)                  # seconds
      h.summary()                       # {"count", "p50_ms", ...}
    """

    def __init__(self, lo: float = 1e-6, hi: float = 120.0,
                 bins_per_decade: int = 32):
        if not (0 < lo < hi):
            raise ValueError(f"need 0 < lo < hi, got {lo}, {hi}")
        self.lo = float(lo)
        decades = np.log10(hi / lo)
        self.bins = int(np.ceil(decades * bins_per_decade)) + 1
        self._ratio = 10.0 ** (1.0 / bins_per_decade)
        # edges[i] = lo * ratio^i; bucket i holds (edges[i-1], edges[i]]
        self._edges = lo * self._ratio ** np.arange(self.bins)
        self._counts = np.zeros((self.bins + 1,), np.int64)  # +overflow
        self._total = 0.0
        self._max = 0.0

    def record(self, seconds: float) -> None:
        s = max(float(seconds), 0.0)
        idx = int(np.searchsorted(self._edges, s, side="left"))
        self._counts[min(idx, self.bins)] += 1
        self._total += s
        self._max = max(self._max, s)

    def merge(self, other: "LatencyHistogram") -> "LatencyHistogram":
        """Fold another histogram's counts into this one (in place;
        returns self for chaining). Lets per-rep/per-stage histograms
        aggregate into one distribution — e.g. the ingest bench's
        per-stage timings across interleaved repetitions — instead of
        only the last rep surviving. Bucket layouts must match exactly
        (same lo/hi/bins_per_decade): merging differently-edged
        histograms would silently misfile counts."""
        if (self.lo, self.bins, self._ratio) != (other.lo, other.bins,
                                                 other._ratio):
            raise ValueError(
                "cannot merge LatencyHistograms with different bucket "
                f"layouts: (lo={self.lo}, bins={self.bins}, "
                f"ratio={self._ratio}) vs (lo={other.lo}, "
                f"bins={other.bins}, ratio={other._ratio})")
        self._counts += other._counts
        self._total += other._total
        self._max = max(self._max, other._max)
        return self

    @property
    def count(self) -> int:
        return int(self._counts.sum())

    def percentile(self, p: float) -> float:
        """The p-th percentile (0..100) in seconds; 0.0 when empty."""
        n = self.count
        if n == 0:
            return 0.0
        rank = np.ceil(n * min(max(p, 0.0), 100.0) / 100.0)
        cum = np.cumsum(self._counts)
        idx = int(np.searchsorted(cum, max(rank, 1)))
        if idx >= self.bins:
            return self._max
        hi = self._edges[idx]
        lo = self._edges[idx - 1] if idx else 0.0
        # linear interpolation inside the bucket by rank position, capped
        # by the true max so a wide top bucket cannot report p99 > max
        prev = cum[idx - 1] if idx else 0
        frac = (rank - prev) / max(self._counts[idx], 1)
        return float(min(lo + (hi - lo) * frac, self._max))

    def summary(self) -> dict:
        n = self.count
        return {
            "count": n,
            "mean_ms": round(self._total / n * 1e3, 3) if n else 0.0,
            "p50_ms": round(self.percentile(50) * 1e3, 3),
            "p95_ms": round(self.percentile(95) * 1e3, 3),
            "p99_ms": round(self.percentile(99) * 1e3, 3),
            "max_ms": round(self._max * 1e3, 3),
        }


def auc_exact(labels: np.ndarray, scores: np.ndarray) -> float:
    """Exact ROC AUC via the rank-sum (Mann-Whitney U) formulation; host-side
    reference for tests and small validation sets."""
    labels = np.asarray(labels).reshape(-1)
    scores = np.asarray(scores).reshape(-1)
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    sorted_scores = scores[order]
    # average ranks for ties
    n = len(scores)
    ranks_seq = np.arange(1, n + 1, dtype=np.float64)
    uniq, inv, counts = np.unique(sorted_scores, return_inverse=True,
                                  return_counts=True)
    cum = np.cumsum(counts)
    start = cum - counts
    avg = (start + cum + 1) / 2.0
    ranks[order] = avg[inv]
    pos = labels > 0.5
    n_pos = int(pos.sum())
    n_neg = n - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.0
    u = ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0
    return float(u / (n_pos * n_neg))
