"""Evaluation + serving metrics.

The reference evaluates DLRM with tf.keras.metrics.AUC over allgathered
predictions (reference: examples/dlrm/main.py:223-243). The TPU-native
equivalent is a thresholded streaming AUC whose accumulation is a fixed-size
histogram update — jit-friendly (static shapes, no host sync per batch), with
the final trapezoidal integration on host at epoch end.

`LatencyHistogram` is the serving-side counterpart: a host-side,
geometrically-bucketed latency histogram the micro-batcher uses for
p50/p95/p99 request latency (serving/batcher.py) — O(1) per record, fixed
memory, no per-request list growth on long-lived servers. Since ISSUE 11
it LIVES in `obs.registry` (it is the metric registry's histogram type);
this re-export keeps serving/pipeline/bench imports unchanged. New code
should obtain histograms through a `MetricRegistry` — direct construction
outside ``obs/`` is lint-banned (``shadow-metric``).
"""

from typing import NamedTuple, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from distributed_embeddings_tpu.obs.registry import LatencyHistogram

__all__ = ["StreamingAUC", "auc_exact", "LatencyHistogram"]


class AUCState(NamedTuple):
    tp: jax.Array  # [bins] true positives per score bin
    fp: jax.Array  # [bins] false positives per score bin


class StreamingAUC:
    """Histogram-based ROC AUC (the tf.keras.metrics.AUC approach: bucket
    scores into `bins` thresholds, integrate the ROC curve).

    Usage:
      metric = StreamingAUC(bins=8192)
      state = metric.init()
      state = metric.update(state, labels, scores)   # inside jit if desired
      value = metric.result(state)                    # host-side float
    """

    def __init__(self, bins: int = 8192, from_logits: bool = True):
        self.bins = bins
        self.from_logits = from_logits

    def init(self) -> AUCState:
        z = jnp.zeros((self.bins,), jnp.float32)
        return AUCState(tp=z, fp=z)

    def update(self, state: AUCState, labels: jax.Array,
               scores: jax.Array) -> AUCState:
        labels = labels.reshape(-1).astype(jnp.float32)
        scores = scores.reshape(-1).astype(jnp.float32)
        if self.from_logits:
            scores = jax.nn.sigmoid(scores)
        idx = jnp.clip((scores * self.bins).astype(jnp.int32), 0,
                       self.bins - 1)
        tp = state.tp.at[idx].add(labels)
        fp = state.fp.at[idx].add(1.0 - labels)
        return AUCState(tp=tp, fp=fp)

    def result(self, state: AUCState) -> float:
        tp = np.asarray(state.tp)[::-1]   # descending threshold
        fp = np.asarray(state.fp)[::-1]
        ctp = np.cumsum(tp)
        cfp = np.cumsum(fp)
        pos, neg = ctp[-1], cfp[-1]
        if pos == 0 or neg == 0:
            return 0.0
        tpr = ctp / pos
        fpr = cfp / neg
        tpr = np.concatenate([[0.0], tpr])
        fpr = np.concatenate([[0.0], fpr])
        trapezoid = getattr(np, "trapezoid", None) or np.trapz  # numpy<2
        return float(trapezoid(tpr, fpr))


def auc_exact(labels: np.ndarray, scores: np.ndarray) -> float:
    """Exact ROC AUC via the rank-sum (Mann-Whitney U) formulation; host-side
    reference for tests and small validation sets."""
    labels = np.asarray(labels).reshape(-1)
    scores = np.asarray(scores).reshape(-1)
    order = np.argsort(scores, kind="mergesort")
    ranks = np.empty_like(order, dtype=np.float64)
    sorted_scores = scores[order]
    # average ranks for ties
    n = len(scores)
    ranks_seq = np.arange(1, n + 1, dtype=np.float64)
    uniq, inv, counts = np.unique(sorted_scores, return_inverse=True,
                                  return_counts=True)
    cum = np.cumsum(counts)
    start = cum - counts
    avg = (start + cum + 1) / 2.0
    ranks[order] = avg[inv]
    pos = labels > 0.5
    n_pos = int(pos.sum())
    n_neg = n - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.0
    u = ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0
    return float(u / (n_pos * n_neg))
