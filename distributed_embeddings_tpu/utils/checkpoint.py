"""Checkpoint / resume.

The reference has no checkpoint *format* — its mechanism is the
get_weights()/set_weights() global resharding round-trip over collectives
(reference: dist_model_parallel.py:971-1162) plus example-level np.savez
(examples/dlrm/main.py:246-248). The TPU-native design keeps both layers:

  * ``save_checkpoint``/``restore_checkpoint`` — Orbax-backed sharded
    checkpoint of the *placed* params/opt_state pytree. Each host writes its
    own shards (no gather), restore honors the plan's NamedShardings. This is
    the fast path for resume-on-same-topology.
  * ``save_global_weights``/``load_global_weights`` — the reference-parity
    portable format: one array per original table in original order
    (np.savez or a directory of .npy), produced by
    ``DistributedEmbedding.get_weights`` and consumed by ``set_weights``
    (which accepts mmap'd file paths for larger-than-memory loads,
    reference :911-950). Survives topology changes.

Hot-row replication (ISSUE 4): layers built with ``hot_rows=`` carry a
replicated hot shard in ``params["hot"]`` that is AUTHORITATIVE for its
resident rows (the canonical tables stop receiving their gradients).
Both checkpoint layers stay correct:

  * the Orbax path saves/restores ``params["hot"]`` (membership + rows)
    as ordinary pytree leaves, so a same-topology resume continues with
    the hot set intact;
  * the portable path is already merged — ``get_weights`` overlays the
    resident hot rows onto the canonical tables — and ``set_weights``
    restarts with an EMPTY hot set (re-admit via
    ``sync_hot_rows(admit=True)`` after loading).

To hand raw ``params["tp"]`` arrays to anything else (serving handoff,
external dumps), run ``DistributedEmbedding.sync_hot_rows`` first — that
is the explicit consistency step that writes hot rows (and their
optimizer-state rows) back into the canonical tables.
"""

import json
import os
import warnings
import zlib
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax

from distributed_embeddings_tpu import faults

__all__ = [
    "save_checkpoint",
    "restore_checkpoint",
    "latest_step",
    "save_global_weights",
    "load_global_weights",
    "save_row_delta",
    "load_row_delta",
    "load_row_delta_meta",
    "StreamIntegrityError",
    "verify_stream_payload",
    "legacy_load_count",
    "publish_atomic",
    "sweep_orphan_tmp",
    "STREAM_CONTAINER_VERSION",
    "STREAM_PAYLOAD_DTYPES",
]

# ---------------------------------------------------------------- container
# payload dtypes the stream container can declare (ISSUE 15) — mirrors
# ops/wire.STORE_DTYPES without importing jax at checkpoint-import time;
# tests pin the two registries equal so they cannot drift
STREAM_PAYLOAD_DTYPES = ("f32", "int8", "fp8")


def _check_payload_dtype(meta: dict, path: str) -> None:
    """Refuse a payload dtype this consumer does not support — a CONFIG
    error (ValueError), never `StreamIntegrityError`: the file is
    healthy, the fleet is mismatched (e.g. an int4 publisher ahead of
    this build, or an fp8 stream on a backend without float8). Damage
    classification (quarantine) must not eat it."""
    dtype = meta.get("dtype", "f32")
    if dtype not in STREAM_PAYLOAD_DTYPES:
        raise ValueError(
            f"{path}: stream payload dtype {dtype!r} is not supported by "
            f"this consumer (supported: {STREAM_PAYLOAD_DTYPES}); upgrade "
            "the consumer or republish at a supported dtype")
    if dtype == "fp8":
        from distributed_embeddings_tpu.ops.wire import fp8_supported
        if not fp8_supported():
            raise ValueError(
                f"{path}: stream payload is fp8 but this backend ships "
                "no float8_e4m3fn — republish at int8/f32 or upgrade "
                "the consumer's toolchain")


# Stream-file container version (ISSUE 13). v2 adds integrity checksums:
# a per-array crc32 table plus a crc over the canonicalized metadata
# header itself, both verified on load. v1 (checksum-less) files still
# load — with one loud process-wide warning and a counter — so streams
# published by older builds survive a rolling upgrade.
STREAM_CONTAINER_VERSION = 2


class StreamIntegrityError(ValueError):
    """A stream file's payload or metadata header fails its checksum —
    the file is corrupt (torn write, bit rot, truncation that the zip
    layer happened not to catch) and must be quarantined, never
    applied."""


_legacy_loads = 0
_legacy_warned = False


def legacy_load_count() -> int:
    """Process-wide count of checksum-less (container v1) stream files
    loaded — the rolling-upgrade signal a fleet watches to know when
    every publisher writes v2 and legacy tolerance can be dropped."""
    return _legacy_loads


def _note_legacy(path: str) -> None:
    global _legacy_loads, _legacy_warned
    _legacy_loads += 1
    if not _legacy_warned:
        _legacy_warned = True
        warnings.warn(
            f"{path}: checksum-less legacy stream file (container v1) — "
            "loaded WITHOUT integrity verification. One warning per "
            "process; count via checkpoint.legacy_load_count().",
            RuntimeWarning, stacklevel=3)


def _array_crc(arr) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def _header_crc(meta: dict) -> int:
    clean = {k: meta[k] for k in meta if k != "header_crc"}
    return zlib.crc32(
        json.dumps(clean, sort_keys=True).encode()) & 0xFFFFFFFF


def verify_stream_payload(meta: dict, arrays: Dict[str, np.ndarray],
                          path: str = "<stream>") -> bool:
    """Verify a loaded stream file against its embedded checksums.
    Returns True when verified, False for legacy (v1) files (counted +
    warned once); raises `StreamIntegrityError` on any mismatch."""
    if "crc" not in meta:
        _note_legacy(path)
        return False
    if "header_crc" in meta and _header_crc(meta) != int(meta["header_crc"]):
        raise StreamIntegrityError(
            f"{path}: metadata header checksum mismatch")
    crc = meta["crc"]
    bad = [n for n in arrays
           if n not in crc or _array_crc(arrays[n]) != int(crc[n])]
    missing = [n for n in crc if n not in arrays]
    if bad or missing:
        raise StreamIntegrityError(
            f"{path}: payload checksum failure "
            f"(mismatched={bad}, missing={missing})")
    return True


# ------------------------------------------------------------- durability
def _fsync_fd_of(path: str) -> None:
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def publish_atomic(tmp: str, final: str) -> str:
    """Durable atomic publication: fsync the written tmp file BEFORE the
    rename (so the rename can never point at data the kernel has not
    persisted) and fsync the directory AFTER (so the new name itself
    survives power loss — `os.replace` is atomic against concurrent
    readers but says nothing about durability). Directory fsync is
    best-effort: some filesystems refuse it, and rename atomicity holds
    regardless."""
    _fsync_fd_of(tmp)
    os.replace(tmp, final)
    try:
        _fsync_fd_of(os.path.dirname(os.path.abspath(final)) or ".")
    except OSError:
        pass
    return final


def sweep_orphan_tmp(directory: str) -> List[str]:
    """Remove orphaned ``*.tmp*`` files a crashed publisher left behind
    (write-then-rename means a tmp name on disk is by definition dead
    state — no reader ever matches it, it only leaks bytes). Returns the
    removed paths. Publishers call this once at startup; the directory
    is single-publisher by contract (docs/serving.md)."""
    removed: List[str] = []
    if not os.path.isdir(directory):
        return removed
    for name in sorted(os.listdir(directory)):
        if ".tmp" in name:
            path = os.path.join(directory, name)
            try:
                os.remove(path)
                removed.append(path)
            except OSError:
                continue
    return removed


def _checkpointer():
    import orbax.checkpoint as ocp
    return ocp.StandardCheckpointer()


def _step_dir(path: str, step: Optional[int]) -> str:
    return os.path.join(path, f"step_{step}") if step is not None else path


def save_checkpoint(path: str, state: Any, step: Optional[int] = None,
                    force: bool = False) -> str:
    """Save a (possibly sharded) pytree checkpoint with Orbax.

    Args:
      path: checkpoint root directory.
      state: pytree of jax.Arrays (params / {'params':..., 'opt_state':...}).
      step: optional step number -> saved under path/step_{step}.
      force: overwrite an existing checkpoint at the target (Orbax's safer
        default is to refuse; pass True to opt into clobbering).
    Returns the directory written.
    """
    target = os.path.abspath(_step_dir(path, step))
    ckptr = _checkpointer()
    ckptr.save(target, state, force=force)
    ckptr.wait_until_finished()
    return target


def restore_checkpoint(path: str, template: Any, step: Optional[int] = None,
                       shardings: Any = None) -> Any:
    """Restore a pytree checkpoint.

    Args:
      template: pytree with the target structure/shapes/dtypes (e.g. the
        output of model.init, or jax.eval_shape thereof).
      shardings: optional matching pytree of NamedShardings — restored
        arrays are placed accordingly (single-controller or multihost).
    """
    import orbax.checkpoint as ocp
    target = os.path.abspath(_step_dir(path, step))
    ckptr = _checkpointer()

    def abstractify(x, s=None):
        x = jax.eval_shape(lambda: x) if not hasattr(x, "shape") else x
        return jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=s)

    if shardings is not None:
        abstract = jax.tree.map(abstractify, template, shardings)
    else:
        abstract = jax.tree.map(abstractify, template)
    return ckptr.restore(target, abstract)


def checkpoint_keys(path: str,
                    step: Optional[int] = None) -> Optional[List[str]]:
    """Top-level keys of a saved checkpoint tree, from Orbax metadata
    (no array reads). Lets callers detect a checkpoint's format — e.g. a
    params-only save vs {'params', 'opt_state'} — instead of guessing from
    restore failures. Returns None when the metadata cannot be read
    (callers must NOT treat that as any particular format)."""
    target = os.path.abspath(_step_dir(path, step))
    try:
        meta = _checkpointer().metadata(target)
    except Exception:  # noqa: BLE001 - metadata layout varies across orbax
        return None
    item = getattr(meta, "item_metadata", meta)
    tree = getattr(item, "tree", None)
    if not isinstance(tree, dict):
        # older orbax returns the metadata tree as the bare mapping
        tree = item if isinstance(item, dict) else None
    if not isinstance(tree, dict):
        return None
    return sorted(tree)


def latest_step(path: str) -> Optional[int]:
    """Largest step_{N} subdirectory under path, or None."""
    if not os.path.isdir(path):
        return None
    steps = []
    for name in os.listdir(path):
        if name.startswith("step_"):
            try:
                steps.append(int(name[5:]))
            except ValueError:
                continue
    return max(steps) if steps else None


def save_global_weights(path: str, weights: Sequence[np.ndarray],
                        npz: bool = True) -> str:
    """Reference-parity portable embedding dump (dlrm example :246-248).

    Args:
      path: .npz file path (npz=True) or directory for per-table .npy files.
      weights: output of DistributedEmbedding.get_weights — one global
        [vocab, width] array per table, original order.
    """
    if npz:
        np.savez(path, *[np.asarray(w) for w in weights])
        return path if path.endswith(".npz") else path + ".npz"
    os.makedirs(path, exist_ok=True)
    for i, w in enumerate(weights):
        np.save(os.path.join(path, f"table_{i}.npy"), np.asarray(w))
    return path


def save_row_delta(path: str, meta: dict, arrays: Dict[str, np.ndarray]
                   ) -> str:
    """One weight-streaming file (ISSUE 6): named numpy arrays plus a
    JSON metadata header, in one uncompressed .npz (uncompressed so the
    on-disk byte count IS the wire-byte accounting the delta-vs-full
    model is built on, and loads are mmap-friendly).

    Two kinds share the container (see store/table_store.py):
      * kind='delta'    — per touched tp bucket / row table a
        ``{kind}{idx}_keys`` int64 array (dedup'd flat row keys) and a
        ``{kind}{idx}_rows`` f32 [n, width] payload of MERGED row
        values, plus each dp table whole (``dp{j}_full``);
      * kind='snapshot' — every table whole (``table{i}``), the
        compaction/resync anchor.
    `meta` must carry {"version", "base_version", "kind",
    "published_at", "sig"} — `version` is the publisher's monotonic
    store version, `base_version` the previous published version a
    delta chains from (None for snapshots/first publish), `sig` the
    per-table (input_dim, output_dim) list consumers verify.

    Container v2 (ISSUE 13): the written header additionally carries
    ``container`` (format version), ``crc`` (per-array crc32 over raw
    bytes) and ``header_crc`` (crc32 of the canonicalized header minus
    itself); `load_row_delta` verifies all three. The zip layer's own
    per-member CRC catches most in-file damage at read time — this
    layer exists for what it cannot: header/payload cross-consistency,
    damage applied after extraction, and a versioned, self-describing
    on-disk contract.

    Payload dtype (ISSUE 15): the header's ``dtype`` field declares how
    row payloads are stored — 'f32' (stamped here when the caller set
    none, so every file is self-describing), or 'int8'/'fp8' (each
    ``*_rows``/``table{i}`` array quantized with a ``*_scale`` f32
    sibling; dp tables stay f32). Consumers REFUSE a dtype they cannot
    decode at load time — loudly, as the config error it is
    (`StreamIntegrityError` stays reserved for damage)."""
    if not path.endswith(".npz"):
        path = path + ".npz"
    meta = dict(meta)
    meta.setdefault("dtype", "f32")
    if meta["dtype"] not in STREAM_PAYLOAD_DTYPES:
        raise ValueError(
            f"save_row_delta: payload dtype {meta['dtype']!r} is not a "
            f"stream container dtype (expected one of "
            f"{STREAM_PAYLOAD_DTYPES})")
    meta["container"] = STREAM_CONTAINER_VERSION
    meta["crc"] = {name: _array_crc(arr) for name, arr in arrays.items()}
    meta["header_crc"] = _header_crc(meta)
    np.savez(path, __meta__=np.asarray(json.dumps(meta)), **arrays)
    return path


def load_row_delta(path: str, verify: bool = True
                   ) -> Tuple[dict, Dict[str, np.ndarray]]:
    """Read a weight-streaming file: (meta dict, {name: array}).

    ``verify=True`` (default) checks the container-v2 checksums —
    header crc and every array's crc32 — raising `StreamIntegrityError`
    on mismatch (checksum-less legacy files load with a one-time
    warning + `legacy_load_count`). Note verification materializes
    every member; pass verify=False only for trusted local tooling.
    Any parse-level damage (bad zip structure, member CRC failure,
    torn/truncated payload, unparseable header) re-raises as
    `StreamIntegrityError` — the ONE type consumers classify as
    corrupt, so errors raised by post-load logic (shape-signature
    mismatch, guards) keep propagating as the config/programming
    errors they are. `OSError` passes through untouched (the
    transient class consumers retry).

    The ``store.load`` fault point wraps this read (ISSUE 13)."""
    faults.check_raise("store.load", path=path)
    try:
        data = np.load(path, allow_pickle=False)
        meta = json.loads(str(data["__meta__"]))
        # materializing every member here surfaces lazy zip CRC
        # failures inside this classification boundary
        arrays = {k: data[k] for k in data.files if k != "__meta__"}
    except (OSError, StreamIntegrityError):
        raise
    except Exception as e:  # noqa: BLE001 - parse damage = corrupt file
        raise StreamIntegrityError(
            f"{path}: unreadable stream container "
            f"({type(e).__name__}: {e})") from e
    # dtype refusal OUTSIDE the damage classification (ISSUE 15): an
    # unsupported payload dtype is a config error and must propagate as
    # ValueError, never quarantine a healthy stream
    _check_payload_dtype(meta, path)
    if verify:
        verify_stream_payload(meta, arrays, path=path)
    return meta, arrays


def load_row_delta_meta(path: str, verify: bool = True) -> dict:
    """Read ONLY the metadata header of a weight-streaming file — npz
    members load lazily, so a consumer's chain check (which may scan many
    candidate deltas per poll) never materializes row payloads.
    ``verify=True`` checks the header's own crc (not the arrays').
    Parse-level damage re-raises as `StreamIntegrityError` exactly
    like `load_row_delta` (see there)."""
    faults.check_raise("store.load", path=path)
    try:
        data = np.load(path, allow_pickle=False)
        meta = json.loads(str(data["__meta__"]))
    except (OSError, StreamIntegrityError):
        raise
    except Exception as e:  # noqa: BLE001 - parse damage = corrupt file
        raise StreamIntegrityError(
            f"{path}: unreadable stream header "
            f"({type(e).__name__}: {e})") from e
    if verify and "header_crc" in meta \
            and _header_crc(meta) != int(meta["header_crc"]):
        raise StreamIntegrityError(
            f"{path}: metadata header checksum mismatch")
    _check_payload_dtype(meta, path)
    return meta


def load_global_weights(path: str, mmap: bool = True) -> List[np.ndarray]:
    """Load a global weights dump. Directory form returns mmap'd arrays /
    file paths usable directly by set_weights (which np.loads with
    mmap_mode='r', reference :911-950) for larger-than-memory tables."""
    mode = "r" if mmap else None
    if os.path.isdir(path):
        files = sorted((f for f in os.listdir(path)
                        if f.startswith("table_") and f.endswith(".npy")),
                       key=lambda f: int(f[6:-4]))
        return [np.load(os.path.join(path, f), mmap_mode=mode) for f in files]
    data = np.load(path)
    return [data[k] for k in sorted(data.files,
                                    key=lambda k: int(k.split("_")[1]))]
