"""Initializer registry.

Plays the role of keras.initializers in the reference's config IR
(reference embedding.py:96, dist_model_parallel.py:686-687): initializers are
named specs (or callables) carried inside TableConfig so the planner can
re-instantiate sliced/concatenated tables deterministically.

An initializer is a callable ``(key, shape, dtype) -> jax.Array``.
"""

import math
from typing import Callable, Sequence, Union

import jax
import jax.numpy as jnp

InitializerSpec = Union[str, dict, Callable]


def _uniform(scale: float):
    def init(key, shape, dtype=jnp.float32):
        return jax.random.uniform(key, shape, dtype, minval=-scale, maxval=scale)
    return init


def _glorot_uniform(key, shape, dtype=jnp.float32):
    fan_in, fan_out = shape[0], shape[1]
    limit = math.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, minval=-limit, maxval=limit)


def _zeros(key, shape, dtype=jnp.float32):
    del key
    return jnp.zeros(shape, dtype)


def _ones(key, shape, dtype=jnp.float32):
    del key
    return jnp.ones(shape, dtype)


def _normal(key, shape, dtype=jnp.float32):
    return jax.random.normal(key, shape, dtype) * 0.05


_REGISTRY = {
    # keras 'uniform'/'random_uniform' default is +-0.05
    "uniform": _uniform(0.05),
    "random_uniform": _uniform(0.05),
    "glorot_uniform": _glorot_uniform,
    "zeros": _zeros,
    "ones": _ones,
    "normal": _normal,
    "random_normal": _normal,
}


def _from_keras_config(class_name: str, config: dict) -> Callable:
    """Keras-serialized initializer dicts ({'class_name', 'config'}) — the
    form keras `get_config()` emits and the reference's planner IR carries
    through slicing/concat (reference dist_model_parallel.py:363-366)."""
    name = class_name.lower()
    if name in ("randomuniform", "random_uniform", "uniform"):
        lo = config.get("minval", -0.05)
        hi = config.get("maxval", 0.05)

        def init(key, shape, dtype=jnp.float32):
            return jax.random.uniform(key, shape, dtype, minval=lo, maxval=hi)
        return init
    if name in ("randomnormal", "random_normal", "truncatednormal",
                "truncated_normal", "normal"):
        mean = config.get("mean", 0.0)
        stddev = config.get("stddev", 0.05)

        def init(key, shape, dtype=jnp.float32):
            draw = (jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype)
                    if "truncated" in name
                    else jax.random.normal(key, shape, dtype))
            return draw * stddev + mean
        return init
    if name in ("zeros", "ones", "glorot_uniform", "glorotuniform"):
        return _REGISTRY["glorot_uniform" if "glorot" in name else name]
    if name == "constant":
        value = config.get("value", 0.0)

        def init(key, shape, dtype=jnp.float32):
            del key
            return jnp.full(shape, value, dtype)
        return init
    raise ValueError(f"Unknown keras initializer class '{class_name}'")


def get_initializer(spec: InitializerSpec) -> Callable:
    """Resolve an initializer spec: a callable, a registry name, or a
    keras-serialized {'class_name', 'config'} dict."""
    if callable(spec):
        return spec
    if isinstance(spec, str):
        if spec not in _REGISTRY:
            raise ValueError(f"Unknown initializer '{spec}'")
        return _REGISTRY[spec]
    if isinstance(spec, dict) and "class_name" in spec:
        return _from_keras_config(spec["class_name"], spec.get("config") or {})
    raise TypeError(f"Initializer spec must be str, keras config dict or "
                    f"callable, got {type(spec)}")


class ConcatInitializer:
    """Initialize a row-concatenated (fused) table as if each sub-table had
    been initialized independently — preserves shape-dependent behavior
    (reference ConcatInitializer, dist_model_parallel.py:29-40)."""

    def __init__(self, initializer: InitializerSpec, sizes: Sequence[int]):
        self._initializer = get_initializer(initializer)
        self.sizes = list(sizes)

    def __call__(self, key, shape, dtype=jnp.float32):
        keys = jax.random.split(key, len(self.sizes))
        parts = [
            self._initializer(k, (size, shape[1]), dtype)
            for k, size in zip(keys, self.sizes)
        ]
        return jnp.concatenate(parts, axis=0)
