"""Profiling and timing utilities.

The reference has no tracing subsystem — performance work is wall timing in
example scripts with a device-sync-by-print idiom (reference:
examples/benchmarks/synthetic_models/main.py:140-158). On TPU, first-class
tools exist; this module packages the two workflows:

  * ``benchmark(fn, *args)`` — compile-excluded, device-synced step timing
    (block_until_ready, not print) with mean/p50/min.
  * ``trace(logdir)`` — context manager around jax.profiler producing an
    XPlane trace viewable in TensorBoard/Perfetto (op-level HLO timing,
    HBM traffic, ICI collectives).
"""

import contextlib
import statistics
import time
from typing import Callable, NamedTuple, Optional, Sequence

import jax

__all__ = ["BenchResult", "benchmark", "benchmark_batches", "trace",
           "annotate", "fetch_sync", "hlo_op_counts",
           "hlo_collective_bytes", "hlo_collective_overlap"]


def hlo_op_counts(lowered, ops: Sequence[str] = ("sort", "scatter", "gather",
                                                 "all_to_all")) -> dict:
    """Count StableHLO op mentions in a lowered (not yet compiled) jax
    program — the static twin of a profiler trace: op COUNTS are decided
    at trace time, so regressions like "the train step re-sorts the same
    ids three times" (docs/perf_model.md 'Sort folding') are catchable on
    any backend, hardware or not.

    Ported onto the typed IR (`analysis.ir.op_counts`, ISSUE 10) —
    behavior-identical to the regex era, asserted on recorded fixtures:
    counts are per TEXTUAL mention as whole words (``sort`` counts
    ``stablehlo.sort`` but not ``sort_key``; attribute-embedded
    references like ``#stablehlo.gather<...>`` count too), stable for
    equality/upper-bound assertions, not a dynamic execution count.

    Args:
      lowered: a ``jax.jit(f).lower(...)`` result, its ``.as_text()``
        string (StableHLO MLIR), or a pre-parsed ``analysis.ir.Module``.
      ops: StableHLO op mnemonics.

    Returns {op: count}.
    """
    from distributed_embeddings_tpu.analysis import ir
    return ir.op_counts(_hlo_text(lowered), ops)


def _hlo_text(lowered):
    from distributed_embeddings_tpu.analysis import ir
    if isinstance(lowered, (str, ir.Module)):
        return lowered
    return lowered.as_text()


_COLLECTIVES = ("ragged_all_to_all", "all_to_all", "all_gather",
                "reduce_scatter", "collective_permute")


def hlo_collective_bytes(lowered, collectives=_COLLECTIVES) -> dict:
    """Sum the payload (first-operand) bytes of each collective op in a
    lowered program, split by element dtype — the byte-level twin of
    `hlo_op_counts` and the static audit behind the wire-compression
    claim (ISSUE 5, docs/perf_model.md "Wire compression"). Ported onto
    the typed IR (`analysis.ir.collective_bytes`, ISSUE 10).

    Shapes inside shard_map bodies are per-device — ratios between two
    lowerings of the same program are what the audit asserts;
    `analysis.programs.expected_collective_bytes` is the exact
    model-side twin when fleet accounting is needed.

    Returns {op: {dtype: bytes}, "total": {dtype: bytes},
    "float_bytes": int, "int_bytes": int}.
    """
    from distributed_embeddings_tpu.analysis import ir
    return ir.collective_bytes(_hlo_text(lowered), collectives)


def hlo_collective_overlap(lowered, collectives=_COLLECTIVES,
                           compute_ops=("dot_general",
                                        "convolution")) -> dict:
    """Classify every collective in a lowered program by its dependency
    relation to the module's dense compute — the static overlap audit
    behind the lookahead pipeline (ISSUE 9, docs/perf_model.md
    "Lookahead prefetch"). Ported onto the typed IR
    (`analysis.ir.collective_overlap`, ISSUE 10), which owns the long
    method docs: call-site granularity over the interprocedural
    shmap_body call graph, conservative region folding, two-direction
    taint.

    Returns {"collectives_total", "overlap_candidates",
    "serialized_collectives", "candidates_by_op", "compute_sites"}.
    """
    from distributed_embeddings_tpu.analysis import ir
    return ir.collective_overlap(_hlo_text(lowered), collectives,
                                 compute_ops)


def fetch_sync(out) -> float:
    """Drain the device queue by FETCHING a value derived from ``out``.

    ``jax.block_until_ready`` is not a reliable sync on every backend: on the
    experimental remote-attached 'axon' TPU platform it was observed (round 3,
    2026-07-31) to return before device work finished, yielding physically
    impossible timings — e.g. a 2.9M-key sort "measured" at 15us and a train
    step 63x FASTER than the chip's HBM roofline. A host fetch of a scalar
    reduced from the outputs cannot complete before the data exists, so a
    fetch is the sync of record for all timing in this repo.

    Cost per leaf is one element's slice + host fetch — NOT a full-leaf
    reduction (an astype/sum would materialize an f32 copy of every leaf:
    for a 4 GiB bf16 table that is an 8 GiB temp inside the timed region).
    A one-element slice carries the same guarantee: it cannot be produced
    before the leaf's buffer exists. Returns the summed scalar so callers
    can sanity-check it (note: only element [0...] of each leaf is
    observed — use a full device-side reduction if you need finiteness of
    the whole output).
    """
    import jax.numpy as jnp
    total = 0.0
    fetched = False
    for leaf in jax.tree.leaves(out):
        if not hasattr(leaf, "dtype") or leaf.size == 0:
            continue
        first = leaf.reshape(-1)[0] if leaf.ndim else leaf
        total += float(first.astype(jnp.float32))
        fetched = True
    if not fetched:
        # no fetchable array leaf (empty/none): fall back to
        # block_until_ready — weaker on axon, but better than silently
        # timing only dispatch (ADVICE r3)
        jax.block_until_ready(out)
    return total


class BenchResult(NamedTuple):
    mean_s: float
    p50_s: float
    min_s: float
    iters: int
    compile_s: float

    @property
    def mean_ms(self) -> float:
        return self.mean_s * 1e3

    def __str__(self):
        return (f"mean={self.mean_s * 1e3:.3f}ms p50={self.p50_s * 1e3:.3f}ms "
                f"min={self.min_s * 1e3:.3f}ms (compile {self.compile_s:.1f}s, "
                f"{self.iters} iters)")


def benchmark(fn: Callable, *args, iters: int = 20, warmup: int = 2,
              **kwargs) -> BenchResult:
    """Time `fn(*args)` with device sync per iteration.

    The first call (compile) is timed separately; `warmup` additional calls
    run before measurement to settle caches/autotuning.
    """
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    fetch_sync(out)
    compile_s = time.perf_counter() - t0
    for _ in range(warmup):
        out = fn(*args, **kwargs)
        # sync EVERY call: XLA:CPU's in-process collectives deadlock when
        # several collective-bearing executions are queued concurrently
        # (rendezvous termination after 40s); on TPU this just serializes
        # warmup, which is fine. fetch_sync, not block_until_ready: the
        # latter lies on the axon platform (see its docstring)
        fetch_sync(out)

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        fetch_sync(out)
        times.append(time.perf_counter() - t0)
    return BenchResult(mean_s=statistics.mean(times),
                       p50_s=statistics.median(times),
                       min_s=min(times), iters=iters, compile_s=compile_s)


def benchmark_batches(fn: Callable, batches: Sequence, iters: int = 20,
                      warmup: int = 2) -> BenchResult:
    """Like `benchmark` but rotates through pre-built batches (tuples of
    args) so input-dependent effects (e.g. power-law gather locality) are
    averaged. fn is called as fn(*batches[i % len(batches)])."""
    t0 = time.perf_counter()
    out = fn(*batches[0])
    fetch_sync(out)
    compile_s = time.perf_counter() - t0
    for i in range(warmup):
        out = fn(*batches[i % len(batches)])
        fetch_sync(out)   # see benchmark(): CPU collective safety + axon sync

    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        out = fn(*batches[i % len(batches)])
        fetch_sync(out)
        times.append(time.perf_counter() - t0)
    return BenchResult(mean_s=statistics.mean(times),
                       p50_s=statistics.median(times),
                       min_s=min(times), iters=iters, compile_s=compile_s)


@contextlib.contextmanager
def trace(logdir: str, host_tracer_level: int = 2,
          python_tracer_level: Optional[int] = None):
    """Capture a jax.profiler trace for everything inside the block:

        with profiling.trace("/tmp/trace"):
            step(params, batch)
            jax.block_until_ready(...)

    View with TensorBoard's profile plugin or ui.perfetto.dev.

    Args:
      host_tracer_level: TraceMe verbosity (1 critical, 2 info — the
        default, 3 verbose).
      python_tracer_level: 0 disables the per-python-call tracer. THE
        knob for long captures (ISSUE 14): the python tracer emits one
        event per interpreted call, and a multi-second bench run
        overflows the profiler's host event buffer with them — observed
        to silently DROP the later `TraceAnnotation` events the
        attribution parser needs (`obs.attribution`; the kernels bench's
        late arms lost their span windows). None (default) keeps the
        profiler's stock behavior.

    When either knob differs from the stock (2, None) the session is
    built directly with `ProfileOptions`; if this jaxlib cannot (API
    drift), the capture falls back to the stock tracer rather than
    failing the run — the options are fidelity, not correctness.
    """
    if host_tracer_level == 2 and python_tracer_level is None:
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
        return
    sess = None
    try:
        from jax._src.lib import xla_client
        opts = xla_client.profiler.ProfileOptions()
        opts.host_tracer_level = int(host_tracer_level)
        if python_tracer_level is not None:
            opts.python_tracer_level = int(python_tracer_level)
        # backends must wake before the tracer (the stock start_trace
        # does the same — on Cloud TPU a later libtpu init would miss
        # the device tracer entirely)
        jax.devices()
        sess = xla_client.profiler.ProfilerSession(opts)
    except Exception:  # noqa: BLE001 - options are best-effort fidelity
        sess = None
    if sess is None:
        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()
        return
    try:
        yield
    finally:
        sess.stop_and_export(str(logdir))


def annotate(name: str):
    """Named region that shows up in profiler traces (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


def benchmark_chained(step: Callable, state, iters: int = 20) -> BenchResult:
    """Steady-state timing of `state -> state` work as ONE device program:
    a jitted fori_loop executes `step` `iters` times with the carried state
    forcing inter-iteration dependencies. Immune to per-dispatch latency and
    async-dispatch ambiguity (both observed to distort per-call timing over
    remote-attached TPUs); wall-clock / iters is pure device time.

    Timing is SLOPE-BASED with fetch sync (see ``fetch_sync``): the loop
    program runs once (t1) and then twice back-to-back (t2); per-iter time is
    (t2 - t1) / iters, which cancels every constant overhead — dispatch,
    fetch round-trip, queue drain — even on backends where
    ``block_until_ready`` is unreliable.
    """
    from jax import lax

    lf = jax.jit(lambda s: lax.fori_loop(0, iters, lambda i, s: step(s), s))
    t0 = time.perf_counter()
    out = lf(state)
    fetch_sync(out)
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = lf(state)
    fetch_sync(out)
    t1 = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = lf(state)
    out = lf(out)
    fetch_sync(out)
    t2 = time.perf_counter() - t0

    per_iter = max(t2 - t1, 1e-9) / iters
    return BenchResult(mean_s=per_iter, p50_s=per_iter,
                       min_s=min(per_iter, t1 / iters), iters=iters,
                       compile_s=compile_s)
