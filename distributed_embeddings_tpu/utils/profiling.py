"""Profiling and timing utilities.

The reference has no tracing subsystem — performance work is wall timing in
example scripts with a device-sync-by-print idiom (reference:
examples/benchmarks/synthetic_models/main.py:140-158). On TPU, first-class
tools exist; this module packages the two workflows:

  * ``benchmark(fn, *args)`` — compile-excluded, device-synced step timing
    (block_until_ready, not print) with mean/p50/min.
  * ``trace(logdir)`` — context manager around jax.profiler producing an
    XPlane trace viewable in TensorBoard/Perfetto (op-level HLO timing,
    HBM traffic, ICI collectives).
"""

import contextlib
import statistics
import time
from typing import Any, Callable, NamedTuple, Sequence

import jax

__all__ = ["BenchResult", "benchmark", "benchmark_batches", "trace",
           "annotate", "fetch_sync", "hlo_op_counts",
           "hlo_collective_bytes", "hlo_collective_overlap"]


def hlo_op_counts(lowered, ops: Sequence[str] = ("sort", "scatter", "gather",
                                                 "all_to_all")) -> dict:
    """Count StableHLO ops in a lowered (not yet compiled) jax program.

    The static twin of a profiler trace: op COUNTS are decided at trace
    time, so regressions like "the train step re-sorts the same ids three
    times" (docs/perf_model.md 'Sort folding') are catchable on any
    backend, hardware or not — tools/hlo_audit.py builds the repo's
    regression gate on this.

    Args:
      lowered: a ``jax.jit(f).lower(...)`` result, or its ``.as_text()``
        string (StableHLO MLIR).
      ops: StableHLO op mnemonics, counted as whole words (``sort`` counts
        ``stablehlo.sort`` but not ``sort_key`` identifiers).

    Returns {op: count}. Counts are per textual op instance; an op inside
    a called sub-function counts once per textual occurrence, not per call
    site — stable for equality/upper-bound assertions, not a dynamic
    execution count.
    """
    import re
    text = lowered if isinstance(lowered, str) else lowered.as_text()
    return {op: len(re.findall(rf'stablehlo\.{re.escape(op)}\b', text))
            for op in ops}


_DTYPE_BYTES = {"f64": 8, "f32": 4, "bf16": 2, "f16": 2, "f8": 1,
                "i64": 8, "ui64": 8, "i32": 4, "ui32": 4,
                "i16": 2, "ui16": 2, "i8": 1, "ui8": 1, "i1": 1}

_COLLECTIVES = ("ragged_all_to_all", "all_to_all", "all_gather",
                "reduce_scatter", "collective_permute")


def hlo_collective_bytes(lowered, collectives=_COLLECTIVES) -> dict:
    """Sum the operand bytes of each collective op in a lowered program,
    split by element dtype — the byte-level twin of `hlo_op_counts` and
    the static audit behind the wire-compression claim (ISSUE 5,
    docs/perf_model.md "Wire compression"): whether the compiled step's
    exchange operands actually narrowed is decided at trace time, so a
    bf16-wire regression is catchable on any backend, no hardware.

    Only the FIRST operand of each op is counted (the payload; e.g.
    `ragged_all_to_all`'s five metadata operands are bookkeeping).
    Shapes inside shard_map bodies are per-device — ratios between two
    lowerings of the same program are what the audit asserts, not
    absolute fleet bytes.

    Args:
      lowered: ``jax.jit(f).lower(...)`` result or its ``.as_text()``.
      collectives: StableHLO op mnemonics to scan.

    Returns {op: {dtype: bytes}, "total": {dtype: bytes},
    "float_bytes": int, "int_bytes": int} — float_bytes aggregates
    f64/f32/bf16/f16 payloads (the compressible activation/weight wire),
    int_bytes the id wire.
    """
    import re
    text = lowered if isinstance(lowered, str) else lowered.as_text()
    out = {op: {} for op in collectives}
    total: dict = {}
    pat = re.compile(
        r'"?stablehlo\.(' + "|".join(map(re.escape, collectives))
        + r')"?.*?:\s*\(tensor<([^>]+)>', re.DOTALL)
    for m in pat.finditer(text):
        op, sig = m.group(1), m.group(2)
        parts = sig.split("x")
        dtype = parts[-1]
        elems = 1
        for p in parts[:-1]:
            elems *= int(p)
        nbytes = elems * _DTYPE_BYTES.get(dtype, 4)
        out[op][dtype] = out[op].get(dtype, 0) + nbytes
        total[dtype] = total.get(dtype, 0) + nbytes
    float_b = sum(v for k, v in total.items()
                  if k in ("f64", "f32", "bf16", "f16", "f8"))
    int_b = sum(v for k, v in total.items() if k.startswith(("i", "ui")))
    out["total"] = total
    out["float_bytes"] = float_b
    out["int_bytes"] = int_b
    return out


def hlo_collective_overlap(lowered, collectives=_COLLECTIVES,
                           compute_ops=("dot_general",
                                        "convolution")) -> dict:
    """Classify every collective in a lowered program by its dependency
    relation to the module's dense compute — the static overlap audit
    behind the lookahead pipeline (ISSUE 9, docs/perf_model.md
    "Lookahead prefetch").

    A collective with dense compute (dot_general/convolution) in NEITHER
    its transitive fan-in NOR its transitive fan-out is an **overlap
    candidate**: no data dependency orders it against the dense stage,
    so XLA's latency-hiding scheduler is free to run it concurrently
    with the MXU work (async collective start/done pairs). In the
    monolithic sequential step every exchange collective fails this test
    — the forward exchange FEEDS the dense ops and the gradient
    transpose CONSUMES them — so `overlap_candidates` is 0 there, while
    the fused lookahead step's prefetch subgraph (batch N+1's exchange,
    reading only params and the next batch's ids) passes it. That is
    checkable at trace time on any backend, which makes it both the CI
    regression gate for the pipeline structure and the attribution
    artifact for TPU timing (tools/hlo_audit.py).

    Method: the StableHLO SSA text is parsed into a per-function
    dataflow graph; private helper functions (jax lowers shard_map
    bodies and jnp helpers to `call @fn` sites) are summarized
    transitively — a call-site inherits its callee's collective counts
    and compute content — and the public entry function's graph is
    taint-propagated in both directions. Granularity is the call SITE,
    so a helper shared by the prefetch and drain stages is classified
    per use, not once globally. Conservative where imprecise: a callee
    mixing compute and collectives taints the whole call site, and
    instructions inside nested REGIONS (stablehlo.while / case bodies,
    e.g. a scanned multi-step program) fold into the enclosing op's
    node — in both cases the mixed node's collectives count as
    serialized, never as candidates.

    Args:
      lowered: ``jax.jit(f).lower(...)`` result or its ``.as_text()``.
      collectives / compute_ops: StableHLO op mnemonics.

    Returns {"collectives_total", "overlap_candidates",
    "serialized_collectives", "candidates_by_op", "compute_sites"}.
    """
    import re
    text = lowered if isinstance(lowered, str) else lowered.as_text()
    line_re = re.compile(r'^\s*(%[\w]+)(?::\d+)?\s*=\s*(.*)$')
    op_re = re.compile(r'"?(?:stablehlo|mhlo|chlo)\.([\w.]+)"?')
    call_re = re.compile(r'(?:func\.)?call\s+@([\w$.-]+)')
    func_re = re.compile(r'func\.func\s+(?:public\s+|private\s+)?'
                         r'@([\w$.-]+)')

    # Each node is one TOP-LEVEL instruction of a function. Instructions
    # inside nested regions (stablehlo.while/case bodies) reference
    # region block args a flat SSA graph cannot resolve, so their op
    # kinds and operand refs FOLD INTO the enclosing op's node —
    # conservative in the safe direction: a region mixing collectives
    # and compute taints one node, and its collectives count as
    # serialized, never as overlap candidates.
    funcs: dict = {}
    cur = None
    depth = 0
    for raw in text.splitlines():
        fm = func_re.search(raw)
        if fm:
            cur = fm.group(1)
            funcs[cur] = []
            # the signature line's opening brace is the body baseline
            depth = raw.count("{") - raw.count("}")
            continue
        if cur is None:
            continue
        at_top = depth <= 1
        depth += raw.count("{") - raw.count("}")
        m = line_re.match(raw)
        if not m:
            continue
        lhs, rhs = m.group(1), m.group(2)
        callee_m = call_re.search(rhs)
        callee = callee_m.group(1) if callee_m else None
        op_m = op_re.search(rhs)
        op = op_m.group(1) if op_m else (
            "call" if callee else rhs.split("(")[0].split()[0])
        # operand refs: %N and %argN tokens on the rhs, multi-result
        # projections (%5#1) resolve to their base value
        operands = [t.split("#")[0] for t in
                    re.findall(r'%[A-Za-z0-9_]+', rhs)]
        if at_top or not funcs[cur]:
            funcs[cur].append({"lhs": lhs, "ops": [op],
                               "callees": [callee] if callee else [],
                               "operands": operands})
        else:
            owner = funcs[cur][-1]
            owner["ops"].append(op)
            if callee:
                owner["callees"].append(callee)
            owner["operands"].extend(operands)

    # ---- transitive per-function summaries (call graph is acyclic)
    summaries: dict = {}

    def summarize(fn, stack=()):
        if fn in summaries:
            return summaries[fn]
        if fn not in funcs or fn in stack:
            return {"coll": {}, "compute": False}
        coll: dict = {}
        compute = False
        for node in funcs[fn]:
            for op in node["ops"]:
                if op in collectives:
                    coll[op] = coll.get(op, 0) + 1
                if op in compute_ops:
                    compute = True
            for callee in node["callees"]:
                sub = summarize(callee, stack + (fn,))
                compute = compute or sub["compute"]
                for k, v in sub["coll"].items():
                    coll[k] = coll.get(k, 0) + v
        summaries[fn] = {"coll": coll, "compute": compute}
        return summaries[fn]

    entry = "main" if "main" in funcs else (
        max(funcs, key=lambda f: len(funcs[f])) if funcs else None)
    if entry is None:
        return {"collectives_total": 0, "overlap_candidates": 0,
                "serialized_collectives": 0, "candidates_by_op": {},
                "compute_sites": 0}
    body = funcs[entry]
    n = len(body)
    producer = {}
    for i, node in enumerate(body):
        producer[node["lhs"]] = i
    deps = [[producer[o] for o in node["operands"] if o in producer]
            for node in body]
    node_coll = []
    node_compute = []
    for node in body:
        c: dict = {}
        compute = False
        for op in node["ops"]:
            if op in collectives:
                c[op] = c.get(op, 0) + 1
            if op in compute_ops:
                compute = True
        for callee in node["callees"]:
            sub = summarize(callee)
            compute = compute or sub["compute"]
            for k, v in sub["coll"].items():
                c[k] = c.get(k, 0) + v
        node_coll.append(c)
        node_compute.append(compute)

    # SSA text order is topological: one forward pass taints fan-ins,
    # one reverse pass taints fan-outs
    dot_in_fanin = [False] * n
    for i in range(n):
        dot_in_fanin[i] = any(node_compute[d] or dot_in_fanin[d]
                              for d in deps[i])
    consumers: list = [[] for _ in range(n)]
    for i, ds in enumerate(deps):
        for d in ds:
            consumers[d].append(i)
    dot_in_fanout = [False] * n
    for i in range(n - 1, -1, -1):
        dot_in_fanout[i] = any(node_compute[c] or dot_in_fanout[c]
                               for c in consumers[i])

    total = 0
    cand_by_op: dict = {}
    candidates = 0
    for i in range(n):
        cnt = sum(node_coll[i].values())
        if not cnt:
            continue
        total += cnt
        # a site that itself CONTAINS compute is never a candidate (the
        # collective may order against its own callee's dots)
        if (not dot_in_fanin[i] and not dot_in_fanout[i]
                and not node_compute[i]):
            candidates += cnt
            for k, v in node_coll[i].items():
                cand_by_op[k] = cand_by_op.get(k, 0) + v
    return {"collectives_total": total,
            "overlap_candidates": candidates,
            "serialized_collectives": total - candidates,
            "candidates_by_op": cand_by_op,
            "compute_sites": sum(node_compute)}


def fetch_sync(out) -> float:
    """Drain the device queue by FETCHING a value derived from ``out``.

    ``jax.block_until_ready`` is not a reliable sync on every backend: on the
    experimental remote-attached 'axon' TPU platform it was observed (round 3,
    2026-07-31) to return before device work finished, yielding physically
    impossible timings — e.g. a 2.9M-key sort "measured" at 15us and a train
    step 63x FASTER than the chip's HBM roofline. A host fetch of a scalar
    reduced from the outputs cannot complete before the data exists, so a
    fetch is the sync of record for all timing in this repo.

    Cost per leaf is one element's slice + host fetch — NOT a full-leaf
    reduction (an astype/sum would materialize an f32 copy of every leaf:
    for a 4 GiB bf16 table that is an 8 GiB temp inside the timed region).
    A one-element slice carries the same guarantee: it cannot be produced
    before the leaf's buffer exists. Returns the summed scalar so callers
    can sanity-check it (note: only element [0...] of each leaf is
    observed — use a full device-side reduction if you need finiteness of
    the whole output).
    """
    import jax.numpy as jnp
    total = 0.0
    fetched = False
    for leaf in jax.tree.leaves(out):
        if not hasattr(leaf, "dtype") or leaf.size == 0:
            continue
        first = leaf.reshape(-1)[0] if leaf.ndim else leaf
        total += float(first.astype(jnp.float32))
        fetched = True
    if not fetched:
        # no fetchable array leaf (empty/none): fall back to
        # block_until_ready — weaker on axon, but better than silently
        # timing only dispatch (ADVICE r3)
        jax.block_until_ready(out)
    return total


class BenchResult(NamedTuple):
    mean_s: float
    p50_s: float
    min_s: float
    iters: int
    compile_s: float

    @property
    def mean_ms(self) -> float:
        return self.mean_s * 1e3

    def __str__(self):
        return (f"mean={self.mean_s * 1e3:.3f}ms p50={self.p50_s * 1e3:.3f}ms "
                f"min={self.min_s * 1e3:.3f}ms (compile {self.compile_s:.1f}s, "
                f"{self.iters} iters)")


def benchmark(fn: Callable, *args, iters: int = 20, warmup: int = 2,
              **kwargs) -> BenchResult:
    """Time `fn(*args)` with device sync per iteration.

    The first call (compile) is timed separately; `warmup` additional calls
    run before measurement to settle caches/autotuning.
    """
    t0 = time.perf_counter()
    out = fn(*args, **kwargs)
    fetch_sync(out)
    compile_s = time.perf_counter() - t0
    for _ in range(warmup):
        out = fn(*args, **kwargs)
        # sync EVERY call: XLA:CPU's in-process collectives deadlock when
        # several collective-bearing executions are queued concurrently
        # (rendezvous termination after 40s); on TPU this just serializes
        # warmup, which is fine. fetch_sync, not block_until_ready: the
        # latter lies on the axon platform (see its docstring)
        fetch_sync(out)

    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        fetch_sync(out)
        times.append(time.perf_counter() - t0)
    return BenchResult(mean_s=statistics.mean(times),
                       p50_s=statistics.median(times),
                       min_s=min(times), iters=iters, compile_s=compile_s)


def benchmark_batches(fn: Callable, batches: Sequence, iters: int = 20,
                      warmup: int = 2) -> BenchResult:
    """Like `benchmark` but rotates through pre-built batches (tuples of
    args) so input-dependent effects (e.g. power-law gather locality) are
    averaged. fn is called as fn(*batches[i % len(batches)])."""
    t0 = time.perf_counter()
    out = fn(*batches[0])
    fetch_sync(out)
    compile_s = time.perf_counter() - t0
    for i in range(warmup):
        out = fn(*batches[i % len(batches)])
        fetch_sync(out)   # see benchmark(): CPU collective safety + axon sync

    times = []
    for i in range(iters):
        t0 = time.perf_counter()
        out = fn(*batches[i % len(batches)])
        fetch_sync(out)
        times.append(time.perf_counter() - t0)
    return BenchResult(mean_s=statistics.mean(times),
                       p50_s=statistics.median(times),
                       min_s=min(times), iters=iters, compile_s=compile_s)


@contextlib.contextmanager
def trace(logdir: str, host_tracer_level: int = 2):
    """Capture a jax.profiler trace for everything inside the block:

        with profiling.trace("/tmp/trace"):
            step(params, batch)
            jax.block_until_ready(...)

    View with TensorBoard's profile plugin or ui.perfetto.dev.
    """
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region that shows up in profiler traces (TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


def benchmark_chained(step: Callable, state, iters: int = 20) -> BenchResult:
    """Steady-state timing of `state -> state` work as ONE device program:
    a jitted fori_loop executes `step` `iters` times with the carried state
    forcing inter-iteration dependencies. Immune to per-dispatch latency and
    async-dispatch ambiguity (both observed to distort per-call timing over
    remote-attached TPUs); wall-clock / iters is pure device time.

    Timing is SLOPE-BASED with fetch sync (see ``fetch_sync``): the loop
    program runs once (t1) and then twice back-to-back (t2); per-iter time is
    (t2 - t1) / iters, which cancels every constant overhead — dispatch,
    fetch round-trip, queue drain — even on backends where
    ``block_until_ready`` is unreliable.
    """
    from jax import lax

    lf = jax.jit(lambda s: lax.fori_loop(0, iters, lambda i, s: step(s), s))
    t0 = time.perf_counter()
    out = lf(state)
    fetch_sync(out)
    compile_s = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = lf(state)
    fetch_sync(out)
    t1 = time.perf_counter() - t0

    t0 = time.perf_counter()
    out = lf(state)
    out = lf(out)
    fetch_sync(out)
    t2 = time.perf_counter() - t0

    per_iter = max(t2 - t1, 1e-9) / iters
    return BenchResult(mean_s=per_iter, p50_s=per_iter,
                       min_s=min(per_iter, t1 / iters), iters=iters,
                       compile_s=compile_s)
