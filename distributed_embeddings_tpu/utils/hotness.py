"""Counter-based hot-row admission, shared by serving and training.

Production recommender traffic is power-law distributed: a small set of
hot rows absorbs most lookups. Two subsystems exploit that skew with the
SAME admission policy and must not drift:

  * the serving HBM hot-row cache (`serving/cache.py` `HotRowCache`) —
    hot rows of a host-offloaded bucket are served from device memory;
  * the training hot-row shard (`layers/dist_model_parallel.py`,
    `DistributedEmbedding(hot_rows=...)`) — hot rows of a model-parallel
    bucket are replicated data-parallel so hits skip the id exchange and
    the table-scale gather/scatter.

`HotnessTracker` is the factored host-side core both use: per-row access
counters, a bounded-memory pruning rule, a pending set of
threshold-crossers, a fixed-capacity resident set (key -> slot), and the
admission/eviction policy. It never touches device state — callers copy
rows around; the tracker only decides WHICH rows are hot.

Rows are keyed by an opaque non-negative integer (the stacked-bucket
``world_slice * rows_max + local_row`` flat key in both current callers).
"""

from typing import Dict, List, Optional, Tuple

import numpy as np

__all__ = ["HotnessTracker"]


class HotnessTracker:
    """Access counters + admission policy over a fixed-capacity hot set.

    Args:
      capacity: number of resident slots (static).
      promote_threshold: access count at which a row becomes
        promotion-eligible (>= 1; 1 promotes on first touch).
      max_tracked: bound on the counter dict; beyond it, counters prune
        back to the hottest max_tracked/2 keys (plus residents). Default
        max(64 * capacity, 4096).
    """

    def __init__(self, capacity: int, promote_threshold: int = 2,
                 max_tracked: Optional[int] = None):
        if capacity <= 0:
            raise ValueError(f"capacity must be positive, got {capacity}")
        if promote_threshold < 1:
            raise ValueError("promote_threshold must be >= 1")
        self.capacity = int(capacity)
        self.promote_threshold = int(promote_threshold)
        self.max_tracked = int(max_tracked or max(64 * capacity, 4096))
        self._index: Dict[int, int] = {}          # row key -> slot
        self.slot_keys = np.full((self.capacity,), -1, np.int64)
        self._counts: Dict[int, int] = {}         # row key -> access count
        self._pending: set = set()                # threshold-crossed keys
        # stats (valid lanes only — callers mask padding before observing)
        self.hits = 0
        self.misses = 0
        self.promotions = 0
        self.evictions = 0

    # ------------------------------------------------------------- observe
    def lookup_slots(self, keys: np.ndarray,
                     valid: Optional[np.ndarray] = None,
                     observe: bool = True) -> np.ndarray:
        """Map row keys to resident slots: >= 0 on hit, -1 on miss.

        Args:
          keys: integer array (any shape) of row keys.
          valid: optional same-shape bool mask; invalid lanes (exchange
            padding) always map to -1 and never touch counters or stats.
          observe: update access counters + hit/miss stats (warmup passes
            set False so compile-ahead does not skew admission).

        Returns an int32 array of `keys`' shape.
        """
        flat = np.asarray(keys, np.int64).reshape(-1)
        vmask = (np.ones(flat.shape, bool) if valid is None
                 else np.asarray(valid, bool).reshape(-1))
        out = np.full(flat.shape, -1, np.int32)
        uniq, inv, counts = np.unique(flat[vmask], return_inverse=True,
                                      return_counts=True)
        slot_of = np.full(uniq.shape, -1, np.int32)
        for u, key in enumerate(uniq.tolist()):
            s = self._index.get(key)
            if s is not None:
                slot_of[u] = s
            if observe:
                c = self._counts.get(key, 0) + int(counts[u])
                self._counts[key] = c
                if s is None and c >= self.promote_threshold:
                    self._pending.add(key)
        if observe and len(self._counts) > self.max_tracked:
            self._prune_counts()
        out[vmask] = slot_of[inv]
        if observe:
            n_hit = int((out[vmask] >= 0).sum())
            self.hits += n_hit
            self.misses += int(vmask.sum()) - n_hit
        return out.reshape(np.asarray(keys).shape)

    def observe(self, keys: np.ndarray,
                valid: Optional[np.ndarray] = None) -> None:
        """Count-only observation (the training warmup scan's form)."""
        self.lookup_slots(keys, valid=valid, observe=True)

    def _prune_counts(self) -> None:
        """Bound the counter dict: keep resident keys plus the hottest
        half of max_tracked; everything colder restarts from zero if seen
        again (an admissible information loss — a pruned key was, by
        construction, colder than max_tracked/2 other keys)."""
        resident = set(self._index)
        keep_n = self.max_tracked // 2
        hottest = sorted(self._counts.items(), key=lambda kv: -kv[1])[:keep_n]
        kept = {k: c for k, c in hottest}
        for k in resident:
            if k in self._counts:
                kept[k] = self._counts[k]
        self._counts = kept
        self._pending &= set(kept)

    # ----------------------------------------------------------- admission
    def _promotion_candidates(self) -> List[Tuple[int, int]]:
        """Uncached keys whose count crossed the threshold, hottest first —
        drawn from the `_pending` set, not a full counter scan."""
        self._pending -= set(self._index)
        cands = [(self._counts.get(k, 0), k) for k in self._pending]
        cands.sort(reverse=True)
        return cands

    def plan_admissions(self) -> List[Tuple[int, int]]:
        """Run the admission policy against the current counters.

        Returns the (slot, key) assignment plan, hottest first. Free slots
        fill first; when full, a candidate evicts the coldest resident row
        only if the candidate's count is strictly higher. The plan updates
        `slot_keys` (and pops evicted keys from the index, counting
        `evictions`) immediately so a second plan in the same round sees
        the new occupancy; callers copy the planned rows, then call
        `commit_admissions(plan)` to make them resident.
        """
        cands = self._promotion_candidates()
        if not cands:
            return []
        free = [s for s in range(self.capacity) if self.slot_keys[s] < 0]
        plan: List[Tuple[int, int]] = []
        for count, key in cands:
            if free:
                slot = free.pop()
            else:
                # full: evict the coldest resident only for a strictly
                # hotter row. Slots planned earlier this round already
                # carry their NEW key, so the scan ranks them by the
                # newcomer's count, never as empty.
                coldest = min(range(self.capacity),
                              key=lambda s: self._counts.get(
                                  int(self.slot_keys[s]), 0))
                cold_key = int(self.slot_keys[coldest])
                if count <= self._counts.get(cold_key, 0):
                    break                          # sorted: nothing hotter left
                self._index.pop(cold_key, None)
                self.evictions += 1
                slot = coldest
            self.slot_keys[slot] = key
            plan.append((slot, key))
        return plan

    def commit_admissions(self, plan: List[Tuple[int, int]]) -> int:
        """Make a `plan_admissions` plan resident (caller copied the rows).
        Returns rows promoted."""
        for slot, key in plan:
            self._index[key] = slot
            self._pending.discard(key)
        self.promotions += len(plan)
        return len(plan)

    def set_resident(self, keys: np.ndarray) -> None:
        """Replace the resident set wholesale (planner-driven admission,
        e.g. top-H from IntegerLookup counts): key i occupies slot i.
        Evicted keys are not counted as evictions — this is a reset, not
        the online policy."""
        keys = np.asarray(keys, np.int64).reshape(-1)
        if len(keys) > self.capacity:
            raise ValueError(
                f"{len(keys)} keys exceed capacity {self.capacity}")
        if len(np.unique(keys)) != len(keys):
            raise ValueError("resident keys must be unique")
        self._index = {int(k): i for i, k in enumerate(keys.tolist())}
        self.slot_keys.fill(-1)
        self.slot_keys[:len(keys)] = keys
        self._pending -= set(self._index)

    def invalidate(self) -> None:
        """Drop every resident row (hits resume only after re-admission)."""
        for k in self._index:
            if self._counts.get(k, 0) >= self.promote_threshold:
                self._pending.add(k)       # still hot: re-promotable
        self._index.clear()
        self.slot_keys.fill(-1)

    def resident_keys(self) -> np.ndarray:
        """Current resident keys ([R] int64, slot order, R <= capacity)."""
        return self.slot_keys[self.slot_keys >= 0].copy()

    def top_keys(self, n: Optional[int] = None) -> np.ndarray:
        """The hottest n tracked keys by count (default: capacity) —
        the 'warmup scan' admission input: observe batches, then
        ``set_resident(top_keys())``."""
        n = self.capacity if n is None else int(n)
        items = sorted(self._counts.items(), key=lambda kv: (-kv[1], kv[0]))
        return np.asarray([k for k, _ in items[:n]], np.int64)

    # ---------------------------------------------------------------- stats
    def reset_stats(self) -> None:
        """Zero the hit/miss counters (NOT the frequency counters or the
        resident set) — callers window measured hit rates to a residency
        epoch, e.g. the training hot shard resets at each re-admission so
        reported rates describe the CURRENT hot set, not the all-miss
        warmup stream."""
        self.hits = 0
        self.misses = 0

    @property
    def resident(self) -> int:
        return int((self.slot_keys >= 0).sum())

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def stats(self) -> dict:
        return {"capacity": self.capacity, "resident": self.resident,
                "hits": self.hits, "misses": self.misses,
                "hit_rate": round(self.hit_rate, 4),
                "promotions": self.promotions, "evictions": self.evictions,
                "tracked": len(self._counts), "pending": len(self._pending)}
